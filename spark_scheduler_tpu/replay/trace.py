"""Versioned JSONL decision-trace codec (ISSUE 17 tentpole, part a).

A trace is a complete, self-contained re-execution input for the
extender/solver: one header line carrying the active InstallConfig
fingerprint, then every INPUT a decision consumed in arrival order —
backend node/pod events (keyed by the registry epoch), predicate requests,
and explicit reconcile / reservation-delete directives. Scheduler-
ORIGINATED writes (reservations, demands, binds the engine itself makes)
are deliberately NOT journaled: replay regenerates them, which is exactly
what makes bit-identity a checkable property rather than a tautology.
Recorded `result` events carry the verdict/placement/failure-map the live
run answered, so replay (replay/engine.py) can assert byte-identical
decisions event-for-event.

Format: one canonical JSON object per line (sorted keys, no spaces), so
write -> read -> write round-trips byte-identically. Event kinds:

  header     {"k","v","config","hash","source","t","meta"}
  node       {"k","s","t","op":add|update|delete, "node"|"name", "epoch"}
  pod        {"k","s","t","op":add|update|delete, "pod"|{"ns","name"}}
  rr         {"k","s","t","op":"add","rr":<wire>}      (bootstrap only)
  predicate  {"k","s","t","w","mode":solo|window,"bind","reqs":[...]}
  result     {"k","s","t","w","res":[[outcome,node,failed],...]}
  decision   {"k","s","t","rec":<DecisionRecord>}       (informational)
  rr_delete  {"k","s","t","ns","name"}
  reconcile  {"k","s","t"}
  meta       {"k","s","t", ...free-form...}

`failed` in a result row is None (success), the compressed uniform form
["u", message, count] when every candidate carries the same reason (the
overwhelmingly common denial shape), or the explicit per-node map. A
predicate request whose candidate list equals the writer's full roster
mirror stores "*" instead of repeating 10k names per request, and one
whose pod is identity-equal to the object the backend holds (i.e. the
stream already carries its bytes in a pod add/update event) stores
{"ref": [ns, name]} instead of the full wire pod.

Durability posture mirrors store/durable.py: the reader tolerates a torn
final line (crash mid-append) silently and counts mid-file corruption,
and the writer NEVER fails the serving path — IO errors are swallowed
and surfaced as a counter (/debug/trace, foundry.spark.scheduler.trace.*).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
from typing import Any, Iterator, Optional

TRACE_VERSION = 1

# Candidate-list sentinel: "the writer's full node roster at this point of
# the stream" ("*" is not a valid k8s node name).
ALL_NODES = "*"


# One shared encoder instance: json.dumps() with non-default options
# builds a fresh JSONEncoder per call, and dumps_event rides the serving
# path once per journaled event.
_ENCODER = json.JSONEncoder(
    separators=(",", ":"), sort_keys=True, ensure_ascii=False
)


def dumps_event(ev: dict) -> str:
    """THE canonical encoding — sorted keys, no spaces — shared by the
    writer, the round-trip test, and the generators' byte-identity
    contract."""
    return _ENCODER.encode(ev)


# --------------------------------------------------------------- fingerprint


def config_fingerprint(config) -> dict:
    """The InstallConfig as plain JSON-able data (nested dataclasses —
    FifoConfig, LabelPriorityOrder — become dicts)."""
    return dataclasses.asdict(config)


def config_hash(fingerprint: dict) -> str:
    return hashlib.sha256(dumps_event(fingerprint).encode()).hexdigest()[:16]


def config_from_fingerprint(
    fingerprint: dict,
    overrides: Optional[dict] = None,
    forced: Optional[dict] = None,
):
    """Rebuild an InstallConfig from a trace header. Unknown keys (a trace
    written by a newer build) are dropped; `overrides` is the what-if
    surface (field names, dashes accepted); `forced` wins last (the replay
    engine pins the backend-free harness fields)."""
    from spark_scheduler_tpu.core.extender import FifoConfig
    from spark_scheduler_tpu.server.config import (
        InstallConfig,
        LabelPriorityOrder,
    )

    known = {f.name for f in dataclasses.fields(InstallConfig)}
    kw = {k: v for k, v in fingerprint.items() if k in known}
    if isinstance(kw.get("fifo_config"), dict):
        kw["fifo_config"] = FifoConfig(**kw["fifo_config"])
    for key in (
        "driver_prioritized_node_label",
        "executor_prioritized_node_label",
    ):
        if isinstance(kw.get(key), dict):
            kw[key] = LabelPriorityOrder(**kw[key])
    for src in (overrides or {}), (forced or {}):
        for k, v in src.items():
            k = k.replace("-", "_")
            if k not in known:
                raise KeyError(f"unknown config field: {k}")
            kw[k] = v
    return InstallConfig(**kw)


# ------------------------------------------------------------- failure maps


def normalize_failed(
    failed: Optional[dict], candidates: list[str]
) -> Optional[Any]:
    """Canonical encoding of an ExtenderFilterResult failure map. The
    extender's _fail builds {name: message for name in candidates} — one
    uniform reason across exactly the candidate set — so that shape
    compresses to ["u", message, count]; anything else (solver-built maps,
    truncated maps) stays explicit. Success (empty map) is None."""
    if not failed:
        return None
    msgs = set(failed.values())
    if (
        len(msgs) == 1
        and len(failed) == len(candidates)
        and set(failed) == set(candidates)
    ):
        return ["u", next(iter(msgs)), len(failed)]
    return dict(failed)


def encode_result(res, candidates: list[str]) -> list:
    """[outcome, placed-node-or-None, normalized failure map] — the
    bit-identity tuple replay compares."""
    return [
        res.outcome,
        res.node_names[0] if res.node_names else None,
        normalize_failed(res.failed_nodes, candidates),
    ]


# ------------------------------------------------------------------- writer


class TraceWriter:
    """Append-only JSONL trace sink.

    One instance serves three producers: backend subscriptions (node/pod
    events), the extender's capture wrappers (predicate/result events),
    and the FlightRecorder sink hook (decision events). All three ride the
    serving path, so every write is one lock + one buffered file append,
    and an IO failure is counted, never raised."""

    def __init__(
        self,
        path: str,
        *,
        clock=time.time,
        decisions: bool = False,
        epoch_fn=None,
        source: str = "server",
    ):
        self.path = path
        self._clock = clock
        self._decisions = decisions
        self._epoch_fn = epoch_fn
        self._source = source
        self._lock = threading.Lock()
        self._seq = 0
        self._wid = 0
        # Node-roster mirror for the "*" candidate compression: appended on
        # add, removed on delete, order-stable on update — exactly the dict
        # insertion order backend.list_nodes() yields.
        self._roster: list[str] = []
        self._roster_set: set[str] = set()
        # (ns, name) -> id(pod) of the object the backend currently holds,
        # maintained by the pod hooks. A predicate request whose pod IS
        # that object (identity, not equality — cheap and sufficient)
        # journals as {"ref": [ns, name]} instead of re-dumping the full
        # wire pod the stream already carries; replay resolves the ref
        # against its backend. This halves the serving-path encode cost:
        # the pod bytes ride the trace exactly once.
        self._pod_ids: dict[tuple, int] = {}
        # wid -> per-request candidate lists, parked between on_predicate
        # and on_results so result rows normalize against the REAL request
        # candidates (the uniform ["u", msg, count] form must not equate
        # two different node sets of the same size).
        self._candidates: dict[int, list[list[str]]] = {}
        self.events = 0
        self.bytes = 0
        self.write_errors = 0
        # 1 MiB buffer: the serving path pays one syscall per megabyte of
        # trace instead of one per ~8 KiB; flush()/close() still make the
        # stream durable at the points the harness and tests rely on.
        self._fh = open(path, "w", encoding="utf-8", buffering=1 << 20)

    # -- plumbing ----------------------------------------------------------

    def _emit(self, ev: dict) -> None:
        with self._lock:
            self._seq += 1
            ev["s"] = self._seq
            ev.setdefault("t", self._clock())
            try:
                line = dumps_event(ev)
                self._fh.write(line + "\n")
                self.events += 1
                self.bytes += len(line) + 1
            except Exception:
                self.write_errors += 1

    def _next_wid(self) -> int:
        with self._lock:
            self._wid += 1
            return self._wid

    def _epoch(self):
        fn = self._epoch_fn
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None

    # -- header / bootstrap ------------------------------------------------

    def write_header(self, config, meta: Optional[dict] = None) -> None:
        fp = config_fingerprint(config)
        # The trace's own output path is self-referential noise: it can't
        # influence a decision, and keeping it would make two otherwise
        # identical re-captures differ byte-wise on their header line.
        fp["trace_path"] = None
        self._emit(
            {
                "k": "header",
                "v": TRACE_VERSION,
                "config": fp,
                "hash": config_hash(fp),
                "source": self._source,
                "meta": meta or {},
            }
        )

    def bootstrap(self, backend) -> None:
        """Journal the pre-existing world (a writer attached to a live
        server mid-life): nodes, pods, and hard reservations, so the trace
        stands alone. Call BEFORE subscribing the event hooks."""
        from spark_scheduler_tpu.server.kube_io import node_to_k8s, pod_to_k8s
        from spark_scheduler_tpu.store.durable import _rr_to_record

        for node in backend.list_nodes():
            self.on_node_add(node)
        for pod in backend.list("pods"):
            self._emit({"k": "pod", "op": "add", "pod": pod_to_k8s(pod)})
        try:
            rrs = backend.list("resourcereservations")
        except Exception:
            rrs = []
        for rr in rrs:
            self._emit({"k": "rr", "op": "add", "rr": _rr_to_record(rr)})

    # -- backend event hooks ----------------------------------------------

    def on_node_add(self, node) -> None:
        from spark_scheduler_tpu.server.kube_io import node_to_k8s

        with self._lock:
            if node.name not in self._roster_set:
                self._roster.append(node.name)
                self._roster_set.add(node.name)
        self._emit(
            {
                "k": "node",
                "op": "add",
                "node": node_to_k8s(node),
                "epoch": self._epoch(),
            }
        )

    def on_node_update(self, old, new) -> None:
        from spark_scheduler_tpu.server.kube_io import node_to_k8s

        self._emit(
            {
                "k": "node",
                "op": "update",
                "node": node_to_k8s(new),
                "epoch": self._epoch(),
            }
        )

    def on_node_delete(self, node) -> None:
        with self._lock:
            if node.name in self._roster_set:
                self._roster.remove(node.name)
                self._roster_set.discard(node.name)
        self._emit(
            {
                "k": "node",
                "op": "delete",
                "name": node.name,
                "epoch": self._epoch(),
            }
        )

    def on_pod_add(self, pod) -> None:
        from spark_scheduler_tpu.server.kube_io import pod_to_k8s

        self._pod_ids[(pod.namespace, pod.name)] = id(pod)
        self._emit({"k": "pod", "op": "add", "pod": pod_to_k8s(pod)})

    def on_pod_update(self, old, new) -> None:
        from spark_scheduler_tpu.server.kube_io import pod_to_k8s

        self._pod_ids[(new.namespace, new.name)] = id(new)
        self._emit({"k": "pod", "op": "update", "pod": pod_to_k8s(new)})

    def on_pod_delete(self, pod) -> None:
        self._pod_ids.pop((pod.namespace, pod.name), None)
        self._emit(
            {
                "k": "pod",
                "op": "delete",
                "ns": pod.namespace,
                "name": pod.name,
            }
        )

    # -- extender capture --------------------------------------------------

    def on_predicate(self, args_list, mode: str, bind: bool = False) -> int:
        """Journal one serving window's (or solo request's) inputs; returns
        the window id its `result` event will carry."""
        from spark_scheduler_tpu.server.kube_io import pod_to_k8s

        wid = self._next_wid()
        reqs = []
        candidates = []
        with self._lock:
            roster = list(self._roster)
        for args in args_list:
            names = list(args.node_names)
            candidates.append(names)
            stored: Any = ALL_NODES if names == roster else names
            pod = args.pod
            key = (pod.namespace, pod.name)
            if self._pod_ids.get(key) == id(pod):
                # the stream already carries these exact pod bytes (the
                # add/update event for THIS object) — reference, don't
                # re-dump. A distinct-but-equal object (e.g. a pod parsed
                # fresh from an HTTP body) journals inline: identity is
                # the only cheap proof the backend copy matches.
                reqs.append({"ref": [pod.namespace, pod.name], "nodes": stored})
            else:
                reqs.append({"pod": pod_to_k8s(pod), "nodes": stored})
        with self._lock:
            self._candidates[wid] = candidates
        ev: dict = {"k": "predicate", "w": wid, "mode": mode, "reqs": reqs}
        if bind:
            ev["bind"] = True
        self._emit(ev)
        return wid

    def on_results(self, wid: int, results) -> None:
        with self._lock:
            candidates = self._candidates.pop(wid, None)
        if candidates is None:
            candidates = [list(r.failed_nodes) for r in results]
        self._emit(
            {
                "k": "result",
                "w": wid,
                "res": [
                    encode_result(r, c) for r, c in zip(results, candidates)
                ],
            }
        )

    # -- recorder sink -----------------------------------------------------

    def on_decision(self, rec) -> None:
        if self._decisions:
            self._emit({"k": "decision", "rec": rec.to_dict()})

    # -- directives --------------------------------------------------------

    def emit_rr_delete(self, namespace: str, name: str) -> None:
        self._emit({"k": "rr_delete", "ns": namespace, "name": name})

    def emit_reconcile(self) -> None:
        self._emit({"k": "reconcile"})

    def emit_meta(self, **kw) -> None:
        self._emit({"k": "meta", **kw})

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> dict:
        return {
            "path": self.path,
            "events": self.events,
            "bytes": self.bytes,
            "write_errors": self.write_errors,
            "windows": self._wid,
        }

    def flush(self) -> None:
        try:
            self._fh.flush()
        except Exception:
            self.write_errors += 1

    def close(self) -> None:
        try:
            self._fh.flush()
            self._fh.close()
        except Exception:
            self.write_errors += 1


# ------------------------------------------------------------------- reader


class TraceReader:
    """Streaming trace reader with durable.py's tail discipline: a parse
    failure on the LAST line is a torn tail (crash mid-append) and is
    silently ignored; a failure mid-file is corruption, counted and
    skipped so the rest of the trace still replays."""

    def __init__(self, path: str):
        self.path = path
        self.header: Optional[dict] = None
        self.malformed = 0
        self.torn_tail = False
        with open(path, "r", encoding="utf-8") as fh:
            first = fh.readline()
        try:
            header = json.loads(first)
        except (json.JSONDecodeError, ValueError):
            raise ValueError(f"trace {path}: unreadable header line")
        if header.get("k") != "header":
            raise ValueError(f"trace {path}: first line is not a header")
        version = header.get("v")
        if version != TRACE_VERSION:
            raise ValueError(
                f"trace {path}: version {version} "
                f"(this build reads {TRACE_VERSION})"
            )
        self.header = header

    def events(self) -> Iterator[dict]:
        """Every event after the header, in stream order."""
        with open(self.path, "r", encoding="utf-8") as fh:
            lines = fh.read().split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        last = len(lines) - 1
        for i, line in enumerate(lines):
            if i == 0:
                continue  # header, parsed in __init__
            try:
                yield json.loads(line)
            except (json.JSONDecodeError, ValueError):
                if i == last:
                    self.torn_tail = True
                else:
                    self.malformed += 1

    def raw_lines(self) -> list[str]:
        """Parseable lines verbatim (round-trip tests)."""
        with open(self.path, "r", encoding="utf-8") as fh:
            out = fh.read().split("\n")
        if out and out[-1] == "":
            out.pop()
        return out
