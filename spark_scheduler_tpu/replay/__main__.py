"""Trace replay CLI (ISSUE 17; `sweep` grid driver ISSUE 18).

  python -m spark_scheduler_tpu.replay info    TRACE
  python -m spark_scheduler_tpu.replay verify  TRACE [--strict]
  python -m spark_scheduler_tpu.replay whatif  TRACE --set binpack-algo=distribute-evenly [...]
  python -m spark_scheduler_tpu.replay sweep   TRACE --grid binpack-algo=tightly-pack,distribute-evenly [...]
  python -m spark_scheduler_tpu.replay generate {diurnal|bursty|churn} OUT --seed N [...]
  python -m spark_scheduler_tpu.replay run     TRACE OUT

`verify` re-drives a captured trace and exits non-zero on any decision
divergence. `run` replays an input-only (generated) trace with binding
and re-captures it through the live TraceWriter wiring — its output is a
full captured trace that `verify` can then pin. `--set` takes repeated
`field=value` pairs (JSON parsed, falling back to raw string; dashes OK).

`sweep` replays ONE trace under the cartesian product of repeated
`--grid field=v1,v2,...` axes (plus `--set` overrides common to every
arm) concurrently over one shared host build — see replay/sweep.py.
Default output is the JSON summary; `--markdown` prints the grid-study
table instead.
"""

from __future__ import annotations

import argparse
import json
import sys

from spark_scheduler_tpu.replay.engine import replay_trace, what_if
from spark_scheduler_tpu.replay.generators import GENERATORS, generate
from spark_scheduler_tpu.replay.trace import TraceReader, config_hash


def _parse_sets(pairs: list[str]) -> dict:
    out = {}
    for p in pairs:
        key, sep, raw = p.partition("=")
        if not sep:
            raise SystemExit(f"--set expects field=value, got {p!r}")
        try:
            out[key] = json.loads(raw)
        except ValueError:
            out[key] = raw
    return out


def _parse_grid(pairs: list[str]) -> dict:
    """`--grid field=v1,v2,...` -> {field: [v1, v2, ...]} with each value
    JSON-parsed (falling back to raw string, same as `--set`)."""
    grid: dict = {}
    for p in pairs:
        key, sep, raw = p.partition("=")
        if not sep or not raw:
            raise SystemExit(f"--grid expects field=v1,v2,..., got {p!r}")
        vals = []
        for v in raw.split(","):
            try:
                vals.append(json.loads(v))
            except ValueError:
                vals.append(v)
        grid[key] = vals
    return grid


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m spark_scheduler_tpu.replay")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("info", help="print a trace's header + event census")
    p.add_argument("trace")

    p = sub.add_parser("verify", help="replay; report decision divergence")
    p.add_argument("trace")
    p.add_argument("--strict", action="store_true",
                   help="raise on first summary of mismatches")

    p = sub.add_parser("whatif", help="replay base vs overridden config")
    p.add_argument("trace")
    p.add_argument("--set", dest="sets", action="append", default=[],
                   metavar="FIELD=VALUE", required=True)

    p = sub.add_parser("sweep", help="replay one trace under a config grid")
    p.add_argument("trace")
    p.add_argument("--grid", dest="grid", action="append", default=[],
                   metavar="FIELD=V1,V2,...",
                   help="grid axis; repeat for a cartesian product")
    p.add_argument("--set", dest="sets", action="append", default=[],
                   metavar="FIELD=VALUE",
                   help="override applied to every arm")
    p.add_argument("--no-accel", action="store_true",
                   help="disable certified top-K prune acceleration")
    p.add_argument("--markdown", action="store_true",
                   help="print the grid-study markdown table, not JSON")

    p = sub.add_parser("generate", help="emit a synthetic workload trace")
    p.add_argument("kind", choices=sorted(GENERATORS))
    p.add_argument("out")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--nodes", type=int, default=None)
    p.add_argument("--bursts", type=int, default=None,
                   help="burst count (bursty kind only)")
    p.add_argument("--binpack-algo", default=None)

    p = sub.add_parser("run", help="replay with binding; re-capture output")
    p.add_argument("trace")
    p.add_argument("out")

    args = ap.parse_args(argv)

    if args.cmd == "info":
        r = TraceReader(args.trace)
        census: dict[str, int] = {}
        for ev in r.events():
            k = ev.get("k", "?")
            census[k] = census.get(k, 0) + 1
        print(json.dumps({
            "version": r.header.get("v"),
            "source": r.header.get("source"),
            "config_hash": config_hash(r.header["config"]),
            "meta": r.header.get("meta"),
            "events": census,
            "torn_tail": r.torn_tail,
            "malformed": r.malformed,
        }, indent=2, sort_keys=True))
        return 0

    if args.cmd == "verify":
        rep = replay_trace(args.trace, strict=args.strict)
        print(json.dumps(rep.summary(), indent=2, sort_keys=True))
        if rep.mismatches:
            for m in rep.mismatches[:10]:
                print(f"MISMATCH {m}", file=sys.stderr)
            return 1
        return 0

    if args.cmd == "whatif":
        print(json.dumps(what_if(args.trace, _parse_sets(args.sets)),
                         indent=2, sort_keys=True))
        return 0

    if args.cmd == "sweep":
        from spark_scheduler_tpu.replay.sweep import grid_arms, run_sweep

        grid = _parse_grid(args.grid)
        base = _parse_sets(args.sets)
        arms = grid_arms(grid, base) if grid else [base]
        sw = run_sweep(args.trace, arms, accelerate=not args.no_accel)
        if args.markdown:
            print(sw.markdown())
        else:
            print(json.dumps(sw.summary(), indent=2, sort_keys=True))
        return 0

    if args.cmd == "generate":
        sizing = {}
        if args.nodes is not None:
            sizing["n_nodes"] = args.nodes
        if args.bursts is not None:
            sizing["bursts"] = args.bursts
        if args.binpack_algo is not None:
            sizing["binpack_algo"] = args.binpack_algo
        stats = generate(args.kind, args.out, args.seed, **sizing)
        print(json.dumps(stats, indent=2, sort_keys=True))
        return 0

    if args.cmd == "run":
        rep = replay_trace(args.trace, record_path=args.out)
        print(json.dumps(rep.summary(), indent=2, sort_keys=True))
        return 0

    return 2


if __name__ == "__main__":
    sys.exit(main())
