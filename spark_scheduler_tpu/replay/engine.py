"""Deterministic trace replay + what-if simulation (ISSUE 17, parts b+c;
lane-factored for the multi-arm sweep driver in ISSUE 18).

`replay_trace` boots a backend-free harness — an InMemoryBackend plus the
full real scheduler from `build_scheduler_app`, under the trace header's
recorded config — and re-drives the extender event-for-event:

  * node/pod events apply to the backend (tolerantly: an `add` of an
    existing object becomes an update, a `delete` of a missing one is
    skipped — mid-life traces bootstrap-journal the world they attached
    to, so the stream is self-contained either way);
  * `predicate` events dispatch serving windows through the SAME two-phase
    API the live serving loop used (`predicate_window_dispatch` /
    `predicate_window_complete`), completing each window at its recorded
    `result` event — so backend events that landed between a window's
    dispatch and its completion replay in the exact pipelined
    interleaving, epoch bumps and in-flight dedup included;
  * recorded `result` rows are compared against the replayed verdict /
    placement / normalized failure map — any divergence is a
    ReplayMismatch (strict mode raises).

The clock is the trace's: every event's recorded wall time drives a
monotonic-max ReplayClock the whole app reads, so age thresholds and the
resync-gap heuristic see what the live run saw.

The per-arm machinery lives in `ReplayLane`: one lane is one replayed
scheduler app plus its event-step state (roster mirror, pending windows,
placements). `replay_trace` drives a single lane event-by-event; the
sweep driver (replay/sweep.py) drives M lanes in LOCKSTEP over one shared
decoded stream — which is why the predicate step is split into a
dispatch phase (`predicate_begin`) and a completion phase
(`predicate_finish`): the sweep dispatches every arm's window first, so
the coordinator can solve all arms as one stacked device dispatch, then
completes them. Driving the two phases back-to-back is exactly the
sequential replay.

Per-window latencies subtract XLA compile time (measured via the
process-wide jax.monitoring listener, observability/telemetry.py) and
book it separately as `replay_compile_ms` — so a cold bucket's
multi-second compile stops polluting the p99 of a study's latency
quantiles (ISSUE 18 satellite; the 145 ms p99 vs 1.71 ms p50 tail in
the original what-if study was compile, not solve).

What-if (`what_if`) replays the same trace under the recorded config and
under overrides — since ISSUE 18 as a thin 2-arm sweep — and diffs the
two runs: placement changes, per-arm p50/p99 decision latency, denial
counts, and final-state utilization/fragmentation. Bind events are
re-pointed at the replaying arm's OWN placements (a pod the variant
placed on node Y binds to Y, not the recorded X), so each arm's world
stays self-consistent.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from spark_scheduler_tpu.replay.trace import (
    ALL_NODES,
    TraceReader,
    config_from_fingerprint,
    config_hash,
    encode_result,
)

# Config fields every replay pins regardless of what the trace recorded:
# the harness is backend-free (no kube ingestion, no WAL, no HA group, no
# background loops) and must not re-write the trace it is reading.
FORCED_FIELDS = dict(
    sync_writes=True,
    kube_api_url=None,
    conversion_webhook_url=None,
    durable_store_path=None,
    runtime_config_path=None,
    metrics_log=None,
    jax_compilation_cache_dir=None,
    cert_file=None,
    key_file=None,
    ha_enabled=False,
    autoscaler_enabled=False,
    debug_routes=False,
    trace_path=None,
)


def _compile_seconds() -> float:
    from spark_scheduler_tpu.observability.telemetry import compile_stats

    return compile_stats()["seconds"]


class ReplayClock:
    """Monotonic-max clock fed by event timestamps: the whole replayed app
    reads the wall time the LIVE run saw at this point of the stream."""

    def __init__(self, t0: float = 0.0):
        self._t = t0

    def __call__(self) -> float:
        return self._t

    def set(self, t) -> None:
        if isinstance(t, (int, float)) and t > self._t:
            self._t = float(t)


class ReplayMismatchError(AssertionError):
    pass


@dataclasses.dataclass
class ReplayReport:
    """One replay arm's outcome."""

    config_hash: str = ""
    events: int = 0
    decisions: int = 0
    compared: int = 0
    mismatches: list = dataclasses.field(default_factory=list)
    uncompared_windows: int = 0
    verdict_counts: dict = dataclasses.field(default_factory=dict)
    denials: int = 0
    # (namespace, pod_name) -> node, for every placed decision
    placements: dict = dataclasses.field(default_factory=dict)
    latencies_ms: list = dataclasses.field(default_factory=list)
    torn_tail: bool = False
    malformed: int = 0
    utilization: dict = dataclasses.field(default_factory=dict)
    fragmentation: dict = dataclasses.field(default_factory=dict)
    overcommit: int = 0
    # XLA compile wall time booked during this arm's windows, kept OUT of
    # latencies_ms (a cold padding bucket's compile is a one-time process
    # cost, not a decision latency).
    replay_compile_ms: float = 0.0

    def latency_ms(self, q: float) -> Optional[float]:
        if not self.latencies_ms:
            return None
        xs = sorted(self.latencies_ms)
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 3)

    def summary(self) -> dict:
        return {
            "config_hash": self.config_hash,
            "events": self.events,
            "decisions": self.decisions,
            "compared": self.compared,
            "mismatches": len(self.mismatches),
            "uncompared_windows": self.uncompared_windows,
            "verdicts": dict(self.verdict_counts),
            "denials": self.denials,
            "latency_p50_ms": self.latency_ms(0.50),
            "latency_p99_ms": self.latency_ms(0.99),
            "replay_compile_ms": round(self.replay_compile_ms, 3),
            "utilization": self.utilization,
            "fragmentation": self.fragmentation,
            "overcommit": self.overcommit,
            "torn_tail": self.torn_tail,
            "malformed": self.malformed,
        }

    def decision_summary(self) -> dict:
        """The deterministic subset of `summary()` — everything that is a
        DECISION, nothing that is a wall-clock measurement. Two replays of
        the same trace under the same config produce identical
        decision_summary() dicts (the sweep-determinism pin)."""
        s = self.summary()
        for k in ("latency_p50_ms", "latency_p99_ms", "replay_compile_ms"):
            s.pop(k)
        return s


class _Pending:
    """A dispatched-but-uncompleted replay window."""

    __slots__ = ("wid", "ticket", "candidates", "bind", "t0")

    def __init__(self, wid, ticket, candidates, bind, t0):
        self.wid = wid
        self.ticket = ticket
        self.candidates = candidates
        self.bind = bind
        self.t0 = t0


class ReplayLane:
    """One replay arm: a full backend-free scheduler app plus the
    event-step state that drives it.

    The event loop is factored into per-kind step methods so a caller can
    interleave MULTIPLE lanes over one decoded stream (the sweep driver).
    `predicate` is two phases — `predicate_begin` dispatches the window
    (and, for solo-mode events, completes it too: solo predicates never
    pipeline), `predicate_finish` completes immediate-bind windows or
    parks the pending ticket. A sequential caller runs them back-to-back;
    the lockstep sweep runs every lane's begin, flushes the stacked
    cross-arm solve, then every lane's finish.
    """

    def __init__(
        self,
        header: dict,
        config,
        *,
        compare: bool,
        has_result_events: bool,
        record_path: Optional[str] = None,
        candidate_memo: Optional[dict] = None,
    ):
        from spark_scheduler_tpu.server.app import build_scheduler_app
        from spark_scheduler_tpu.store.backend import (
            DEMAND_CRD,
            InMemoryBackend,
        )

        self.compare = compare
        self.has_result_events = has_result_events
        self.record_path = record_path
        self.report = ReplayReport(config_hash=config_hash(header["config"]))
        self.backend = InMemoryBackend()
        self.backend.register_crd(DEMAND_CRD)
        self.clock = ReplayClock(float(header.get("t") or 0.0))
        self.app = build_scheduler_app(self.backend, config, clock=self.clock)
        self.ext = self.app.extender
        if candidate_memo is not None:
            # Sweep mode: cross-lane candidate-mask memo (registry state is
            # arm-invariant, so lane 2..M reuse lane 1's mask builds).
            self.app.solver._sweep_shared = candidate_memo
        meta = header.get("meta") or {}
        if meta.get("resync_suppressed"):
            self.ext._last_request = float("inf")
            # carry the suppression into a re-capture trace (its header is
            # written by build_scheduler_app, which doesn't know this meta)
            if self.app.trace_writer is not None:
                self.app.trace_writer.emit_meta(resync_suppressed=True)

        self.roster: list[str] = []  # mirror of the WRITER's roster, for "*"
        self.pending: list[_Pending] = []
        self.parked: dict[int, tuple] = {}  # wid -> (results, candidates, ms)
        self.placed: dict[tuple, str] = {}

    # ------------------------------------------------------------- steps

    def begin_event(self, ev: dict) -> None:
        self.report.events += 1
        self.clock.set(ev.get("t"))

    def expand(self, names) -> list[str]:
        return list(self.roster) if names == ALL_NODES else list(names)

    def _note_results(self, p: _Pending, results, ms: float) -> None:
        report, backend = self.report, self.backend
        per_decision = ms / max(1, len(results))
        for args, res in zip(p.ticket.args_list, results):
            report.decisions += 1
            report.latencies_ms.append(per_decision)
            report.verdict_counts[res.outcome] = (
                report.verdict_counts.get(res.outcome, 0) + 1
            )
            if res.outcome.startswith("failure"):
                report.denials += 1
            key = (args.pod.namespace, args.pod.name)
            if res.node_names:
                self.placed[key] = res.node_names[0]
                report.placements[key] = res.node_names[0]
            if p.bind and res.node_names:
                cur = backend.get("pods", args.pod.namespace, args.pod.name)
                if cur is not None and not cur.node_name:
                    backend.bind_pod(cur, res.node_names[0])

    def _timed(self, fn):
        """Run `fn`, returning (result, seconds) with XLA compile wall time
        subtracted from the measurement and booked to replay_compile_ms."""
        c0 = _compile_seconds()
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        dc = _compile_seconds() - c0
        if dc > 0.0:
            self.report.replay_compile_ms += dc * 1e3
            dt = max(0.0, dt - dc)
        return out, dt

    def _force_complete(self, p: _Pending) -> None:
        results, secs = self._timed(
            lambda: self.ext.predicate_window_complete(p.ticket)
        )
        ms = (secs + p.t0) * 1e3
        self._note_results(p, results, ms)
        self.parked[p.wid] = (results, p.candidates, ms)

    def predicate_begin(self, ev: dict, candidates=None) -> Optional[_Pending]:
        """Dispatch one predicate event's window. Returns the pending
        window for `predicate_finish`, or None when the event completed
        entirely in this phase (solo mode). `candidates` lets the sweep
        driver pass pre-expanded per-request candidate lists (shared
        across lanes); None expands from this lane's own roster mirror."""
        from spark_scheduler_tpu.core.extender import ExtenderArgs
        from spark_scheduler_tpu.core.solver import PipelineDrainRequired
        from spark_scheduler_tpu.server.kube_io import pod_from_k8s

        wid = ev["w"]
        if candidates is None:
            candidates = [self.expand(r["nodes"]) for r in ev["reqs"]]
        backend = self.backend

        def resolve(r):
            if "ref" in r:
                ns, name = r["ref"]
                pod = backend.get("pods", ns, name)
                if pod is None:
                    raise AssertionError(
                        f"trace ref to unknown pod {ns}/{name}"
                    )
                return pod
            return pod_from_k8s(r["pod"])

        args_list = [
            ExtenderArgs(pod=resolve(r), node_names=c)
            for r, c in zip(ev["reqs"], candidates)
        ]
        bind = bool(ev.get("bind"))
        if ev.get("mode") == "solo":
            (res, secs) = self._timed(lambda: self.ext.predicate(args_list[0]))
            ms = secs * 1e3
            p = _Pending(wid, None, candidates, bind, 0.0)
            p.ticket = type("T", (), {"args_list": args_list})()
            self._note_results(p, [res], ms)
            self.parked[wid] = ([res], candidates, ms)
            return None

        def dispatch_once():
            for _ in range(4):
                try:
                    return self.ext.predicate_window_dispatch(args_list)
                except PipelineDrainRequired:
                    # The live loop drained and retried here too; its
                    # drained results are already behind us in the stream
                    # (journaled before this predicate event), so the
                    # pending list SHOULD be empty — but mirror the
                    # contract defensively.
                    if not self.pending:
                        raise
                    self._force_complete(self.pending.pop(0))
            raise AssertionError(
                "dispatch kept raising PipelineDrainRequired"
            )

        ticket, secs = self._timed(dispatch_once)
        return _Pending(wid, ticket, candidates, bind, secs)

    def predicate_finish(self, p: Optional[_Pending]) -> None:
        if p is None:
            return
        if p.bind and not self.has_result_events:
            # Input-only (generated) trace: no result event will arrive —
            # complete immediately so binds land before the next event.
            results, secs = self._timed(
                lambda: self.ext.predicate_window_complete(p.ticket)
            )
            self._note_results(p, results, (p.t0 + secs) * 1e3)
        else:
            self.pending.append(p)

    def result(self, ev: dict) -> None:
        wid = ev["w"]
        if wid in self.parked:
            results, candidates, ms = self.parked.pop(wid)
        else:
            # Completions are FIFO: anything older than this wid in the
            # pipeline completes (parking its results) first.
            while self.pending and self.pending[0].wid != wid:
                self._force_complete(self.pending.pop(0))
            if not self.pending:
                return  # result for a window we never saw dispatch
            p = self.pending.pop(0)
            results, secs = self._timed(
                lambda: self.ext.predicate_window_complete(p.ticket)
            )
            ms = (secs + p.t0) * 1e3
            self._note_results(p, results, ms)
            candidates = p.candidates
        if self.compare:
            report = self.report
            for i, (res, rec) in enumerate(zip(results, ev["res"])):
                got = encode_result(res, candidates[i])
                if got != rec:
                    report.mismatches.append(
                        {
                            "window": wid,
                            "index": i,
                            "recorded": rec,
                            "replayed": got,
                        }
                    )
            report.compared += len(ev["res"])

    def apply(self, ev: dict) -> None:
        """Every non-predicate, non-result event kind."""
        from spark_scheduler_tpu.server.kube_io import node_from_k8s, pod_from_k8s

        app, backend = self.app, self.backend
        k = ev.get("k")
        if k == "node":
            op = ev["op"]
            if op == "delete":
                name = ev["name"]
                if name in self.roster:
                    self.roster.remove(name)
                if backend.get("nodes", "", name) is not None:
                    backend.delete("nodes", "", name)
            else:
                node = node_from_k8s(ev["node"])
                if op == "add" and node.name not in self.roster:
                    self.roster.append(node.name)
                if backend.get("nodes", "", node.name) is None:
                    backend.add_node(node)
                else:
                    backend.update("nodes", node)
        elif k == "pod":
            op = ev["op"]
            if op == "delete":
                if backend.get("pods", ev["ns"], ev["name"]) is not None:
                    backend.delete("pods", ev["ns"], ev["name"])
            else:
                pod = pod_from_k8s(ev["pod"])
                if pod.node_name:
                    # Re-point binds at THIS arm's placement so the world
                    # stays self-consistent under what-if configs (under
                    # the recorded config the two coincide bit-for-bit).
                    own = self.placed.get((pod.namespace, pod.name))
                    if own is not None and own != pod.node_name:
                        pod = dataclasses.replace(pod, node_name=own)
                if backend.get("pods", pod.namespace, pod.name) is None:
                    backend.add_pod(pod)
                else:
                    backend.update_pod(pod)
        elif k == "rr":
            from spark_scheduler_tpu.store.durable import _rr_from_record

            rr = _rr_from_record(ev["rr"])
            if app.rr_cache.get(rr.namespace, rr.name) is None:
                app.rr_cache.create(rr)
        elif k == "rr_delete":
            if app.rr_cache.get(ev["ns"], ev["name"]) is not None:
                app.rr_cache.delete(ev["ns"], ev["name"])
            # Directives are INPUTS the backend subscriptions can't see
            # (the writer only watches nodes/pods — scheduler-originated
            # RR writes must stay un-journaled). Forward them into a
            # re-capture trace by hand or its verify run would drift.
            if app.trace_writer is not None:
                app.trace_writer.emit_rr_delete(ev["ns"], ev["name"])
        elif k == "reconcile":
            app.reconciler.sync_resource_reservations_and_demands()
            if app.trace_writer is not None:
                app.trace_writer.emit_reconcile()
        elif k == "meta":
            if ev.get("resync_suppressed"):
                self.ext._last_request = float("inf")
            if app.trace_writer is not None:
                app.trace_writer.emit_meta(
                    **{a: b for a, b in ev.items() if a not in ("k", "s", "t")}
                )
        # decision events are informational (the recorder's own records
        # ride the replayed app's recorder) — skipped.

    def drain(self) -> None:
        while self.pending:
            self.report.uncompared_windows += 1
            self._force_complete(self.pending.pop(0))

    def finish(self, reader: TraceReader) -> ReplayReport:
        self.report.torn_tail = reader.torn_tail
        self.report.malformed = reader.malformed
        _final_state_metrics(self.app, self.backend, self.report)
        if self.record_path and self.app.trace_writer is not None:
            self.app.trace_writer.close()
        self.app.solver.close()
        return self.report


def replay_trace(
    trace_path: str,
    overrides: Optional[dict] = None,
    strict: bool = False,
    record_path: Optional[str] = None,
    progress=None,
) -> ReplayReport:
    """Re-drive one trace. `overrides` switches the run into what-if
    territory (an altered config — recorded results are then informational
    and comparison is skipped); `record_path` re-captures the replay
    through the normal TraceWriter wiring, which is how generated
    input-only traces become full captured traces (`run` mode)."""
    reader = TraceReader(trace_path)
    config = config_from_fingerprint(
        reader.header["config"],
        overrides=overrides,
        forced={**FORCED_FIELDS, "trace_path": record_path},
    )
    # Input-only traces (generators) carry bind-predicates and no result
    # events; captured traces carry result events (and re-captured "run"
    # traces both). Sniff which shape this stream is once, up front.
    events = list(reader.events())
    has_results = any(ev.get("k") == "result" for ev in events)
    lane = ReplayLane(
        reader.header,
        config,
        compare=not overrides,
        has_result_events=has_results,
        record_path=record_path,
    )
    for ev in events:
        lane.begin_event(ev)
        if progress is not None and lane.report.events % 5000 == 0:
            progress(lane.report.events)
        k = ev.get("k")
        if k == "predicate":
            lane.predicate_finish(lane.predicate_begin(ev))
        elif k == "result":
            lane.result(ev)
        else:
            lane.apply(ev)
    lane.drain()
    report = lane.finish(reader)
    if strict and report.mismatches:
        raise ReplayMismatchError(
            f"{len(report.mismatches)} replay mismatches "
            f"(of {report.compared} compared decisions); first: "
            f"{report.mismatches[0]}"
        )
    return report


def _final_state_metrics(app, backend, report: ReplayReport) -> None:
    """End-of-trace cluster posture: reserved utilization, stranded free
    capacity on partially-used nodes (the fragmentation proxy a binpack
    strategy moves), and the over-commit invariant."""
    from spark_scheduler_tpu.testing.harness import overcommit_violations

    nodes = backend.list_nodes()
    if not nodes:
        return
    usage = app.reservation_manager.get_reserved_resources()
    total = {"cpu": 0.0, "memory": 0.0}
    used = {"cpu": 0.0, "memory": 0.0}
    stranded = {"cpu": 0.0, "memory": 0.0}
    for n in nodes:
        total["cpu"] += n.allocatable.cpu_milli
        total["memory"] += n.allocatable.mem_kib
        u = usage.get(n.name)
        if u is None:
            continue
        used["cpu"] += u.cpu_milli
        used["memory"] += u.mem_kib
        if u.cpu_milli > 0 or u.mem_kib > 0:
            stranded["cpu"] += max(0, n.allocatable.cpu_milli - u.cpu_milli)
            stranded["memory"] += max(0, n.allocatable.mem_kib - u.mem_kib)
    report.utilization = {
        r: round(used[r] / total[r], 4) if total[r] else 0.0 for r in total
    }
    report.fragmentation = {
        r: round(stranded[r] / total[r], 4) if total[r] else 0.0
        for r in total
    }
    try:
        report.overcommit = len(overcommit_violations(app, backend))
    except Exception:
        report.overcommit = -1


# ----------------------------------------------------------------- what-if


def what_if(trace_path: str, overrides: dict) -> dict:
    """Replay under the recorded config AND under `overrides`; emit the
    structured diff report (ISSUE 17 part c). Since ISSUE 18 this is a
    thin 2-arm wrapper over the sweep driver — the base arm replays once
    and both arms share the decoded stream, roster build, and candidate
    masks — with the output schema unchanged. The base arm's mismatch
    count doubles as the report's confidence check: a non-zero base
    mismatch means the trace itself doesn't replay cleanly and every
    delta should be read with suspicion."""
    from spark_scheduler_tpu.replay.sweep import run_sweep

    sweep = run_sweep(trace_path, [{}, dict(overrides)])
    base, variant = sweep.reports
    return _whatif_diff(trace_path, overrides, base, variant)


def _whatif_diff(
    trace_path: str,
    overrides: dict,
    base: ReplayReport,
    variant: ReplayReport,
) -> dict:
    same = changed = 0
    moves = []
    for key, node in base.placements.items():
        v = variant.placements.get(key)
        if v is None:
            continue
        if v == node:
            same += 1
        else:
            changed += 1
            if len(moves) < 50:
                moves.append(
                    {"pod": f"{key[0]}/{key[1]}", "base": node, "variant": v}
                )
    only_base = sum(
        1 for k in base.placements if k not in variant.placements
    )
    only_variant = sum(
        1 for k in variant.placements if k not in base.placements
    )

    def delta(a, b):
        if a is None or b is None:
            return None
        return round(b - a, 4)

    return {
        "trace": trace_path,
        "overrides": dict(overrides),
        "base_config_hash": base.config_hash,
        "base_mismatches": len(base.mismatches),
        "decisions": {"base": base.decisions, "variant": variant.decisions},
        "verdicts": {
            "base": dict(base.verdict_counts),
            "variant": dict(variant.verdict_counts),
        },
        "denials": {
            "base": base.denials,
            "variant": variant.denials,
            "delta": variant.denials - base.denials,
        },
        "placements": {
            "same": same,
            "changed": changed,
            "only_base": only_base,
            "only_variant": only_variant,
            "moves_sample": moves,
        },
        "latency_ms": {
            "base": {"p50": base.latency_ms(0.5), "p99": base.latency_ms(0.99)},
            "variant": {
                "p50": variant.latency_ms(0.5),
                "p99": variant.latency_ms(0.99),
            },
            "p50_delta": delta(base.latency_ms(0.5), variant.latency_ms(0.5)),
            "p99_delta": delta(base.latency_ms(0.99), variant.latency_ms(0.99)),
        },
        "utilization": {
            "base": base.utilization,
            "variant": variant.utilization,
            "cpu_delta": delta(
                base.utilization.get("cpu"), variant.utilization.get("cpu")
            ),
        },
        "fragmentation": {
            "base": base.fragmentation,
            "variant": variant.fragmentation,
            "cpu_delta": delta(
                base.fragmentation.get("cpu"),
                variant.fragmentation.get("cpu"),
            ),
        },
        "overcommit": {"base": base.overcommit, "variant": variant.overcommit},
    }
