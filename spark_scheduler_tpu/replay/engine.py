"""Deterministic trace replay + what-if simulation (ISSUE 17, parts b+c).

`replay_trace` boots a backend-free harness — an InMemoryBackend plus the
full real scheduler from `build_scheduler_app`, under the trace header's
recorded config — and re-drives the extender event-for-event:

  * node/pod events apply to the backend (tolerantly: an `add` of an
    existing object becomes an update, a `delete` of a missing one is
    skipped — mid-life traces bootstrap-journal the world they attached
    to, so the stream is self-contained either way);
  * `predicate` events dispatch serving windows through the SAME two-phase
    API the live serving loop used (`predicate_window_dispatch` /
    `predicate_window_complete`), completing each window at its recorded
    `result` event — so backend events that landed between a window's
    dispatch and its completion replay in the exact pipelined
    interleaving, epoch bumps and in-flight dedup included;
  * recorded `result` rows are compared against the replayed verdict /
    placement / normalized failure map — any divergence is a
    ReplayMismatch (strict mode raises).

The clock is the trace's: every event's recorded wall time drives a
monotonic-max ReplayClock the whole app reads, so age thresholds and the
resync-gap heuristic see what the live run saw.

What-if (`what_if`) replays the same trace twice — once under the
recorded config, once under overrides — and diffs the two runs:
placement changes, per-arm p50/p99 decision latency (both re-measured
in-process, so the comparison is apples-to-apples), denial counts, and
final-state utilization/fragmentation. Bind events are re-pointed at the
replaying arm's OWN placements (a pod the variant placed on node Y binds
to Y, not the recorded X), so each arm's world stays self-consistent.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from spark_scheduler_tpu.replay.trace import (
    ALL_NODES,
    TraceReader,
    config_from_fingerprint,
    config_hash,
    encode_result,
)

# Config fields every replay pins regardless of what the trace recorded:
# the harness is backend-free (no kube ingestion, no WAL, no HA group, no
# background loops) and must not re-write the trace it is reading.
FORCED_FIELDS = dict(
    sync_writes=True,
    kube_api_url=None,
    conversion_webhook_url=None,
    durable_store_path=None,
    runtime_config_path=None,
    metrics_log=None,
    jax_compilation_cache_dir=None,
    cert_file=None,
    key_file=None,
    ha_enabled=False,
    autoscaler_enabled=False,
    debug_routes=False,
    trace_path=None,
)


class ReplayClock:
    """Monotonic-max clock fed by event timestamps: the whole replayed app
    reads the wall time the LIVE run saw at this point of the stream."""

    def __init__(self, t0: float = 0.0):
        self._t = t0

    def __call__(self) -> float:
        return self._t

    def set(self, t) -> None:
        if isinstance(t, (int, float)) and t > self._t:
            self._t = float(t)


class ReplayMismatchError(AssertionError):
    pass


@dataclasses.dataclass
class ReplayReport:
    """One replay arm's outcome."""

    config_hash: str = ""
    events: int = 0
    decisions: int = 0
    compared: int = 0
    mismatches: list = dataclasses.field(default_factory=list)
    uncompared_windows: int = 0
    verdict_counts: dict = dataclasses.field(default_factory=dict)
    denials: int = 0
    # (namespace, pod_name) -> node, for every placed decision
    placements: dict = dataclasses.field(default_factory=dict)
    latencies_ms: list = dataclasses.field(default_factory=list)
    torn_tail: bool = False
    malformed: int = 0
    utilization: dict = dataclasses.field(default_factory=dict)
    fragmentation: dict = dataclasses.field(default_factory=dict)
    overcommit: int = 0

    def latency_ms(self, q: float) -> Optional[float]:
        if not self.latencies_ms:
            return None
        xs = sorted(self.latencies_ms)
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 3)

    def summary(self) -> dict:
        return {
            "config_hash": self.config_hash,
            "events": self.events,
            "decisions": self.decisions,
            "compared": self.compared,
            "mismatches": len(self.mismatches),
            "uncompared_windows": self.uncompared_windows,
            "verdicts": dict(self.verdict_counts),
            "denials": self.denials,
            "latency_p50_ms": self.latency_ms(0.50),
            "latency_p99_ms": self.latency_ms(0.99),
            "utilization": self.utilization,
            "fragmentation": self.fragmentation,
            "overcommit": self.overcommit,
            "torn_tail": self.torn_tail,
            "malformed": self.malformed,
        }


class _Pending:
    """A dispatched-but-uncompleted replay window."""

    __slots__ = ("wid", "ticket", "candidates", "bind", "t0")

    def __init__(self, wid, ticket, candidates, bind, t0):
        self.wid = wid
        self.ticket = ticket
        self.candidates = candidates
        self.bind = bind
        self.t0 = t0


def replay_trace(
    trace_path: str,
    overrides: Optional[dict] = None,
    strict: bool = False,
    record_path: Optional[str] = None,
    progress=None,
) -> ReplayReport:
    """Re-drive one trace. `overrides` switches the run into what-if
    territory (an altered config — recorded results are then informational
    and comparison is skipped); `record_path` re-captures the replay
    through the normal TraceWriter wiring, which is how generated
    input-only traces become full captured traces (`run` mode)."""
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.core.solver import PipelineDrainRequired
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.kube_io import node_from_k8s, pod_from_k8s
    from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend

    reader = TraceReader(trace_path)
    header = reader.header
    compare = not overrides
    config = config_from_fingerprint(
        header["config"],
        overrides=overrides,
        forced={**FORCED_FIELDS, "trace_path": record_path},
    )
    report = ReplayReport(config_hash=config_hash(header["config"]))

    backend = InMemoryBackend()
    backend.register_crd(DEMAND_CRD)
    clock = ReplayClock(float(header.get("t") or 0.0))
    app = build_scheduler_app(backend, config, clock=clock)
    ext = app.extender
    meta = header.get("meta") or {}
    if meta.get("resync_suppressed"):
        ext._last_request = float("inf")
        # carry the suppression into a re-capture trace (its header is
        # written by build_scheduler_app, which doesn't know this meta)
        if app.trace_writer is not None:
            app.trace_writer.emit_meta(resync_suppressed=True)

    roster: list[str] = []  # mirror of the WRITER's roster, for "*"
    pending: list[_Pending] = []
    parked: dict[int, tuple] = {}  # wid -> (results, candidates, ms)
    placed: dict[tuple, str] = {}

    def expand(names) -> list[str]:
        return list(roster) if names == ALL_NODES else list(names)

    def note_results(p: _Pending, results, ms: float) -> None:
        per_decision = ms / max(1, len(results))
        for args, res in zip(p.ticket.args_list, results):
            report.decisions += 1
            report.latencies_ms.append(per_decision)
            report.verdict_counts[res.outcome] = (
                report.verdict_counts.get(res.outcome, 0) + 1
            )
            if res.outcome.startswith("failure"):
                report.denials += 1
            key = (args.pod.namespace, args.pod.name)
            if res.node_names:
                placed[key] = res.node_names[0]
                report.placements[key] = res.node_names[0]
            if p.bind and res.node_names:
                cur = backend.get("pods", args.pod.namespace, args.pod.name)
                if cur is not None and not cur.node_name:
                    backend.bind_pod(cur, res.node_names[0])

    def force_complete(p: _Pending) -> None:
        t0 = time.perf_counter()
        results = ext.predicate_window_complete(p.ticket)
        ms = (time.perf_counter() - t0 + p.t0) * 1e3
        note_results(p, results, ms)
        parked[p.wid] = (results, p.candidates, ms)

    def dispatch(args_list, candidates, wid, bind) -> None:
        t0 = time.perf_counter()
        for _ in range(4):
            try:
                ticket = ext.predicate_window_dispatch(args_list)
                break
            except PipelineDrainRequired:
                # The live loop drained and retried here too; its drained
                # results are already behind us in the stream (journaled
                # before this predicate event), so the pending list SHOULD
                # be empty — but mirror the contract defensively.
                if not pending:
                    raise
                force_complete(pending.pop(0))
        else:
            raise AssertionError("dispatch kept raising PipelineDrainRequired")
        p = _Pending(wid, ticket, candidates, bind, time.perf_counter() - t0)
        if bind and "result" not in bind_modes:
            # Input-only (generated) trace: no result event will arrive —
            # complete immediately so binds land before the next event.
            results = ext.predicate_window_complete(p.ticket)
            ms = (time.perf_counter() - t0) * 1e3
            note_results(p, results, ms)
        else:
            pending.append(p)

    # Input-only traces (generators) carry bind-predicates and no result
    # events; captured traces carry result events (and re-captured "run"
    # traces both). Sniff which shape this stream is once, up front.
    bind_modes: set = set()
    events = list(reader.events())
    for ev in events:
        if ev.get("k") == "result":
            bind_modes.add("result")
            break

    for ev in events:
        report.events += 1
        if progress is not None and report.events % 5000 == 0:
            progress(report.events)
        clock.set(ev.get("t"))
        k = ev.get("k")
        if k == "node":
            op = ev["op"]
            if op == "delete":
                name = ev["name"]
                if name in roster:
                    roster.remove(name)
                if backend.get("nodes", "", name) is not None:
                    backend.delete("nodes", "", name)
            else:
                node = node_from_k8s(ev["node"])
                if op == "add" and node.name not in roster:
                    roster.append(node.name)
                if backend.get("nodes", "", node.name) is None:
                    backend.add_node(node)
                else:
                    backend.update("nodes", node)
        elif k == "pod":
            op = ev["op"]
            if op == "delete":
                if backend.get("pods", ev["ns"], ev["name"]) is not None:
                    backend.delete("pods", ev["ns"], ev["name"])
            else:
                pod = pod_from_k8s(ev["pod"])
                if pod.node_name:
                    # Re-point binds at THIS arm's placement so the world
                    # stays self-consistent under what-if configs (under
                    # the recorded config the two coincide bit-for-bit).
                    own = placed.get((pod.namespace, pod.name))
                    if own is not None and own != pod.node_name:
                        pod = dataclasses.replace(pod, node_name=own)
                if backend.get("pods", pod.namespace, pod.name) is None:
                    backend.add_pod(pod)
                else:
                    backend.update_pod(pod)
        elif k == "rr":
            from spark_scheduler_tpu.store.durable import _rr_from_record

            rr = _rr_from_record(ev["rr"])
            if app.rr_cache.get(rr.namespace, rr.name) is None:
                app.rr_cache.create(rr)
        elif k == "rr_delete":
            if app.rr_cache.get(ev["ns"], ev["name"]) is not None:
                app.rr_cache.delete(ev["ns"], ev["name"])
            # Directives are INPUTS the backend subscriptions can't see
            # (the writer only watches nodes/pods — scheduler-originated
            # RR writes must stay un-journaled). Forward them into a
            # re-capture trace by hand or its verify run would drift.
            if app.trace_writer is not None:
                app.trace_writer.emit_rr_delete(ev["ns"], ev["name"])
        elif k == "reconcile":
            app.reconciler.sync_resource_reservations_and_demands()
            if app.trace_writer is not None:
                app.trace_writer.emit_reconcile()
        elif k == "meta":
            if ev.get("resync_suppressed"):
                ext._last_request = float("inf")
            if app.trace_writer is not None:
                app.trace_writer.emit_meta(
                    **{a: b for a, b in ev.items() if a not in ("k", "s", "t")}
                )
        elif k == "predicate":
            wid = ev["w"]
            candidates = [expand(r["nodes"]) for r in ev["reqs"]]

            def resolve(r):
                if "ref" in r:
                    ns, name = r["ref"]
                    pod = backend.get("pods", ns, name)
                    if pod is None:
                        raise AssertionError(
                            f"trace ref to unknown pod {ns}/{name}"
                        )
                    return pod
                return pod_from_k8s(r["pod"])

            args_list = [
                ExtenderArgs(pod=resolve(r), node_names=c)
                for r, c in zip(ev["reqs"], candidates)
            ]
            bind = bool(ev.get("bind"))
            if ev.get("mode") == "solo":
                t0 = time.perf_counter()
                res = ext.predicate(args_list[0])
                ms = (time.perf_counter() - t0) * 1e3
                p = _Pending(wid, None, candidates, bind, 0.0)
                p.ticket = type("T", (), {"args_list": args_list})()
                note_results(p, [res], ms)
                parked[wid] = ([res], candidates, ms)
            else:
                dispatch(args_list, candidates, wid, bind)
        elif k == "result":
            wid = ev["w"]
            if wid in parked:
                results, candidates, ms = parked.pop(wid)
            else:
                # Completions are FIFO: anything older than this wid in
                # the pipeline completes (parking its results) first.
                while pending and pending[0].wid != wid:
                    force_complete(pending.pop(0))
                if not pending:
                    continue  # result for a window we never saw dispatch
                p = pending.pop(0)
                t0 = time.perf_counter()
                results = ext.predicate_window_complete(p.ticket)
                ms = (time.perf_counter() - t0 + p.t0) * 1e3
                note_results(p, results, ms)
                candidates = p.candidates
            if compare:
                for i, (res, rec) in enumerate(zip(results, ev["res"])):
                    got = encode_result(res, candidates[i])
                    if got != rec:
                        report.mismatches.append(
                            {
                                "window": wid,
                                "index": i,
                                "recorded": rec,
                                "replayed": got,
                            }
                        )
                report.compared += len(ev["res"])
        # decision events are informational (the recorder's own records
        # ride the replayed app's recorder) — skipped.

    while pending:
        report.uncompared_windows += 1
        force_complete(pending.pop(0))

    report.torn_tail = reader.torn_tail
    report.malformed = reader.malformed
    _final_state_metrics(app, backend, report)
    if record_path and app.trace_writer is not None:
        app.trace_writer.close()
    app.solver.close()
    if strict and report.mismatches:
        raise ReplayMismatchError(
            f"{len(report.mismatches)} replay mismatches "
            f"(of {report.compared} compared decisions); first: "
            f"{report.mismatches[0]}"
        )
    return report


def _final_state_metrics(app, backend, report: ReplayReport) -> None:
    """End-of-trace cluster posture: reserved utilization, stranded free
    capacity on partially-used nodes (the fragmentation proxy a binpack
    strategy moves), and the over-commit invariant."""
    from spark_scheduler_tpu.testing.harness import overcommit_violations

    nodes = backend.list_nodes()
    if not nodes:
        return
    usage = app.reservation_manager.get_reserved_resources()
    total = {"cpu": 0.0, "memory": 0.0}
    used = {"cpu": 0.0, "memory": 0.0}
    stranded = {"cpu": 0.0, "memory": 0.0}
    for n in nodes:
        total["cpu"] += n.allocatable.cpu_milli
        total["memory"] += n.allocatable.mem_kib
        u = usage.get(n.name)
        if u is None:
            continue
        used["cpu"] += u.cpu_milli
        used["memory"] += u.mem_kib
        if u.cpu_milli > 0 or u.mem_kib > 0:
            stranded["cpu"] += max(0, n.allocatable.cpu_milli - u.cpu_milli)
            stranded["memory"] += max(0, n.allocatable.mem_kib - u.mem_kib)
    report.utilization = {
        r: round(used[r] / total[r], 4) if total[r] else 0.0 for r in total
    }
    report.fragmentation = {
        r: round(stranded[r] / total[r], 4) if total[r] else 0.0
        for r in total
    }
    try:
        report.overcommit = len(overcommit_violations(app, backend))
    except Exception:
        report.overcommit = -1


# ----------------------------------------------------------------- what-if


def what_if(trace_path: str, overrides: dict) -> dict:
    """Replay under the recorded config AND under `overrides`; emit the
    structured diff report (ISSUE 17 part c). The base arm's mismatch
    count doubles as the report's confidence check: a non-zero base
    mismatch means the trace itself doesn't replay cleanly and every
    delta should be read with suspicion."""
    base = replay_trace(trace_path)
    variant = replay_trace(trace_path, overrides=overrides)

    same = changed = 0
    moves = []
    for key, node in base.placements.items():
        v = variant.placements.get(key)
        if v is None:
            continue
        if v == node:
            same += 1
        else:
            changed += 1
            if len(moves) < 50:
                moves.append(
                    {"pod": f"{key[0]}/{key[1]}", "base": node, "variant": v}
                )
    only_base = sum(
        1 for k in base.placements if k not in variant.placements
    )
    only_variant = sum(
        1 for k in variant.placements if k not in base.placements
    )

    def delta(a, b):
        if a is None or b is None:
            return None
        return round(b - a, 4)

    return {
        "trace": trace_path,
        "overrides": dict(overrides),
        "base_config_hash": base.config_hash,
        "base_mismatches": len(base.mismatches),
        "decisions": {"base": base.decisions, "variant": variant.decisions},
        "verdicts": {
            "base": dict(base.verdict_counts),
            "variant": dict(variant.verdict_counts),
        },
        "denials": {
            "base": base.denials,
            "variant": variant.denials,
            "delta": variant.denials - base.denials,
        },
        "placements": {
            "same": same,
            "changed": changed,
            "only_base": only_base,
            "only_variant": only_variant,
            "moves_sample": moves,
        },
        "latency_ms": {
            "base": {"p50": base.latency_ms(0.5), "p99": base.latency_ms(0.99)},
            "variant": {
                "p50": variant.latency_ms(0.5),
                "p99": variant.latency_ms(0.99),
            },
            "p50_delta": delta(base.latency_ms(0.5), variant.latency_ms(0.5)),
            "p99_delta": delta(base.latency_ms(0.99), variant.latency_ms(0.99)),
        },
        "utilization": {
            "base": base.utilization,
            "variant": variant.utilization,
            "cpu_delta": delta(
                base.utilization.get("cpu"), variant.utilization.get("cpu")
            ),
        },
        "fragmentation": {
            "base": base.fragmentation,
            "variant": variant.fragmentation,
            "cpu_delta": delta(
                base.fragmentation.get("cpu"),
                variant.fragmentation.get("cpu"),
            ),
        },
        "overcommit": {"base": base.overcommit, "variant": variant.overcommit},
    }
