"""Batched multi-arm what-if: one trace, M config arms, lockstep replay
with stacked cross-arm window solves (ISSUE 18).

A sweep of M arms used to pay M full sequential `replay_trace()` runs of
the SAME input stream. But almost everything a replay does is
decision-independent: the event decode, the roster mirror, the registry
interning order, the statics tensors, and the candidate-mask tickets are
functions of the INPUT stream (node events are inputs, not decisions), so
they are arm-invariant. Only the availability carry — what each arm's
decisions subtracted — differs. `run_sweep` exploits exactly that split:

  * **Stream dedup.** Arms whose configs differ only in identity-pinned
    knobs (prune top-k/slack, delta statics, scale tier — every field the
    equivalence suites pin byte-identical) map to one decision STREAM:
    the trace replays once per stream, not once per arm, and each arm
    clones its stream's report.
  * **Lockstep lanes over one shared build.** Each stream is a
    `ReplayLane` (replay/engine.py) — a full, real scheduler app. All
    lanes step through ONE decoded event list; predicate candidates
    expand once from the driver's shared roster mirror (a digest-keyed
    list the candidate-mask LRU can key without hashing 10k names), and
    lanes share a cross-lane candidate-mask memo
    (`solver._sweep_shared`), so lane 2..S never re-walk the name->row
    map lane 1 already walked.
  * **Stacked window solves.** The predicate step is two-phase: every
    lane DISPATCHES its window (deferred — the solver's `_dispatch_lane`
    hook parks the built app batch + availability with this
    coordinator), then the coordinator flushes: payloads whose app
    batches and statics digest-match are stacked `[M, N, 3]` and solved
    as ONE arm-vmapped `batched_fifo_pack` dispatch
    (`ops/batched.arm_stacked_fifo_pack`) with ONE device_get for all
    arms' blobs. Strategy selection is NOT a `lax.switch` — under vmap
    every switch branch executes select-ized (measured 30x pathological
    on the 2-core CPU rig) — the kernel statically groups equal fills
    instead. Payloads that diverge (different window composition under
    different strategies, incompatible shapes) fall back to per-lane
    solves over the same shared host build: the `lane_fallbacks`
    counter.
  * **Certified pruning as sweep fuel.** Streams whose strategy is
    prune-eligible ride the two-tier top-K solve even when the arm
    itself didn't ask for it (`accelerate=True`): pruned decisions are
    certificate-verified at fetch with exact escalation, so they are
    byte-identical BY CONSTRUCTION — the sweep buys the [K,3] solve
    without touching the correctness bar. `accelerate=False` opts out.
  * **One jit cache, compile booked separately.** All arms share the
    process's jit cache (one compile per shape, not per arm); sweep
    lanes drop the row-bucket quantum to 8 (under vmap padding rows
    EXECUTE, so tight buckets are pure win), and every flush books XLA
    compile wall time to `replay_compile_ms` instead of the latency
    quantiles.

Correctness bar (pinned by tests/test_replay_sweep.py): every arm's
verdicts/placements are bit-identical to its own sequential
`replay_trace()` under the same config. The serving path never sees any
of this — `_dispatch_lane`/`_sweep_shared` are None outside this driver
(the fleet dispatch coordinator, fleet/dispatch.py, installs its own
lane on fleet serving solvers when stacking is enabled).

CLI: `python -m spark_scheduler_tpu.replay sweep TRACE
--grid binpack-algo=tightly-pack,distribute-evenly --set ... [--markdown]`.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import itertools
import time
from typing import Optional

import numpy as np

from spark_scheduler_tpu.replay.engine import (
    FORCED_FIELDS,
    ReplayLane,
    _compile_seconds,
)
from spark_scheduler_tpu.replay.trace import (
    ALL_NODES,
    TraceReader,
    config_from_fingerprint,
)

# Config fields that cannot move decisions: the repo's equivalence suites
# pin each of them byte-identical (prune: certificate-verified with exact
# escalation; delta statics / scale tier / lazy warm start: delta-vs-full
# and parity suites; the flight recorder only observes; device pool /
# mesh / fused dispatch: the multi-device parity suites — pooling moves
# WALL time, never bytes; autoscaler policy knobs: replay forces
# autoscaler_enabled=False (FORCED_FIELDS), so its tuning cannot reach a
# decision). Arms that differ ONLY in these share one decision stream —
# which is exactly what makes `grid_arms` sweeps over device-pool and
# autoscaler policy grids cheap: F x A arms, one decision stream.
IDENTITY_PINNED_FIELDS = frozenset(
    {
        "solver_prune_top_k",
        "solver_prune_slack",
        "solver_delta_statics",
        "solver_scale_tier",
        "solver_build_oracle",
        "solver_lazy_warm_start",
        "flight_recorder",
        "flight_recorder_capacity",
        "solver_device_pool",
        "solver_mesh_groups",
        "solver_mesh_node_shards",
        "solver_fuse_windows",
        "autoscaler_max_cluster_size",
        "autoscaler_idle_ttl_s",
        "autoscaler_poll_interval_s",
        "autoscaler_node_cpu",
        "autoscaler_node_memory",
        "autoscaler_node_gpu",
        "autoscaler_zones",
    }
)

# Identity-pinned TOPOLOGY knobs a sweep lane must not actually build:
# the stacked sweep overlaps arms its own way (one shared roster, vmapped
# lanes), so a pooled/meshed/fused solver inside one lane would burn
# compiles for zero decision delta. Stripped from every stream's
# effective overrides (decisions pinned identical by the parity suites).
_NEUTRALIZED_TOPOLOGY_FIELDS = (
    "solver_device_pool",
    "solver_mesh_groups",
    "solver_mesh_node_shards",
    "solver_fuse_windows",
)

# Top-K injected into prune-eligible streams under accelerate=True. The
# planner lower-bounds K by window demand x slack, so small windows stay
# exact-by-construction and large rosters solve [K,3] instead of [N,3].
ACCEL_PRUNE_TOP_K = 64

# Row-bucket quantum for sweep lanes (serving keeps 32): stacked lanes
# execute padding rows (vmap lowers lax.cond to select), and the sweep
# shares one jit cache across arms anyway, so tight buckets cost compiles
# once and save solve time every window.
SWEEP_ROW_BUCKET = 8

# Last completed sweep's counters, for /debug/trace (server/routing.py):
# an embedding process that ran a sweep surfaces it next to the trace
# writer's stats.
_LAST_TELEMETRY: dict = {}


def last_sweep_telemetry() -> dict:
    return dict(_LAST_TELEMETRY)


class _SharedNames(list):
    """A candidate-name list with a content-version digest: the
    candidate-mask cache keys on the digest instead of materializing and
    hashing a 10k-string tuple per request (the same fast path native
    ingest tickets get). One instance per roster version is shared by
    every request of every lane — which is what makes the cross-lane mask
    memo hit without any per-lane hashing."""

    __slots__ = ("names_digest",)

    def __init__(self, names, digest):
        super().__init__(names)
        self.names_digest = digest

    def __hash__(self):  # type: ignore[override]
        return hash(self.names_digest)

    def __eq__(self, other):
        od = getattr(other, "names_digest", None)
        if od is not None:
            return od == self.names_digest
        return list.__eq__(self, other)

    def __ne__(self, other):
        return not self.__eq__(other)


class _SweepBlobFuture:
    """Future protocol (`result`/`done`/`cancel`) for a deferred window
    blob, fulfilled by the coordinator's stacked flush. A `result()`
    before the flush force-resolves the payload singly — correct, counted
    (`forced_resolves`), and never hit by the lockstep driver itself."""

    __slots__ = ("_coord", "payload", "_value", "_done")

    def __init__(self, coord):
        self._coord = coord
        self.payload = None
        self._value = None
        self._done = False

    def _set(self, value) -> None:
        self._value = value
        self._done = True

    def result(self, timeout=None):
        if not self._done:
            self._coord._force_resolve(self.payload)
        return self._value

    def done(self) -> bool:
        return self._done

    def cancel(self) -> bool:
        return False


class _DeferredBlob:
    """Dispatch-time stand-in for the decision blob. The solver stores it
    on the WindowHandle and wires `sweep_future` as the handle's
    blob_future; nothing ever treats it as an array."""

    __slots__ = ("sweep_future",)

    def __init__(self, future):
        self.sweep_future = future


class _DeferredAvail:
    """Dispatch-time stand-in for `available_after`, parked in the
    solver's pipeline carry until the flush patches the real per-arm
    slice in. Its identity doubles as the patch guard."""

    __slots__ = ()


class _Payload:
    """One lane's deferred window: everything the flush needs to solve it
    (stacked or singly) and patch the lane's pipeline."""

    __slots__ = (
        "solver", "apps", "avail", "statics", "host",
        "fill", "emax", "num_zones", "future", "marker", "_key",
    )

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)
        self._key = None

    def group_key(self):
        """Payloads stack iff this matches: same node axis, same static
        shapes, and a content digest over the app batch AND host statics —
        the proof that the window the arms are solving is the SAME window
        (strategies that already diverged the FIFO queue produce different
        app batches and fall out into their own groups)."""
        if self._key is None:
            from spark_scheduler_tpu.models.cluster import cluster_statics

            h = hashlib.blake2b(digest_size=16)
            for a in self.apps:
                if a is not None:
                    h.update(np.ascontiguousarray(a).tobytes())
            for a in cluster_statics(self.host):
                h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
            self._key = (
                int(self.avail.shape[0]),
                self.emax,
                self.num_zones,
                h.digest(),
            )
        return self._key


class SweepCoordinator:
    """The solver-side hook object (`solver._dispatch_lane`): collects
    every lane's deferred window between lockstep barriers, then flushes
    them as stacked cross-arm dispatches."""

    # Dispatch-lane protocol (core/solver.py): the sweep drops the
    # solvers' own quantum to 8 at lane setup, so no per-lane override.
    row_bucket_quantum = None

    def __init__(self, telemetry: dict):
        self.tel = telemetry
        self.pending: list[_Payload] = []

    def accepts(self, solver) -> bool:
        """Every pipelined XLA window defers — replay lanes run in
        lockstep, so a stacking partner is always coming."""
        return True

    # Called from PlacementSolver.pack_window_dispatch (replay-only).
    def defer_window(
        self, solver, apps, *, avail, statics, host, fill, emax, num_zones
    ):
        fut = _SweepBlobFuture(self)
        payload = _Payload(
            solver=solver, apps=apps, avail=avail, statics=statics,
            host=host, fill=fill, emax=emax, num_zones=num_zones,
            future=fut, marker=_DeferredAvail(),
        )
        fut.payload = payload
        self.pending.append(payload)
        return _DeferredBlob(fut), payload.marker

    def _patch(self, payload: _Payload, avail_after) -> None:
        p = payload.solver._pipe
        if p is not None and p.get("avail") is payload.marker:
            p["avail"] = avail_after

    def _solve_single(self, payload: _Payload) -> None:
        import jax

        from spark_scheduler_tpu.core.solver import _window_blob_donated

        blob, avail_after = _window_blob_donated(
            payload.avail, payload.statics, payload.apps,
            fill=payload.fill, emax=payload.emax,
            num_zones=payload.num_zones,
        )
        self._patch(payload, avail_after)
        payload.future._set(np.asarray(jax.device_get(blob)))

    def _solve_stacked(self, members: list[_Payload]) -> None:
        import jax
        import jax.numpy as jnp

        from spark_scheduler_tpu.ops.batched import arm_stacked_fifo_pack

        # Equal fills must be adjacent (the kernel vmaps per same-fill
        # sub-stack); stable sort keeps lane order deterministic inside a
        # fill.
        members.sort(key=lambda pl: pl.fill)
        fills = tuple(pl.fill for pl in members)
        stack = jnp.stack([pl.avail for pl in members])
        lead = members[0]
        blob, avail_after = arm_stacked_fifo_pack(
            stack, lead.statics, lead.apps,
            fills=fills, emax=lead.emax, num_zones=lead.num_zones,
        )
        # ONE d2h for every arm's decisions.
        np_blob = np.asarray(jax.device_get(blob))
        for i, pl in enumerate(members):
            self._patch(pl, avail_after[i])
            pl.future._set(np_blob[i])

    def _force_resolve(self, payload: _Payload) -> None:
        self.pending.remove(payload)
        self.tel["forced_resolves"] += 1
        self._solve_single(payload)

    def flush(self) -> None:
        if not self.pending:
            return
        payloads, self.pending = self.pending, []
        c0 = _compile_seconds()
        t0 = time.perf_counter()
        groups: dict = {}
        for pl in payloads:
            groups.setdefault(pl.group_key(), []).append(pl)
        for members in groups.values():
            if len(members) == 1:
                self.tel["lane_fallbacks"] += 1
                self._solve_single(members[0])
            else:
                self.tel["stacked_dispatches"] += 1
                self.tel["stacked_arm_windows"] += len(members)
                self._solve_stacked(members)
        dc = _compile_seconds() - c0
        self.tel["replay_compile_ms"] += dc * 1e3
        self.tel["windows"] += len(payloads)
        self.tel["solve_s"] += max(0.0, time.perf_counter() - t0 - dc)


@dataclasses.dataclass
class SweepReport:
    """M arms' replay outcomes plus the shared-build/stacking evidence."""

    trace: str
    arms: list  # [{"name", "overrides", "stream"}]
    reports: list  # per-ARM ReplayReport (stream reports cloned per arm)
    telemetry: dict
    wall_s: float

    def summary(self) -> dict:
        return {
            "trace": self.trace,
            "arms": [
                {**a, "report": r.summary()}
                for a, r in zip(self.arms, self.reports)
            ],
            "telemetry": dict(self.telemetry),
            "wall_s": round(self.wall_s, 3),
        }

    def decision_summary(self) -> dict:
        """Wall-clock-free projection — identical across runs of the same
        trace + grid (the sweep-determinism pin)."""
        return {
            "trace": self.trace,
            "arms": [
                {**a, "report": r.decision_summary()}
                for a, r in zip(self.arms, self.reports)
            ],
            "dedup": {
                k: self.telemetry[k]
                for k in ("arms", "streams", "dedup_arms")
            },
        }

    def markdown(self) -> str:
        """The grid study as a GitHub table, one row per arm."""
        head = (
            "| arm | decisions | placed | denials | util cpu | frag cpu "
            "| p50 ms | p99 ms |\n"
            "|---|---|---|---|---|---|---|---|"
        )
        rows = []
        for a, r in zip(self.arms, self.reports):
            rows.append(
                f"| {a['name']} | {r.decisions} | {len(r.placements)} "
                f"| {r.denials} | {r.utilization.get('cpu', 0.0)} "
                f"| {r.fragmentation.get('cpu', 0.0)} "
                f"| {r.latency_ms(0.5)} | {r.latency_ms(0.99)} |"
            )
        t = self.telemetry
        tail = (
            f"\n{t['arms']} arms / {t['streams']} streams · "
            f"{t['windows']} stacked-path windows · "
            f"{t['stacked_dispatches']} stacked dispatches "
            f"({t['stacked_arm_windows']} arm-windows) · "
            f"{t['lane_fallbacks']} lane fallbacks · "
            f"{t['shared_build_hits']} shared-build hits · "
            f"{round(t['windows_per_s'], 1)} windows/s · "
            f"wall {round(self.wall_s, 2)} s"
        )
        return "\n".join([head] + rows) + tail


def _normalize_arms(arms) -> list[dict]:
    """Accept [{overrides}] or [{"name":..., "overrides": {...}}]; emit
    [{"name", "overrides"}] with dash-keys normalized to field names."""
    out = []
    for i, arm in enumerate(arms):
        if isinstance(arm, dict) and "overrides" in arm and (
            "name" in arm or len(arm) <= 2
        ):
            name, ov = arm.get("name"), arm["overrides"]
        else:
            name, ov = None, arm
        ov = {str(k).replace("-", "_"): v for k, v in dict(ov).items()}
        if name is None:
            name = (
                ",".join(f"{k}={v}" for k, v in sorted(ov.items()))
                or "base"
            )
        out.append({"name": name, "overrides": ov})
    return out


def _stream_plan(norm_arms: list[dict], accelerate: bool):
    """Group arms into decision streams and pick each stream's effective
    override set (first member's, plus the prune acceleration)."""
    streams: list[dict] = []
    stream_of: list[int] = []
    index: dict = {}
    for arm in norm_arms:
        ov = arm["overrides"]
        key = tuple(
            sorted(
                (k, repr(v))
                for k, v in ov.items()
                if k not in IDENTITY_PINNED_FIELDS
            )
        )
        sid = index.get(key)
        if sid is None:
            sid = len(streams)
            index[key] = sid
            streams.append({"overrides": dict(ov), "members": []})
        streams[sid]["members"].append(arm)
        stream_of.append(sid)
    for s in streams:
        eff = s["overrides"]
        explicit = next(
            (
                m["overrides"]
                for m in s["members"]
                if m["overrides"].get("solver_prune_top_k")
            ),
            None,
        )
        if explicit is not None:
            for k in ("solver_prune_top_k", "solver_prune_slack"):
                if k in explicit:
                    eff[k] = explicit[k]
        elif accelerate and not eff.get("solver_prune_top_k"):
            # Certified pruning (decisions byte-identical by construction:
            # every pruned verdict is certificate-checked at fetch with
            # exact escalation) — free speed for eligible plain-fill
            # streams, a no-op for the rest.
            eff["solver_prune_top_k"] = ACCEL_PRUNE_TOP_K
        for k in _NEUTRALIZED_TOPOLOGY_FIELDS:
            eff.pop(k, None)
        # Comparison against recorded results is only meaningful when the
        # stream's DECISION config is the recorded one (identity-pinned
        # overrides don't move decisions, so they don't disqualify it).
        s["compare"] = not any(
            k not in IDENTITY_PINNED_FIELDS for k in s["overrides"]
        )
    return streams, stream_of


def run_sweep(
    trace_path: str,
    arms,
    *,
    accelerate: bool = True,
    progress=None,
) -> SweepReport:
    """Replay `trace_path` under every arm in `arms` (a list of override
    dicts, or {"name", "overrides"} entries) concurrently over one shared
    event stream. Returns a SweepReport whose `reports[i]` is bit-identical
    (verdicts/placements) to `replay_trace(trace_path, arms[i])`."""
    t_start = time.perf_counter()
    c_start = _compile_seconds()
    norm_arms = _normalize_arms(arms)
    streams, stream_of = _stream_plan(norm_arms, accelerate)

    reader = TraceReader(trace_path)
    header = reader.header
    events = list(reader.events())
    has_results = any(ev.get("k") == "result" for ev in events)

    telemetry = {
        "arms": len(norm_arms),
        "streams": len(streams),
        "dedup_arms": len(norm_arms) - len(streams),
        "windows": 0,
        "stacked_dispatches": 0,
        "stacked_arm_windows": 0,
        "lane_fallbacks": 0,
        "forced_resolves": 0,
        "shared_build_hits": 0,
        "replay_compile_ms": 0.0,
        "solve_s": 0.0,
    }
    coordinator = SweepCoordinator(telemetry)
    shared_masks: dict = {}

    lanes: list[ReplayLane] = []
    for s in streams:
        config = config_from_fingerprint(
            header["config"],
            overrides=s["overrides"],
            forced=dict(FORCED_FIELDS),
        )
        lane = ReplayLane(
            header,
            config,
            compare=s["compare"],
            has_result_events=has_results,
            candidate_memo=shared_masks,
        )
        lane.app.solver._dispatch_lane = coordinator
        lane.app.solver._row_bucket_quantum = SWEEP_ROW_BUCKET
        lanes.append(lane)

    # The driver's own roster mirror: candidates expand ONCE per event and
    # the shared list carries a (roster-version) digest, so every lane's
    # candidate-mask lookup is a cheap digest hit instead of an O(roster)
    # tuple hash — and lanes 2..S hit the cross-lane mask memo.
    roster: list[str] = []
    roster_version = 0
    roster_names: Optional[_SharedNames] = None

    def shared_expand(names):
        nonlocal roster_names
        if names == ALL_NODES:
            if roster_names is None:
                roster_names = _SharedNames(
                    roster, ("sweep-roster", roster_version)
                )
            return roster_names
        return list(names)

    n_events = 0
    for ev in events:
        n_events += 1
        if progress is not None and n_events % 5000 == 0:
            progress(n_events)
        k = ev.get("k")
        for lane in lanes:
            lane.begin_event(ev)
        if k == "predicate":
            candidates = [shared_expand(r["nodes"]) for r in ev["reqs"]]
            pends = [
                lane.predicate_begin(ev, candidates=list(candidates))
                for lane in lanes
            ]
            # The lockstep barrier: every arm's window is parked — solve
            # them as stacked cross-arm dispatches, then complete.
            coordinator.flush()
            for lane, p in zip(lanes, pends):
                lane.predicate_finish(p)
        elif k == "result":
            for lane in lanes:
                lane.result(ev)
        else:
            if k == "node":
                op = ev.get("op")
                if op == "delete":
                    if ev.get("name") in roster:
                        roster.remove(ev["name"])
                        roster_version += 1
                        roster_names = None
                elif op == "add":
                    name = ev["node"]["metadata"]["name"]
                    if name not in roster:
                        roster.append(name)
                        roster_version += 1
                        roster_names = None
            for lane in lanes:
                lane.apply(ev)
    for lane in lanes:
        lane.drain()
    coordinator.flush()

    stream_reports = [lane.finish(reader) for lane in lanes]
    telemetry["shared_build_hits"] = shared_masks.pop("__hits__", 0)
    telemetry["lane_roster_rebuilds"] = [
        lane.ext.features.stats()["roster_rebuilds"] for lane in lanes
    ]
    telemetry["lane_full_snapshots"] = [
        lane.app.solver.build_stats["full_snapshots"] for lane in lanes
    ]
    telemetry["lane_pruned_windows"] = [
        lane.app.solver.prune_stats["windows"] for lane in lanes
    ]
    wall = time.perf_counter() - t_start
    telemetry["replay_compile_ms"] = round(
        max(
            telemetry["replay_compile_ms"],
            (_compile_seconds() - c_start) * 1e3,
        ),
        3,
    )
    telemetry["windows_per_s"] = round(
        telemetry["windows"] / wall if wall > 0 else 0.0, 3
    )
    telemetry["solve_s"] = round(telemetry["solve_s"], 3)

    arms_out = []
    reports = []
    for arm, sid in zip(norm_arms, stream_of):
        arms_out.append({**arm, "stream": sid})
        # Clone so an arm's report is independently mutable/serializable
        # even when several arms share a stream.
        reports.append(copy.deepcopy(stream_reports[sid]))

    _LAST_TELEMETRY.clear()
    _LAST_TELEMETRY.update(
        {k: v for k, v in telemetry.items()}, wall_s=round(wall, 3)
    )
    return SweepReport(
        trace=trace_path,
        arms=arms_out,
        reports=reports,
        telemetry=telemetry,
        wall_s=wall,
    )


def grid_arms(grid: dict, base: Optional[dict] = None) -> list[dict]:
    """Cartesian product of `{field: [values...]}` into sweep arms, each
    carrying `base` plus its grid point. The CLI's `--grid` feeds this."""
    base = {str(k).replace("-", "_"): v for k, v in (base or {}).items()}
    fields = sorted(grid)
    arms = []
    for combo in itertools.product(*(grid[f] for f in fields)):
        ov = dict(base)
        ov.update(
            {
                str(f).replace("-", "_"): v
                for f, v in zip(fields, combo)
            }
        )
        arms.append(ov)
    return arms
