"""CRD lifecycle: ensure-on-startup and lazy establishment watching.

Rebuilds internal/crd/utils.go:32-151 and internal/crd/demand_informer.go:
the scheduler owns the ResourceReservation CRD (creates or upgrades it at
startup, verifies it becomes Established, deletes a failed create), while
the Demand CRD belongs to the external autoscaler — the scheduler only
*polls* for it (1/min) and lazily enables demand features when it appears.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from spark_scheduler_tpu.store.backend import RESERVATION_CRD

ESTABLISH_POLL_INTERVAL_S = 0.05
ESTABLISH_TIMEOUT_S = 10.0  # crd/utils.go poll-verify window
DEMAND_CRD_POLL_INTERVAL_S = 60.0  # demand_informer.go:75-97 (1/min)


class CRDError(Exception):
    pass


def check_crd_exists(backend, name: str) -> bool:
    """Established-condition check (crd/utils.go:32-55)."""
    return backend.crd_exists(name)


def ensure_resource_reservations_crd(
    backend,
    name: str = RESERVATION_CRD,
    timeout_s: float = ESTABLISH_TIMEOUT_S,
    clock=time.monotonic,
    sleep=time.sleep,
    webhook_url: str | None = None,
    ca_bundle: str | None = None,
) -> None:
    """Create-or-upgrade the reservation CRD — the FULL manifest with
    openAPI schemas, served/storage versions and (when `webhook_url` is
    given) the webhook conversion strategy — then poll until it reports
    Established; on verification failure delete the half-created CRD and
    raise, so a restart retries cleanly (crd/utils.go:98-151,
    crd_resource_reservation.go:83-115)."""
    from spark_scheduler_tpu.models.crds import resource_reservation_crd

    definition = resource_reservation_crd(webhook_url=webhook_url, ca_bundle=ca_bundle)
    # Upsert even when the CRD already exists: the reference's ensure path
    # *updates* an existing CRD to the current definition (version upgrade).
    backend.register_crd(name, definition)
    deadline = clock() + timeout_s
    while not backend.crd_exists(name):
        if clock() > deadline:
            try:
                backend.unregister_crd(name)
            except Exception:
                pass
            raise CRDError(f"CRD {name} did not become established in {timeout_s}s")
        sleep(ESTABLISH_POLL_INTERVAL_S)


class LazyDemandCRDWatcher:
    """Poll for the Demand CRD until it exists, then fire ready callbacks
    once (internal/crd/demand_informer.go:75-138). The SafeDemandCache keeps
    gating every operation on crd_exists(); this watcher is the push-style
    complement that lets components (demand GC, waste reporter wiring)
    initialize as soon as demands become available."""

    def __init__(
        self,
        backend,
        crd_name: str,
        poll_interval_s: float = DEMAND_CRD_POLL_INTERVAL_S,
    ):
        self._backend = backend
        self._crd_name = crd_name
        self._poll_interval_s = poll_interval_s
        self._ready = threading.Event()
        self._callbacks: list[Callable[[], None]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def on_ready(self, callback: Callable[[], None]) -> None:
        """Register a callback; fires immediately if already ready."""
        fire = False
        with self._lock:
            if self._ready.is_set():
                fire = True
            else:
                self._callbacks.append(callback)
        if fire:
            callback()

    def ready(self) -> bool:
        return self._ready.is_set()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return self._ready.wait(timeout)

    def check_now(self) -> bool:
        """One poll step (also the test hook): fire callbacks on first hit."""
        if self._ready.is_set():
            return True
        if not self._backend.crd_exists(self._crd_name):
            return False
        with self._lock:
            if self._ready.is_set():
                return True
            callbacks, self._callbacks = self._callbacks, []
            self._ready.set()
        for cb in callbacks:
            cb()
        return True

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.is_set():
                if self.check_now():
                    return
                self._stop.wait(self._poll_interval_s)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="lazy-demand-crd"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
