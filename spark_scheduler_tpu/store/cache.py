"""Write-through caches with async write-back.

Rebuilds internal/cache/{cache.go,resourcereservations.go,demands.go,
safedemands.go}: the cache owner is the SOLE writer for its objects —
Create/Update/Delete mutate the local store synchronously and enqueue a
write; watch events may only fast-forward resourceVersions (external
creates/updates are ignored to avoid conflicts) and apply deletions. Each
CRD kind gets 5 write workers over a sharded dedup queue
(resourceReservationClients=5, resourcereservations.go:29-34).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

from spark_scheduler_tpu.store.async_client import (
    DEFAULT_MAX_RETRIES,
    AsyncClient,
    AsyncClientMetrics,
)
from spark_scheduler_tpu.store.backend import DEMAND_CRD, ClusterBackend
from spark_scheduler_tpu.store.object_store import ObjectStore
from spark_scheduler_tpu.store.queue import Request, RequestType, make_sharded_queue

NUM_WRITE_CLIENTS = 5


class BatchableListener:
    """A mutation listener with a batched variant.

    `WriteThroughCache.create_many` (the serving window's coalesced commit)
    delivers all of a batch's (old, new) pairs in ONE `batch(pairs)` call to
    listeners registered through this wrapper — the delta consumer takes its
    own lock once per window instead of once per reservation. Single
    mutations still arrive through `__call__` exactly as before."""

    __slots__ = ("_fn", "batch")

    def __init__(self, fn, batch):
        self._fn = fn
        self.batch = batch

    def __call__(self, old, new) -> None:
        self._fn(old, new)


class WriteThroughCache:
    def __init__(
        self,
        backend: ClusterBackend,
        kind: str,
        *,
        num_clients: int = NUM_WRITE_CLIENTS,
        max_retries: int = DEFAULT_MAX_RETRIES,
        sync_writes: bool = False,
        retry_policy=None,
        breaker=None,
        on_retry=None,
    ):
        """sync_writes=True drains the queue inline after every mutation —
        deterministic mode for tests and single-threaded deployments."""
        self._store = ObjectStore()
        self._queue = make_sharded_queue(num_clients)
        self._sync = sync_writes
        self._defer_threads: dict[int, int] = {}  # see deferred_sync()
        # Mutation listeners: fn(old, new) fired synchronously after every
        # local-store mutation (create: old=None; delete: new=None). This is
        # the delta feed for incremental aggregates (ReservedUsageTracker).
        # The read-old -> write -> notify sequence is serialized by
        # `_write_mutex`: the owner is the sole REQUEST-path writer, but the
        # watch thread delivers `apply_external_delete`, so without the mutex
        # racing writers could deliver mismatched (old, new) pairs and
        # permanently corrupt delta-maintained state.
        self._mutation_listeners: list = []
        self._write_mutex = threading.RLock()
        # Per-thread deferred-notification state: {tid: [depth, pairs]} —
        # see deferred_notifications().
        self._deferred_notify: dict[int, list] = {}
        self.client = AsyncClient(
            backend, kind, self._store, self._queue,
            max_retries=max_retries, metrics=AsyncClientMetrics(),
            retry_policy=retry_policy, breaker=breaker, on_retry=on_retry,
        )
        # Initial fill from the backend (cache/resourcereservations.go:53-60).
        for obj in backend.list(kind):
            self._store.put(obj)
        backend.subscribe(
            kind,
            on_add=self._store.override_resource_version_if_newer,
            on_update=lambda old, new: self._store.override_resource_version_if_newer(new),
            on_delete=lambda obj: None,  # see note below
        )
        # NOTE on deletes: the reference removes watched deletions from the
        # store (cache.go:127-133). With the in-memory backend the only
        # deleter is this cache itself (delete already removed it); a k8s
        # adapter should call `apply_external_delete` from its watch stream.

    def add_mutation_listener(self, fn) -> None:
        """fn(old, new); see __init__ note. Must be fast and non-blocking."""
        self._mutation_listeners.append(fn)

    def set_max_retries(self, n: int) -> None:
        """Live write-back retry-budget change (runtime config reload)."""
        self.client.set_max_retries(n)

    def _notify(self, old: Any, new: Any) -> None:
        deferred = self._deferred_notify.get(threading.get_ident())
        if deferred is not None:
            deferred[1].append((old, new))
            return
        for fn in self._mutation_listeners:
            fn(old, new)

    def apply_external_delete(self, namespace: str, name: str) -> None:
        with self._write_mutex:
            old = self._store.get(namespace, name)
            self._store.delete(namespace, name)
            if old is not None:
                self._notify(old, None)

    def apply_external_upsert(self, obj: Any) -> None:
        """Absorb another writer's committed object (HA standby tailing):
        store it and notify listeners with the LOCAL previous version as
        `old` so delta consumers (usage tracker) apply the correct diff.
        No write-back is enqueued — the object came FROM the backend.
        Callers must dedup self-originated events (the owner's own writes
        already notified through create/update)."""
        with self._write_mutex:
            old = self._store.get(obj.namespace, obj.name)
            self._store.put(obj)
            self._notify(old, obj)

    def start(self) -> None:
        if not self._sync:
            self.client.start()

    def stop(self) -> None:
        self.client.stop()

    def flush(self) -> None:
        self.client.drain_sync()

    @contextlib.contextmanager
    def deferred_sync(self):
        """Batch sync-mode write-back FOR THE CALLING THREAD: inside the
        context its per-mutation drains are suppressed; ONE drain runs at
        exit. A serving window applies dozens of mutations back to back —
        per-write queue drains (num_buckets pops each) were measurable
        host time, and deferring them changes nothing observable for this
        thread: reads go through the local store (write-through), and the
        drain still completes before the window's responses are released.
        Scoped per thread so a CONCURRENT writer (watch handlers, GC
        subscribers) keeps the full sync-mode drain-on-write guarantee.
        No-op in async mode. Reentrant."""
        if not self._sync:
            yield
            return
        tid = threading.get_ident()
        self._defer_threads[tid] = self._defer_threads.get(tid, 0) + 1
        try:
            yield
        finally:
            n = self._defer_threads[tid] - 1
            if n:
                self._defer_threads[tid] = n
            else:
                del self._defer_threads[tid]
                self.client.drain_sync()

    def _after_write(self) -> None:
        if self._sync and threading.get_ident() not in self._defer_threads:
            self.client.drain_sync()

    def _notify_batch(self, pairs: list) -> None:
        """Deliver a batch of (old, new) pairs: batch-aware listeners
        (BatchableListener) get ONE call, plain listeners get one per pair.
        Must run inside `_write_mutex` like `_notify`, so batched pairs
        cannot interleave with a concurrent writer's notifications."""
        if not pairs:
            return
        for fn in self._mutation_listeners:
            batch = getattr(fn, "batch", None)
            if batch is not None:
                batch(pairs)
            else:
                for old, new in pairs:
                    fn(old, new)

    @contextlib.contextmanager
    def deferred_notifications(self):
        """Coalesce THIS THREAD's mutation notifications into ONE batched
        delivery at context exit (batch-aware listeners get a single
        `batch(pairs)` call — see BatchableListener). A serving window
        commits dozens of reservations back to back, and per-mutation
        listener fan-out (a lock + delta application per consumer per
        write) was measurable host time; one batch per window keeps it
        O(window).

        Correctness contract: the registered delta consumers commute —
        the usage tracker applies additive per-slot diffs and the overhead
        store recomputes from current state — so delivering this thread's
        pairs after a concurrent writer's interleaved mutations reaches
        the same aggregates. A listener that requires immediate
        per-mutation delivery must not run under this context. Local-store
        reads are unaffected (write-through). Reentrant; pairs are
        delivered even when the body raises."""
        tid = threading.get_ident()
        state = self._deferred_notify.get(tid)
        if state is None:
            state = self._deferred_notify[tid] = [0, []]
        state[0] += 1
        try:
            yield
        finally:
            state[0] -= 1
            if state[0] == 0:
                del self._deferred_notify[tid]
                if state[1]:
                    with self._write_mutex:
                        self._notify_batch(state[1])

    def create(self, obj: Any) -> bool:
        with self._write_mutex:
            if not self._store.put_if_absent(obj):
                return False
            self._queue.add_if_absent(Request(key=(obj.namespace, obj.name), type=RequestType.CREATE))
            self._notify(None, obj)
        self._after_write()
        return True

    def update(self, obj: Any) -> bool:
        with self._write_mutex:
            old = self._store.get(obj.namespace, obj.name)
            if old is None:
                return False
            self._store.put(obj)
            self._queue.add_if_absent(Request(key=(obj.namespace, obj.name), type=RequestType.UPDATE))
            self._notify(old, obj)
        self._after_write()
        return True

    def delete(self, namespace: str, name: str) -> None:
        with self._write_mutex:
            old = self._store.get(namespace, name)
            self._store.delete(namespace, name)
            self._queue.add_if_absent(Request(key=(namespace, name), type=RequestType.DELETE))
            if old is not None:
                self._notify(old, None)
        self._after_write()

    def get(self, namespace: str, name: str) -> Optional[Any]:
        return self._store.get(namespace, name)

    def list(self) -> list[Any]:
        return self._store.list()

    def queue_lengths(self) -> list[int]:
        return self._queue.queue_lengths()


class ResourceReservationCache(WriteThroughCache):
    def __init__(self, backend: ClusterBackend, **kw):
        super().__init__(backend, "resourcereservations", **kw)


class DemandCache(WriteThroughCache):
    def __init__(self, backend: ClusterBackend, **kw):
        super().__init__(backend, "demands", **kw)


class SafeDemandCache:
    """Demand cache gated on Demand-CRD existence (safedemands.go:40-127 +
    crd/demand_informer.go): lazily initializes the real cache the first
    time the CRD is observed; all operations no-op before that."""

    def __init__(self, backend: ClusterBackend, **kw):
        self._backend = backend
        self._kw = kw
        self._cache: DemandCache | None = None

    def crd_exists(self) -> bool:
        if self._cache is not None:
            return True
        if self._backend.crd_exists(DEMAND_CRD):
            self._cache = DemandCache(self._backend, **self._kw)
            self._cache.start()
            return True
        return False

    def set_max_retries(self, n: int) -> None:
        self._kw["max_retries"] = int(n)  # applies if the cache appears later
        if self._cache is not None:
            self._cache.set_max_retries(n)

    def get(self, namespace: str, name: str):
        return self._cache.get(namespace, name) if self.crd_exists() else None

    def create(self, obj) -> bool:
        if not self.crd_exists():
            return False
        return self._cache.create(obj)

    def delete(self, namespace: str, name: str) -> None:
        if self.crd_exists():
            self._cache.delete(namespace, name)

    def list(self) -> list[Any]:
        return self._cache.list() if self.crd_exists() else []

    @contextlib.contextmanager
    def deferred_sync(self):
        # Bind the inner cache's context only if the CRD cache exists NOW;
        # a cache appearing mid-context just drains per-write as before.
        if self._cache is None:
            yield
            return
        with self._cache.deferred_sync():
            yield

    def queue_lengths(self) -> list[int]:
        return self._cache.queue_lengths() if self._cache is not None else []

    def flush(self) -> None:
        if self._cache is not None:
            self._cache.flush()

    def stop(self) -> None:
        if self._cache is not None:
            self._cache.stop()
