"""File-backed durable ClusterBackend (the etcd slot).

In the reference, reservations/demands persist in etcd via CRDs — the CRDs
*are* the checkpoint (SURVEY.md §5.4): a restarted leader refills its cache
from the apiserver (cache/resourcereservations.go:53-60) and reconciles
drift from observed pods (failover.go:35-72). `DurableBackend` gives a
standalone deployment the same property without an apiserver: every
mutation appends one JSON-line record (k8s wire-shaped object payloads) to
a log; on startup the log replays into memory, after which the normal
failover reconciliation runs against real persisted state.

Record format (one JSON object per line):

    {"verb": "create|update|delete", "kind": "<collection>",
     "ns": "...", "name": "...", "object": {<k8s wire form>}}
    {"verb": "register_crd"|"unregister_crd", "name": "...",
     "definition": {...}}

`compact()` rewrites the log as one create per live object (the etcd
compaction analog) — callable any time; the scheduler also compacts on
startup after replay so the log stays bounded across restart cycles.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

from spark_scheduler_tpu.models.demands import Demand
from spark_scheduler_tpu.models.kube import Node, Pod
from spark_scheduler_tpu.models.reservations import ResourceReservation
from spark_scheduler_tpu.store.backend import InMemoryBackend


def _rr_to_record(rr: ResourceReservation) -> dict:
    from spark_scheduler_tpu.server.conversion import rr_v1beta2_to_wire

    wire = rr_v1beta2_to_wire(rr)
    # The ownerReference to the driver pod normally lives in ObjectMeta
    # (newResourceReservation sets it); models carry it as owner_pod_uid.
    if rr.owner_pod_uid and not wire["metadata"].get("ownerReferences"):
        wire["metadata"]["ownerReferences"] = [
            {"apiVersion": "v1", "kind": "Pod", "uid": rr.owner_pod_uid}
        ]
    return wire


def _rr_from_record(raw: dict) -> ResourceReservation:
    from spark_scheduler_tpu.server.conversion import rr_v1beta2_from_wire

    rr = rr_v1beta2_from_wire(raw)
    for ref in (raw.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("kind") == "Pod" and ref.get("uid"):
            rr.owner_pod_uid = ref["uid"]
            break
    return rr


def _demand_to_record(d: Demand) -> dict:
    from spark_scheduler_tpu.server.conversion import demand_v1alpha2_to_wire

    return demand_v1alpha2_to_wire(d)


def _demand_from_record(raw: dict) -> Demand:
    from spark_scheduler_tpu.server.conversion import demand_v1alpha2_from_wire

    return demand_v1alpha2_from_wire(raw)


def _pod_to_record(p: Pod) -> dict:
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s

    return pod_to_k8s(p)


def _pod_from_record(raw: dict) -> Pod:
    from spark_scheduler_tpu.server.kube_io import pod_from_k8s

    return pod_from_k8s(raw)


def _node_to_record(n: Node) -> dict:
    from spark_scheduler_tpu.server.kube_io import node_to_k8s

    return node_to_k8s(n)


def _node_from_record(raw: dict) -> Node:
    from spark_scheduler_tpu.server.kube_io import node_from_k8s

    return node_from_k8s(raw)


def _lease_to_record(lease) -> dict:
    return lease.to_wire()


def _lease_from_record(raw: dict):
    from spark_scheduler_tpu.ha.lease import LeaseRecord

    return LeaseRecord.from_wire(raw)


_CODECS = {
    "pods": (_pod_to_record, _pod_from_record),
    "nodes": (_node_to_record, _node_from_record),
    "resourcereservations": (_rr_to_record, _rr_from_record),
    "demands": (_demand_to_record, _demand_from_record),
    # HA leader lease (ha/lease.py): renewals ride the WAL like any other
    # mutation; replay restores the epoch so fencing stays monotonic
    # across restarts. (Multi-PROCESS deployments arbitrate through the
    # flock-guarded FileLeaseStore sidecar instead — the WAL has no
    # cross-process CAS.)
    "leases": (_lease_to_record, _lease_from_record),
}


class DurableBackend(InMemoryBackend):
    """InMemoryBackend + JSONL write-ahead persistence. Replays the log on
    construction (before any component subscribes, so no spurious events
    fire), then compacts it."""

    def __init__(
        self,
        path: str,
        fsync: bool = False,
        compact_on_load: bool = True,
        follow: bool = False,
    ):
        super().__init__()
        self.path = path
        self._fsync = fsync
        self._log_lock = threading.Lock()
        self._replaying = False
        self._file: Optional[Any] = None
        # FOLLOWER mode (HA warm standby over a shared WAL): read-only —
        # never compacts, never truncates, never opens an append handle;
        # `poll_log()` tails the leader's appended records and applies
        # them WITH events so subscribed caches stay warm. A promoted
        # follower calls `promote_to_writer()` before its first write.
        self._follow = follow
        # End offset of the last complete record consumed (replay/poll).
        self._log_offset = 0
        # FaultInjector seam (wal.<op>.<kind>): fn(op, record) fired
        # inside _append — raising makes the commit fail exactly where a
        # full disk or torn fsync would.
        self.wal_fault_hook = None
        # Records whose append FAILED after their in-memory commit. The
        # base backend commits, then _on_committed appends — so by the
        # time an append can fail, the state change is already visible
        # and a caller's retry is an AlreadyExists no-op that never
        # re-appends. Parking the record and draining the buffer ahead of
        # the next successful append (commit order preserved: the lock is
        # held across both) keeps the log complete — a faulted append
        # delays durability, it never silently drops a committed record.
        self._wal_pending: list = []
        self.wal_append_failures = 0
        if os.path.exists(path):
            self._replay()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if follow:
            return
        if compact_on_load:
            self.compact()
        else:
            self._file = open(self.path, "a", encoding="utf-8")

    # -- persistence plumbing ------------------------------------------------

    def _append(self, record: dict) -> None:
        # Followers never write the shared log (promote_to_writer flips
        # the flag); replay/poll application must not re-append.
        if self._replaying or self._follow:
            return
        with self._log_lock:
            hook = self.wal_fault_hook
            try:
                if hook is not None:
                    hook("append", record)
                if self._file is None:
                    self._file = open(self.path, "a", encoding="utf-8")
                while self._wal_pending:
                    self._file.write(json.dumps(self._wal_pending[0]) + "\n")
                    del self._wal_pending[0]
                self._file.write(json.dumps(record) + "\n")
                self._file.flush()
            except Exception:
                self.wal_append_failures += 1
                self._wal_pending.append(record)
                raise
            # Past this point the record is written and flushed: an fsync
            # fault below must NOT park it — it is already on disk.
            if hook is not None:
                hook("fsync", record)
            if self._fsync:
                os.fsync(self._file.fileno())

    def wal_flush(self) -> int:
        """Drain any parked (append-faulted) records to the log; returns
        how many were flushed. Called by close() and by chaos soaks before
        comparing the log against live state."""
        with self._log_lock:
            if not self._wal_pending or self._follow:
                return 0
            if self._file is None:
                self._file = open(self.path, "a", encoding="utf-8")
            n = 0
            while self._wal_pending:
                self._file.write(json.dumps(self._wal_pending[0]) + "\n")
                del self._wal_pending[0]
                n += 1
            self._file.flush()
            if self._fsync:
                os.fsync(self._file.fileno())
            return n

    def _replay(self) -> None:
        """Replay the log, tracking the byte offset of the last COMPLETE
        record. A torn trailing line (crash mid-append) is TRUNCATED away
        with a warning — leaving the partial bytes in place would corrupt
        the next appended record too (it would land on the same line).
        A torn record mid-log (good records after it) can only be skipped;
        that is data damage worth a loud warning, not a raise."""
        import warnings

        self._replaying = True
        good_end = 0
        bad = 0
        tail_torn = False
        try:
            with open(self.path, "rb") as f:
                pos = 0
                for raw in f:
                    pos += len(raw)
                    line = raw.strip()
                    if not line:
                        if not tail_torn:
                            good_end = pos
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        bad += 1
                        tail_torn = True
                        continue
                    tail_torn = False
                    self._apply_record(record)
                    good_end = pos
        finally:
            self._replaying = False
        if bad:
            if tail_torn and not self._follow:
                warnings.warn(
                    f"durable log {self.path}: torn trailing record (crash "
                    f"mid-append) — truncated to the last complete record "
                    f"({good_end} bytes)",
                    RuntimeWarning,
                    stacklevel=2,
                )
                with open(self.path, "r+b") as f:
                    f.truncate(good_end)
            elif tail_torn and bad == 1:
                # Follower booting while the live writer is mid-append: a
                # healthy log, not damage — poll_log consumes the line
                # once the writer completes it. Stay silent.
                pass
            else:
                warnings.warn(
                    f"durable log {self.path}: {bad} undecodable record(s) "
                    "skipped on replay",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self._log_offset = good_end

    # -- follower mode (HA warm standby over a shared WAL) -------------------

    def poll_log(self) -> int:
        """Apply records the writer appended since the last replay/poll,
        WITH events (subscribed caches, feature stores, and standby
        tailers observe them like any live mutation). Only complete lines
        are consumed — a partially flushed tail stays for the next poll.
        Returns the number of records applied."""
        if not self._follow:
            return 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        if size < self._log_offset:
            # The writer compacted (rewrote) the log under us — which no
            # HA writer ever does (promote_to_writer never compacts);
            # this means a NON-HA writer was pointed at a tailed log.
            # Re-applying from the top converges for upserts, but a
            # deletion that happened past our offset AND was compacted
            # away is invisible: this follower keeps the deleted object
            # (stale usage) until its next promotion reconcile. Warn
            # loudly — this is an operational misconfiguration.
            import warnings

            warnings.warn(
                f"durable log {self.path} was compacted under a live "
                "follower (mixed HA/non-HA writers?): re-syncing from the "
                "top; deletions compacted past this follower's offset are "
                "lost until the next promotion reconcile",
                RuntimeWarning,
                stacklevel=2,
            )
            self._log_offset = 0
        if size == self._log_offset:
            return 0
        with open(self.path, "rb") as f:
            f.seek(self._log_offset)
            buf = f.read()
        applied = 0
        pos = 0
        while True:
            nl = buf.find(b"\n", pos)
            if nl < 0:
                break  # incomplete tail: the writer is mid-append
            line = buf[pos:nl].strip()
            pos = nl + 1
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue  # torn mid-log line; the writer's restart repairs
            self._apply_record_live(record)
            applied += 1
        self._log_offset += pos
        return applied

    def _apply_record_live(self, record: dict) -> None:
        """Apply one tailed record through the PUBLIC mutators (events
        fire, pod indexes and nodes_version maintained) with WAL re-append
        suppressed. Verbs are applied as idempotent upserts: the follower
        may observe a create for an object it already holds (log
        compaction) or a delete for one it never saw."""
        from spark_scheduler_tpu.store.backend import (
            AlreadyExistsError,
            NotFoundError,
        )

        self._replaying = True
        try:
            verb = record.get("verb")
            if verb == "register_crd":
                self.register_crd(record["name"], record.get("definition"))
                return
            if verb == "unregister_crd":
                self.unregister_crd(record["name"])
                return
            kind = record.get("kind")
            if kind not in _CODECS:
                return
            ns, name = record.get("ns", ""), record.get("name", "")
            if verb == "delete":
                try:
                    self.delete(kind, ns, name)
                except NotFoundError:
                    pass
                return
            if verb not in ("create", "update"):
                return
            obj = _CODECS[kind][1](record["object"])
            cur = self.get(kind, ns, name)
            try:
                if cur is None:
                    if hasattr(obj, "resource_version"):
                        obj.resource_version = 0
                    self.create(kind, obj)
                else:
                    if hasattr(obj, "resource_version") and hasattr(
                        cur, "resource_version"
                    ):
                        obj.resource_version = cur.resource_version
                    self.update(kind, obj)
            except (AlreadyExistsError, NotFoundError):
                pass  # single poller; a race here means test-injected state
        finally:
            self._replaying = False

    def promote_to_writer(self) -> None:
        """A promoted follower becomes the WAL's writer: consume any
        complete records still unpolled, truncate the dead leader's torn
        mid-append tail (appending onto partial bytes would weld our first
        record to them into one undecodable line — losing BOTH on the next
        replay), then stop tailing and open the append handle.

        NOTE: a promoted writer never compacts the log — followers tail by
        byte offset, and a rewrite under them would tear their position
        mid-record. Compacting an HA log is a maintenance operation for
        the whole replica group (generation files are future work)."""
        if not self._follow:
            return
        import warnings

        self.poll_log()  # final catch-up: only a newline-less tail remains
        self._follow = False
        with self._log_lock:
            try:
                size = os.path.getsize(self.path)
            except OSError:
                size = self._log_offset
            if size > self._log_offset:
                # The residual bytes are either a COMPLETE record whose
                # trailing newline never hit the disk — a committed write
                # that cold-restart replay (`for raw in f`) would keep, so
                # losing it here would make failover stricter than restart
                # — or genuinely torn bytes.
                with open(self.path, "rb") as f:
                    f.seek(self._log_offset)
                    tail = f.read()
                try:
                    record = json.loads(tail)
                except ValueError:
                    record = None
                if record is not None:
                    self._apply_record_live(record)
                    with open(self.path, "ab") as f:
                        f.write(b"\n")  # terminate it for the next replay
                    self._log_offset = size + 1
                else:
                    warnings.warn(
                        f"durable log {self.path}: dead writer's torn "
                        f"mid-append tail ({size - self._log_offset} bytes) "
                        f"truncated at promotion",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    with open(self.path, "r+b") as f:
                        f.truncate(self._log_offset)
            if self._file is None:
                self._file = open(self.path, "a", encoding="utf-8")

    def _apply_record(self, record: dict) -> None:
        verb = record.get("verb")
        if verb == "register_crd":
            self._crds.add(record["name"])
            if record.get("definition"):
                self._crd_definitions[record["name"]] = record["definition"]
            return
        if verb == "unregister_crd":
            self._crds.discard(record["name"])
            self._crd_definitions.pop(record["name"], None)
            return
        # fall through to object records
        kind = record.get("kind")
        if kind not in _CODECS:
            return
        decode = _CODECS[kind][1]
        key = (record.get("ns", ""), record.get("name", ""))
        if verb == "delete":
            self._objects[kind].pop(key, None)
        elif verb in ("create", "update"):
            obj = decode(record["object"])
            if hasattr(obj, "resource_version"):
                # Fresh rv domain per process life; replayed order preserves
                # monotonicity.
                obj.resource_version = self._next_rv()
            self._objects[kind][key] = obj
        # No handler fires during replay: components subscribe only after
        # the backend is constructed (build_scheduler_app ordering).

    def compact(self) -> None:
        """Rewrite the log to one create per live object + the CRD registry
        (atomic via rename)."""
        tmp = self.path + ".tmp"
        # Same lock order as the mutation path (backend lock, then log lock).
        with self._lock, self._log_lock:
            with open(tmp, "w", encoding="utf-8") as f:
                for name in sorted(self._crds):
                    f.write(
                        json.dumps(
                            {
                                "verb": "register_crd",
                                "name": name,
                                **(
                                    {"definition": self._crd_definitions[name]}
                                    if name in self._crd_definitions
                                    else {}
                                ),
                            }
                        )
                        + "\n"
                    )
                for kind, (encode, _) in _CODECS.items():
                    for (ns, name), obj in sorted(self._objects[kind].items()):
                        f.write(
                            json.dumps(
                                {
                                    "verb": "create",
                                    "kind": kind,
                                    "ns": ns,
                                    "name": name,
                                    "object": encode(obj),
                                }
                            )
                            + "\n"
                        )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            # The snapshot subsumes any append-faulted parked records —
            # draining them after it would replay stale mutations.
            self._wal_pending.clear()
            if self._file is not None:
                self._file.close()
            self._file = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self.wal_flush()
        with self._log_lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    # -- logged mutations ----------------------------------------------------
    # WAL records are appended from _on_committed / _on_crd_committed, which
    # the base backend invokes INSIDE its mutation lock: log order therefore
    # equals commit order even with concurrent writers (request threads +
    # async write-back workers). Lock order is backend._lock -> _log_lock
    # everywhere, including compact().

    def _on_committed(self, kind: str, verb: str, obj: Any) -> None:
        if kind not in _CODECS:
            return
        if verb == "delete":
            ns, name = obj
            self._append({"verb": "delete", "kind": kind, "ns": ns, "name": name})
            return
        encode = _CODECS[kind][0]
        self._append(
            {
                "verb": verb,
                "kind": kind,
                "ns": getattr(obj, "namespace", ""),
                "name": obj.name,
                "object": encode(obj),
            }
        )

    def _on_crd_committed(self, verb: str, name: str, definition) -> None:
        self._append(
            {
                "verb": verb,
                "name": name,
                **({"definition": definition} if definition is not None else {}),
            }
        )
