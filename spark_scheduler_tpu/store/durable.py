"""File-backed durable ClusterBackend (the etcd slot).

In the reference, reservations/demands persist in etcd via CRDs — the CRDs
*are* the checkpoint (SURVEY.md §5.4): a restarted leader refills its cache
from the apiserver (cache/resourcereservations.go:53-60) and reconciles
drift from observed pods (failover.go:35-72). `DurableBackend` gives a
standalone deployment the same property without an apiserver: every
mutation appends one JSON-line record (k8s wire-shaped object payloads) to
a log; on startup the log replays into memory, after which the normal
failover reconciliation runs against real persisted state.

Record format (one JSON object per line):

    {"verb": "create|update|delete", "kind": "<collection>",
     "ns": "...", "name": "...", "object": {<k8s wire form>}}
    {"verb": "register_crd"|"unregister_crd", "name": "...",
     "definition": {...}}

`compact()` rewrites the log as one create per live object (the etcd
compaction analog) — callable any time; the scheduler also compacts on
startup after replay so the log stays bounded across restart cycles.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

from spark_scheduler_tpu.models.demands import Demand
from spark_scheduler_tpu.models.kube import Node, Pod
from spark_scheduler_tpu.models.reservations import ResourceReservation
from spark_scheduler_tpu.store.backend import InMemoryBackend


def _rr_to_record(rr: ResourceReservation) -> dict:
    from spark_scheduler_tpu.server.conversion import rr_v1beta2_to_wire

    wire = rr_v1beta2_to_wire(rr)
    # The ownerReference to the driver pod normally lives in ObjectMeta
    # (newResourceReservation sets it); models carry it as owner_pod_uid.
    if rr.owner_pod_uid and not wire["metadata"].get("ownerReferences"):
        wire["metadata"]["ownerReferences"] = [
            {"apiVersion": "v1", "kind": "Pod", "uid": rr.owner_pod_uid}
        ]
    return wire


def _rr_from_record(raw: dict) -> ResourceReservation:
    from spark_scheduler_tpu.server.conversion import rr_v1beta2_from_wire

    rr = rr_v1beta2_from_wire(raw)
    for ref in (raw.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("kind") == "Pod" and ref.get("uid"):
            rr.owner_pod_uid = ref["uid"]
            break
    return rr


def _demand_to_record(d: Demand) -> dict:
    from spark_scheduler_tpu.server.conversion import demand_v1alpha2_to_wire

    return demand_v1alpha2_to_wire(d)


def _demand_from_record(raw: dict) -> Demand:
    from spark_scheduler_tpu.server.conversion import demand_v1alpha2_from_wire

    return demand_v1alpha2_from_wire(raw)


def _pod_to_record(p: Pod) -> dict:
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s

    return pod_to_k8s(p)


def _pod_from_record(raw: dict) -> Pod:
    from spark_scheduler_tpu.server.kube_io import pod_from_k8s

    return pod_from_k8s(raw)


def _node_to_record(n: Node) -> dict:
    from spark_scheduler_tpu.server.kube_io import node_to_k8s

    return node_to_k8s(n)


def _node_from_record(raw: dict) -> Node:
    from spark_scheduler_tpu.server.kube_io import node_from_k8s

    return node_from_k8s(raw)


_CODECS = {
    "pods": (_pod_to_record, _pod_from_record),
    "nodes": (_node_to_record, _node_from_record),
    "resourcereservations": (_rr_to_record, _rr_from_record),
    "demands": (_demand_to_record, _demand_from_record),
}


class DurableBackend(InMemoryBackend):
    """InMemoryBackend + JSONL write-ahead persistence. Replays the log on
    construction (before any component subscribes, so no spurious events
    fire), then compacts it."""

    def __init__(self, path: str, fsync: bool = False, compact_on_load: bool = True):
        super().__init__()
        self.path = path
        self._fsync = fsync
        self._log_lock = threading.Lock()
        self._replaying = False
        self._file: Optional[Any] = None
        if os.path.exists(path):
            self._replay()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if compact_on_load:
            self.compact()
        else:
            self._file = open(self.path, "a", encoding="utf-8")

    # -- persistence plumbing ------------------------------------------------

    def _append(self, record: dict) -> None:
        if self._replaying:
            return
        with self._log_lock:
            if self._file is None:
                self._file = open(self.path, "a", encoding="utf-8")
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
            if self._fsync:
                os.fsync(self._file.fileno())

    def _replay(self) -> None:
        self._replaying = True
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail write from a crash — skip
                    self._apply_record(record)
        finally:
            self._replaying = False

    def _apply_record(self, record: dict) -> None:
        verb = record.get("verb")
        if verb == "register_crd":
            self._crds.add(record["name"])
            if record.get("definition"):
                self._crd_definitions[record["name"]] = record["definition"]
            return
        if verb == "unregister_crd":
            self._crds.discard(record["name"])
            self._crd_definitions.pop(record["name"], None)
            return
        # fall through to object records
        kind = record.get("kind")
        if kind not in _CODECS:
            return
        decode = _CODECS[kind][1]
        key = (record.get("ns", ""), record.get("name", ""))
        if verb == "delete":
            self._objects[kind].pop(key, None)
        elif verb in ("create", "update"):
            obj = decode(record["object"])
            if hasattr(obj, "resource_version"):
                # Fresh rv domain per process life; replayed order preserves
                # monotonicity.
                obj.resource_version = self._next_rv()
            self._objects[kind][key] = obj
        # No handler fires during replay: components subscribe only after
        # the backend is constructed (build_scheduler_app ordering).

    def compact(self) -> None:
        """Rewrite the log to one create per live object + the CRD registry
        (atomic via rename)."""
        tmp = self.path + ".tmp"
        # Same lock order as the mutation path (backend lock, then log lock).
        with self._lock, self._log_lock:
            with open(tmp, "w", encoding="utf-8") as f:
                for name in sorted(self._crds):
                    f.write(
                        json.dumps(
                            {
                                "verb": "register_crd",
                                "name": name,
                                **(
                                    {"definition": self._crd_definitions[name]}
                                    if name in self._crd_definitions
                                    else {}
                                ),
                            }
                        )
                        + "\n"
                    )
                for kind, (encode, _) in _CODECS.items():
                    for (ns, name), obj in sorted(self._objects[kind].items()):
                        f.write(
                            json.dumps(
                                {
                                    "verb": "create",
                                    "kind": kind,
                                    "ns": ns,
                                    "name": name,
                                    "object": encode(obj),
                                }
                            )
                            + "\n"
                        )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            if self._file is not None:
                self._file.close()
            self._file = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._log_lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    # -- logged mutations ----------------------------------------------------
    # WAL records are appended from _on_committed / _on_crd_committed, which
    # the base backend invokes INSIDE its mutation lock: log order therefore
    # equals commit order even with concurrent writers (request threads +
    # async write-back workers). Lock order is backend._lock -> _log_lock
    # everywhere, including compact().

    def _on_committed(self, kind: str, verb: str, obj: Any) -> None:
        if kind not in _CODECS:
            return
        if verb == "delete":
            ns, name = obj
            self._append({"verb": "delete", "kind": kind, "ns": ns, "name": name})
            return
        encode = _CODECS[kind][0]
        self._append(
            {
                "verb": verb,
                "kind": kind,
                "ns": getattr(obj, "namespace", ""),
                "name": obj.name,
                "object": encode(obj),
            }
        )

    def _on_crd_committed(self, verb: str, name: str, definition) -> None:
        self._append(
            {
                "verb": verb,
                "name": name,
                **({"definition": definition} if definition is not None else {}),
            }
        )
