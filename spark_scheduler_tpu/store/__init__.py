"""Durable-state layer: object store, sharded write queue, async write-back,
write-through caches, and the pluggable cluster backend (the framework's
"apiserver"). Rebuilds the reference's internal/cache + internal/cache/store."""

from spark_scheduler_tpu.store.object_store import ObjectStore  # noqa: F401
from spark_scheduler_tpu.store.queue import ShardedUniqueQueue, Request, RequestType  # noqa: F401
from spark_scheduler_tpu.store.backend import (  # noqa: F401
    ClusterBackend,
    InMemoryBackend,
    ConflictError,
    NotFoundError,
    AlreadyExistsError,
    NamespaceTerminatingError,
)
from spark_scheduler_tpu.store.cache import (  # noqa: F401
    WriteThroughCache,
    ResourceReservationCache,
    DemandCache,
    SafeDemandCache,
)
