"""Thread-safe object store with resource-version semantics.

Rebuilds internal/cache/store/store.go:26-130: a map keyed by (namespace,
name) whose writers are the cache owner (Put/PutIfAbsent/Delete) and whose
watch stream may only fast-forward resourceVersions of objects it already
holds (OverrideResourceVersionIfNewer) — external mutations never clobber
local pending state.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

Key = tuple[str, str]  # (namespace, name)


def obj_key(obj: Any) -> Key:
    return (obj.namespace, obj.name)


class ObjectStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: dict[Key, Any] = {}

    def put(self, obj: Any) -> None:
        with self._lock:
            self._store[obj_key(obj)] = obj

    def put_if_absent(self, obj: Any) -> bool:
        with self._lock:
            k = obj_key(obj)
            if k in self._store:
                return False
            self._store[k] = obj
            return True

    def override_resource_version_if_newer(self, obj: Any) -> None:
        """Apply a watch event: only bump the stored object's resourceVersion
        (store.go:96-118) — the cache owner is the sole writer of content."""
        with self._lock:
            cur = self._store.get(obj_key(obj))
            if cur is not None and obj.resource_version > cur.resource_version:
                cur.resource_version = obj.resource_version

    def get(self, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            return self._store.get((namespace, name))

    def delete(self, namespace: str, name: str) -> None:
        with self._lock:
            self._store.pop((namespace, name), None)

    def list(self) -> list[Any]:
        with self._lock:
            return list(self._store.values())

    def apply(self, namespace: str, name: str, fn: Callable[[Any], Any]) -> Optional[Any]:
        """Atomically read-modify-write one entry; fn gets the current object
        (or None) and returns the replacement (or None to leave unchanged)."""
        with self._lock:
            cur = self._store.get((namespace, name))
            new = fn(cur)
            if new is not None:
                self._store[(namespace, name)] = new
            return new

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)
