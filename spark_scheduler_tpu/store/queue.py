"""Sharded unique write queue.

Rebuilds internal/cache/store/queue.go:22-144: per-key dedup via an
"inflight" set (consecutive create/update requests for the same key are
compacted — the consumer reads the latest object from the store when it
drains), FNV-1a sharding so one key always drains on one consumer (write
ordering per object), bounded buffers with a non-blocking TryAdd variant.
Delete requests are never compacted into a prior create/update
(queue.go:58-62) so freshly-created objects still reach the backend.
"""

from __future__ import annotations

import dataclasses
import enum
import queue as _queue
import threading
from typing import Callable

Key = tuple[str, str]

QUEUE_BUFFER_SIZE = 100  # asyncRequestBufferSize, queue.go:22-27


class RequestType(enum.Enum):
    CREATE = "create"
    UPDATE = "update"
    DELETE = "delete"


@dataclasses.dataclass
class Request:
    key: Key
    type: RequestType
    retry_count: int = 0

    def with_increased_retry(self) -> "Request":
        return Request(self.key, self.type, self.retry_count + 1)


def _fnv1a_32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


class ShardedUniqueQueue:
    def __init__(self, buckets: int, buffer_size: int = QUEUE_BUFFER_SIZE):
        self._queues = [_queue.Queue(maxsize=buffer_size) for _ in range(buckets)]
        self._inflight: set[Key] = set()
        self._lock = threading.Lock()

    def _bucket(self, key: Key) -> int:
        return _fnv1a_32(f"{key[0]}/{key[1]}".encode()) % len(self._queues)

    def _add_inflight_if_absent(self, key: Key) -> bool:
        with self._lock:
            if key in self._inflight:
                return False
            self._inflight.add(key)
            return True

    def _release(self, req: Request) -> Request:
        """Consumers call this when taking a request: clears the inflight
        mark so later writes re-enqueue (queue.go:100-112)."""
        with self._lock:
            self._inflight.discard(req.key)
        return req

    def add_if_absent(self, req: Request) -> None:
        added = self._add_inflight_if_absent(req.key)
        if added or req.type == RequestType.DELETE:
            self._queues[self._bucket(req.key)].put(lambda: self._release(req))

    def try_add_if_absent(self, req: Request) -> bool:
        added = self._add_inflight_if_absent(req.key)
        if added or req.type == RequestType.DELETE:
            try:
                self._queues[self._bucket(req.key)].put_nowait(
                    lambda: self._release(req)
                )
                return True
            except _queue.Full:
                if added:
                    with self._lock:
                        self._inflight.discard(req.key)
                return False
        return True

    def consumers(self) -> list[_queue.Queue]:
        return self._queues

    def queue_lengths(self) -> list[int]:
        return [q.qsize() for q in self._queues]

    @property
    def num_buckets(self) -> int:
        return len(self._queues)

    def pop(self, bucket: int, timeout_s: float | None) -> Request | None:
        """Consumer-side take (same surface as the native queue): one request
        from shard `bucket`, or None on timeout/empty."""
        return drain_one(self._queues[bucket], timeout=timeout_s)


def drain_one(q: _queue.Queue, timeout: float | None = None) -> Request | None:
    """Take one request thunk off a consumer queue (returns None on timeout)."""
    try:
        if timeout == 0:
            thunk: Callable[[], Request] = q.get_nowait()
        else:
            thunk = q.get(timeout=timeout)
    except _queue.Empty:
        return None
    return thunk()


def make_sharded_queue(
    buckets: int,
    buffer_size: int = QUEUE_BUFFER_SIZE,
    prefer_native: bool = True,
):
    """Native C++ queue when the runtime library is available (the default),
    else the pure-Python implementation. Both expose add_if_absent /
    try_add_if_absent / pop / queue_lengths / num_buckets."""
    if prefer_native:
        from spark_scheduler_tpu import native

        if native.available():
            return native.NativeShardedQueue(buckets, buffer_size)
    return ShardedUniqueQueue(buckets, buffer_size)
