"""Async write-back workers.

Rebuilds internal/cache/async.go:44-224: N worker threads (one per queue
shard) drain write requests and replay them against the backend. Create and
update read the CURRENT object from the local store at drain time (so
compacted consecutive writes collapse into one request carrying the latest
state); conflicts re-read the backend object, fast-forward the stored
resourceVersion and retry; failures retry up to `max_retries` then drop with
a metric. Creates into terminating namespaces are dropped (async.go:88-96);
deletes of already-gone objects succeed.

ISSUE 9 replaced the bare retry count with the shared retry ladder: a
RetryPolicy computes each requeue's backoff (exponential + full jitter,
slept by the background worker — never by drain_sync, whose callers need
deterministic inline drains), a CircuitBreaker fails background writes
fast while the backend is down (a refused request requeues WITHOUT
consuming its retry budget, so nothing is lost — the backend just stops
being hammered; drain_sync bypasses the gate), and `fault_hook` is the
FaultInjector's seam over every drained write (`kube.write.<verb>`).
`max_retries` / `async_client_retry_count` keep working as the attempt
budget: they are the policy's max_attempts minus one.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from spark_scheduler_tpu.faults.retry import CircuitBreaker, RetryPolicy
from spark_scheduler_tpu.store.backend import (
    AlreadyExistsError,
    ClusterBackend,
    ConflictError,
    NamespaceTerminatingError,
    NotFoundError,
)
from spark_scheduler_tpu.store.object_store import ObjectStore
from spark_scheduler_tpu.store.queue import Request, RequestType, ShardedUniqueQueue

DEFAULT_MAX_RETRIES = 5  # config.go:72-77

# Write-back backoff defaults: short base (a conflict storm resolves in
# milliseconds), capped well under the reservation-GC horizon.
DEFAULT_RETRY_POLICY = RetryPolicy(
    max_attempts=DEFAULT_MAX_RETRIES + 1,
    base_delay_s=0.02,
    multiplier=2.0,
    max_delay_s=2.0,
)


class AsyncClientMetrics:
    """Counters mirroring AsyncClientMetrics (async.go:180-224)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.applied: dict[str, int] = {}
        self.retries = 0
        self.dropped = 0
        self.conflicts = 0

    def mark_applied(self, verb: str) -> None:
        with self.lock:
            self.applied[verb] = self.applied.get(verb, 0) + 1

    def mark_retry(self) -> None:
        with self.lock:
            self.retries += 1

    def mark_dropped(self) -> None:
        with self.lock:
            self.dropped += 1

    def mark_conflict(self) -> None:
        with self.lock:
            self.conflicts += 1


class AsyncClient:
    """Write-back pump between an ObjectStore and a backend kind."""

    def __init__(
        self,
        backend: ClusterBackend,
        kind: str,
        store: ObjectStore,
        queue: ShardedUniqueQueue,
        max_retries: int = DEFAULT_MAX_RETRIES,
        metrics: Optional[AsyncClientMetrics] = None,
        on_error: Optional[Callable[[Request, Exception], None]] = None,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        on_retry: Optional[Callable[[int, float], None]] = None,
    ):
        self._backend = backend
        self._kind = kind
        self._store = store
        self._queue = queue
        self._max_retries = max_retries
        # `max_retries` stays the attempt budget (back-compat alias for
        # `async-client-retry-count`); the policy supplies the DELAYS.
        self._retry_policy = retry_policy or DEFAULT_RETRY_POLICY
        self._breaker = breaker
        self._on_retry = on_retry  # fn(retry_count, backoff_s) — telemetry
        self.metrics = metrics or AsyncClientMetrics()
        self._on_error = on_error
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # FaultInjector seam: fn(request) fired on every drained write
        # BEFORE it reaches the backend (the kube client failing, not the
        # apiserver); raising routes into the retry ladder.
        self.fault_hook: Optional[Callable[[Request], None]] = None

    def set_max_retries(self, n: int) -> None:
        """Live retry-budget change (runtime config reload). Read by workers
        without a lock: int assignment is atomic, and an in-flight request
        observing either budget is acceptable."""
        self._max_retries = int(n)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for bucket in range(self._queue.num_buckets):
            t = threading.Thread(
                target=self._run_worker, args=(bucket,), daemon=True,
                name=f"async-{self._kind}-{bucket}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        """Signal workers and join them. Joining matters for the native
        queue backend: destroying the C++ queue while a worker is blocked in
        queue_pop would free the shard mutex under a waiter; the 0.05s pop
        timeout bounds the join."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = [t for t in self._threads if t.is_alive()]

    def _run_worker(self, bucket: int) -> None:
        while not self._stop.is_set():
            req = self._queue.pop(bucket, timeout_s=0.05)
            if req is not None:
                self.process(req, allow_backoff=True)

    def drain_sync(self) -> None:
        """Synchronously drain every shard — deterministic test mode and
        graceful-shutdown flush."""
        for bucket in range(self._queue.num_buckets):
            while True:
                req = self._queue.pop(bucket, timeout_s=0)
                if req is None:
                    break
                self.process(req)

    # -- request processing -------------------------------------------------

    def process(self, req: Request, allow_backoff: bool = False) -> None:
        from spark_scheduler_tpu.faults.errors import BreakerOpenError
        from spark_scheduler_tpu.tracing import tracer

        with tracer().span(
            "write-back",
            verb=req.type.name.lower(),
            key=f"{req.key[0]}/{req.key[1]}",
        ):
            breaker = self._breaker
            try:
                if (
                    breaker is not None
                    and allow_backoff
                    and not breaker.allow()
                ):
                    # Backend known-down: fail fast into the requeue
                    # instead of another doomed round-trip. Background
                    # path only — drain_sync needs inline determinism
                    # (and termination), so it always attempts the call.
                    raise BreakerOpenError(breaker.name or self._kind)
                if self.fault_hook is not None:
                    self.fault_hook(req)
                if req.type == RequestType.CREATE:
                    self._do_create(req)
                elif req.type == RequestType.UPDATE:
                    self._do_update(req)
                else:
                    self._do_delete(req)
            except NamespaceTerminatingError:
                self.metrics.mark_dropped()  # not retryable (async.go:88-96)
                if breaker is not None:
                    # The backend ANSWERED — this is a healthy dependency
                    # refusing one request, and it must release a
                    # half-open probe slot or the breaker wedges open.
                    breaker.on_success()
            except BreakerOpenError:
                # The refusal is the breaker's state, not this request's
                # failure: requeue WITHOUT consuming retry budget (the
                # 5-step ladder exhausts in well under reset_timeout, so
                # burning it here would drop every write queued while
                # the breaker is open) and wait out the policy backoff.
                self.metrics.mark_retry()
                pause = self._retry_policy.delay(req.retry_count)
                if self._on_retry is not None:
                    self._on_retry(req.retry_count + 1, pause)
                if pause > 0:
                    self._stop.wait(pause)
                self._queue.add_if_absent(req)
            except Exception as exc:  # bounded retry (async.go:139-154)
                if breaker is not None:
                    breaker.on_failure()
                self._maybe_retry(req, exc, allow_backoff)
            else:
                if breaker is not None:
                    breaker.on_success()

    def _do_create(self, req: Request) -> None:
        obj = self._store.get(*req.key)
        if obj is None:
            return  # deleted since enqueue
        try:
            created = self._backend.create(self._kind, obj)
        except AlreadyExistsError:
            latest = self._backend.get(self._kind, *req.key)
            if latest is not None:
                self._store.override_resource_version_if_newer(latest)
            self.metrics.mark_applied("create")
            return
        self._store.override_resource_version_if_newer(created)
        self.metrics.mark_applied("create")

    def _do_update(self, req: Request) -> None:
        obj = self._store.get(*req.key)
        if obj is None:
            return
        try:
            updated = self._backend.update(self._kind, obj)
        except ConflictError:
            self.metrics.mark_conflict()
            latest = self._backend.get(self._kind, *req.key)
            if latest is not None:
                # fast-forward and retry with the new resourceVersion
                self._store.override_resource_version_if_newer(latest)
            raise
        except NotFoundError:
            # object vanished server-side; recreate it (lost-write recovery)
            created = self._backend.create(self._kind, obj)
            self._store.override_resource_version_if_newer(created)
            self.metrics.mark_applied("update")
            return
        self._store.override_resource_version_if_newer(updated)
        self.metrics.mark_applied("update")

    def _do_delete(self, req: Request) -> None:
        try:
            self._backend.delete(self._kind, *req.key)
        except NotFoundError:
            pass  # already gone — success
        self.metrics.mark_applied("delete")

    def _maybe_retry(
        self, req: Request, exc: Exception, allow_backoff: bool = False
    ) -> None:
        if req.retry_count < self._max_retries:
            self.metrics.mark_retry()
            pause = self._retry_policy.delay(req.retry_count)
            if self._on_retry is not None:
                self._on_retry(req.retry_count + 1, pause)
            if allow_backoff and pause > 0:
                # Background worker only: the requeue waits out the
                # backoff (interruptible by stop()) so a failing backend
                # is probed at the policy's cadence, not the pop loop's.
                # drain_sync callers need inline determinism and skip it.
                self._stop.wait(pause)
            self._queue.add_if_absent(req.with_increased_retry())
        else:
            self.metrics.mark_dropped()
            if self._on_error is not None:
                self._on_error(req, exc)
