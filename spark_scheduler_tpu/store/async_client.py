"""Async write-back workers.

Rebuilds internal/cache/async.go:44-224: N worker threads (one per queue
shard) drain write requests and replay them against the backend. Create and
update read the CURRENT object from the local store at drain time (so
compacted consecutive writes collapse into one request carrying the latest
state); conflicts re-read the backend object, fast-forward the stored
resourceVersion and retry; failures retry up to `max_retries` then drop with
a metric. Creates into terminating namespaces are dropped (async.go:88-96);
deletes of already-gone objects succeed.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from spark_scheduler_tpu.store.backend import (
    AlreadyExistsError,
    ClusterBackend,
    ConflictError,
    NamespaceTerminatingError,
    NotFoundError,
)
from spark_scheduler_tpu.store.object_store import ObjectStore
from spark_scheduler_tpu.store.queue import Request, RequestType, ShardedUniqueQueue

DEFAULT_MAX_RETRIES = 5  # config.go:72-77


class AsyncClientMetrics:
    """Counters mirroring AsyncClientMetrics (async.go:180-224)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.applied: dict[str, int] = {}
        self.retries = 0
        self.dropped = 0
        self.conflicts = 0

    def mark_applied(self, verb: str) -> None:
        with self.lock:
            self.applied[verb] = self.applied.get(verb, 0) + 1

    def mark_retry(self) -> None:
        with self.lock:
            self.retries += 1

    def mark_dropped(self) -> None:
        with self.lock:
            self.dropped += 1

    def mark_conflict(self) -> None:
        with self.lock:
            self.conflicts += 1


class AsyncClient:
    """Write-back pump between an ObjectStore and a backend kind."""

    def __init__(
        self,
        backend: ClusterBackend,
        kind: str,
        store: ObjectStore,
        queue: ShardedUniqueQueue,
        max_retries: int = DEFAULT_MAX_RETRIES,
        metrics: Optional[AsyncClientMetrics] = None,
        on_error: Optional[Callable[[Request, Exception], None]] = None,
    ):
        self._backend = backend
        self._kind = kind
        self._store = store
        self._queue = queue
        self._max_retries = max_retries
        self.metrics = metrics or AsyncClientMetrics()
        self._on_error = on_error
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def set_max_retries(self, n: int) -> None:
        """Live retry-budget change (runtime config reload). Read by workers
        without a lock: int assignment is atomic, and an in-flight request
        observing either budget is acceptable."""
        self._max_retries = int(n)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for bucket in range(self._queue.num_buckets):
            t = threading.Thread(
                target=self._run_worker, args=(bucket,), daemon=True,
                name=f"async-{self._kind}-{bucket}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        """Signal workers and join them. Joining matters for the native
        queue backend: destroying the C++ queue while a worker is blocked in
        queue_pop would free the shard mutex under a waiter; the 0.05s pop
        timeout bounds the join."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = [t for t in self._threads if t.is_alive()]

    def _run_worker(self, bucket: int) -> None:
        while not self._stop.is_set():
            req = self._queue.pop(bucket, timeout_s=0.05)
            if req is not None:
                self.process(req)

    def drain_sync(self) -> None:
        """Synchronously drain every shard — deterministic test mode and
        graceful-shutdown flush."""
        for bucket in range(self._queue.num_buckets):
            while True:
                req = self._queue.pop(bucket, timeout_s=0)
                if req is None:
                    break
                self.process(req)

    # -- request processing -------------------------------------------------

    def process(self, req: Request) -> None:
        from spark_scheduler_tpu.tracing import tracer

        with tracer().span(
            "write-back",
            verb=req.type.name.lower(),
            key=f"{req.key[0]}/{req.key[1]}",
        ):
            try:
                if req.type == RequestType.CREATE:
                    self._do_create(req)
                elif req.type == RequestType.UPDATE:
                    self._do_update(req)
                else:
                    self._do_delete(req)
            except NamespaceTerminatingError:
                self.metrics.mark_dropped()  # not retryable (async.go:88-96)
            except Exception as exc:  # bounded retry (async.go:139-154)
                self._maybe_retry(req, exc)

    def _do_create(self, req: Request) -> None:
        obj = self._store.get(*req.key)
        if obj is None:
            return  # deleted since enqueue
        try:
            created = self._backend.create(self._kind, obj)
        except AlreadyExistsError:
            latest = self._backend.get(self._kind, *req.key)
            if latest is not None:
                self._store.override_resource_version_if_newer(latest)
            self.metrics.mark_applied("create")
            return
        self._store.override_resource_version_if_newer(created)
        self.metrics.mark_applied("create")

    def _do_update(self, req: Request) -> None:
        obj = self._store.get(*req.key)
        if obj is None:
            return
        try:
            updated = self._backend.update(self._kind, obj)
        except ConflictError:
            self.metrics.mark_conflict()
            latest = self._backend.get(self._kind, *req.key)
            if latest is not None:
                # fast-forward and retry with the new resourceVersion
                self._store.override_resource_version_if_newer(latest)
            raise
        except NotFoundError:
            # object vanished server-side; recreate it (lost-write recovery)
            created = self._backend.create(self._kind, obj)
            self._store.override_resource_version_if_newer(created)
            self.metrics.mark_applied("update")
            return
        self._store.override_resource_version_if_newer(updated)
        self.metrics.mark_applied("update")

    def _do_delete(self, req: Request) -> None:
        try:
            self._backend.delete(self._kind, *req.key)
        except NotFoundError:
            pass  # already gone — success
        self.metrics.mark_applied("delete")

    def _maybe_retry(self, req: Request, exc: Exception) -> None:
        if req.retry_count < self._max_retries:
            self.metrics.mark_retry()
            self._queue.add_if_absent(req.with_increased_retry())
        else:
            self.metrics.mark_dropped()
            if self._on_error is not None:
                self._on_error(req, exc)
