"""Pluggable cluster backend — the framework's "apiserver".

The reference talks to a real Kubernetes apiserver through typed clientsets
and informers (SURVEY.md §2c); its tests swap in fake in-memory clientsets
(extendertest harness). This framework makes that boundary explicit: every
control-plane component takes a `ClusterBackend`, which provides

  - CRUD with optimistic concurrency (resourceVersion conflict on update,
    already-exists on create, not-found on delete) for four kinds:
    pods, nodes, resource reservations, demands;
  - informer-style event subscription (add/update/delete callbacks fired
    synchronously after each mutation);
  - CRD registry (the Demand CRD may not exist yet — SafeDemandCache gates
    on it, internal/cache/safedemands.go:91);
  - namespace-termination simulation (async write-back gives up on writes
    into terminating namespaces, internal/cache/async.go:88-96).

`InMemoryBackend` is both the test harness backend and the state engine for
standalone deployments; a k8s-REST adapter can implement the same interface.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional

from spark_scheduler_tpu.models.kube import Node, Pod


class BackendError(Exception):
    pass


class ConflictError(BackendError):
    """resourceVersion mismatch on update (async.go:111-120 retry path)."""


class NotFoundError(BackendError):
    pass


class AlreadyExistsError(BackendError):
    pass


class NamespaceTerminatingError(BackendError):
    """Create into a terminating namespace — not retryable (async.go:88-96)."""


class _Handlers:
    def __init__(self):
        self.add: list[Callable[[Any], None]] = []
        self.update: list[Callable[[Any, Any], None]] = []
        self.delete: list[Callable[[Any], None]] = []


KINDS = ("pods", "nodes", "resourcereservations", "demands", "leases")

DEMAND_CRD = "demands.scaler.palantir.com"
RESERVATION_CRD = "resourcereservations.sparkscheduler.palantir.com"


class ClusterBackend:
    """Interface; see InMemoryBackend for semantics."""


class InMemoryBackend(ClusterBackend):
    def __init__(self):
        self._lock = threading.RLock()
        self._objects: dict[str, dict[tuple[str, str], Any]] = {k: {} for k in KINDS}
        self._handlers: dict[str, _Handlers] = {k: _Handlers() for k in KINDS}
        self._rv_counter = 0
        # Bumped on every NODE add/update/delete: lets serving-path
        # consumers (domain caches, the solver's arena sync) skip O(nodes)
        # re-walks between requests when the topology hasn't changed.
        self.nodes_version = 0
        self._crds: set[str] = {RESERVATION_CRD}
        # Full CRD manifests (openAPI schemas etc.) keyed by CRD name; the
        # reference ships complete CustomResourceDefinition objects
        # (crd_resource_reservation.go:83-115), not just names.
        self._crd_definitions: dict[str, dict] = {}
        self.terminating_namespaces: set[str] = set()
        # Write fault injection for tests: fn(kind, verb, obj) -> Exception | None
        self.fault_injector: Optional[Callable[[str, str, Any], Optional[Exception]]] = None
        # Incrementally-maintained pod indexes by label key — the informer
        # indexer slot (the reference's clientsets list pods through indexed
        # informer caches, never by scanning every pod). Registered lazily
        # by consumers (SparkPodLister); list_pods uses them when the filter
        # carries an indexed key.
        self._pod_indexes: dict[str, dict[str, dict[tuple[str, str], Pod]]] = {}

    # -- CRDs ---------------------------------------------------------------

    def _on_committed(self, kind: str, verb: str, obj: Any) -> None:
        """Hook invoked INSIDE the mutation lock, after the store changed
        but before the lock releases. DurableBackend appends its WAL record
        here so the log order cannot diverge from commit order under
        concurrent writers (request threads + async write-back workers)."""

    def _on_crd_committed(self, verb: str, name: str, definition) -> None:
        """CRD-registry twin of _on_committed (also inside the lock)."""

    def register_crd(self, name: str, definition: Optional[dict] = None) -> None:
        """Create-or-upgrade: re-registering an existing CRD replaces its
        definition (the reference's EnsureResourceReservationsCRD update
        path, crd/utils.go:98-133)."""
        with self._lock:
            self._crds.add(name)
            if definition is not None:
                self._crd_definitions[name] = definition
            self._on_crd_committed("register_crd", name, definition)

    def crd_exists(self, name: str) -> bool:
        with self._lock:
            return name in self._crds

    def get_crd_definition(self, name: str) -> Optional[dict]:
        with self._lock:
            return self._crd_definitions.get(name)

    def unregister_crd(self, name: str) -> None:
        """Delete-on-failed-verify path (crd/utils.go:134-149)."""
        with self._lock:
            self._crds.discard(name)
            self._crd_definitions.pop(name, None)
            self._on_crd_committed("unregister_crd", name, None)

    # -- event subscription -------------------------------------------------

    def subscribe(
        self,
        kind: str,
        on_add: Callable[[Any], None] | None = None,
        on_update: Callable[[Any, Any], None] | None = None,
        on_delete: Callable[[Any], None] | None = None,
    ) -> None:
        h = self._handlers[kind]
        if on_add:
            h.add.append(on_add)
        if on_update:
            h.update.append(on_update)
        if on_delete:
            h.delete.append(on_delete)

    def _fire(self, kind: str, event: str, *args) -> None:
        h = self._handlers[kind]
        for cb in getattr(h, event):
            cb(*args)

    # -- generic CRUD -------------------------------------------------------

    @staticmethod
    def _key(obj: Any) -> tuple[str, str]:
        return (getattr(obj, "namespace", ""), obj.name)

    def _next_rv(self) -> int:
        self._rv_counter += 1
        return self._rv_counter

    def _check_fault(self, kind: str, verb: str, obj: Any) -> None:
        if self.fault_injector is not None:
            exc = self.fault_injector(kind, verb, obj)
            if exc is not None:
                raise exc

    def create(self, kind: str, obj: Any) -> Any:
        with self._lock:
            self._check_fault(kind, "create", obj)
            ns = getattr(obj, "namespace", "")
            if ns in self.terminating_namespaces:
                raise NamespaceTerminatingError(ns)
            k = self._key(obj)
            if k in self._objects[kind]:
                raise AlreadyExistsError(f"{kind} {k}")
            if hasattr(obj, "resource_version"):
                obj.resource_version = self._next_rv()
            self._objects[kind][k] = obj
            if kind == "pods":
                self._pod_index_add(obj)
            elif kind == "nodes":
                self.nodes_version += 1
            self._on_committed(kind, "create", obj)
        self._fire(kind, "add", obj)
        return obj

    def update(self, kind: str, obj: Any) -> Any:
        with self._lock:
            self._check_fault(kind, "update", obj)
            k = self._key(obj)
            cur = self._objects[kind].get(k)
            if cur is None:
                raise NotFoundError(f"{kind} {k}")
            if hasattr(obj, "resource_version") and hasattr(cur, "resource_version"):
                if obj.resource_version != cur.resource_version:
                    raise ConflictError(
                        f"{kind} {k}: rv {obj.resource_version} != {cur.resource_version}"
                    )
                obj.resource_version = self._next_rv()
            old = cur
            self._objects[kind][k] = obj
            if kind == "pods":
                self._pod_index_remove(old)
                self._pod_index_add(obj)
            elif kind == "nodes":
                self.nodes_version += 1
            self._on_committed(kind, "update", obj)
        self._fire(kind, "update", old, obj)
        return obj

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            self._check_fault(kind, "delete", (namespace, name))
            cur = self._objects[kind].pop((namespace, name), None)
            if cur is None:
                raise NotFoundError(f"{kind} {(namespace, name)}")
            if kind == "pods":
                self._pod_index_remove(cur)
            elif kind == "nodes":
                self.nodes_version += 1
            self._on_committed(kind, "delete", (namespace, name))
        self._fire(kind, "delete", cur)

    def get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            return self._objects[kind].get((namespace, name))

    def list(self, kind: str) -> list[Any]:
        with self._lock:
            return list(self._objects[kind].values())

    # -- typed conveniences -------------------------------------------------

    def add_node(self, node: Node) -> Node:
        return self.create("nodes", node)

    def get_node(self, name: str) -> Optional[Node]:
        return self.get("nodes", "", name)

    def list_nodes(self) -> list[Node]:
        return self.list("nodes")

    def add_pod(self, pod: Pod) -> Pod:
        return self.create("pods", pod)

    def update_pod(self, pod: Pod) -> Pod:
        return self.update("pods", pod)

    def delete_pod(self, pod: Pod) -> None:
        self.delete("pods", pod.namespace, pod.name)

    def register_pod_index(self, label_key: str) -> None:
        """Maintain a pods-by-label-value index for `label_key`; list_pods
        filters carrying that key then touch only the matching bucket
        instead of scanning every pod (informer-indexer semantics)."""
        with self._lock:
            if label_key in self._pod_indexes:
                return
            idx: dict[str, dict[tuple[str, str], Pod]] = {}
            for k, p in self._objects["pods"].items():
                v = p.labels.get(label_key)
                if v is not None:
                    idx.setdefault(v, {})[k] = p
            self._pod_indexes[label_key] = idx

    def _pod_index_add(self, pod: Pod) -> None:
        k = self._key(pod)
        for label_key, idx in self._pod_indexes.items():
            v = pod.labels.get(label_key)
            if v is not None:
                idx.setdefault(v, {})[k] = pod

    def _pod_index_remove(self, pod: Pod) -> None:
        k = self._key(pod)
        for label_key, idx in self._pod_indexes.items():
            v = pod.labels.get(label_key)
            if v is not None:
                bucket = idx.get(v)
                if bucket is not None:
                    bucket.pop(k, None)
                    if not bucket:
                        idx.pop(v, None)

    def list_pods(
        self,
        namespace: str | None = None,
        labels: dict[str, str] | None = None,
    ) -> list[Pod]:
        with self._lock:
            pods: Iterable[Pod] = None  # type: ignore[assignment]
            if labels:
                for k in labels:
                    idx = self._pod_indexes.get(k)
                    if idx is not None:
                        pods = idx.get(labels[k], {}).values()
                        break
            if pods is None:
                pods = self._objects["pods"].values()
            out = []
            for p in pods:
                if namespace is not None and p.namespace != namespace:
                    continue
                if labels and any(p.labels.get(k) != v for k, v in labels.items()):
                    continue
                out.append(p)
            return out

    def bind_pod(self, pod: Pod, node_name: str, phase: str = "Running") -> Pod:
        """Simulate kube-scheduler binding + kubelet running the pod — the
        harness's Schedule write-back (extender_test_utils.go:176-190)."""
        with self._lock:
            cur = self._objects["pods"].get((pod.namespace, pod.name))
            if cur is None:
                raise NotFoundError(pod.name)
            old = Pod(**{f.name: getattr(cur, f.name) for f in cur.__dataclass_fields__.values()})  # type: ignore[attr-defined]
            cur.node_name = node_name
            cur.phase = phase
            self._on_committed("pods", "update", cur)
        self._fire("pods", "update", old, cur)
        return cur
