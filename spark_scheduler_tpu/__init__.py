"""spark_scheduler_tpu — a TPU-native gang-scheduling framework.

A ground-up rebuild of the capabilities of Palantir's `k8s-spark-scheduler`
(reference: /root/reference, a Go kube-scheduler extender) as a TPU-first
framework: the combinatorial core — gang fit-checking and driver/executor
bin-packing over the cluster free-resource matrix — is a batched, vectorized
placement solver built on JAX/XLA, holding cluster state as device-resident
tensors and scoring many pending applications per kernel invocation.

Package layout (see each subpackage's docstring for its reference mapping):
  models/    domain state: resource algebra, cluster-state tensors, Spark app
             shapes, ResourceReservation / Demand records (CRD equivalents).
  ops/       XLA compute kernels: node-capacity, the five bin-packing
             strategies, node-priority sorting, packing efficiency.
  core/      the gang-admission engine (the reference's `internal/extender`):
             predicate entry, reservation manager, soft reservations,
             overhead, demands, failover reconciliation.
  store/     object store, sharded dedup queue, async write-back client,
             write-through caches (the reference's `internal/cache`).
  server/    install config + dependency wiring + serving layer.
  testing/   the component-test harness (the reference's extendertest).
"""

__version__ = "0.1.0"
