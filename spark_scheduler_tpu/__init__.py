"""spark_scheduler_tpu — a TPU-native gang-scheduling framework.

A ground-up rebuild of the capabilities of Palantir's `k8s-spark-scheduler`
(reference: /root/reference, a Go kube-scheduler extender) as a TPU-first
framework: the combinatorial core — gang fit-checking and driver/executor
bin-packing over the cluster free-resource matrix — is a batched, vectorized
placement solver built on JAX/XLA, holding cluster state as device-resident
tensors and scoring many pending applications per kernel invocation.

Package layout:
  models/    domain state: resource algebra, cluster-state tensors, Spark app
             shapes, ResourceReservation / Demand records (CRD equivalents).
  ops/       XLA compute kernels: node-capacity, the five bin-packing
             strategies, node-priority sorting, packing efficiency, batched
             FIFO gang admission.
  parallel/  multi-chip sharding: mesh construction and the shard_map'd
             node-sharded solver (ICI/DCN collectives via XLA).
  core/      the gang-admission engine (the reference's `internal/extender`):
             predicate entry, reservation manager, soft reservations,
             overhead, demands, failover reconciliation.
  store/     object store, sharded dedup queue, async write-back client,
             write-through caches (the reference's `internal/cache`).
  server/    extender-protocol HTTP front-end, config, wiring.
  metrics/   metric registry + reporters (foundry.spark.scheduler.* parity).
  utils/     pod/demand helpers, sets, instance-group extraction.
"""

__version__ = "0.1.0"
