"""Candidate-pruning A/B (the two-tier solve): window service time and
per-window h2d bytes, pruned vs full, at 10k and 100k nodes.

Drives the SOLVER's pipelined window path directly (build_tensors_pipelined
-> pack_window_dispatch -> pack_window_fetch, serialized per window so the
measurement is service time, not pipeline overlap) over a seeded workload
of serving windows with usage churn between windows. Three arms per node
count: full (prune off) and pruned at each swept `prune-slack`; pruned
decisions are ASSERTED byte-identical to the full arm's (the certificate's
escalation path makes that unconditional — a mismatch is a bug, and this
bench aborts on it). Certificate-escalation rate is reported per arm.

One JSON line per (nodes, arm) on stdout; standalone:
    python hack/prune_bench.py
Env: PRUNE_BENCH_NODES="10000,100000"  PRUNE_BENCH_SLACKS="1.5,3.0"
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import json
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

import numpy as np

WINDOWS = {10_000: 14, 100_000: 6}
REQS_PER_WINDOW = 8
EXECS = 3


def _nodes(n):
    from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
    from spark_scheduler_tpu.models.resources import Resources

    alloc = Resources.from_quantities("8", "8Gi", "1", round_up=False)
    return [
        Node(
            name=f"pb-n{i:06d}",
            allocatable=alloc,
            labels={ZONE_LABEL: f"z{i % 4}"},
        )
        for i in range(n)
    ]


def _workload(rng, names, n_windows):
    """Seeded windows + per-window usage churn, identical across arms."""
    from spark_scheduler_tpu.core.solver import WindowRequest
    from spark_scheduler_tpu.models.resources import Resources

    one = Resources.from_quantities("1", "1Gi")
    two = Resources.from_quantities("2", "2Gi")
    windows, usages = [], []
    for _ in range(n_windows):
        reqs = []
        for _ in range(REQS_PER_WINDOW):
            res = two if rng.random() < 0.3 else one
            reqs.append(
                WindowRequest(
                    rows=[(res, one, int(rng.integers(1, EXECS + 1)), False)],
                    driver_candidate_names=names,
                )
            )
        windows.append(reqs)
        usage = {}
        for i in rng.choice(len(names), size=24, replace=False):
            usage[names[i]] = Resources.from_quantities(
                str(int(rng.integers(1, 4))), "1Gi"
            )
        usages.append(usage)
    return windows, usages


def run_arm(nodes, windows, usages, *, top_k, slack):
    from spark_scheduler_tpu.core.solver import PlacementSolver
    from spark_scheduler_tpu.observability.telemetry import (
        TRANSFER_BYTES,
        SolverTelemetry,
    )

    solver = PlacementSolver(prune_top_k=top_k, prune_slack=slack)
    solver.telemetry = SolverTelemetry(None)
    h2d = solver.telemetry.registry.counter(TRANSFER_BYTES, direction="h2d")

    # Warmup window (compiles + cold featurize) outside the clock.
    t = solver.build_tensors_pipelined(nodes, {}, {})
    solver.pack_window_fetch(
        solver.pack_window_dispatch("tightly-pack", t, windows[0])
    )
    solver.discard_pipeline()

    times_ms, decisions = [], []
    h2d_start = h2d.value
    for usage, win in zip(usages, windows):
        t0 = time.perf_counter()
        t = solver.build_tensors_pipelined(nodes, usage, {})
        h = solver.pack_window_dispatch("tightly-pack", t, win)
        decs = solver.pack_window_fetch(h)
        times_ms.append((time.perf_counter() - t0) * 1e3)
        decisions.append(
            tuple(
                (
                    d.admitted,
                    d.packing.driver_node,
                    tuple(d.packing.executor_nodes),
                )
                for d in decs
            )
        )
    st = dict(solver.prune_stats)
    return {
        "window_p50_ms": round(float(np.percentile(times_ms, 50)), 2),
        "window_mean_ms": round(float(np.mean(times_ms)), 2),
        "h2d_bytes_per_window": int(
            (h2d.value - h2d_start) / max(1, len(windows))
        ),
        "windows": len(windows),
        "pruned_windows": st["windows"],
        "prune_escalations": st["escalations"],
        "escalation_rate": round(
            st["escalations"] / st["windows"], 4
        ) if st["windows"] else 0.0,
        "escalation_reasons": st["reasons"],
        "kept_rows_per_window": round(
            st["kept_rows"] / st["windows"], 1
        ) if st["windows"] else None,
        "window_path_counts": dict(solver.window_path_counts),
    }, decisions


def main() -> None:
    node_counts = [
        int(x)
        for x in os.environ.get(
            "PRUNE_BENCH_NODES", "10000,100000"
        ).split(",")
    ]
    slacks = [
        float(x)
        for x in os.environ.get("PRUNE_BENCH_SLACKS", "1.5,3.0").split(",")
    ]
    for n in node_counts:
        nodes = _nodes(n)
        names = [nd.name for nd in nodes]
        rng = np.random.default_rng(1234 + n)
        windows, usages = _workload(rng, names, WINDOWS.get(n, 8))

        full_stats, full_decs = run_arm(
            nodes, windows, usages, top_k=0, slack=2.0
        )
        print(
            json.dumps({"nodes": n, "arm": "full", **full_stats}),
            flush=True,
        )
        for slack in slacks:
            st, decs = run_arm(
                nodes, windows, usages, top_k=16, slack=slack
            )
            assert decs == full_decs, (
                f"pruned decisions diverged from full at {n} nodes, "
                f"slack {slack}"
            )
            speedup = (
                full_stats["window_p50_ms"] / st["window_p50_ms"]
                if st["window_p50_ms"]
                else 0.0
            )
            h2d_shrink = (
                full_stats["h2d_bytes_per_window"]
                / max(1, st["h2d_bytes_per_window"])
            )
            print(
                json.dumps(
                    {
                        "nodes": n,
                        "arm": f"pruned_slack{slack}",
                        "prune_slack": slack,
                        **st,
                        "speedup_vs_full": round(speedup, 2),
                        "h2d_shrink_vs_full": round(h2d_shrink, 1),
                        "decisions_byte_identical": True,
                    }
                ),
                flush=True,
            )


if __name__ == "__main__":
    main()
