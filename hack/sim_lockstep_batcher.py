"""Simulate the lockstep client cohort against PredicateBatcher with a stub
extender whose 'fetch' resolves after a configurable RTT — reproduces the
TPU serving dynamics (window coalescing, hold behavior) without the TPU.

Run: python hack/sim_lockstep_batcher.py [--clients 32] [--rtt-ms 100]
"""

import argparse
import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace

import sys

sys.path.insert(0, ".")

from spark_scheduler_tpu.server.http import PredicateBatcher  # noqa: E402


class StubTicket:
    def __init__(self, n, handle):
        self.n = n
        self.handle = handle
        self.sync = False
        self.done = False


class StubExtender:
    """Mimics the real extender's timing: host work at dispatch/complete,
    a device fetch that lands RTT ms after dispatch."""

    def __init__(self, rtt_s, host_dispatch_s, host_complete_per_req_s):
        self.rtt_s = rtt_s
        self.host_dispatch_s = host_dispatch_s
        self.host_complete_s = host_complete_per_req_s
        self.windows = []

    def predicate_window_dispatch(self, args_list):
        time.sleep(self.host_dispatch_s + 0.0005 * len(args_list))
        fut = Future()
        fut.set_running_or_notify_cancel()
        timer = threading.Timer(self.rtt_s, fut.set_result, args=(None,))
        timer.daemon = True
        timer.start()
        return StubTicket(len(args_list), SimpleNamespace(blob_future=fut))

    def predicate_window_complete(self, t):
        t.handle.blob_future.result()
        time.sleep(self.host_complete_s * t.n)
        self.windows.append(t.n)
        return ["ok"] * t.n


def run(n_clients, rounds, rtt_ms, label, **batcher_kw):
    ext = StubExtender(rtt_ms / 1e3, 0.010, 0.0015)
    b = PredicateBatcher(ext, **batcher_kw)
    lats = []
    lock = threading.Lock()

    def client(ci):
        for r in range(rounds):
            t0 = time.perf_counter()
            b.submit(("req", ci, r))
            with lock:
                lats.append((time.perf_counter() - t0) * 1e3)
            time.sleep(0.001)  # client-side think time (json, bind)

    threads = [
        threading.Thread(target=client, args=(ci,)) for ci in range(n_clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    b.stop()
    lats.sort()
    total = n_clients * rounds
    print(
        f"{label}: {total/wall:.0f} req/s, "
        f"p50 {lats[len(lats)//2]:.0f} ms, p95 {lats[int(len(lats)*0.95)]:.0f} ms, "
        f"mean_window {sum(ext.windows)/len(ext.windows):.1f}, "
        f"windows {len(ext.windows)}"
    )
    return total / wall


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--rtt-ms", type=float, default=100.0)
    args = ap.parse_args()
    run(
        args.clients, args.rounds, args.rtt_ms,
        f"{args.clients} clients lockstep",
        max_window=32, hold_ms=25.0, pipeline_depth=3,
    )
    run(
        16, args.rounds, args.rtt_ms,
        "16 clients after (fresh batcher)",
        max_window=32, hold_ms=25.0, pipeline_depth=3,
    )
