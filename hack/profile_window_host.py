"""Profile the HOST-side cost of one serving window cycle
(predicate_window_dispatch + predicate_window_complete), bench-shaped:
500 nodes, FIFO on, windows of 32 drivers x 8 executors.

Run: python hack/profile_window_host.py [--windows N] [--window-size K]
CPU-pinned (jax_platforms=cpu) — on the tunneled TPU the device is hidden
by the pipeline, so host work is what bounds serving throughput
(VERDICT r3 weak #1).
"""

import argparse
import cProfile
import io
import pstats
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, ".")

from spark_scheduler_tpu.core.extender import ExtenderArgs  # noqa: E402
from spark_scheduler_tpu.server.app import build_scheduler_app  # noqa: E402
from spark_scheduler_tpu.server.config import InstallConfig  # noqa: E402
from spark_scheduler_tpu.store.backend import InMemoryBackend  # noqa: E402
from spark_scheduler_tpu.testing.harness import (  # noqa: E402
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--windows", type=int, default=10)
    ap.add_argument("--window-size", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=500)
    ap.add_argument("--execs", type=int, default=8)
    ap.add_argument("--sort", default="cumulative")
    ap.add_argument("--limit", type=int, default=45)
    args = ap.parse_args()

    backend = InMemoryBackend()
    node_names = []
    for i in range(args.nodes):
        n = new_node(f"bench-n{i}", zone=f"zone{i % 4}")
        backend.add_node(n)
        node_names.append(n.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True, instance_group_label=INSTANCE_GROUP_LABEL
        ),
    )
    ext = app.extender

    def run_window(tag):
        drivers = []
        for c in range(args.window_size):
            d = static_allocation_spark_pods(f"{tag}-{c}", args.execs)[0]
            backend.add_pod(d)
            drivers.append(d)
        t = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d, node_names=list(node_names)) for d in drivers]
        )
        results = ext.predicate_window_complete(t)
        for d, r in zip(drivers, results):
            if not r.node_names:
                raise RuntimeError(f"{d.name}: {r.outcome}")
            backend.bind_pod(d, r.node_names[0])

    # Warm: XLA compiles + caches.
    for w in range(3):
        run_window(f"warm-{w}")

    t0 = time.perf_counter()
    pr = cProfile.Profile()
    pr.enable()
    for w in range(args.windows):
        run_window(f"run-{w}")
    pr.disable()
    wall = time.perf_counter() - t0
    print(
        f"== {args.windows} windows x {args.window_size} drivers "
        f"({args.nodes} nodes, fifo): {wall*1e3/args.windows:.1f} ms/window, "
        f"{args.windows*args.window_size/wall:.1f} decisions/s (CPU device)"
    )
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats(args.sort)
    ps.print_stats(args.limit)
    print(s.getvalue())


if __name__ == "__main__":
    main()
