"""HA sharded-serving + chaos arms of bench.py's ha_failover section.

Measures 2-active-replica instance-group sharding (ha/replica.py
ShardedServingGroup) against a single unsharded replica on the SAME
workload, twice: a pure-CPU arm (informational — a single XLA CPU solve
already saturates every host core, so two concurrent solves cannot scale
there) and a simulated-RTT arm (testing/rtt_shim.py, the tunneled-TPU
regime the paper deploys on, where the control serializes one device
round trip per window and the shards overlap theirs — the arm that
carries the >= 1.5x bar). Byte-identical per-group placements are
ASSERTED in both arms. Then runs the leader-kill chaos soak
(testing/soak.py HAChaosSoak, >= 3 cycles).

Runs as a SUBPROCESS of bench.py (like hack/multidevice_bench.py). The
persistent XLA compilation cache is ENABLED again: the historical flake
(concurrently-serving solvers intermittently produced wrong window
decisions on executables reloaded from the cache, so this arm used to
run cache-free) is closed by InstallConfig.serialize_jax_cache_io() —
the cache's executable serialize/deserialize + file I/O now runs behind
one process-wide lock, which enable_jax_compile_cache installs. The
equivalence assertions below are the regression guard: a recurrence
fails the arm loudly. One JSON line per arm on stdout; standalone:
    python hack/ha_shard_bench.py
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import contextlib
import copy
import json
import sys
import threading
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

N_GROUPS = 8
APPS_PER_GROUP = 16
WINDOW = 8


def sharded_arm(nodes_per_group: int, rtt_ms):
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.ha.replica import ShardedServingGroup
    from spark_scheduler_tpu.ha.shard import ShardMap
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        Harness,
        new_node,
        static_allocation_spark_pods,
    )
    from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT

    shard_map = ShardMap(2)
    groups = [f"shard-group-{i}" for i in range(N_GROUPS)]
    # One compile-warmup group OWNED BY EACH replica so both solvers (and
    # the control) pay jit warmup outside the timed section.
    warm_groups = []
    for owner in (0, 1):
        warm_groups.append(
            next(
                g
                for g in (f"warmup-{i}" for i in range(64))
                if shard_map.owner(g) == owner and g not in warm_groups
            )
        )
    nodes = []
    for gi, g in enumerate(groups):
        nodes.extend(
            new_node(f"g{gi}-n{i}", zone=f"zone{i % 3}", instance_group=g)
            for i in range(nodes_per_group)
        )
    for wi, g in enumerate(warm_groups):
        nodes.extend(
            new_node(f"w{wi}-n{i}", zone="zone0", instance_group=g)
            for i in range(WINDOW * 2)
        )
    node_names = [n.name for n in nodes]
    workload = []
    for g in warm_groups:
        workload.append((g, [
            static_allocation_spark_pods(
                f"{g}-app-{a}", 1, instance_group=g)[0]
            for a in range(WINDOW)
        ], True))
    for g in groups:
        for w in range(APPS_PER_GROUP // WINDOW):
            workload.append((g, [
                static_allocation_spark_pods(
                    f"{g}-app-{w}-{a}", 1, instance_group=g)[0]
                for a in range(WINDOW)
            ], False))
    timed = [(g, pods) for g, pods, warm in workload if not warm]

    def args_of(pods):
        return [
            ExtenderArgs(pod=copy.deepcopy(p), node_names=list(node_names))
            for p in pods
        ]

    shim = SimulatedRTT(rtt_ms) if rtt_ms else contextlib.nullcontext()
    with shim:
        # Control: ONE unsharded replica serves every window sequentially.
        control = Harness(binpack_algo="tightly-pack", fifo=True)
        control.add_nodes(*(copy.deepcopy(n) for n in nodes))
        control_placed = {}
        for g, pods, warm in workload:
            if warm:
                for res in control.extender.predicate_batch(args_of(pods)):
                    assert res.ok
        t0 = time.perf_counter()
        for g, pods in timed:
            for p, res in zip(
                pods, control.extender.predicate_batch(args_of(pods))
            ):
                assert res.ok, (g, p.name, res.outcome)
                control_placed[p.name] = res.node_names[0]
        single_s = time.perf_counter() - t0

        # Sharded: 2 active replicas over one shared backend, one serving
        # thread per replica driving ITS OWN groups' windows.
        shared = InMemoryBackend()
        shared.register_crd(DEMAND_CRD)
        sharded = ShardedServingGroup(
            shared,
            2,
            config_factory=lambda i: InstallConfig(
                fifo=True,
                binpack_algo="tightly-pack",
                instance_group_label=INSTANCE_GROUP_LABEL,
                sync_writes=True,
                ha_enabled=True,
            ),
        )
        sharded.start()
        for n in nodes:
            shared.add_node(copy.deepcopy(n))
        for g, pods, warm in workload:
            if warm:
                idx = shard_map.owner(g)
                ext = sharded.replicas[idx].app.extender
                for res in ext.predicate_batch(args_of(pods)):
                    assert res.ok
        per_replica = {0: [], 1: []}
        for g, pods in timed:
            per_replica[shard_map.owner(g)].append((g, pods))
        sharded_placed = {}
        placed_lock = threading.Lock()
        errors = []

        def serve(idx):
            try:
                ext = sharded.replicas[idx].app.extender
                for g, pods in per_replica[idx]:
                    results = ext.predicate_batch(args_of(pods))
                    with placed_lock:
                        for p, res in zip(pods, results):
                            assert res.ok, (g, p.name, res.outcome)
                            sharded_placed[p.name] = res.node_names[0]
            except Exception as exc:  # surfaced after join
                errors.append(exc)

        threads = [
            threading.Thread(target=serve, args=(i,)) for i in (0, 1)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sharded_s = time.perf_counter() - t0
        assert not errors, errors
        forwarded = sharded.forwarded
        sharded.stop()
    # Byte-identical per group: every driver landed on the same node.
    assert sharded_placed == control_placed, {
        k: (control_placed.get(k), sharded_placed.get(k))
        for k in set(control_placed) | set(sharded_placed)
        if control_placed.get(k) != sharded_placed.get(k)
    }
    decisions = len(control_placed)
    return {
        "single_replica_dps": round(decisions / single_s, 1),
        "sharded_2replica_dps": round(decisions / sharded_s, 1),
        "speedup": round(single_s / sharded_s, 2),
        "decisions": decisions,
        "groups": N_GROUPS,
        "nodes": len(nodes),
        "rtt_ms": rtt_ms,
        "byte_identical_per_group": True,
        "forwarded": forwarded,
    }


def main() -> None:
    from spark_scheduler_tpu.server.config import InstallConfig

    InstallConfig.enable_jax_compile_cache(
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        )
    )
    # Pure-CPU arm: informational on shared-core boxes.
    pure = sharded_arm(512, None)
    print(json.dumps({"arm": "pure_cpu", **pure}), flush=True)
    # Tunneled-TPU regime: 50 ms simulated device RTT per window — the
    # control serializes round trips, the shards overlap theirs. This arm
    # carries the >= 1.5x bar.
    rtt = sharded_arm(256, 50.0)
    print(json.dumps({"arm": "rtt50", **rtt}), flush=True)

    from spark_scheduler_tpu.testing.soak import HAChaosSoak

    soak = HAChaosSoak(strategy="tightly-pack", n_nodes=24, ttl_s=1.0)
    stats = soak.run(cycles=3, burst=6)
    print(json.dumps({"arm": "chaos", **stats}), flush=True)


if __name__ == "__main__":
    main()
