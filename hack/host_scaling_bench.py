"""Host-scaling sweep (ISSUE 11, the million-node tier).

One JSON line per (tier, pool) arm (default tiers 10k / 100k / 1M via
BENCH_SCALE_TIERS, pools {1, 2} via BENCH_SCALE_POOLS — ISSUE 15),
measuring the numbers the tier is judged on:

  window_p50_ms        steady-state serving-window service time (extender
                       dispatch -> decisions, pruned two-tier solve;
                       pool arms serve 2-group PARTITIONED windows);
  node_update_ms /     cost of one node event: the event applied through
  node_add_ms          the backend, then ONE single-request window served
                       (snapshot patch + O(changed) build + delta upload +
                       solve) — the end-to-end node-event path;
  upload_bytes_per_event
                       h2d bytes per device-state upload during the event
                       phase (the O(changed) claim as a number);
  warm_restart_ms      discard the pipeline and re-serve from warm host
                       caches — the warm-standby promotion analog (caches
                       hot, device state cold; the HA promotion itself is
                       measured in PR 8's ha_failover section);
  wide (16-req) arm    plan/gather phase means recorded separately for
                       the wide windows (ISSUE 15 residual (d): the
                       reused-plan 16-wide host cost must track window
                       size, not cluster size).

Everything runs in process against the local jax backend: no HTTP hop, no
tunnel — this is the HOST scaling story. Candidate names ride an
identity-keyed ticket (the in-process analog of the native ingest lane's
digest ticket) so the 1M-name candidate list is not re-hashed per request.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_POOLS = [
    int(x) for x in os.environ.get("BENCH_SCALE_POOLS", "1,2").split(",")
]
if max(_POOLS) > 1 and "xla_force_host_platform_device_count" not in (
    os.environ.get("XLA_FLAGS", "")
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={max(_POOLS)}"
    )

import numpy as np  # noqa: E402


class NameTicket(list):
    """Candidate-name list with O(1) identity hash/eq — the in-process
    stand-in for server/ingest.NativeNodeNames, so the solver's
    candidate-mask LRU hits without hashing N strings per request."""

    __hash__ = object.__hash__

    def __eq__(self, other):
        return self is other

    @property
    def names_digest(self):
        return id(self)


def _pct(vals, q):
    return round(float(np.percentile(vals, q)), 3)


def run_tier(n_nodes: int, windows: int, pool: int = 1) -> dict:
    import dataclasses

    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
        static_allocation_spark_pods,
    )

    backend = InMemoryBackend()
    t0 = time.perf_counter()
    for i in range(n_nodes):
        if pool > 1:
            # Two instance groups: serving windows PARTITION across the
            # device pool (ISSUE 15 — the pooled million-node arm).
            backend.add_node(
                new_node(
                    f"s{i:07d}", zone=f"zone{i % 4}",
                    instance_group=f"ig{i % 2}",
                )
            )
        else:
            backend.add_node(new_node(f"s{i:07d}", zone=f"zone{i % 4}"))
    roster_ingest_s = time.perf_counter() - t0
    names = NameTicket(f"s{i:07d}" for i in range(n_nodes))

    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=False,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_prune_top_k=64,
            solver_device_pool=pool,
            flight_recorder=False,
        ),
    )
    ext = app.extender
    ext._last_request = float("inf")
    seq = iter(range(10_000_000))

    def serve_window(n_req=4, execs=2):
        args = []
        for r in range(n_req):
            kw = {"instance_group": f"ig{r % 2}"} if pool > 1 else {}
            d = static_allocation_spark_pods(
                f"hs-{next(seq)}", execs, **kw
            )[0]
            backend.add_pod(d)
            args.append(ExtenderArgs(pod=d, node_names=names))
        t0 = time.perf_counter()
        tok = ext.predicate_window_dispatch(args)
        res = ext.predicate_window_complete(tok)
        return (time.perf_counter() - t0) * 1e3, res

    # Boot: cold featurize + first full upload + first (compiling) window.
    t0 = time.perf_counter()
    boot_ms_raw, res = serve_window(1)
    boot_ms = (time.perf_counter() - t0) * 1e3
    assert res[0].node_names, "boot window failed to place"

    # Pin the boot-time roster out of GC traversal: at 1M nodes the heap
    # holds ~10M long-lived objects, and CPython gen-2 collections were
    # the dominant per-event p99 noise (multi-hundred-ms pauses with no
    # scheduler counter moving). Standard long-lived-heap serving
    # practice; steady-state garbage still collects normally. Unfrozen
    # (and collected) before this arm returns — a sweep runs 6 arms in
    # one process, and permanently freezing each arm's heap would leak
    # every dead roster into the next arm's measurements.
    import gc

    gc.collect()
    gc.freeze()

    # Steady-state window service (4-request windows), plus a WIDE arm
    # (16-request windows — the natural fill at fleet-scale traffic):
    # per-decision cost is the tier's acceptance number, and the wide
    # windows amortize the per-window host passes exactly as real load
    # does.
    lat = [serve_window()[0] for _ in range(windows)]

    # Pipelined arm (depth 2): dispatch window N+1 BEFORE completing N —
    # the serving loop's actual operating mode, where a pool overlaps
    # window N+1's host build + upload with window N's solve across
    # slots. Sequential dispatch→complete (the p50 above) cannot show
    # that overlap.
    def dispatch_only(n_req=4, execs=2):
        args = []
        for r in range(n_req):
            kw = {"instance_group": f"ig{r % 2}"} if pool > 1 else {}
            d = static_allocation_spark_pods(
                f"hs-{next(seq)}", execs, **kw
            )[0]
            backend.add_pod(d)
            args.append(ExtenderArgs(pod=d, node_names=names))
        return ext.predicate_window_dispatch(args)

    t0 = time.perf_counter()
    prev = dispatch_only()
    for _ in range(windows - 1):
        cur = dispatch_only()
        ext.predicate_window_complete(prev)
        prev = cur
    ext.predicate_window_complete(prev)
    window_pipelined_ms = (time.perf_counter() - t0) * 1e3 / windows

    serve_window(16)  # untimed: compiles the wide-bucket kernels
    prune_stats = app.solver.prune_stats
    pr0 = {
        k: prune_stats[k]
        for k in ("windows", "plan_ms", "gather_ms", "offset_ms")
    }
    lat_wide = [
        serve_window(16)[0] for _ in range(max(4, windows // 2))
    ]
    # Per-phase host cost of the WIDE (16-request) arm alone — the
    # reused-plan gather/plan residual ISSUE 15 (d) pins to ≤1.5x the
    # 100k cost.
    wide_n = max(int(prune_stats["windows"] - pr0["windows"]), 1)
    wide_phases = {
        f"wide_{k}_mean": round(
            (prune_stats[k] - pr0[k]) / wide_n, 4
        )
        for k in ("plan_ms", "gather_ms", "offset_ms")
    }

    stats = app.solver.device_state_stats

    def upload_bytes_per_event(before, after):
        events = sum(
            after[k] - before[k]
            for k in ("full_uploads", "delta_uploads", "static_delta_uploads")
        )
        if not events:
            return 0.0
        return round((after["upload_bytes"] - before["upload_bytes"]) / events, 1)

    # Node events: updates (unschedulable flip on high-index idle nodes)
    # and adds, each followed by ONE single-request window.
    upd_lat, add_lat = [], []
    before_events = dict(stats)
    for j in range(6):
        name = f"s{n_nodes - 1 - j:07d}"
        cur = backend.get_node(name)
        t0 = time.perf_counter()
        backend.update(
            "nodes", dataclasses.replace(cur, unschedulable=not cur.unschedulable)
        )
        w_ms, _ = serve_window(1)
        upd_lat.append((time.perf_counter() - t0) * 1e3)
    for j in range(6):
        t0 = time.perf_counter()
        backend.add_node(new_node(f"late{j:03d}", zone=f"zone{j % 4}"))
        w_ms, _ = serve_window(1)
        add_lat.append((time.perf_counter() - t0) * 1e3)
    after_events = dict(stats)

    # Node-ADD burst arm (ISSUE 13): BENCH_ADD_BURST sequential adds, one
    # served window each — p50/p99 per add measures the AMORTIZED growth
    # claim (preallocated roster/master buffers, O(changed) patches), not
    # a single lucky event.
    burst_n = int(os.environ.get("BENCH_ADD_BURST", "100"))
    grows_before = ext.features.stats()["array_grows"]
    burst_lat = []
    for j in range(burst_n):
        t0 = time.perf_counter()
        backend.add_node(new_node(f"burst{j:04d}", zone=f"zone{j % 4}"))
        serve_window(1)
        burst_lat.append((time.perf_counter() - t0) * 1e3)
    burst_grows = ext.features.stats()["array_grows"] - grows_before

    fs = ext.features.stats()

    # Warm restart (promotion analog): device state dropped, host caches hot.
    app.solver.discard_pipeline()
    t0 = time.perf_counter()
    serve_window(1)
    warm_restart_ms = (time.perf_counter() - t0) * 1e3

    out = {
        "n_nodes": n_nodes,
        "pool": pool,
        "roster_ingest_s": round(roster_ingest_s, 2),
        "boot_ms": round(boot_ms, 1),
        **wide_phases,
        "window_p50_ms": _pct(lat, 50),
        "window_p95_ms": _pct(lat, 95),
        "window_pipelined_ms": round(window_pipelined_ms, 3),
        "decisions_per_s": round(4 / (_pct(lat, 50) / 1e3), 1),
        "window16_p50_ms": _pct(lat_wide, 50),
        "per_decision_ms": round(_pct(lat_wide, 50) / 16, 3),
        "node_update_ms_p50": _pct(upd_lat, 50),
        "node_add_ms_p50": _pct(add_lat, 50),
        "add_burst_n": burst_n,
        "add_burst_p50_ms": _pct(burst_lat, 50),
        "add_burst_p99_ms": _pct(burst_lat, 99),
        "add_burst_array_grows": burst_grows,
        "upload_bytes_per_event": upload_bytes_per_event(
            before_events, after_events
        ),
        "warm_restart_ms": round(warm_restart_ms, 1),
        "roster_rebuilds_after_boot": fs["roster_rebuilds"] - 1,
        "roster_add_patches": fs["roster_add_patches"],
        "build": dict(app.solver.build_stats),
        "array_grows": fs["array_grows"],
        "device_state": dict(stats),
        "prune": dict(app.solver.prune_stats, reasons=dict(
            app.solver.prune_stats["reasons"])),
        "native_arena": app.solver.uses_native_arena,
    }
    app.stop()
    gc.unfreeze()
    gc.collect()
    return out


def main():
    tiers = [
        int(x)
        for x in os.environ.get(
            "BENCH_SCALE_TIERS", "10000,100000,1000000"
        ).split(",")
    ]
    windows = int(os.environ.get("BENCH_SCALE_WINDOWS", "12"))
    for pool in _POOLS:
        for n in tiers:
            out = run_tier(n, windows, pool=pool)
            print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
