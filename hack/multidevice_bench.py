"""Multi-device window-solve engine: decisions/s vs device count at 10k nodes.

Runs IN-PROCESS pipelined serving windows (predicate_window_dispatch /
predicate_window_complete) against a 10,240-node cluster split into 8
instance groups — the reference's real topology (failover.go:276-313) and
the shape that lets the engine partition each window into disjoint-domain
sub-solves. One arm per device-pool size:

  pool 1   = the single-device serving path (the engine disabled — today's
             baseline, whole 10k-node windows on the default device);
  pool 2/4/8 = the engine: each window partitions by instance group into
             gathered sub-cluster solves running CONCURRENTLY across the
             pool, the committed base scatter-combined between windows.

Forces an 8-device virtual CPU mesh BEFORE jax initializes, so it must run
as a subprocess (bench.py `multi_device_serving` section) — the parent
process's jax is already bound to its backend. One JSON line per arm on
stdout; standalone:
    python hack/multidevice_bench.py
"""

from __future__ import annotations

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")  # before any jax op

import json
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

import numpy as np

N_GROUPS = 8
NODES_PER_GROUP = 1280  # 8 x 1280 = 10,240 nodes
WINDOW = 32  # 4 drivers per group per window
N_WINDOWS = 6
POOLS = (1, 2, 4, 8)


def _build(pool: int):
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )

    backend = InMemoryBackend()
    group_names: dict[int, list[str]] = {}
    for g in range(N_GROUPS):
        group_names[g] = []
        for i in range(NODES_PER_GROUP):
            node = new_node(
                f"g{g}-n{i}", zone=f"zone{i % 4}",
                instance_group=f"group-{g}",
            )
            backend.add_node(node)
            group_names[g].append(node.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_device_pool=pool,
        ),
    )
    return backend, app, group_names


def _run_arm(pool: int) -> dict:
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )

    backend, app, group_names = _build(pool)
    ext = app.extender

    def dispatch_window(tag, k):
        drivers = []
        args = []
        for j in range(WINDOW):
            g = j % N_GROUPS
            pod = static_allocation_spark_pods(
                f"mdb-{tag}-{k}-{j}", 4, instance_group=f"group-{g}"
            )[0]
            backend.add_pod(pod)
            drivers.append(pod)
            args.append(
                ExtenderArgs(pod=pod, node_names=list(group_names[g]))
            )
        return drivers, ext.predicate_window_dispatch(args)

    def complete_window(drivers, t):
        results = ext.predicate_window_complete(t)
        for d, r in zip(drivers, results):
            if not r.node_names:
                raise RuntimeError(f"{d.name}: {r.outcome}")
            backend.bind_pod(d, r.node_names[0])

    # Warm: compiles for every window shape this arm hits.
    for w in range(2):
        complete_window(*dispatch_window("warm", w))
    t0 = time.perf_counter()
    prev = dispatch_window("run", 0)
    for k in range(1, N_WINDOWS):
        nxt = dispatch_window("run", k)
        complete_window(*prev)
        prev = nxt
    complete_window(*prev)
    wall = time.perf_counter() - t0
    solver = app.solver
    out = {
        "devices": pool,
        "decisions_per_s": round(WINDOW * N_WINDOWS / wall, 1),
        "windows_of": WINDOW,
        "windows": N_WINDOWS,
        "nodes": N_GROUPS * NODES_PER_GROUP,
        "instance_groups": N_GROUPS,
        "window_path_counts": dict(solver.window_path_counts),
        "device_pool_stats": solver.device_pool_stats(),
        "partitions_last_window": (
            (solver.last_solve_info or {}).get("partitions")
        ),
        "pipelined": True,
        "fused_k": 1,
        "path": (
            "single-device serving path (engine off)"
            if pool == 1
            else "device pool: disjoint-domain partitions solved "
            "concurrently, committed base scatter-combined"
        ),
    }
    app.stop()
    return out


def main() -> int:
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))
    baseline = None
    for pool in POOLS:
        arm = _run_arm(pool)
        if pool == 1:
            baseline = arm["decisions_per_s"]
        arm["speedup_vs_single_device"] = (
            round(arm["decisions_per_s"] / baseline, 2) if baseline else None
        )
        print(json.dumps(arm), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
