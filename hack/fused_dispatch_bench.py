"""Fused multi-window dispatch: decisions/s vs simulated device RTT.

The fused engine's win is invisible on a local CPU backend (device
boundaries are microseconds), so this bench injects the tunneled-TPU cost
with the simulated-RTT device shim (testing/rtt_shim.py): every window
DISPATCH pays rtt/2 on the dispatcher thread and every decision pull pays
rtt/2 on a fetch thread — the structure BENCH_r05 measured as
`device_rtt_floor_ms` (~70-104 ms per window, capping a tunneled TPU at
~10 windows/s per device).

Arms: fused_k in {1, 4} (1 = today's one-window-per-dispatch serving
loop, pipelined dispatch-before-fetch; 4 = the fused claim — 4 windows
per device round trip) x simulated RTT in {10, 50, 100} ms on a single
device, plus an RTT-50 pair on a 2-slot device pool (fused batches ride
the same partition/overlap machinery). In-process windows through the
REAL extender dispatch/complete path (reservations, write-back, epoch
machinery) — the HTTP layer is out of frame, as in the in-process
controls of every serving section.

Runs as a subprocess of bench.py's `fused_dispatch` section (the pool
arms need the 8-device virtual CPU mesh forced before jax initializes).
One JSON line per arm on stdout; standalone:
    python hack/fused_dispatch_bench.py
"""

from __future__ import annotations

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")  # before any jax op

import json
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

N_GROUPS = 2
NODES_PER_GROUP = 128
WINDOW = 8  # requests per serving window
N_WINDOWS = 8  # measured windows per arm
EXECS = 2
# (pool, fused_k, rtt_ms): the single-device RTT sweep is the
# PERFORMANCE.md table; the pool pair shows fusion composing with the
# multi-device engine.
ARMS = (
    (1, 1, 10), (1, 4, 10),
    (1, 1, 50), (1, 4, 50), (1, 8, 50),
    (1, 1, 100), (1, 4, 100), (1, 8, 100),
    (2, 1, 50), (2, 4, 50),
)


def _build(pool: int):
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )

    backend = InMemoryBackend()
    group_names: dict[int, list[str]] = {}
    for g in range(N_GROUPS):
        group_names[g] = []
        for i in range(NODES_PER_GROUP):
            node = new_node(
                f"g{g}-n{i}", zone=f"zone{i % 2}",
                instance_group=f"group-{g}",
            )
            backend.add_node(node)
            group_names[g].append(node.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=False, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_device_pool=pool,
        ),
    )
    return backend, app, group_names


def _run_arm(pool: int, fused_k: int, rtt_ms: float) -> dict:
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )
    from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT

    backend, app, group_names = _build(pool)
    ext = app.extender

    def make_window(tag, k):
        drivers, args = [], []
        for j in range(WINDOW):
            g = j % N_GROUPS
            pod = static_allocation_spark_pods(
                f"fd-{tag}-{k}-{j}", EXECS, instance_group=f"group-{g}"
            )[0]
            backend.add_pod(pod)
            drivers.append(pod)
            args.append(
                ExtenderArgs(pod=pod, node_names=list(group_names[g]))
            )
        return drivers, args

    def complete(drivers, ticket):
        for d, r in zip(drivers, ext.predicate_window_complete(ticket)):
            if not r.node_names:
                raise RuntimeError(f"{d.name}: {r.outcome}")
            backend.bind_pod(d, r.node_names[0])

    def dispatch_group(tag, k, n_windows):
        """One dispatch unit: a single window (fused_k=1) or a fused
        group of n_windows sub-windows in ONE device program."""
        members = [make_window(tag, k * fused_k + i) for i in range(n_windows)]
        if n_windows == 1:
            tickets = [ext.predicate_window_dispatch(members[0][1])]
        else:
            tickets = ext.predicate_windows_dispatch(
                [args for _, args in members]
            )
        return [(drivers, t) for (drivers, _), t in zip(members, tickets)]

    def complete_group(group):
        for drivers, t in group:
            complete(drivers, t)

    # Warm (shim off): compiles for every window shape this arm hits.
    n_groups_run = N_WINDOWS // fused_k
    complete_group(dispatch_group("warm", 0, fused_k))
    complete_group(dispatch_group("warm2", 1, 1))

    shim = SimulatedRTT(rtt_ms=rtt_ms)
    with shim:
        t0 = time.perf_counter()
        # Pipelined one dispatch-unit ahead, like the serving batcher.
        prev = dispatch_group("run", 0, fused_k)
        for k in range(1, n_groups_run):
            nxt = dispatch_group("run", k, fused_k)
            complete_group(prev)
            prev = nxt
        complete_group(prev)
        wall = time.perf_counter() - t0
    decisions = WINDOW * N_WINDOWS
    out = {
        "pool": pool,
        "fused_k": fused_k,
        "rtt_ms": rtt_ms,
        "decisions_per_s": round(decisions / wall, 1),
        "amortized_rtt_floor_ms_per_window": round(
            wall * 1e3 / N_WINDOWS, 2
        ),
        "windows": N_WINDOWS,
        "window_requests": WINDOW,
        "nodes": N_GROUPS * NODES_PER_GROUP,
        "shim_events": dict(shim.counts),
        "window_path_counts": dict(app.solver.window_path_counts),
        "path": (
            "one-window-per-dispatch (pipelined)"
            if fused_k == 1
            else f"fused {fused_k}-window dispatch on resident carry state"
        ),
    }
    app.stop()
    return out


def main() -> int:
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))
    baselines: dict[tuple, float] = {}
    for pool, fused_k, rtt in ARMS:
        arm = _run_arm(pool, fused_k, rtt)
        key = (pool, rtt)
        if fused_k == 1:
            baselines[key] = arm["decisions_per_s"]
        base = baselines.get(key)
        arm["speedup_vs_unfused"] = (
            round(arm["decisions_per_s"] / base, 2)
            if base and fused_k > 1
            else None
        )
        print(json.dumps(arm), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
