#!/usr/bin/env bash
# Hot-swap the scheduler in the dev cluster on demand or on file change
# (VERDICT r3 #8; the reference's hack/dev/live-reload.sh slot, extended
# with a watch mode): rebuild docker/Dockerfile, load it into the kind
# cluster, restart the deployment, and tail the new pod's logs.
#
#   hack/dev/live-reload.sh           # one reload + log tail
#   hack/dev/live-reload.sh --watch   # reload whenever source changes
#
# Requires: the run-in-kind.sh cluster (kind, kubectl, docker).
set -o errexit
set -o nounset
set -o pipefail

CLUSTER="spark-scheduler-tpu"
NAMESPACE="spark"
DEPLOY="spark-scheduler-tpu"
IMG="spark-scheduler-tpu:latest"
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"

say() { echo ">>> $*"; }

reload() {
  # Explicit '|| return 1' per step: the watch loop calls reload with an
  # '||' guard, which DISABLES errexit inside the function — without
  # these, a failed docker build would still kind-load the stale image
  # and "succeed".
  say "building $IMG"
  docker build -q -f "$REPO/docker/Dockerfile" -t "$IMG" "$REPO" || return 1
  say "loading image into kind cluster $CLUSTER"
  kind load docker-image --name "$CLUSTER" "$IMG" || return 1
  say "restarting $DEPLOY"
  kubectl -n "$NAMESPACE" rollout restart "deployment/$DEPLOY" || return 1
  kubectl -n "$NAMESPACE" rollout status "deployment/$DEPLOY" \
    --timeout=180s || return 1
}

src_hash() {
  # Hash of everything the image build consumes (docker/Dockerfile COPY
  # list: pyproject.toml, spark_scheduler_tpu/, native/, docker/var/conf).
  { find "$REPO/spark_scheduler_tpu" "$REPO/native" "$REPO/docker" \
      -type f \( -name '*.py' -o -name '*.cpp' -o -name '*.h' \
        -o -name 'Dockerfile' -o -name '*.yml' \) -print0;
    printf '%s\0' "$REPO/pyproject.toml"; } \
    | sort -z | xargs -0 sha256sum | sha256sum | cut -d' ' -f1
}

if [ "${1:-}" = "--watch" ]; then
  say "watching for source changes (ctrl-c to stop)"
  last="$(src_hash)"
  # A failed build/rollout must not kill the watcher — mid-edit breakage
  # is exactly what watch mode iterates through.
  reload || say "reload failed; waiting for the next change"
  while true; do
    sleep 2
    cur="$(src_hash)"
    if [ "$cur" != "$last" ]; then
      last="$cur"
      say "change detected"
      reload || say "reload failed; waiting for the next change"
    fi
  done
else
  reload
  say "tailing scheduler logs (ctrl-c to stop)"
  kubectl -n "$NAMESPACE" logs -f "deployment/$DEPLOY" \
    -c spark-scheduler-extender
fi
