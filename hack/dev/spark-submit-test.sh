#!/usr/bin/env bash
# Drive a REAL Spark application through the scheduler (VERDICT r3 #8; the
# reference's hack/dev/spark-submit-test.sh slot): spark-submit in k8s
# cluster mode against the current kubectl context, with driver/executor
# pod templates pinned to `schedulerName: spark-scheduler` and the driver
# annotated with the resource set the extender parses
# (core/sparkpods.py:31-40, sparkpods.go:79-138). A real Spark driver
# exercises annotation parsing, executor ramp-up, and churn in ways the
# mock smoke (examples/submit-test-spark-app.sh) cannot.
#
#   hack/dev/spark-submit-test.sh [executors] [driver_cpu] [driver_mem_mb] \
#                                 [executor_cpu] [executor_mem_mb]
#
# Requires: SPARK_HOME pointing at a Spark 3.x distribution (k8s mode), a
# kubeconfig for the target cluster (e.g. the kind cluster from
# run-in-kind.sh), and a Spark container image reachable by the cluster
# (SPARK_IMAGE, default apache/spark:3.5.1).
set -o errexit
set -o nounset
set -o pipefail

EXECUTOR_COUNT="${1:-2}"
DRIVER_CPU="${2:-1}"
DRIVER_MEM="${3:-512}"   # mb
EXECUTOR_CPU="${4:-1}"
EXECUTOR_MEM="${5:-512}" # mb
SPARK_IMAGE="${SPARK_IMAGE:-apache/spark:3.5.1}"
# Override together with SPARK_IMAGE — the examples jar inside the image
# is versioned.
SPARK_EXAMPLES_JAR="${SPARK_EXAMPLES_JAR:-local:///opt/spark/examples/jars/spark-examples_2.12-3.5.1.jar}"
NAMESPACE="${NAMESPACE:-spark}"
APP_ID="spark-real-$RANDOM"

if [ -z "${SPARK_HOME:-}" ] || [ ! -x "$SPARK_HOME/bin/spark-submit" ]; then
  echo "SPARK_HOME is not set (or has no bin/spark-submit)." >&2
  echo "Install a Spark 3.x distribution and export SPARK_HOME to run the" >&2
  echo "real-Spark smoke; the mock gang smoke (run-in-kind.sh) needs none." >&2
  exit 2
fi

MASTER="${K8S_MASTER:-k8s://$(kubectl config view --minify \
  -o jsonpath='{.clusters[0].cluster.server}')}"

# Pod template: route driver AND executors through the extender's
# scheduler (sparkpods.py SPARK_SCHEDULER_NAME) and tag the app id so the
# gang assertions below can find the pods.
TEMPLATE_FILE="$(mktemp /tmp/spark-template-XXXXXX.yml)"
trap 'rm -f "$TEMPLATE_FILE"' EXIT
cat > "$TEMPLATE_FILE" <<EOF
apiVersion: v1
kind: Pod
metadata:
  labels:
    spark-app-id: "$APP_ID"
spec:
  schedulerName: spark-scheduler
EOF

echo ">>> spark-submit $APP_ID: 1 driver + $EXECUTOR_COUNT executors via $MASTER"
"$SPARK_HOME/bin/spark-submit" \
  --master "$MASTER" \
  --deploy-mode cluster \
  --name "spark-real-smoke" \
  --class org.apache.spark.examples.SparkPi \
  --conf "spark.kubernetes.namespace=$NAMESPACE" \
  --conf "spark.kubernetes.container.image=$SPARK_IMAGE" \
  --conf "spark.kubernetes.driver.podTemplateFile=$TEMPLATE_FILE" \
  --conf "spark.kubernetes.executor.podTemplateFile=$TEMPLATE_FILE" \
  --conf "spark.executor.instances=$EXECUTOR_COUNT" \
  --conf "spark.driver.cores=$DRIVER_CPU" \
  --conf "spark.driver.memory=${DRIVER_MEM}m" \
  --conf "spark.executor.cores=$EXECUTOR_CPU" \
  --conf "spark.executor.memory=${EXECUTOR_MEM}m" \
  --conf "spark.kubernetes.driver.label.spark-app-id=$APP_ID" \
  --conf "spark.kubernetes.executor.label.spark-app-id=$APP_ID" \
  --conf "spark.kubernetes.driver.annotation.spark-executor-count=$EXECUTOR_COUNT" \
  --conf "spark.kubernetes.driver.annotation.spark-driver-cpu=$DRIVER_CPU" \
  --conf "spark.kubernetes.driver.annotation.spark-driver-mem=${DRIVER_MEM}Mi" \
  --conf "spark.kubernetes.driver.annotation.spark-executor-cpu=$EXECUTOR_CPU" \
  --conf "spark.kubernetes.driver.annotation.spark-executor-mem=${EXECUTOR_MEM}Mi" \
  "$SPARK_EXAMPLES_JAR" 100 &
SUBMIT_PID=$!

echo ">>> waiting for the gang ($((EXECUTOR_COUNT + 1)) pods) to schedule"
deadline=$(( $(date +%s) + 300 ))
want=$(( EXECUTOR_COUNT + 1 ))
while true; do
  scheduled=$(kubectl -n "$NAMESPACE" get pods -l "spark-app-id=$APP_ID" \
    -o jsonpath='{range .items[*]}{.spec.nodeName}{"\n"}{end}' 2>/dev/null \
    | grep -c . || true)
  [ "$scheduled" -ge "$want" ] && break
  if [ "$(date +%s)" -gt "$deadline" ]; then
    echo "FAIL: only $scheduled/$want spark pods scheduled" >&2
    kubectl -n "$NAMESPACE" get pods -l "spark-app-id=$APP_ID" -o wide || true
    kill "$SUBMIT_PID" 2>/dev/null || true
    exit 1
  fi
  sleep 3
done

echo ">>> verifying the gang landed on its reserved nodes"
reserved=$(kubectl -n "$NAMESPACE" get resourcereservation "$APP_ID" \
  -o jsonpath='{range .spec.reservations.*}{.node}{"\n"}{end}' | sort -u)
landed=$(kubectl -n "$NAMESPACE" get pods -l "spark-app-id=$APP_ID" \
  -o jsonpath='{range .items[*]}{.spec.nodeName}{"\n"}{end}' | sort -u)
echo ">>> reserved: $(echo $reserved)  landed: $(echo $landed)"
for n in $landed; do
  if ! grep -qx "$n" <<<"$reserved"; then
    echo "FAIL: spark pod landed on $n outside the reservation" >&2
    exit 1
  fi
done
echo ">>> OK: real Spark gang of $want pods scheduled on reserved nodes"
wait "$SUBMIT_PID" || true
