#!/usr/bin/env bash
# Generate a self-signed CA + server cert for the extender and emit the
# `scheduler-secrets` Secret manifest on stdout (the reference's
# hack/dev/generate-certs.sh flow):
#
#   hack/dev/generate-certs.sh | kubectl apply -f -
set -euo pipefail

DIR="$(mktemp -d)"
trap 'rm -rf "$DIR"' EXIT
CN="${1:-scheduler-service.spark.svc}"

openssl req -x509 -newkey rsa:2048 -nodes -days 365 \
  -keyout "$DIR/rootCA.key" -out "$DIR/rootCA.crt" \
  -subj "/CN=spark-scheduler-dev-ca" 2>/dev/null

openssl req -newkey rsa:2048 -nodes \
  -keyout "$DIR/spark-scheduler.key" -out "$DIR/spark-scheduler.csr" \
  -subj "/CN=$CN" 2>/dev/null

openssl x509 -req -days 365 -in "$DIR/spark-scheduler.csr" \
  -CA "$DIR/rootCA.crt" -CAkey "$DIR/rootCA.key" -CAcreateserial \
  -out "$DIR/spark-scheduler.crt" \
  -extfile <(printf "subjectAltName=DNS:%s,DNS:localhost,IP:127.0.0.1" "$CN") \
  2>/dev/null

b64() { base64 < "$1" | tr -d '\n'; }

cat <<EOF
apiVersion: v1
kind: Secret
metadata:
  name: scheduler-secrets
  namespace: spark
type: Opaque
data:
  rootCA.crt: $(b64 "$DIR/rootCA.crt")
  spark-scheduler.crt: $(b64 "$DIR/spark-scheduler.crt")
  spark-scheduler.key: $(b64 "$DIR/spark-scheduler.key")
EOF
