#!/usr/bin/env bash
# Local dev loop without a cluster (the reference's run-in-minikube.sh
# moral equivalent for this repo): start an in-process fake apiserver with
# N nodes, run the scheduler against it with a durable WAL, submit a test
# app through the apiserver, and show the resulting reservation.
#
#   hack/dev/run-local.sh [num-nodes] [num-executors]
set -euo pipefail

NUM_NODES="${1:-10}"
NUM_EXECUTORS="${2:-4}"
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"

exec python - "$NUM_NODES" "$NUM_EXECUTORS" <<PY
import json, http.client, subprocess, sys, tempfile, time
sys.path.insert(0, "$REPO")

import jax
jax.config.update("jax_platforms", "cpu")

from spark_scheduler_tpu.kube.apiserver import FakeKubeAPIServer

num_nodes, num_executors = int(sys.argv[1]), int(sys.argv[2])

def k8s_node(name):
    return {"kind": "Node", "apiVersion": "v1",
            "metadata": {"name": name, "labels": {
                "failure-domain.beta.kubernetes.io/zone": f"zone{hash(name) % 2}",
                "instance-group": "batch-medium-priority"}},
            "status": {"allocatable": {"cpu": "8", "memory": "8Gi"},
                       "conditions": [{"type": "Ready", "status": "True"}]}}

def spark_pod(name, app, role, execs=0):
    ann = ({"spark-driver-cpu": "1", "spark-driver-mem": "1Gi",
            "spark-executor-cpu": "1", "spark-executor-mem": "1Gi",
            "spark-executor-count": str(execs)} if role == "driver" else {})
    return {"kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": name, "namespace": "spark",
                         "labels": {"spark-role": role, "spark-app-id": app},
                         "annotations": ann,
                         "creationTimestamp": time.time()},
            "spec": {"schedulerName": "spark-scheduler",
                     "nodeSelector": {"instance-group": "batch-medium-priority"},
                     "containers": [{"name": "c", "resources": {
                         "requests": {"cpu": "1", "memory": "1Gi"}}}]},
            "status": {"phase": "Pending"}}

api = FakeKubeAPIServer()
api.start()
for i in range(num_nodes):
    api.create("nodes", k8s_node(f"node-{i}"))
print(f"fake apiserver on {api.base_url} with {num_nodes} nodes")

wal = tempfile.mktemp(suffix=".jsonl")
proc = subprocess.Popen(
    [sys.executable, "-m", "spark_scheduler_tpu", "server",
     "--host", "127.0.0.1", "--port", "8484",
     "--kube-api-url", api.base_url, "--durable-store", wal],
    env={"PYTHONPATH": "$REPO", "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin:/usr/local/bin"})
conn = None
try:
    for _ in range(120):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", 8484, timeout=2)
            conn.request("GET", "/status/readiness")
            if conn.getresponse().status == 200:
                break
        except OSError:
            pass
        time.sleep(0.5)  # also on 503: listening-but-not-synced
    else:
        raise SystemExit("scheduler never became ready")
    print("scheduler ready on :8484")

    nodes = [f"node-{i}" for i in range(num_nodes)]
    driver = spark_pod("demo-driver", "demo", "driver", num_executors)
    api.create("pods", driver)
    time.sleep(0.5)
    conn.request("POST", "/predicates", body=json.dumps(
        {"Pod": driver, "NodeNames": nodes}).encode())
    result = json.loads(conn.getresponse().read())
    print("driver ->", result["NodeNames"] or result["FailedNodes"])
    bound = json.loads(json.dumps(driver))
    bound["spec"]["nodeName"] = result["NodeNames"][0]
    bound["status"]["phase"] = "Running"
    api.update("pods", bound)
    for i in range(num_executors):
        ex = spark_pod(f"demo-exec-{i}", "demo", "executor")
        api.create("pods", ex)
        time.sleep(0.2)
        conn.request("POST", "/predicates", body=json.dumps(
            {"Pod": ex, "NodeNames": nodes}).encode())
        r = json.loads(conn.getresponse().read())
        print(f"executor {i} ->", r["NodeNames"] or r["FailedNodes"])
    conn.request("GET", "/metrics")
    metrics = json.loads(conn.getresponse().read())
    sched = {k: v for k, v in metrics.items() if "schedule" in k}
    print("schedule metrics:", json.dumps(sched, indent=2)[:400])
    print("WAL at", wal)
finally:
    if conn:
        conn.close()
    proc.terminate()
    proc.wait(timeout=10)
    api.stop()
PY
