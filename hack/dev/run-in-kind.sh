#!/usr/bin/env bash
# Cluster-in-a-box dev loop + gang-scheduling smoke (VERDICT r2 #9; the
# reference's hack/dev/run-in-minikube.sh + spark-submit-test.sh slot):
#
#   1. create (or reuse) a kind cluster
#   2. build docker/Dockerfile and load it into the cluster
#   3. apply examples/{namespace,crds,extender}.yml and wait for rollout
#   4. submit a mock Spark app (examples/submit-test-spark-app.sh)
#   5. assert the gang landed: every pod of the app is Scheduled on a node
#      recorded in the app's ResourceReservation
#
#   hack/dev/run-in-kind.sh [app-id] [num-executors]
#
# Requires: kind, kubectl, docker. Tear down with:
#   kind delete cluster --name spark-scheduler-tpu
set -euo pipefail

APP_ID="${1:-kind-smoke-$RANDOM}"
NUM_EXECUTORS="${2:-2}"
CLUSTER="spark-scheduler-tpu"
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"
IMG="spark-scheduler-tpu:latest"

say() { echo ">>> $*"; }

# 1. cluster ---------------------------------------------------------------
if ! kind get clusters 2>/dev/null | grep -qx "$CLUSTER"; then
  say "creating kind cluster $CLUSTER (1 control plane + 2 workers)"
  kind create cluster --name "$CLUSTER" --wait 120s --config=- <<'YAML'
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
nodes:
  - role: control-plane
  - role: worker
  - role: worker
YAML
else
  say "reusing kind cluster $CLUSTER"
fi
kubectl config use-context "kind-$CLUSTER" >/dev/null

# The scheduler sorts and filters by zone + instance-group labels.
for node in $(kubectl get nodes -l '!node-role.kubernetes.io/control-plane' -o name); do
  kubectl label --overwrite "$node" \
    topology.kubernetes.io/zone=zone1 instance-group=batch-medium-priority >/dev/null
done

# 2. image -----------------------------------------------------------------
say "building $IMG"
docker build -q -f "$REPO/docker/Dockerfile" -t "$IMG" "$REPO"
say "loading image into kind"
kind load docker-image --name "$CLUSTER" "$IMG"

# 3. deploy ----------------------------------------------------------------
say "applying manifests"
kubectl apply -f "$REPO/examples/namespace.yml"
kubectl apply -f "$REPO/examples/crds.yml"
kubectl apply -f "$REPO/examples/extender.yml"
say "waiting for the scheduler rollout"
kubectl -n spark rollout status deployment/spark-scheduler-tpu --timeout=180s

# 4. submit ----------------------------------------------------------------
say "submitting mock spark app $APP_ID (1 driver + $NUM_EXECUTORS executors)"
"$REPO/examples/submit-test-spark-app.sh" "$APP_ID" "$NUM_EXECUTORS"

# 5. assert the gang landed on reserved nodes ------------------------------
say "waiting for the gang to schedule"
deadline=$(( $(date +%s) + 180 ))
want=$(( NUM_EXECUTORS + 1 ))
while true; do
  scheduled=$(kubectl -n spark get pods -l "spark-app-id=$APP_ID" \
    -o jsonpath='{range .items[*]}{.spec.nodeName}{"\n"}{end}' | grep -c . || true)
  [ "$scheduled" -ge "$want" ] && break
  if [ "$(date +%s)" -gt "$deadline" ]; then
    say "FAIL: only $scheduled/$want pods scheduled"
    kubectl -n spark get pods -l "spark-app-id=$APP_ID" -o wide
    kubectl -n spark logs deployment/spark-scheduler-tpu -c spark-scheduler-extender --tail=50
    exit 1
  fi
  sleep 2
done

say "verifying pods landed on the reserved nodes"
reserved_nodes=$(kubectl -n spark get resourcereservation "$APP_ID" \
  -o jsonpath='{range .spec.reservations.*}{.node}{"\n"}{end}' | sort -u)
pod_nodes=$(kubectl -n spark get pods -l "spark-app-id=$APP_ID" \
  -o jsonpath='{range .items[*]}{.spec.nodeName}{"\n"}{end}' | sort -u)
say "reserved: $(echo $reserved_nodes)  landed: $(echo $pod_nodes)"
for n in $pod_nodes; do
  if ! grep -qx "$n" <<<"$reserved_nodes"; then
    say "FAIL: pod landed on $n which holds no reservation for $APP_ID"
    kubectl -n spark get resourcereservation "$APP_ID" -o yaml
    exit 1
  fi
done

say "OK: gang of $want pods scheduled on reserved nodes"

# 6. optional REAL Spark stage ---------------------------------------------
# REAL_SPARK=1 additionally drives an actual spark-submit (k8s cluster
# mode) through the scheduler — annotation parsing, executor ramp-up and
# churn as Spark itself produces them. Needs SPARK_HOME (Spark 3.x).
if [ "${REAL_SPARK:-0}" = "1" ]; then
  say "running the real spark-submit smoke"
  "$REPO/hack/dev/spark-submit-test.sh" "$NUM_EXECUTORS"
fi
