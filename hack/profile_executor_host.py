"""Profile the HOST-side executor binding ladder, bench-shaped: 500 nodes,
8 apps x 16 executors bound through windowed serving (the executors ride
the post-window solo loop). Run: python hack/profile_executor_host.py"""

import cProfile
import io
import pstats
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, ".")

from spark_scheduler_tpu.core.extender import ExtenderArgs  # noqa: E402
from spark_scheduler_tpu.server.app import build_scheduler_app  # noqa: E402
from spark_scheduler_tpu.server.config import InstallConfig  # noqa: E402
from spark_scheduler_tpu.store.backend import InMemoryBackend  # noqa: E402
from spark_scheduler_tpu.testing.harness import (  # noqa: E402
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)


def main():
    n_apps, execs_per_app, window = 8, 16, 16
    backend = InMemoryBackend()
    node_names = []
    for i in range(500):
        n = new_node(f"bench-n{i}", zone=f"zone{i % 4}")
        backend.add_node(n)
        node_names.append(n.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True, instance_group_label=INSTANCE_GROUP_LABEL
        ),
    )
    ext = app.extender

    exec_pods = []
    for i in range(n_apps):
        pods = static_allocation_spark_pods(f"exb-{i}", execs_per_app)
        backend.add_pod(pods[0])
        r = ext.predicate(ExtenderArgs(pod=pods[0], node_names=list(node_names)))
        assert r.node_names, r.outcome
        backend.bind_pod(pods[0], r.node_names[0])
        exec_pods.extend(pods[1:])

    def bind_window(pods):
        for p in pods:
            backend.add_pod(p)
        t = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=p, node_names=list(node_names)) for p in pods]
        )
        results = ext.predicate_window_complete(t)
        for p, r in zip(pods, results):
            assert r.node_names, (p.name, r.outcome)
            backend.bind_pod(p, r.node_names[0])

    # Warm one window.
    bind_window(exec_pods[:window])
    rest = exec_pods[window:]

    t0 = time.perf_counter()
    pr = cProfile.Profile()
    pr.enable()
    for i in range(0, len(rest), window):
        bind_window(rest[i : i + window])
    pr.disable()
    wall = time.perf_counter() - t0
    print(
        f"== {len(rest)} executor bindings in windows of {window}: "
        f"{wall*1e3/len(rest):.2f} ms/binding, {len(rest)/wall:.0f} bindings/s"
    )
    s = io.StringIO()
    pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(40)
    print(s.getvalue())


if __name__ == "__main__":
    main()
