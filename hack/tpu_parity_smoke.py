"""On-device parity smoke: golden oracle checks on the REAL default backend.

The pytest suite pins JAX to a virtual CPU mesh (tests/conftest.py), so the
golden parity proofs normally never execute on TPU silicon. This script
runs a reduced randomized sweep of THE SAME checks — it imports
`random_cluster` / `check_case` straight from tests/test_packing_golden.py,
so the on-device smoke and the CPU golden suite are provably the same
assertions — on whatever backend JAX resolves (the TPU chip under the axon
tunnel, a Cloud TPU VM, or CPU as fallback). One shape bucket keeps the
compile count low.

Run directly (prints one JSON verdict line):
    python hack/tpu_parity_smoke.py
or through pytest when a chip is available:
    SPARK_SCHEDULER_TPU_SMOKE=1 python -m pytest tests/test_tpu_parity.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_NODES = 64  # one shape bucket: a single compile per (fill, program)
TRIALS = 12


def run() -> dict:
    """Run the sweep; returns {"device", "cases_checked", "parity"} (raises
    on any parity violation). bench.py folds this into every bench run
    (VERDICT r2 #5) so kernel changes are parity-checked on real silicon."""
    import jax

    from tests import greedy_oracle as G
    from tests import test_packing_golden as TG
    from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch

    emax, num_zones = TG.EMAX, TG.NUM_ZONES
    device = str(jax.devices()[0])
    rng = np.random.default_rng(1234)
    checked = 0

    # -- single-app kernels vs oracle, on device: the golden suite's own
    #    fixtures and slot-exact assertions (test_packing_golden.check_case)
    for fill in ("tightly-pack", "distribute-evenly", "minimal-fragmentation"):
        for trial in range(TRIALS):
            c = TG.random_cluster(rng, N_NODES, with_labels=trial % 3 == 0)
            driver_req = rng.integers(0, 12, size=3).astype(np.int32)
            exec_req = rng.integers(0, 10, size=3).astype(np.int32)
            count = int(rng.integers(0, emax + 1))
            driver_mask = rng.random(N_NODES) < 0.7
            domain = rng.random(N_NODES) < 0.9
            TG.check_case(c, driver_req, exec_req, count, driver_mask, domain, fill)
            checked += 1

    # -- batched FIFO program: admitted rows equal the sequential oracle
    #    threading availability (queue-mode eligibility: valid & schedulable
    #    & ready for drivers too, ops/batched.py queue mode)
    for _ in range(TRIALS // 2):
        c = TG.random_cluster(rng, N_NODES)
        b = 6
        drivers = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
        execs = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
        counts = rng.integers(0, emax + 1, size=b).astype(np.int32)
        apps = make_app_batch(drivers, execs, counts, skippable=np.ones(b, bool))
        out = jax.device_get(
            batched_fifo_pack(c, apps, fill="tightly-pack", emax=emax, num_zones=num_zones)
        )
        avail = np.asarray(c.available).astype(np.int64).copy()
        dom = np.asarray(c.valid)
        e_elig = dom & ~np.asarray(c.unschedulable) & np.asarray(c.ready)
        d_order = G.greedy_priority_order(
            np.asarray(c.available), np.asarray(c.zone_id), np.asarray(c.name_rank),
            e_elig, domain=dom, label_rank=np.asarray(c.label_rank_driver),
        )
        e_order = G.greedy_priority_order(
            np.asarray(c.available), np.asarray(c.zone_id), np.asarray(c.name_rank),
            e_elig, domain=dom, label_rank=np.asarray(c.label_rank_executor),
        )
        for i in range(b):
            g_driver, g_execs, g_ok, _ = G.greedy_spark_bin_pack(
                avail, drivers[i].astype(np.int64), execs[i].astype(np.int64),
                int(counts[i]), d_order, e_order, "tightly-pack",
            )
            assert bool(out.admitted[i]) == g_ok, (i, device)
            if g_ok:
                assert int(out.driver_node[i]) == g_driver, (i, device)
                got_execs = [int(x) for x in out.executor_nodes[i] if x >= 0]
                assert got_execs == list(g_execs), (i, device)
                avail[g_driver] -= drivers[i]
                for e in g_execs:
                    avail[e] -= execs[i]
        checked += 1

    # -- single-AZ batched admission on silicon: every admitted row must be
    #    a reference-acceptable zone pick against the threaded availability
    #    (the same acceptance-set oracle as
    #    tests/test_batched.py::test_batched_single_az_matches_sequential_oracle)
    from tests.test_batched import greedy_single_az_candidates

    for strategy in ("az-aware-tightly-pack", "single-az-tightly-pack"):
        c = TG.random_cluster(rng, N_NODES)
        b = 5
        drivers = rng.integers(1, 5, size=(b, 3)).astype(np.int32)
        execs = rng.integers(1, 5, size=(b, 3)).astype(np.int32)
        counts = rng.integers(1, emax + 1, size=b).astype(np.int32)
        apps = make_app_batch(drivers, execs, counts, skippable=np.ones(b, bool))
        out = jax.device_get(
            batched_fifo_pack(c, apps, fill=strategy, emax=emax, num_zones=num_zones)
        )
        avail = np.asarray(c.available).astype(np.int64).copy()
        sched = np.asarray(c.schedulable).astype(np.int64)
        zone = np.asarray(c.zone_id)
        dom = np.asarray(c.valid)
        e_elig = dom & ~np.asarray(c.unschedulable) & np.asarray(c.ready)
        d_order = G.greedy_priority_order(
            np.asarray(c.available), zone, np.asarray(c.name_rank),
            e_elig, domain=dom, label_rank=np.asarray(c.label_rank_driver),
        )
        e_order = G.greedy_priority_order(
            np.asarray(c.available), zone, np.asarray(c.name_rank),
            e_elig, domain=dom, label_rank=np.asarray(c.label_rank_executor),
        )
        for i in range(b):
            acceptable, ok = greedy_single_az_candidates(
                avail, sched, zone, d_order, e_order,
                drivers[i].astype(np.int64), execs[i].astype(np.int64),
                int(counts[i]), strategy,
            )
            assert bool(out.admitted[i]) == ok, (strategy, i, device)
            if ok:
                drv = int(out.driver_node[i])
                got_execs = [int(x) for x in out.executor_nodes[i] if x >= 0]
                assert (drv, got_execs) in acceptable, (strategy, i, device)
                avail[drv] -= drivers[i]
                for e in got_execs:
                    avail[e] -= execs[i]
        checked += 1

    # -- segmented serving windows on silicon: multi-segment scan equals
    #    per-segment solves threaded through the committed base (the
    #    windowed == solo serving property, core/solver.py pack_window)
    import dataclasses

    from tests.test_window_serving import _random_segments, _segment_batch

    for _ in range(2):
        c = TG.random_cluster(rng, N_NODES)
        segments = _random_segments(rng, 4, N_NODES)
        apps, real_row_of = _segment_batch(segments, N_NODES)
        got = jax.device_get(
            batched_fifo_pack(c, apps, fill="tightly-pack", emax=8, num_zones=num_zones)
        )
        base = np.asarray(c.available).copy()
        for s_idx, seg in enumerate(segments):
            sub, sub_real = _segment_batch([seg], N_NODES)
            ci = dataclasses.replace(c, available=base.astype(np.int32))
            want = jax.device_get(
                batched_fifo_pack(ci, sub, fill="tightly-pack", emax=8,
                                  num_zones=num_zones)
            )
            last = sub_real[0]
            real = real_row_of[s_idx]
            assert bool(got.admitted[real]) == bool(want.admitted[last]), (s_idx, device)
            assert int(got.driver_node[real]) == int(want.driver_node[last]), (s_idx, device)
            assert np.array_equal(
                np.asarray(got.executor_nodes[real]),
                np.asarray(want.executor_nodes[last]),
            ), (s_idx, device)
            if bool(want.admitted[last]):
                drv = int(want.driver_node[last])
                base[drv] -= np.asarray(seg["rows"][-1][0])
                for e in np.asarray(want.executor_nodes[last]):
                    if e >= 0:
                        base[e] -= np.asarray(seg["rows"][-1][1])
        checked += 1

    # -- Pallas queue kernel on silicon: the Mosaic program must equal the
    #    XLA scan decision-for-decision (same comparison as
    #    tests/test_pallas_fifo.py, here COMPILED on the real backend).
    from spark_scheduler_tpu.ops.pallas_fifo import (
        PALLAS_FILLS,
        fifo_pack_pallas,
        pallas_available,
    )

    if pallas_available():
        from spark_scheduler_tpu.ops.pallas_fifo import _SUBLANE_FOLD_MIN_NODES

        # Every fill at the small size (flat [1, Np] layout) AND above the
        # sublane-fold threshold (the [8, cols] layout): both compiled
        # layouts of all three fills are parity-checked on silicon.
        cases = [(fill, N_NODES) for fill in PALLAS_FILLS] + [
            (fill, _SUBLANE_FOLD_MIN_NODES + 104) for fill in PALLAS_FILLS
        ]
        for fill, n_case in cases:
            c = TG.random_cluster(rng, n_case)
            b = 8
            drivers = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
            execs = rng.integers(1, 8, size=(b, 3)).astype(np.int32)
            counts = rng.integers(0, emax + 3, size=b).astype(np.int32)
            apps = make_app_batch(
                drivers, execs, counts,
                skippable=rng.random(b) < 0.5,
            )
            want = jax.device_get(
                batched_fifo_pack(c, apps, fill=fill, emax=emax,
                                  num_zones=num_zones)
            )
            got = jax.device_get(
                fifo_pack_pallas(c, apps, fill=fill, emax=emax,
                                 num_zones=num_zones)
            )
            for field in ("driver_node", "executor_nodes", "admitted",
                          "packed", "available_after"):
                assert np.array_equal(
                    np.asarray(getattr(got, field)),
                    np.asarray(getattr(want, field)),
                ), ("pallas", fill, field, device)
            checked += 1

    # -- Pallas single-AZ strategies on silicon (VERDICT r3 #4): per-zone
    #    pack + efficiency-scored zone pick in-kernel == the XLA scan.
    if pallas_available():
        from spark_scheduler_tpu.ops.pallas_fifo import PALLAS_SINGLE_AZ

        for saz_fill in sorted(PALLAS_SINGLE_AZ):
            srng = np.random.default_rng(151 + len(saz_fill))
            c = TG.random_cluster(srng, N_NODES)
            b = 8
            apps = make_app_batch(
                srng.integers(1, 6, size=(b, 3)).astype(np.int32),
                srng.integers(1, 8, size=(b, 3)).astype(np.int32),
                srng.integers(0, emax + 3, size=b).astype(np.int32),
                skippable=srng.random(b) < 0.5,
            )
            want = jax.device_get(
                batched_fifo_pack(c, apps, fill=saz_fill, emax=emax,
                                  num_zones=num_zones)
            )
            got = jax.device_get(
                fifo_pack_pallas(c, apps, fill=saz_fill, emax=emax,
                                 num_zones=num_zones)
            )
            for field in ("driver_node", "executor_nodes", "admitted",
                          "packed", "available_after"):
                assert np.array_equal(
                    np.asarray(getattr(got, field)),
                    np.asarray(getattr(want, field)),
                ), ("pallas-single-az", saz_fill, field, device)
            checked += 1

    # -- Pallas SEGMENTED WINDOW path on silicon (VERDICT r3 #3): the
    #    scan-over-segments Mosaic program must equal the segmented XLA
    #    scan decision-for-decision for all six strategies (plain fills,
    #    and since r5 the single-AZ wrappers through make_gang_solver).
    if pallas_available():
        from tests.test_pallas_window import _cluster as _pw_cluster
        from tests.test_pallas_window import _random_window as _pw_window
        from spark_scheduler_tpu.ops.pallas_fifo import PALLAS_SINGLE_AZ
        from spark_scheduler_tpu.ops.pallas_window import window_pack_pallas

        for fill in PALLAS_FILLS + tuple(PALLAS_SINGLE_AZ):
            prng = np.random.default_rng(97 + len(fill))
            c = _pw_cluster(prng, N_NODES)
            apps, win, flat_map = _pw_window(
                prng, N_NODES, n_requests=4, max_rows=4, emax=emax
            )
            want = jax.device_get(
                batched_fifo_pack(c, apps, fill=fill, emax=emax,
                                  num_zones=num_zones)
            )
            meta, execs_w, base_after = (
                jax.device_get(x)
                for x in window_pack_pallas(
                    c, win, fill=fill, emax=emax, num_zones=num_zones
                )
            )
            for bi, (s, j) in enumerate(flat_map):
                assert meta[s, j, 1] == want.admitted[bi], (
                    "pallas-window", fill, bi, device)
                assert meta[s, j, 0] == want.driver_node[bi], (
                    "pallas-window", fill, bi, device)
                assert np.array_equal(
                    execs_w[s, j], np.asarray(want.executor_nodes[bi])
                ), ("pallas-window", fill, bi, device)
            assert np.array_equal(
                np.asarray(base_after), np.asarray(want.available_after)
            ), ("pallas-window", fill, "base", device)
            checked += 1

    # -- grouped single-chip fast path: the jitted per-group Pallas loop
    #    (grouped_fifo_pack_auto) must equal the vmapped XLA scan
    #    group-for-group on silicon.
    if pallas_available():
        from spark_scheduler_tpu.parallel import (
            grouped_fifo_pack,
            grouped_fifo_pack_auto,
            make_solver_mesh,
            stack_groups,
        )

        # One-device mesh EXPLICITLY: on a multi-chip host a full-device
        # mesh would route auto to the GSPMD scan and this check would
        # vacuously compare the scan with itself.
        mesh = make_solver_mesh(n_groups=1, devices=jax.devices()[:1])
        clusters, app_batches = [], []
        for _ in range(3):
            clusters.append(TG.random_cluster(rng, N_NODES))
            b = 6
            app_batches.append(
                make_app_batch(
                    rng.integers(1, 6, size=(b, 3)).astype(np.int32),
                    rng.integers(1, 6, size=(b, 3)).astype(np.int32),
                    rng.integers(0, emax + 1, size=b).astype(np.int32),
                    skippable=rng.random(b) < 0.5,
                )
            )
        sc, sa = stack_groups(clusters, app_batches)
        want = jax.device_get(
            grouped_fifo_pack(
                mesh, sc, sa, fill="tightly-pack", emax=emax,
                num_zones=num_zones,
            )
        )
        got = jax.device_get(
            grouped_fifo_pack_auto(
                mesh, sc, sa, fill="tightly-pack", emax=emax,
                num_zones=num_zones,
            )
        )
        for field in ("driver_node", "executor_nodes", "admitted", "packed",
                      "available_after"):
            assert np.array_equal(
                np.asarray(getattr(got, field)),
                np.asarray(getattr(want, field)),
            ), ("grouped-pallas", field, device)
        checked += 1

    return {"device": device, "cases_checked": checked, "parity": "ok"}


def main() -> int:
    print(json.dumps(run()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
