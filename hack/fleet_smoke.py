"""Fleet federation smoke (ISSUE 19, the CI `fleet` job leg): boot a
3-cluster FleetFacade behind one HTTP serving endpoint, drive real
gang traffic over POST /predicates with cluster-tagged calls (including
deliberately WRONG tags — the forwarding path), then run the seeded
kill/rejoin chaos soak and hold the fleet invariants:

  * zero double placements — an app's reservation lives in at most one
    cluster at every checkpoint, through a cluster kill and rejoin;
  * zero over-commits on any node of any cluster;
  * every orphaned PENDING gang (routed to the dead cluster, never
    placed) is re-routed off it;
  * resident per-cluster aggregates equal a from-scratch walk;
  * every cluster's decision stream replays byte-identical on a
    standalone stack.

STACKED MODE (ISSUE 20, the CI `fleet-stacked` job leg): with
FLEET_SMOKE_STACK=1 the smoke additionally (a) drives concurrent
per-cluster gang traffic against a facade running the
FleetDispatchCoordinator and asserts stacked_dispatches > 0 with
forced_resolves == 0 and byte-identical oplog equivalence, and (b)
re-runs the chaos soak in stacking mode (concurrent bursts, kill lands
mid-gather) holding every invariant above unchanged.

Env knobs: FLEET_SMOKE_STEPS (default 60), FLEET_SMOKE_SEED (default 1),
FLEET_SMOKE_STACK (default 0). Exits non-zero (assert) on any violation;
prints one JSON summary line.
"""

import json
import os
import sys
import threading
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

STEPS = int(os.environ.get("FLEET_SMOKE_STEPS", "60"))
SEED = int(os.environ.get("FLEET_SMOKE_SEED", "1"))
STACK = os.environ.get("FLEET_SMOKE_STACK", "0") == "1"


def _req(port, method, path, payload=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode() if payload is not None else None,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(r) as resp:
        return resp.status, json.loads(resp.read())


def _k8s_spark_pod(app_id, role, name, group, executors=1):
    return {
        "metadata": {
            "name": name,
            "namespace": "ns",
            "uid": f"uid-{name}",
            "labels": {"spark-role": role, "spark-app-id": app_id},
            "annotations": {
                "spark-driver-cpu": "1",
                "spark-driver-mem": "1Gi",
                "spark-executor-cpu": "1",
                "spark-executor-mem": "1Gi",
                "spark-executor-count": str(executors),
            },
            "creationTimestamp": "2026-08-07T12:00:00Z",
        },
        "spec": {
            "schedulerName": "spark-scheduler",
            "nodeSelector": {"resource_channel": group},
            "containers": [
                {
                    "name": "main",
                    "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
                }
            ],
        },
        "status": {"phase": "Pending"},
    }


def serve_over_http():
    """Boot 3 clusters behind one endpoint; schedule gangs with right AND
    wrong ?cluster= tags; verify forwarding + /debug/fleet."""
    from spark_scheduler_tpu.fleet import FleetFacade, verify_cluster_equivalence
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )

    cfg = InstallConfig(
        fifo=True, sync_writes=True, instance_group_label=INSTANCE_GROUP_LABEL
    )
    facade = FleetFacade(3, cfg, record_ops=True)
    for c in range(3):
        for i in range(2):
            facade.add_node(
                c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
            )
    server = SchedulerHTTPServer(
        facade.stacks[0].app, host="127.0.0.1", port=0, fleet=facade
    )
    server.start()
    try:
        placed = 0
        for k in range(6):
            group = f"ig-{k % 3}"
            app = f"smoke-http-{k}"
            # Tag half the calls with the WRONG cluster endpoint: they
            # must forward to the owner with identical decision bytes.
            via = (k % 3) if k < 3 else ((k + 1) % 3)
            for role, name in (
                ("driver", f"{app}-driver"),
                ("executor", f"{app}-exec-0"),
            ):
                status, result = _req(
                    server.port,
                    "POST",
                    f"/predicates?cluster={via}",
                    {
                        "Pod": _k8s_spark_pod(app, role, name, group),
                        "NodeNames": [],
                    },
                )
                assert status == 200 and result["NodeNames"], (
                    f"{name} via c{via}: {result}"
                )
                assert result["NodeNames"][0].startswith(f"c{k % 3}-"), (
                    f"{name} placed off-home: {result}"
                )
                placed += 1
        status, dbg = _req(server.port, "GET", "/debug/fleet")
        assert status == 200
        assert dbg["forwarded"] == 6, dbg  # 3 wrong-tagged apps x 2 pods
        assert all(c["live"] for c in dbg["clusters"])
        verify_cluster_equivalence(facade)
        return {"http_decisions": placed, "forwarded": dbg["forwarded"]}
    finally:
        server.stop()
        facade.stop()


def stacked_serving():
    """ISSUE 20: concurrent per-cluster gangs against the dispatch
    coordinator — windows must stack (stacked_dispatches > 0) with no
    forced resolves, and every cluster's oplog must replay
    byte-identical on a standalone (unstacked) stack."""
    from spark_scheduler_tpu.fleet import (
        FleetFacade,
        verify_cluster_equivalence,
    )
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
        static_allocation_spark_pods,
    )

    cfg = InstallConfig(
        fifo=True, sync_writes=True, instance_group_label=INSTANCE_GROUP_LABEL
    )
    facade = FleetFacade(
        3, cfg, record_ops=True, stack_window_ms=150.0
    )
    for c in range(3):
        for i in range(2):
            facade.add_node(
                c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
            )

    def pump(c, k):
        pods = static_allocation_spark_pods(
            f"smoke-stack-c{c}-{k}", 1, instance_group=f"ig-{c}"
        )
        for p in pods:
            facade.schedule(p)

    try:
        for k in range(4):
            ts = [
                threading.Thread(target=pump, args=(c, k)) for c in range(3)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        st = facade.state()["stacking"]
        assert st["stacked_dispatches"] > 0, st
        assert st["forced_resolves"] == 0, st
        eq = verify_cluster_equivalence(facade)
        assert all(r["identical"] for r in eq.values()), eq
        return {
            "stacked_dispatches": st["stacked_dispatches"],
            "stack_arms": st["stack_arms"],
            "stack_fallbacks": st["fallbacks"],
        }
    finally:
        facade.stop()


def chaos_soak(stack_window_ms: float = 0.0):
    from spark_scheduler_tpu.testing.soak import FleetSoak

    steps = max(12, STEPS // 3) if stack_window_ms > 0 else STEPS
    soak = FleetSoak(
        n_clusters=3,
        nodes_per_cluster=2,
        seed=SEED,
        stack_window_ms=stack_window_ms,
    )
    try:
        soak.run(
            steps=steps,
            kill_at=max(2, steps * 5 // 8),
            rejoin_at=max(3, steps * 4 // 5),
        )
        v = soak.verdict()
    finally:
        soak.stop()
    assert v["double_placements"] == [], v["double_placements"]
    assert v["overcommit"] == [], v["overcommit"]
    assert v["oracle_mismatches"] == [], v["oracle_mismatches"]
    assert v["orphans_unrouted"] == [], v["orphans_unrouted"]
    assert v["placed"] > 0, v
    assert all(r["identical"] for r in v["equivalence"].values())
    out = {
        "steps": v["steps"],
        "placed": v["placed"],
        "pending": v["pending"],
        "spillovers": v["spillovers"],
        "orphans_at_kill": v["orphans_at_kill"],
        "double_placements": 0,
        "overcommit": 0,
        "byte_identical_clusters": len(v["equivalence"]),
    }
    if stack_window_ms > 0:
        st = v["stacking"]
        assert st["stacked_dispatches"] > 0, st
        out = {f"chaos_{k}": x for k, x in out.items()}
        out["chaos_stacked_dispatches"] = st["stacked_dispatches"]
        out["chaos_forced_resolves"] = st["forced_resolves"]
    else:
        assert v["spillovers"] > 0, v
    return out


def main():
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))
    summary = {"smoke": "fleet-stacked" if STACK else "fleet", "seed": SEED}
    summary.update(serve_over_http())
    if STACK:
        summary.update(stacked_serving())
        summary.update(chaos_soak(stack_window_ms=75.0))
    else:
        summary.update(chaos_soak())
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
