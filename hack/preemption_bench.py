"""Preemption-search A/B (policy subsystem): ONE batched masked-fit pass
over all candidate eviction sets vs the sequential per-candidate loop it
replaces, at 10k and 100k nodes.

Both arms answer the same question the policy engine asks on a fit denial:
"which prefix of the victim list, once evicted, admits this gang?" The
batched arm is the shipping path — a single `solver.preemption_search` call
whose vmapped kernel probes all C candidate sets in one device program. The
sequential arm issues C single-candidate probes (the per-candidate kernel
loop the kernel replaces), early-exiting at the first feasible prefix the
way a host loop would. Feasible-index agreement is asserted between arms.

One JSON line per (nodes, arm) on stdout; standalone:
    python hack/preemption_bench.py
Env: PREEMPT_BENCH_NODES="10000,100000"  PREEMPT_BENCH_REPS="20"
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import json
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

import numpy as np

CANDIDATES = 8  # the policy default: max_evictions=8 nested prefixes
EXECS = 28  # big gang: the minimal eviction set is the LAST prefix —
# the sustained-pressure case, where a host loop pays every probe
STRATEGY = "tightly-pack"


def _nodes(n):
    from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
    from spark_scheduler_tpu.models.resources import Resources

    alloc = Resources.from_quantities("8", "8Gi", "1", round_up=False)
    return [
        Node(
            name=f"pb-n{i:06d}",
            allocatable=alloc,
            labels={ZONE_LABEL: f"z{i % 4}"},
        )
        for i in range(n)
    ]


def _freed_cum(rng, registry, rows, n, victim_res):
    """[C, rows, 3] cumulative freed capacity: victim c releases a 4-slot
    gang on a distinct same-zone node (nodes 0,4,8,... share z0), scattered
    through the solver's registry index space exactly like the real
    enumerator (policy/preemption.py freed_prefixes) — nested prefixes,
    monotone. With a 29-slot requester, only a deep prefix admits it."""
    step = np.zeros((CANDIDATES, rows, victim_res.shape[0]), dtype=np.int64)
    picks = rng.choice(n // 4, size=CANDIDATES, replace=False) * 4
    for c, i in enumerate(picks):
        idx = registry.index_of(f"pb-n{i:06d}")
        assert idx is not None and idx < rows
        step[c, idx] = victim_res * 4
    return np.cumsum(step, axis=0)


def run(n, reps):
    from spark_scheduler_tpu.core.solver import PlacementSolver
    from spark_scheduler_tpu.models.resources import Resources

    rng = np.random.default_rng(4242 + n)
    nodes = _nodes(n)
    names = [nd.name for nd in nodes]
    one = Resources.from_quantities("1", "1Gi")

    solver = PlacementSolver()
    # Saturated cluster: every node fully used, so only freed capacity can
    # admit the gang — the search has real work to do.
    usage = {
        nd.name: Resources.from_quantities("8", "8Gi", "0", round_up=False)
        for nd in nodes
    }
    tensors = solver.build_tensors(nodes, usage, {})
    freed = _freed_cum(
        rng,
        solver.registry,
        tensors.available.shape[0],
        n,
        one.as_array().astype(np.int64),
    )

    def batched():
        return solver.preemption_search(
            STRATEGY, tensors, one, one, EXECS, names, freed
        )[0]

    def sequential():
        # The per-candidate loop the batched kernel replaces: one
        # single-candidate device probe per eviction set, early exit.
        for c in range(CANDIDATES):
            idx, _ = solver.preemption_search(
                STRATEGY, tensors, one, one, EXECS, names, freed[c : c + 1]
            )
            if idx == 0:
                return c  # early exit — the loop's best case
        return -1

    # Warmup (compilation) outside the clock, and the agreement check.
    want = batched()
    assert sequential() == want, "arms disagree on the minimal eviction set"

    out = []
    for label, fn in (("batched", batched), ("sequential", sequential)):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append((time.perf_counter() - t0) * 1e3)
        out.append(
            {
                "nodes": n,
                "arm": label,
                "candidates": CANDIDATES,
                "search_p50_ms": round(float(np.percentile(times, 50)), 2),
                "search_mean_ms": round(float(np.mean(times)), 2),
                "feasible_index": want,
            }
        )
    solver.close()
    return out


def main():
    node_counts = [
        int(x)
        for x in os.environ.get(
            "PREEMPT_BENCH_NODES", "10000,100000"
        ).split(",")
    ]
    reps = int(os.environ.get("PREEMPT_BENCH_REPS", "20"))
    for n in node_counts:
        rows = run(n, reps)
        for r in rows:
            print(json.dumps(r), flush=True)
        b, s = rows[0], rows[1]
        print(
            json.dumps(
                {
                    "nodes": n,
                    "speedup_p50": round(
                        s["search_p50_ms"] / max(b["search_p50_ms"], 1e-9), 2
                    ),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
