"""In-process serving latency on a LOCAL jax backend (VERDICT r4 #7).

The HTTP solo-predicate p50 on the bench rig is transport-bound: one
decision pull rides the ~100 ms tunneled-TPU RTT, so the served number can
never show what the scheduler costs when the accelerator is locally
attached. This script runs the SAME serving path — predicate_batch ->
window solve -> reservation write-back — entirely in process against the
process-local backend (cpu; the site hook's axon platform is overridden
before any jax op), so the per-call cost is the solve itself.

Run by bench.py as a subprocess (one JSON line on stdout); standalone:
    python hack/inprocess_bench.py
"""

from __future__ import annotations

import jax

jax.config.update("jax_platforms", "cpu")  # before any jax op

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def main() -> int:
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
        static_allocation_spark_pods,
    )
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))
    n_nodes = int(os.environ.get("INPROC_NODES", "500"))
    backend = InMemoryBackend()
    names = []
    for i in range(n_nodes):
        node = new_node(f"n{i}", zone=f"zone{i % 4}")
        backend.add_node(node)
        names.append(node.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
        ),
    )
    ext = app.extender
    lats = []
    n_requests, warmup = 48, 8  # warmup covers the row-bucket compiles
    for i in range(n_requests):
        driver = static_allocation_spark_pods(f"ip-{i}", 8)[0]
        backend.add_pod(driver)
        t0 = time.perf_counter()
        res = ext.predicate_batch(
            [ExtenderArgs(pod=driver, node_names=list(names))]
        )[0]
        dt_ms = (time.perf_counter() - t0) * 1e3
        if not res.node_names:
            raise RuntimeError(f"in-process request {i} failed: {res}")
        backend.bind_pod(driver, res.node_names[0])
        if i >= warmup:
            lats.append(dt_ms)
    print(
        json.dumps(
            {
                "p50_ms": round(float(np.percentile(lats, 50)), 3),
                "p95_ms": round(float(np.percentile(lats, 95)), 3),
                "n": len(lats),
                "nodes": n_nodes,
                "device": str(jax.devices()[0]),
                "fused_k": 1,
                "path": "in-process predicate_batch (no HTTP, no tunnel)",
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
