"""Fault-recovery bench (ISSUE 9): slot-kill mid-burst on a 2-slot pool.

Three arms over the SAME seeded workload (1,280 nodes in 2 instance
groups, pipelined serving windows through the extender):

  steady      2-slot pool, no faults — the throughput baseline;
  slot_kill   one device slot dies mid-burst (FaultInjector device
              surface): the dead partition is quarantined and
              re-dispatched on the survivor; reports decisions/s dip vs
              steady, the faulted window's wall latency (time-to-recover
              proxy) vs the steady per-window median, and ASSERTS the
              decisions are byte-identical to the steady arm's;
  all_killed  every slot dies and stays dead: the degraded greedy
              fallback serves the rest of the burst — the throughput
              floor when no device can serve (also asserted
              byte-identical).

Forces an 8-device virtual CPU mesh BEFORE jax initializes, so it runs
as a subprocess (bench.py `fault_recovery` section). One JSON line per
arm on stdout; standalone:
    python hack/fault_recovery_bench.py
"""

from __future__ import annotations

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")  # before any jax op

import json
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

N_GROUPS = 2
NODES_PER_GROUP = 640
WINDOW = 8  # 4 drivers per group per window
N_WINDOWS = 8
KILL_AT_DISPATCH = 5  # device.dispatch event index that dies mid-burst


def _build():
    from spark_scheduler_tpu.faults.degraded import DegradedModeController
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )

    backend = InMemoryBackend()
    group_names: dict[int, list[str]] = {}
    for g in range(N_GROUPS):
        group_names[g] = []
        for i in range(NODES_PER_GROUP):
            node = new_node(
                f"g{g}-n{i}", zone=f"zone{i % 4}",
                instance_group=f"group-{g}",
            )
            backend.add_node(node)
            group_names[g].append(node.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_device_pool=2,
            degraded_mode="greedy",
        ),
    )
    assert isinstance(app.solver.degraded, DegradedModeController)
    return backend, app, group_names


def _run_arm(arm: str) -> dict:
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.faults import FaultInjector, FaultPlan, FaultSpec
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )

    backend, app, group_names = _build()
    ext = app.extender

    def dispatch_window(tag, k):
        drivers = []
        args = []
        for j in range(WINDOW):
            g = j % N_GROUPS
            pod = static_allocation_spark_pods(
                f"frb-{tag}-{k}-{j}", 4, instance_group=f"group-{g}"
            )[0]
            backend.add_pod(pod)
            drivers.append(pod)
            args.append(
                ExtenderArgs(pod=pod, node_names=list(group_names[g]))
            )
        return drivers, ext.predicate_window_dispatch(args)

    def complete_window(drivers, t):
        placements = []
        results = ext.predicate_window_complete(t)
        for d, r in zip(drivers, results):
            if not r.node_names:
                raise RuntimeError(f"{d.name}: {r.outcome}")
            backend.bind_pod(d, r.node_names[0])
            placements.append((d.name, r.node_names[0]))
        return placements

    # Warm compiles for every shape this arm hits (device AND fallback).
    for w in range(2):
        complete_window(*dispatch_window("warm", w))

    plan = None
    if arm == "slot_kill":
        plan = FaultPlan(
            seed=0, name="bench-slot-kill",
            specs=[FaultSpec(surface="device.dispatch", mode="error",
                             at=[KILL_AT_DISPATCH], limit=1)],
        )
    elif arm == "all_killed":
        plan = FaultPlan(
            seed=0, name="bench-pool-down",
            specs=[FaultSpec(surface="device.dispatch", mode="partition",
                             start=KILL_AT_DISPATCH)],
        )
    injector = FaultInjector(plan) if plan is not None else None

    window_ms: list[float] = []
    placements: list = []
    try:
        if injector is not None:
            injector.__enter__()
            injector.install_device()
        t0 = time.perf_counter()
        for k in range(N_WINDOWS):
            w0 = time.perf_counter()
            placements.extend(complete_window(*dispatch_window("run", k)))
            window_ms.append((time.perf_counter() - w0) * 1e3)
        wall = time.perf_counter() - t0
    finally:
        if injector is not None:
            injector.__exit__(None, None, None)

    solver = app.solver
    ordered = sorted(window_ms)
    out = {
        "arm": arm,
        "decisions_per_s": round(WINDOW * N_WINDOWS / wall, 1),
        "windows_of": WINDOW,
        "windows": N_WINDOWS,
        "nodes": N_GROUPS * NODES_PER_GROUP,
        "window_p50_ms": round(ordered[len(ordered) // 2], 2),
        "window_max_ms": round(ordered[-1], 2),
        "device_health": solver.device_health(),
        "redispatches": solver.redispatch_count,
        "placements": placements,
    }
    if solver.degraded is not None:
        snap = solver.degraded.snapshot()
        out["degraded"] = {
            "active": snap["active"],
            "engagements": snap["engagements"],
            "fallback_decisions": snap["fallback_decisions"],
        }
    app.stop()
    return out


def main() -> int:
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))
    steady = _run_arm("steady")
    arms = [steady]
    for arm in ("slot_kill", "all_killed"):
        res = _run_arm(arm)
        # Byte-identical recovery: the same workload must land the same
        # placements with a dead slot (survivor re-dispatch) and with a
        # dead POOL (greedy fallback) as it does fault-free.
        assert res.pop("placements") == steady["placements"], (
            f"{arm} placements diverged from steady"
        )
        res["byte_identical_to_steady"] = True
        res["dip_vs_steady"] = round(
            res["decisions_per_s"] / steady["decisions_per_s"], 3
        )
        # Time-to-recover proxy: the faulted window's wall latency over
        # the steady per-window median — what the burst actually paid for
        # quarantine + re-upload + re-dispatch (or fallback engagement).
        res["recovery_spike_ms"] = round(
            res["window_max_ms"] - steady["window_p50_ms"], 2
        )
        arms.append(res)
    steady_out = dict(steady)
    steady_out.pop("placements", None)
    print(json.dumps(steady_out), flush=True)
    for res in arms[1:]:
        print(json.dumps(res), flush=True)
    # Sanity: the slot-kill arm actually killed a slot, the all-killed
    # arm actually degraded.
    slot_kill, all_killed = arms[1], arms[2]
    assert slot_kill["redispatches"] >= 1
    assert len(slot_kill["device_health"]["quarantined"]) == 1
    assert all_killed["degraded"]["fallback_decisions"] > 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
