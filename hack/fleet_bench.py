"""Fleet scaling bench: F=4 concurrent per-cluster stacks vs ONE cluster
serving the same total load behind one pipeline.

The acceptance bar (ISSUE 19): at F=4 clusters on a >=4-slot pool rig,
aggregate decisions/s >= 3x the single-cluster control — concurrent
per-cluster solves, not round-robin serialization — AND per-cluster
decisions byte-identical to a standalone cluster replaying the same op
stream. Both are asserted IN-ARM: a run that fails either raises.

The device round trip is simulated (testing/rtt_shim.SimulatedRTT, the
fused-dispatch precedent): each window pays a sleeping RTT on the thread
that would pay it over a real tunnel, and sleeps overlap across the
fleet's per-cluster worker threads exactly as the per-device RPCs would.
On this 2-core CPU rig the XLA solve itself is ~ms and partially
serializes on the shared CPU backend; the RTT is what scales, which is
honest to the production shape where the tunnel dominates.

Emits one JSON line per arm (bench.py fleet_scaling section collects
them) and a final summary line.
"""

import os

# A >=4-slot pool rig, forced before jax initializes (CPU container).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse
import json
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

CLUSTERS = 4
APPS_PER_CLUSTER = 5
EXECUTORS = 2  # gang = driver + 2 executors -> 3 decisions per app


def _emit(entry):
    print(json.dumps(entry), flush=True)


def build_apps(cluster, n_apps):
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )

    return [
        static_allocation_spark_pods(
            f"fleet-app-c{cluster}-{k}", EXECUTORS,
            instance_group=f"ig-{cluster}",
        )
        for k in range(n_apps)
    ]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=CLUSTERS)
    ap.add_argument("--apps-per-cluster", type=int, default=APPS_PER_CLUSTER)
    ap.add_argument("--rtt-ms", type=float, default=40.0)
    ap.add_argument("--nodes-per-cluster", type=int, default=8)
    ap.add_argument("--min-speedup", type=float, default=3.0)
    args = ap.parse_args()

    import jax

    from spark_scheduler_tpu.fleet import (
        ClusterStack,
        FleetFacade,
        verify_cluster_equivalence,
    )
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )
    from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))

    n_devices = len(jax.devices())
    F = args.clusters
    cfg = InstallConfig(
        fifo=True, sync_writes=True,
        instance_group_label=INSTANCE_GROUP_LABEL,
    )
    decisions_per_app = 1 + EXECUTORS
    total_apps = F * args.apps_per_cluster
    total_decisions = total_apps * decisions_per_app

    # --- warm the kernels OUTSIDE the timed arms, for BOTH arms' window
    # shapes (the control's consolidated cluster pads to a different
    # bucket than a fleet cluster — an unwarmed control would pay its
    # first-compiles inside the wall clock and flatter the fleet arm).
    for n_nodes, tag in (
        (F * args.nodes_per_cluster, "warm-big"),
        (args.nodes_per_cluster, "warm-small"),
    ):
        warm = ClusterStack(0, cfg, threaded=False)
        for i in range(n_nodes):
            warm.add_node(
                new_node(f"{tag}-n{i}", instance_group=f"ig-{i % F}")
            )
        for c in range(F):
            for pods in build_apps(c, 1):
                for p in pods:
                    warm.schedule(p)
        warm.stop()

    # --- control arm: ONE cluster, all nodes, the whole load through one
    # pipeline (the serialization baseline the facade removes).
    control = ClusterStack(0, cfg, threaded=False, record_ops=False)
    for c in range(F):
        for i in range(args.nodes_per_cluster):
            control.add_node(
                new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
            )
    control_apps = [
        pods
        for c in range(F)
        for pods in build_apps(c, args.apps_per_cluster)
    ]
    with SimulatedRTT(args.rtt_ms):
        t0 = time.perf_counter()
        for pods in control_apps:
            for p in pods:
                r = control.schedule(p)
                assert r.ok, f"control denial: {r.outcome}"
        control_wall = time.perf_counter() - t0
    control.stop()
    control_rate = total_decisions / control_wall
    _emit({
        "metric": "fleet_decisions_per_s_single_cluster",
        "value": round(control_rate, 1),
        "unit": "decisions/s",
        "vs_baseline": 1.0,
        "clusters": 1,
        "spillovers": 0,
        "detail": {
            "decisions": total_decisions,
            "wall_s": round(control_wall, 3),
            "rtt_ms": args.rtt_ms,
            "devices": n_devices,
        },
    })

    # --- fleet arm: F stacks, same total load, one client thread per
    # cluster (kube-scheduler fans out across cluster endpoints), every
    # cluster's op stream recorded for the in-arm equivalence check.
    facade = FleetFacade(F, cfg, record_ops=True)
    for c in range(F):
        for i in range(args.nodes_per_cluster):
            facade.add_node(
                c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
            )
    fleet_apps = {
        c: build_apps(c, args.apps_per_cluster) for c in range(F)
    }
    errors = []

    def pump(c):
        try:
            for pods in fleet_apps[c]:
                for p in pods:
                    d = facade.schedule(p, via=c)
                    assert d.ok, (
                        f"fleet denial c{c}: {d.result.outcome}"
                    )
        except Exception as exc:  # surfaced after join
            errors.append(exc)

    with SimulatedRTT(args.rtt_ms):
        threads = [
            threading.Thread(target=pump, args=(c,)) for c in range(F)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fleet_wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    fleet_rate = total_decisions / fleet_wall
    speedup = fleet_rate / control_rate

    # In-arm assertion #1: concurrency actually scaled throughput.
    assert speedup >= args.min_speedup, (
        f"fleet scaling below bar: {speedup:.2f}x < {args.min_speedup}x "
        f"(fleet {fleet_rate:.1f}/s vs single {control_rate:.1f}/s)"
    )
    # In-arm assertion #2: every cluster's decisions byte-identical to a
    # standalone cluster replaying the same op stream.
    equivalence = verify_cluster_equivalence(facade)

    st = facade.state()
    _emit({
        "metric": f"fleet_decisions_per_s_{F}_clusters",
        "value": round(fleet_rate, 1),
        "unit": "decisions/s",
        # vs_baseline = speedup / 3: >= 1.0 clears the acceptance bar.
        "vs_baseline": round(speedup / args.min_speedup, 2),
        "clusters": F,
        "spillovers": st["spillover"]["spilled"],
        "detail": {
            "decisions": total_decisions,
            "wall_s": round(fleet_wall, 3),
            "speedup_vs_single": round(speedup, 2),
            "rtt_ms": args.rtt_ms,
            "devices": n_devices,
            "byte_identical_clusters": len(equivalence),
            "router_picks": st["router"]["picks"],
            "forwarded": st["forwarded"],
        },
    })
    facade.stop()
    _emit({
        "metric": "fleet_scaling_summary",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / args.min_speedup, 2),
        "clusters": F,
        "spillovers": st["spillover"]["spilled"],
        "detail": {
            "single_cluster_decisions_per_s": round(control_rate, 1),
            "fleet_decisions_per_s": round(fleet_rate, 1),
            "equivalence": {str(k): v for k, v in equivalence.items()},
        },
    })


if __name__ == "__main__":
    main()
