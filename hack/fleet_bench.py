"""Fleet scaling bench: F=4 concurrent per-cluster stacks vs ONE cluster
serving the same total load behind one pipeline — plus the fused-dispatch
A/B (stacked vs unstacked fleet, ISSUE 20).

The acceptance bar (ISSUE 19): at F=4 clusters on a >=4-slot pool rig,
aggregate decisions/s >= 3x the single-cluster control — concurrent
per-cluster solves, not round-robin serialization — AND per-cluster
decisions byte-identical to a standalone cluster replaying the same op
stream. Both are asserted IN-ARM: a run that fails either raises.

The device round trip is simulated (testing/rtt_shim.SimulatedRTT, the
fused-dispatch precedent): each window pays a sleeping RTT on the thread
that would pay it over a real tunnel, and sleeps overlap across the
fleet's per-cluster worker threads exactly as the per-device RPCs would.
On this 2-core CPU rig the XLA solve itself is ~ms and partially
serializes on the shared CPU backend; the RTT is what scales, which is
honest to the production shape where the tunnel dominates.

The STACKED section (ISSUE 20 bar: >=1.5x at F=4 / 40 ms) runs both its
arms under `tunnel_serialized=True` — one shared device link, where F
concurrent per-cluster round trips queue instead of overlapping. That is
the regime the fused fleet dispatch exists for: the unstacked fleet pays
F serialized RTTs per round of windows, the stacked fleet gathers them
into ONE `bucket_stacked_fifo_pack` launch and pays one. Arms INTERLEAVE
(off, on, off, on) over the same offered-load trace after a shared
untimed warm round per mode, so neither mode inherits the other's
compile warmup, and the reported rate is the mean of its reps. Asserted
in-arm: speedup >= --min-stack-speedup, stacked_dispatches > 0,
forced_resolves == 0, and per-cluster byte-identity
(verify_cluster_equivalence) in the same run.

Emits one JSON line per arm (fleet serving lines carry
stacked_dispatches/stack_arms) and a final summary line per section.
"""

import os

# A >=4-slot pool rig, forced before jax initializes (CPU container).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse
import json
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

CLUSTERS = 4
APPS_PER_CLUSTER = 5
EXECUTORS = 2  # gang = driver + 2 executors -> 3 decisions per app


def _emit(entry):
    print(json.dumps(entry), flush=True)


def build_apps(cluster, n_apps):
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )

    return [
        static_allocation_spark_pods(
            f"fleet-app-c{cluster}-{k}", EXECUTORS,
            instance_group=f"ig-{cluster}",
        )
        for k in range(n_apps)
    ]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=CLUSTERS)
    ap.add_argument("--apps-per-cluster", type=int, default=APPS_PER_CLUSTER)
    ap.add_argument("--rtt-ms", type=float, default=40.0)
    ap.add_argument("--nodes-per-cluster", type=int, default=8)
    ap.add_argument("--min-speedup", type=float, default=3.0)
    ap.add_argument(
        "--stack-window-ms",
        type=float,
        default=120.0,
        help="gather window for the stacked arms (fleet.stack-window-ms)",
    )
    ap.add_argument("--min-stack-speedup", type=float, default=1.5)
    ap.add_argument(
        "--stack-reps",
        type=int,
        default=2,
        help="interleaved reps per stacked-section mode (off/on pairs)",
    )
    ap.add_argument(
        "--skip-stacked",
        action="store_true",
        help="run only the ISSUE 19 scaling section",
    )
    args = ap.parse_args()

    import jax

    from spark_scheduler_tpu.fleet import (
        ClusterStack,
        FleetFacade,
        verify_cluster_equivalence,
    )
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )
    from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(os.devnull, "w")))

    n_devices = len(jax.devices())
    F = args.clusters
    cfg = InstallConfig(
        fifo=True, sync_writes=True,
        instance_group_label=INSTANCE_GROUP_LABEL,
    )
    decisions_per_app = 1 + EXECUTORS
    total_apps = F * args.apps_per_cluster
    total_decisions = total_apps * decisions_per_app

    # --- warm the kernels OUTSIDE the timed arms, for BOTH arms' window
    # shapes (the control's consolidated cluster pads to a different
    # bucket than a fleet cluster — an unwarmed control would pay its
    # first-compiles inside the wall clock and flatter the fleet arm).
    for n_nodes, tag in (
        (F * args.nodes_per_cluster, "warm-big"),
        (args.nodes_per_cluster, "warm-small"),
    ):
        warm = ClusterStack(0, cfg, threaded=False)
        for i in range(n_nodes):
            warm.add_node(
                new_node(f"{tag}-n{i}", instance_group=f"ig-{i % F}")
            )
        for c in range(F):
            for pods in build_apps(c, 1):
                for p in pods:
                    warm.schedule(p)
        warm.stop()

    # --- control arm: ONE cluster, all nodes, the whole load through one
    # pipeline (the serialization baseline the facade removes).
    control = ClusterStack(0, cfg, threaded=False, record_ops=False)
    for c in range(F):
        for i in range(args.nodes_per_cluster):
            control.add_node(
                new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
            )
    control_apps = [
        pods
        for c in range(F)
        for pods in build_apps(c, args.apps_per_cluster)
    ]
    with SimulatedRTT(args.rtt_ms):
        t0 = time.perf_counter()
        for pods in control_apps:
            for p in pods:
                r = control.schedule(p)
                assert r.ok, f"control denial: {r.outcome}"
        control_wall = time.perf_counter() - t0
    control.stop()
    control_rate = total_decisions / control_wall
    _emit({
        "metric": "fleet_decisions_per_s_single_cluster",
        "value": round(control_rate, 1),
        "unit": "decisions/s",
        "vs_baseline": 1.0,
        "clusters": 1,
        "spillovers": 0,
        "detail": {
            "decisions": total_decisions,
            "wall_s": round(control_wall, 3),
            "rtt_ms": args.rtt_ms,
            "devices": n_devices,
        },
    })

    # --- fleet arm: F stacks, same total load, one client thread per
    # cluster (kube-scheduler fans out across cluster endpoints), every
    # cluster's op stream recorded for the in-arm equivalence check.
    facade = FleetFacade(F, cfg, record_ops=True)
    for c in range(F):
        for i in range(args.nodes_per_cluster):
            facade.add_node(
                c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
            )
    fleet_apps = {
        c: build_apps(c, args.apps_per_cluster) for c in range(F)
    }
    errors = []

    def pump(c):
        try:
            for pods in fleet_apps[c]:
                for p in pods:
                    d = facade.schedule(p, via=c)
                    assert d.ok, (
                        f"fleet denial c{c}: {d.result.outcome}"
                    )
        except Exception as exc:  # surfaced after join
            errors.append(exc)

    with SimulatedRTT(args.rtt_ms):
        threads = [
            threading.Thread(target=pump, args=(c,)) for c in range(F)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fleet_wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    fleet_rate = total_decisions / fleet_wall
    speedup = fleet_rate / control_rate

    # In-arm assertion #1: concurrency actually scaled throughput.
    assert speedup >= args.min_speedup, (
        f"fleet scaling below bar: {speedup:.2f}x < {args.min_speedup}x "
        f"(fleet {fleet_rate:.1f}/s vs single {control_rate:.1f}/s)"
    )
    # In-arm assertion #2: every cluster's decisions byte-identical to a
    # standalone cluster replaying the same op stream.
    equivalence = verify_cluster_equivalence(facade)

    st = facade.state()
    stacking = st.get("stacking", {})
    _emit({
        "metric": f"fleet_decisions_per_s_{F}_clusters",
        "value": round(fleet_rate, 1),
        "unit": "decisions/s",
        # vs_baseline = speedup / 3: >= 1.0 clears the acceptance bar.
        "vs_baseline": round(speedup / args.min_speedup, 2),
        "clusters": F,
        "spillovers": st["spillover"]["spilled"],
        "stacked_dispatches": stacking.get("stacked_dispatches", 0),
        "stack_arms": stacking.get("stack_arms", 0),
        "detail": {
            "decisions": total_decisions,
            "wall_s": round(fleet_wall, 3),
            "speedup_vs_single": round(speedup, 2),
            "rtt_ms": args.rtt_ms,
            "devices": n_devices,
            "byte_identical_clusters": len(equivalence),
            "router_picks": st["router"]["picks"],
            "forwarded": st["forwarded"],
        },
    })
    facade.stop()
    _emit({
        "metric": "fleet_scaling_summary",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / args.min_speedup, 2),
        "clusters": F,
        "spillovers": st["spillover"]["spilled"],
        "stacked_dispatches": stacking.get("stacked_dispatches", 0),
        "stack_arms": stacking.get("stack_arms", 0),
        "detail": {
            "single_cluster_decisions_per_s": round(control_rate, 1),
            "fleet_decisions_per_s": round(fleet_rate, 1),
            "equivalence": {str(k): v for k, v in equivalence.items()},
        },
    })

    if not args.skip_stacked:
        run_stacked_section(args, cfg)


def run_stacked_section(args, cfg):
    """ISSUE 20 A/B: stacked vs unstacked fleet over ONE shared device
    link (tunnel_serialized RTT), interleaved arms on the same offered
    load. See the module docstring for the protocol."""
    import statistics

    from spark_scheduler_tpu.fleet import (
        FleetFacade,
        verify_cluster_equivalence,
    )
    from spark_scheduler_tpu.testing.harness import new_node
    from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT

    F = args.clusters

    def run_arm(stack_ms, rep):
        """One arm: fresh facade, the SAME offered-load trace (identical
        per-cluster app streams), one pump thread per cluster. The warm
        round (rep < 0) runs WITHOUT the RTT shim so first-compiles of
        this mode's window shapes land outside every timed rep."""
        facade = FleetFacade(
            F, cfg, record_ops=True, stack_window_ms=stack_ms
        )
        for c in range(F):
            for i in range(args.nodes_per_cluster):
                facade.add_node(
                    c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}")
                )
        errors = []

        def pump(c, tag, n_apps):
            try:
                from spark_scheduler_tpu.testing.harness import (
                    static_allocation_spark_pods,
                )

                for k in range(n_apps):
                    pods = static_allocation_spark_pods(
                        f"{tag}-c{c}-{k}", EXECUTORS,
                        instance_group=f"ig-{c}",
                    )
                    for p in pods:
                        d = facade.schedule(p, via=c)
                        assert d.ok, (
                            f"stacked-section denial c{c}: "
                            f"{d.result.outcome}"
                        )
            except Exception as exc:  # surfaced after join
                errors.append(exc)

        def drive(tag, n_apps):
            threads = [
                threading.Thread(target=pump, args=(c, tag, n_apps))
                for c in range(F)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        # Untimed warm round: compiles (incl. the stacked kernel's
        # [M, B, N] shapes when stacking is on) happen here.
        drive("warm", 1)
        with SimulatedRTT(args.rtt_ms, tunnel_serialized=True):
            wall = drive(f"rep{rep}", args.apps_per_cluster)
        if errors:
            raise errors[0]
        decisions = F * args.apps_per_cluster * (1 + EXECUTORS)
        stacking = facade.state().get("stacking", {})
        equivalence = verify_cluster_equivalence(facade)
        facade.stop()
        return decisions / wall, stacking, equivalence

    # Interleave off/on so neither mode systematically inherits cache or
    # rig warm-up from the other.
    rates = {"off": [], "on": []}
    last = {}
    for rep in range(args.stack_reps):
        for mode, stack_ms in (
            ("off", 0.0),
            ("on", args.stack_window_ms),
        ):
            rate, stacking, equivalence = run_arm(stack_ms, rep)
            rates[mode].append(rate)
            last[mode] = (stacking, equivalence)
    off_rate = statistics.mean(rates["off"])
    on_rate = statistics.mean(rates["on"])
    speedup = on_rate / off_rate
    stacking, equivalence = last["on"]

    # In-arm assertion #1: fused launches beat per-cluster launches on
    # the shared link by the acceptance bar.
    assert speedup >= args.min_stack_speedup, (
        f"stacked fleet below bar: {speedup:.2f}x < "
        f"{args.min_stack_speedup}x (stacked {on_rate:.1f}/s vs "
        f"unstacked {off_rate:.1f}/s)"
    )
    # In-arm assertion #2: stacking actually happened, and nothing was
    # force-resolved in steady state.
    assert stacking.get("stacked_dispatches", 0) > 0, (
        f"no stacked dispatches fired: {stacking}"
    )
    assert stacking.get("forced_resolves", 0) == 0, (
        f"forced resolves in steady state: {stacking}"
    )
    # In-arm assertion #3 ran inside run_arm for EVERY stacked rep:
    # verify_cluster_equivalence (stacked == standalone unstacked replay).

    for mode, rate in (("unstacked", off_rate), ("stacked", on_rate)):
        st_line = last["on" if mode == "stacked" else "off"][0]
        _emit({
            "metric": f"fleet_{mode}_serialized_decisions_per_s",
            "value": round(rate, 1),
            "unit": "decisions/s",
            "vs_baseline": 1.0 if mode == "unstacked" else round(
                speedup / args.min_stack_speedup, 2
            ),
            "clusters": F,
            "spillovers": 0,
            "stacked_dispatches": st_line.get("stacked_dispatches", 0),
            "stack_arms": st_line.get("stack_arms", 0),
            "detail": {
                "rtt_ms": args.rtt_ms,
                "tunnel_serialized": True,
                "stack_window_ms": (
                    0.0 if mode == "unstacked" else args.stack_window_ms
                ),
                "reps": rates["off" if mode == "unstacked" else "on"],
            },
        })
    _emit({
        "metric": "fleet_stacking_summary",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / args.min_stack_speedup, 2),
        "clusters": F,
        "spillovers": 0,
        "stacked_dispatches": stacking.get("stacked_dispatches", 0),
        "stack_arms": stacking.get("stack_arms", 0),
        "detail": {
            "unstacked_decisions_per_s": round(off_rate, 1),
            "stacked_decisions_per_s": round(on_rate, 1),
            "rtt_ms": args.rtt_ms,
            "stack_window_ms": args.stack_window_ms,
            "fallbacks": stacking.get("fallbacks", 0),
            "forced_resolves": stacking.get("forced_resolves", 0),
            "gather_wait_ms": stacking.get("gather_wait_ms", 0.0),
            "equivalence": {str(k): v for k, v in equivalence.items()},
        },
    })


if __name__ == "__main__":
    main()
