"""Scale smoke (ISSUE 11 satellite, the `scale-smoke` CI leg).

Boots the scheduler server at a synthetic SCALE_SMOKE_NODES-node roster
(default 1,000,000 — statics only, no predicate traffic), serves exactly
one warm-up window to force the cold featurize + full upload, then applies
a handful of node events and asserts the O(changed) invariants as
COUNTERS, not timings (no hot-loop timing flakiness):

  - zero full roster rebuilds across the event phase — adds ride the
    append patch, updates the patch path, AND deletes the tombstone
    patch (ISSUE 12: `roster_delete_patches` moves, rebuilds do not);
  - per-event state-upload bytes under a fixed ceiling (64 KiB — a full
    1M-node upload is ~40 MB, so an accidental O(N) regression misses the
    ceiling by three orders of magnitude);
  - the prune planner never sweeps: after the one cold build,
    `planner_rows_scanned` stays O(K) (zero here — event churn lands on
    kept rows or merges exactly) and `planner_sweep_rows` stays 0 while
    every window reuses the plan/gather caches (ISSUE 12);
  - boot (roster ingest + cold featurize + first served window) under a
    wall-clock budget (SCALE_SMOKE_BUDGET_S, default 600 — generous: the
    budget catches quadratic boot regressions, not jitter).

SCALE_SMOKE_POOL=2 (ISSUE 15, the pool-2 CI leg) runs the same smoke
against a 2-slot device pool with the roster split across TWO instance
groups and every serving window partitioned across them: the same
invariants must hold — plus ZERO dense mirror syncs (the pooled sparse
debit pins `mirror_dense_syncs` at 0), pooled debit rows engaged, and
planner rows-scanned O(K) with the per-domain plan contexts re-serving
across windows (`planner_sweep_rows` stops at the per-domain cold
sweeps). Event-phase adds/deletes land in a THIRD spare group, so the
served groups' domain tickets stay membership-stable — a membership
change inside a served instance group re-sweeps that domain by design
(the documented residual).

Exit code 0 = pass; assertion failure names the broken invariant.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_NODES = int(os.environ.get("SCALE_SMOKE_NODES", "1000000"))
BUDGET_S = float(os.environ.get("SCALE_SMOKE_BUDGET_S", "600"))
POOL = int(os.environ.get("SCALE_SMOKE_POOL", "1"))
EVENT_BYTES_CEILING = 64 * 1024

if POOL > 1 and "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={POOL}"
    )


def main() -> None:
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
        static_allocation_spark_pods,
    )

    t_boot = time.perf_counter()
    backend = InMemoryBackend()
    for i in range(N_NODES):
        if POOL > 1:
            # Two served instance groups: every window partitions across
            # the pool (the pooled sparse-debit path under test).
            backend.add_node(
                new_node(
                    f"s{i:07d}", zone=f"zone{i % 4}",
                    instance_group=f"ig{i % 2}",
                )
            )
        else:
            backend.add_node(new_node(f"s{i:07d}", zone=f"zone{i % 4}"))
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=False,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_prune_top_k=64,
            solver_device_pool=POOL,
            flight_recorder=False,
        ),
    )
    server = SchedulerHTTPServer(app, host="127.0.0.1", port=0)
    server.start()
    ext = app.extender
    ext._last_request = float("inf")

    # One warm-up window (in process — the leg smokes the host paths, not
    # HTTP throughput) to force cold featurize + the one full upload.
    # Candidate names ride ONE identity-keyed ticket (the in-process
    # analog of the native ingest digest ticket): the full-roster domain
    # keeps its digest across windows, so the solver's candidate-mask LRU
    # and the planner's full-domain memo both hit — the serving windows
    # exercise the O(K + changed) pruned path this smoke pins.
    class NameTicket(list):
        __hash__ = object.__hash__

        def __eq__(self, other):
            return self is other

        @property
        def names_digest(self):
            return id(self)

    names = NameTicket(f"s{i:07d}" for i in range(N_NODES))

    def serve_one(tag: str) -> None:
        if POOL > 1:
            args = []
            for g in ("ig0", "ig1"):
                d = static_allocation_spark_pods(
                    f"smoke-{tag}-{g}", 2, instance_group=g
                )[0]
                backend.add_pod(d)
                args.append(ExtenderArgs(pod=d, node_names=names))
            tok = ext.predicate_window_dispatch(args)
        else:
            d = static_allocation_spark_pods(f"smoke-{tag}", 2)[0]
            backend.add_pod(d)
            tok = ext.predicate_window_dispatch(
                [ExtenderArgs(pod=d, node_names=names)]
            )
        res = ext.predicate_window_complete(tok)
        assert res[0].node_names, f"window {tag} failed to place"

    serve_one("boot")
    boot_s = time.perf_counter() - t_boot
    assert boot_s < BUDGET_S, (
        f"boot took {boot_s:.1f}s > budget {BUDGET_S}s at {N_NODES} nodes"
    )

    store = ext.features
    stats = app.solver.device_state_stats
    prune = app.solver.prune_stats
    rebuilds_before = store.stats()["roster_rebuilds"]
    bytes_before = stats["upload_bytes"]
    events_before = (
        stats["full_uploads"]
        + stats["delta_uploads"]
        + stats["static_delta_uploads"]
    )
    # A couple of warm windows so the planner's cold build is behind us,
    # then pin the O(K) planning claim as counters over the event phase.
    serve_one("warm0")
    serve_one("warm1")
    scanned_before = prune["planner_rows_scanned"]
    cold_before = prune["planner_cold_rows"]
    sweep_after_warm = prune["planner_sweep_rows"]
    build = app.solver.build_stats
    compared_before = build["mirror_rows_compared"]
    dense_before = build["mirror_dense_syncs"]
    grows_before = store.stats()["array_grows"]

    # Event phase: 4 adds + 4 updates + 4 deletes, one served window
    # each. Added/deleted/updated nodes all sort OUTSIDE every kept set
    # (names after the roster's, high indices), so the planner absorbs
    # them as exact merges/static dirt — since ISSUE 15 a boundary-
    # beating add would be INSERTED in O(K) rather than re-scanned. On
    # the pool leg, adds/deletes land in a spare instance group so the
    # served groups' domain tickets stay membership-stable (a membership
    # change re-sweeps that domain by design).
    spare = {"instance_group": "igspare"} if POOL > 1 else {}
    for j in range(4):
        backend.add_node(new_node(f"zlate{j:03d}", zone="zone0", **spare))
        serve_one(f"add{j}")
    for j in range(4):
        name = f"s{N_NODES - 1 - j:07d}"
        cur = backend.get_node(name)
        backend.update(
            "nodes",
            dataclasses.replace(cur, unschedulable=not cur.unschedulable),
        )
        serve_one(f"upd{j}")
    for j in range(4):
        if POOL > 1:
            # Delete the spare-group adds: exercises the delete-tombstone
            # patch without re-keying a served domain.
            backend.delete("nodes", "", f"zlate{j:03d}")
        else:
            backend.delete("nodes", "", f"s{N_NODES - 5 - j:07d}")
        serve_one(f"del{j}")

    fs = store.stats()
    assert fs["roster_rebuilds"] == rebuilds_before, (
        f"node events paid {fs['roster_rebuilds'] - rebuilds_before} full "
        "roster rebuilds (O(N) regression)"
    )
    assert fs["roster_add_patches"] >= 4, fs
    assert fs["roster_delete_patches"] >= 4, fs
    events = (
        stats["full_uploads"]
        + stats["delta_uploads"]
        + stats["static_delta_uploads"]
        - events_before
    )
    per_event = (stats["upload_bytes"] - bytes_before) / max(events, 1)
    assert per_event < EVENT_BYTES_CEILING, (
        f"{per_event:.0f} upload bytes/event >= ceiling "
        f"{EVENT_BYTES_CEILING} (O(N) upload regression)"
    )
    # Planner O(K) invariants (ISSUE 12): no legacy sweep ever ran, the
    # cold build happened exactly once (before the event phase), and the
    # event-phase windows re-scanned at most a K-bounded row count —
    # zero in this synthetic roster: every change merges or is benign.
    scanned = prune["planner_rows_scanned"] - scanned_before
    # Pool leg: the per-domain contexts pay one cold sweep each at warm,
    # then NEVER re-sweep across the event phase (ISSUE 15 tentpole (b));
    # single-device full-domain serving never sweeps at all.
    assert prune["planner_sweep_rows"] == sweep_after_warm, prune
    if POOL == 1:
        assert prune["planner_sweep_rows"] == 0, prune
    assert prune["planner_cold_rows"] == cold_before, (
        "planner re-ran its cold build during the event phase", prune,
    )
    rows_budget = 64 * max(prune["windows"], 1)  # O(K), K = top-k bucket
    assert scanned <= rows_budget, (
        f"planner scanned {scanned} rows across the event phase "
        f"(> O(K) budget {rows_budget}: an O(N) sweep regressed in)",
        prune,
    )
    assert prune["plan_reuse"] > 0 and prune["gather_reuse"] > 0, prune
    # Tensor-build O(changed) invariants (ISSUE 13): the event phase rode
    # the event-fed dirty set — ZERO dense [N]-wide mirror sweeps (the
    # `mirror_rows_compared` counter, the planner rows_scanned pattern) —
    # and the resident build stayed incremental.
    assert build["mirror_rows_compared"] == compared_before, (
        "the tensor build ran a dense mirror sweep in steady state "
        "(O(N) regression)",
        build,
    )
    assert build["mirror_dense_syncs"] == dense_before, build
    assert build["incremental_builds"] > 0, build
    if POOL > 1:
        # Pooled sparse debits (ISSUE 15 tentpole (a)): partitioned
        # windows never downgraded the mirror sync to a dense sweep, and
        # the partition debit rows actually flowed through the ledger.
        assert build["mirror_dense_syncs"] == 0, build
        assert build["pooled_debit_rows"] > 0, build
        assert prune["plan_reuse"] > 0 and prune["gather_reuse"] > 0, prune
    # Amortized roster growth: the add/update/delete burst reallocated NO
    # resident buffer (the preallocated-capacity claim as a counter).
    assert store.stats()["array_grows"] == grows_before, (
        "a node event paid a full-array reallocation "
        "(amortized-growth regression)",
        store.stats(),
    )

    print(
        json.dumps(
            {
                "scale_smoke": "pass",
                "n_nodes": N_NODES,
                "pool": POOL,
                "boot_s": round(boot_s, 1),
                "upload_bytes_per_event": round(per_event, 1),
                "roster_add_patches": fs["roster_add_patches"],
                "roster_delete_patches": fs["roster_delete_patches"],
                "planner_rows_scanned_events": scanned,
                "build": dict(build),
                "array_grows": store.stats()["array_grows"],
                "planner": {
                    k: prune[k]
                    for k in (
                        "windows", "plan_reuse", "gather_reuse",
                        "planner_rows_scanned", "planner_cold_rows",
                        "planner_sweep_rows", "planner_zone_rescans",
                        "planner_merges",
                    )
                },
                "device_state": dict(stats),
            }
        ),
        flush=True,
    )
    server.stop()
    app.stop()


if __name__ == "__main__":
    main()
