"""What-if grid study (replay subsystem): the binpack plug-board × prune
{on, off} swept over ONE generated multi-tenant trace in one batched
multi-arm replay (replay/sweep.py, ISSUE 18).

The trace is generated once (bursty multi-tenant, seeded), then every arm
of the grid replays concurrently over one shared host build: arms whose
configs differ only in identity-pinned knobs share a decision stream,
compatible windows solve as stacked cross-arm device dispatches, and the
sweep telemetry (streams, stacked dispatches, lane fallbacks, shared-build
hits, windows/s) is part of the study output. The base arm doubles as the
bit-identity confidence check against the recorded decisions.

One JSON document on stdout; standalone:
    python hack/whatif_study.py
Env: WHATIF_NODES="10000"  WHATIF_BURSTS="10"  WHATIF_SEED="7"
     WHATIF_GRID="full" for 5 strategies x prune {on,off} (default is the
     2-strategy CI-sized grid)  WHATIF_MARKDOWN="1" for the table too.
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import json
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

from spark_scheduler_tpu.replay import generate, grid_arms, run_sweep

NODES = int(os.environ.get("WHATIF_NODES", "10000"))
BURSTS = int(os.environ.get("WHATIF_BURSTS", "10"))
SEED = int(os.environ.get("WHATIF_SEED", "7"))
FULL = os.environ.get("WHATIF_GRID", "") == "full"

STRATEGIES_FULL = (
    "tightly-pack",
    "distribute-evenly",
    "minimal-fragmentation",
    "single-az-tightly-pack",
    "single-az-minimal-fragmentation",
)
STRATEGIES_CI = ("tightly-pack", "distribute-evenly")


def main() -> None:
    out_dir = tempfile.mkdtemp(prefix="whatif-study-")
    trace = os.path.join(out_dir, "bursty.trace.jsonl")

    t0 = time.perf_counter()
    stats = generate(
        "bursty",
        trace,
        seed=SEED,
        n_nodes=NODES,
        bursts=BURSTS,
        binpack_algo="tightly-pack",
    )
    gen_s = time.perf_counter() - t0

    strategies = STRATEGIES_FULL if FULL else STRATEGIES_CI
    arms = grid_arms(
        {
            "binpack_algo": list(strategies),
            "solver_prune_top_k": [0, 64],
        }
    )
    t0 = time.perf_counter()
    sweep = run_sweep(trace, arms)
    study_s = time.perf_counter() - t0

    # The recorded config is arm 0 (tightly-pack, no explicit prune): its
    # replay must bit-match the recorded decisions.
    base_mismatches = sum(
        len(r.mismatches) for r in sweep.reports[:1]
    )
    doc = {
        "study": (
            f"binpack plug-board x prune {{off,on}} grid, "
            f"{len(arms)} arms / {sweep.telemetry['streams']} streams"
        ),
        "nodes": NODES,
        "bursts": BURSTS,
        "seed": SEED,
        "trace_events": stats["events"],
        "trace_bytes": stats["bytes"],
        "generate_s": round(gen_s, 2),
        "sweep_s": round(study_s, 2),
        "base_mismatches": base_mismatches,
        "sweep": sweep.summary(),
    }
    json.dump(doc, sys.stdout, indent=2, default=str)
    print()
    if os.environ.get("WHATIF_MARKDOWN"):
        print(sweep.markdown(), file=sys.stderr)
    if base_mismatches:
        print(
            f"WARNING: base arm had {base_mismatches} mismatches — "
            "deltas suspect",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
