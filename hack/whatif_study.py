"""What-if strategy study (replay subsystem): tightly-pack vs
distribute-evenly on one generated multi-tenant trace at 10k nodes.

The trace is generated once (bursty multi-tenant, seeded), replayed under
its recorded config (base arm — also the bit-identity confidence check),
then replayed under `binpack-algo: distribute-evenly` via the what-if
engine. The diff that comes back is the study: placement churn, denial
delta, fragmentation delta, and per-arm replay latency (both arms
re-measured in this process, so the latency comparison is fair).

One JSON document on stdout; standalone:
    python hack/whatif_study.py
Env: WHATIF_NODES="10000"  WHATIF_BURSTS="10"  WHATIF_SEED="7"
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import json
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

from spark_scheduler_tpu.replay import generate, what_if

NODES = int(os.environ.get("WHATIF_NODES", "10000"))
BURSTS = int(os.environ.get("WHATIF_BURSTS", "10"))
SEED = int(os.environ.get("WHATIF_SEED", "7"))


def main() -> None:
    out_dir = tempfile.mkdtemp(prefix="whatif-study-")
    trace = os.path.join(out_dir, "bursty.trace.jsonl")

    t0 = time.perf_counter()
    stats = generate(
        "bursty",
        trace,
        seed=SEED,
        n_nodes=NODES,
        bursts=BURSTS,
        binpack_algo="tightly-pack",
    )
    gen_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    diff = what_if(trace, {"binpack-algo": "distribute-evenly"})
    study_s = time.perf_counter() - t0

    doc = {
        "study": "binpack-algo: tightly-pack (recorded) vs distribute-evenly",
        "nodes": NODES,
        "bursts": BURSTS,
        "seed": SEED,
        "trace_events": stats["events"],
        "trace_bytes": stats["bytes"],
        "generate_s": round(gen_s, 2),
        "whatif_s": round(study_s, 2),
        "diff": diff,
    }
    json.dump(doc, sys.stdout, indent=2, default=str)
    print()
    if diff["base_mismatches"]:
        print(
            f"WARNING: base arm had {diff['base_mismatches']} mismatches — "
            "deltas suspect",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
