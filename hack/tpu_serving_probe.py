"""Focused TPU serving experiment: the bench's concurrent phase with the
batcher claim log dumped afterwards — shows exactly how windows formed.

Run (TPU): python hack/tpu_serving_probe.py [--clients 32] [--rounds 5]
"""

import argparse
import http.client
import json
import sys
import threading
import time

sys.path.insert(0, ".")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--warm-rounds", type=int, default=3)
    args = ap.parse_args()

    import bench
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s
    from spark_scheduler_tpu.testing.harness import static_allocation_spark_pods

    backend, app, server, node_names = bench._serving_fixture()
    lat_lock = threading.Lock()

    def run_phase(phase, rounds):
        lats, errs = [], []
        prebuilt = []
        for ci in range(args.clients):
            rows = []
            for r in range(rounds):
                driver = static_allocation_spark_pods(
                    f"pr-{phase}-{ci}-{r}", 8
                )[0]
                body = json.dumps(
                    {"Pod": pod_to_k8s(driver), "NodeNames": node_names}
                ).encode()
                rows.append((driver, body))
            prebuilt.append(rows)

        def client(ci):
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", server.port, timeout=600
                )
                for r, (driver, body) in enumerate(prebuilt[ci]):
                    backend.add_pod(driver)
                    t0 = time.perf_counter()
                    conn.request("POST", "/predicates", body=body)
                    resp = json.loads(conn.getresponse().read())
                    dt = (time.perf_counter() - t0) * 1e3
                    if not resp.get("NodeNames"):
                        raise RuntimeError(f"{phase}-{ci}-{r}: {resp}")
                    backend.bind_pod(driver, resp["NodeNames"][0])
                    with lat_lock:
                        lats.append(dt)
                conn.close()
            except Exception as exc:
                errs.append(exc)

        threads = [
            threading.Thread(target=client, args=(ci,))
            for ci in range(args.clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise errs[0]
        return lats, wall

    # Precompile + warm exactly like the bench.
    from spark_scheduler_tpu.core.solver import WindowRequest
    from spark_scheduler_tpu.models.resources import Resources

    solver = app.solver
    tensors = solver.build_tensors_cached(backend.list_nodes(), {}, {})
    one = Resources.from_quantities("1", "1Gi")
    for rows_total in (32, 64, 128, 256, 512, 1024, 2048):
        per_req = max(1, rows_total // args.clients)
        reqs = [
            WindowRequest(
                rows=[(one, one, 8, False)] * per_req,
                driver_candidate_names=node_names,
            )
            for _ in range(min(args.clients, rows_total))
        ]
        solver.pack_window("tightly-pack", tensors, reqs)

    run_phase("warm", args.warm_rounds)
    server.batcher.claim_log.clear()
    n_before = server.batcher.windows_served
    lats, wall = run_phase("run", args.rounds)
    total = args.clients * args.rounds
    log = list(server.batcher.claim_log)
    stats = server.batcher.stats()
    server.stop()
    lats.sort()
    print(
        f"\n== {total} reqs, {args.clients} clients: "
        f"{total/wall:.1f} decisions/s, p50 {lats[len(lats)//2]:.0f} ms, "
        f"p95 {lats[int(len(lats)*.95)]:.0f} ms, "
        f"windows {stats['windows_served']-n_before}"
    )
    print("claim log (window, queue_after, pending, target, hold_ms):")
    for row in log:
        print("  ", row)


if __name__ == "__main__":
    main()
