"""Refreshable runtime config (VERDICT r2 #6): log level, fifo,
batched-admission, and the async retry budget reload live — without a
restart — on file change (and SIGHUP, same reload primitive).
"""

import io
import time

import yaml

from spark_scheduler_tpu.server.runtime import RuntimeConfig, RuntimeConfigManager
from spark_scheduler_tpu.testing.harness import Harness, new_node
from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log, svc1log


def _write(path, data):
    with open(path, "w") as f:
        yaml.safe_dump(data, f)


def test_runtime_reload_applies_live(tmp_path):
    path = tmp_path / "runtime.yml"
    _write(path, {"logging": {"level": "INFO"}, "fifo": True})

    h = Harness(binpack_algo="tightly-pack", fifo=True)
    h.add_nodes(new_node("n0"))
    stream = io.StringIO()
    old_logger = svc1log()
    set_svc1log(Svc1Logger(stream=stream))
    try:
        mgr = RuntimeConfigManager(h.app, str(path))
        assert mgr.check_now()
        assert h.app.extender._config.fifo is True
        assert svc1log().level == "INFO"

        svc1log().debug("hidden")
        assert "hidden" not in stream.getvalue()

        # Flip everything; mtime granularity needs a distinct timestamp.
        time.sleep(0.02)
        _write(
            path,
            {
                "logging": {"level": "DEBUG"},
                "fifo": False,
                "batched-admission": False,
                "async-client-retry-count": 9,
            },
        )
        import os

        os.utime(path, (time.time() + 2, time.time() + 2))
        assert mgr.check_now()
        assert svc1log().level == "DEBUG"
        svc1log().debug("now visible")
        assert "now visible" in stream.getvalue()
        assert h.app.extender._config.fifo is False
        assert h.app.extender._config.batched_admission is False
        assert h.app.rr_cache.client._max_retries == 9
        assert mgr.reloads == 2
    finally:
        set_svc1log(old_logger)


def test_bad_refresh_keeps_last_good(tmp_path):
    path = tmp_path / "runtime.yml"
    _write(path, {"fifo": False})
    h = Harness(binpack_algo="tightly-pack", fifo=True)
    mgr = RuntimeConfigManager(h.app, str(path))
    assert mgr.check_now()
    assert h.app.extender._config.fifo is False

    import os

    with open(path, "w") as f:
        f.write("fifo: [unclosed\n")
    os.utime(path, (time.time() + 2, time.time() + 2))
    old_logger = svc1log()
    set_svc1log(Svc1Logger(stream=io.StringIO()))
    try:
        assert not mgr.check_now()
    finally:
        set_svc1log(old_logger)
    assert h.app.extender._config.fifo is False  # unchanged
    assert mgr.reloads == 1


def test_unchanged_mtime_is_noop(tmp_path):
    path = tmp_path / "runtime.yml"
    _write(path, {"fifo": True})
    h = Harness(binpack_algo="tightly-pack", fifo=False)
    mgr = RuntimeConfigManager(h.app, str(path))
    assert mgr.check_now()
    assert not mgr.check_now()  # same mtime: no reload
    assert mgr.check_now(force=True)  # SIGHUP path forces re-apply
    assert mgr.reloads == 2


def test_sighup_forces_reapply(tmp_path):
    """SIGHUP re-applies the runtime config immediately (the witchcraft
    refresh signal), even with an unchanged file mtime."""
    import os
    import signal
    import time as _t

    path = tmp_path / "runtime.yml"
    _write(path, {"fifo": True})
    h = Harness(binpack_algo="tightly-pack", fifo=False)
    mgr = RuntimeConfigManager(h.app, str(path), poll_interval_s=60.0)
    mgr.start()  # installs the SIGHUP handler (pytest main thread)
    try:
        deadline = _t.time() + 5
        while mgr.reloads < 1 and _t.time() < deadline:
            _t.sleep(0.01)
        assert mgr.reloads == 1
        assert h.app.extender._config.fifo is True
        os.kill(os.getpid(), signal.SIGHUP)
        deadline = _t.time() + 5
        while mgr.reloads < 2 and _t.time() < deadline:
            _t.sleep(0.01)
        assert mgr.reloads == 2  # forced re-apply despite unchanged mtime
    finally:
        mgr.stop()
        signal.signal(signal.SIGHUP, signal.SIG_DFL)


def test_runtime_config_parse_defaults():
    cfg = RuntimeConfig.from_dict({})
    assert cfg.log_level is None and cfg.fifo is None
    cfg = RuntimeConfig.from_dict({"log-level": "WARN"})
    assert cfg.log_level == "WARN"
