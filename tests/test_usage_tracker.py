"""ReservedUsageTracker: delta-maintained usage == from-scratch rebuild.

VERDICT r1 item 3: per-request host work must be proportional to the delta,
with a consistency proof that the incrementally-maintained aggregate always
equals the reference walk (GetReservedResources,
resourcereservations.go:228-233).
"""

from __future__ import annotations

import numpy as np
import pytest

from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    static_allocation_spark_pods,
)


def oracle_usage(app) -> dict[str, tuple[int, int, int]]:
    """The reference's full walk (the pre-tracker implementation)."""
    usage: dict[str, Resources] = {}
    for rr in app.rr_cache.list():
        for res in rr.spec.reservations.values():
            usage.setdefault(res.node, Resources.zero()).add(res.resources)
    for node, res in app.soft_store.used_soft_reservation_resources().items():
        usage.setdefault(node, Resources.zero()).add(res)
    return {k: v.as_tuple() for k, v in usage.items() if not v.is_zero()}


def tracker_usage(app) -> dict[str, tuple[int, int, int]]:
    return {
        k: v.as_tuple()
        for k, v in app.reservation_manager.usage_tracker.as_map().items()
        if not v.is_zero()
    }


def assert_consistent(app):
    tracker = app.reservation_manager.usage_tracker
    assert tracker_usage(app) == oracle_usage(app)
    # Dense array must equal a from-scratch rebuild too.
    before = tracker.array()
    rebuilds_before = tracker.rebuilds
    tracker.rebuild()
    after = tracker.array(min_rows=before.shape[0])
    np.testing.assert_array_equal(before[: after.shape[0]], after[: before.shape[0]])
    assert tracker.rebuilds == rebuilds_before + 1


def test_tracker_matches_oracle_through_lifecycle():
    h = Harness()
    h.add_nodes(*[new_node(f"n{i}") for i in range(6)])
    nodes = [f"n{i}" for i in range(6)]

    # static app: gang admission creates driver + executor reservations
    pods = static_allocation_spark_pods("app-1", 3)
    results = h.schedule_app(pods, nodes)
    assert all(r.ok for r in results)
    assert_consistent(h.app)

    # dynamic-allocation app: soft reservations over min
    dpods = dynamic_allocation_spark_pods("app-2", 1, 4)
    results = h.schedule_app(dpods, nodes)
    assert all(r.ok for r in results)
    assert_consistent(h.app)

    # executor death -> deletion -> compaction migrates soft into hard slots
    h.terminate_pod(pods[1])
    h.delete_pod(pods[1])
    assert_consistent(h.app)

    # replacement executor rebinds the freed slot
    replacement = static_allocation_spark_pods("app-1", 3)[1]
    replacement.name = "app-1-exec-replacement"
    h.schedule(replacement, nodes)
    assert_consistent(h.app)

    # driver deletion drops the whole soft shell
    h.delete_pod(dpods[0])
    assert_consistent(h.app)


def test_hot_path_uses_deltas_not_rebuilds():
    h = Harness()
    h.add_nodes(*[new_node(f"n{i}") for i in range(8)])
    nodes = [f"n{i}" for i in range(8)]
    tracker = h.app.reservation_manager.usage_tracker
    rebuilds_at_start = tracker.rebuilds

    for i in range(5):
        pods = static_allocation_spark_pods(f"app-{i}", 2)
        assert all(r.ok for r in h.schedule_app(pods, nodes))

    # Scheduling traffic must never trigger a from-scratch rebuild...
    assert tracker.rebuilds == rebuilds_at_start
    # ...but must have applied per-mutation deltas.
    assert tracker.deltas_applied > 0
    assert tracker_usage(h.app) == oracle_usage(h.app)


def test_reserved_usage_returns_dense_array_when_tracked():
    h = Harness()
    h.add_nodes(new_node("n0"))
    out = h.app.reservation_manager.reserved_usage()
    assert isinstance(out, np.ndarray)
    assert out.ndim == 2 and out.shape[1] == 3


@pytest.mark.parametrize("algo", ["tightly-pack", "single-az-tightly-pack"])
def test_scheduling_decisions_unchanged_by_tracker(algo):
    """Same scenario with and without the tracker -> identical placements."""
    results = {}
    for use_tracker in (True, False):
        h = Harness(binpack_algo=algo)
        if not use_tracker:
            h.app.reservation_manager.usage_tracker = None
        h.add_nodes(*[new_node(f"n{i}", zone=f"z{i % 2}") for i in range(4)])
        nodes = [f"n{i}" for i in range(4)]
        placed = []
        for i in range(3):
            pods = static_allocation_spark_pods(f"app-{i}", 2)
            for r in h.schedule_app(pods, nodes):
                placed.append(tuple(r.node_names))
        results[use_tracker] = placed
    assert results[True] == results[False]


def test_dense_and_map_usage_produce_identical_tensors():
    """Satellite parity pin (ISSUE 5): the dense `usage_tracker.array()`
    fast path and the `get_reserved_resources()` map fallback must yield
    byte-identical tensors through `build_tensors` — the serving suites
    only ever exercise the fast path, so this is the map fallback's one
    equivalence anchor."""
    h = Harness()
    h.add_nodes(*[new_node(f"n{i}", zone=f"z{i % 2}") for i in range(6)])
    nodes = [f"n{i}" for i in range(6)]
    for i in range(3):
        pods = static_allocation_spark_pods(f"par-app-{i}", 3)
        assert all(r.ok for r in h.schedule_app(pods, nodes))

    rrm = h.app.reservation_manager
    solver = h.app.solver
    all_nodes = h.backend.list_nodes()
    overhead = h.app.overhead_computer.get_overhead(all_nodes)

    dense = rrm.usage_tracker.array()
    assert dense.any(), "fixture scheduled nothing"
    tracker, rrm.usage_tracker = rrm.usage_tracker, None
    try:
        as_map = rrm.reserved_usage()
        assert isinstance(as_map, dict) and as_map
    finally:
        rrm.usage_tracker = tracker

    t_dense = solver.build_tensors(
        all_nodes, dense, overhead, full_node_list=True
    )
    t_map = solver.build_tensors(
        all_nodes, as_map, overhead, full_node_list=True
    )
    for field in (
        "available", "schedulable", "zone_id", "name_rank", "valid",
        "unschedulable", "ready",
    ):
        assert np.array_equal(
            np.asarray(getattr(t_dense, field)),
            np.asarray(getattr(t_map, field)),
        ), field
