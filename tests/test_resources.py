"""Resource algebra + quantity parsing unit tests (reference:
resources.go:150-245, annotation quantities in sparkpods.go:79-138)."""

import numpy as np

from spark_scheduler_tpu.models.resources import (
    CPU_DIM,
    GPU_DIM,
    MEM_DIM,
    Resources,
    parse_quantity,
)


def test_parse_cpu_quantities():
    assert parse_quantity("1", CPU_DIM) == 1000
    assert parse_quantity("500m", CPU_DIM) == 500
    assert parse_quantity("2.5", CPU_DIM) == 2500
    assert parse_quantity("0.1", CPU_DIM) == 100
    assert parse_quantity(3, CPU_DIM) == 3000


def test_parse_memory_quantities():
    assert parse_quantity("1Ki", MEM_DIM) == 1
    assert parse_quantity("8Gi", MEM_DIM) == 8 * 1024 * 1024
    assert parse_quantity("512Mi", MEM_DIM) == 512 * 1024
    assert parse_quantity("1M", MEM_DIM) == -(-(10**6) // 1024)  # ceil
    assert parse_quantity("1M", MEM_DIM, round_up=False) == 10**6 // 1024
    assert parse_quantity("1.5Gi", MEM_DIM) == 3 * 512 * 1024


def test_parse_rounding_is_conservative():
    # Requests round up, allocatable rounds down.
    assert parse_quantity("100n", CPU_DIM) == 1
    assert parse_quantity("100n", CPU_DIM, round_up=False) == 0
    assert parse_quantity("1023", MEM_DIM) == 1
    assert parse_quantity("1023", MEM_DIM, round_up=False) == 0


def test_parse_gpu():
    assert parse_quantity("1", GPU_DIM) == 1000
    assert parse_quantity("2", GPU_DIM) == 2000


def test_parse_exponents_and_exa():
    # k8s decimalExponent grammar admits both e and E (quantity.go:49).
    assert parse_quantity("1e3", CPU_DIM) == 10**6
    assert parse_quantity("1E3", CPU_DIM) == 10**6
    assert parse_quantity("2e-1", CPU_DIM) == 200
    # Bare E is the exa suffix; value saturates at the int32 bound.
    assert parse_quantity("1E", CPU_DIM) == 2**31 - 2


def test_resources_ops():
    a = Resources.from_quantities("1", "1Gi", "1")
    b = Resources.from_quantities("500m", "512Mi", "0")
    a.add(b)
    assert a.as_tuple() == (1500, 1024 * 1024 + 512 * 1024, 1000)
    a.sub(b)
    assert a.as_tuple() == (1000, 1024 * 1024, 1000)
    assert a.greater_than(b)
    assert not b.greater_than(a)
    # greater_than is ANY-dim (resources.go:242-245)
    c = Resources(1, 0, 0)
    d = Resources(0, 5, 5)
    assert c.greater_than(d)
    assert d.greater_than(c)
    e = b.copy().set_max(Resources(200, 10**9, 500))
    assert e.as_tuple() == (500, 10**9, 500)


def test_array_round_trip():
    r = Resources(5, 7, 9)
    assert Resources.from_array(r.as_array()).as_tuple() == (5, 7, 9)
    assert r.as_array().dtype == np.int32
