"""CRD version conversion (webhook + pure converters) and CRD lifecycle
(ensure/verify, lazy Demand-CRD watching)."""

import threading

from spark_scheduler_tpu.models.demands import (
    Demand,
    DemandSpec,
    DemandStatus,
    DemandUnit,
)
from spark_scheduler_tpu.models.reservations import (
    RESERVATION_SPEC_ANNOTATION,
    Reservation,
    ReservationSpec,
    ReservationStatus,
    ResourceReservation,
)
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.server.conversion import (
    DEMAND_V1ALPHA1,
    DEMAND_V1ALPHA2,
    RR_V1BETA1,
    RR_V1BETA2,
    convert_review,
    demand_v1alpha2_to_wire,
    rr_v1beta2_from_wire,
    rr_v1beta2_to_wire,
)
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend
from spark_scheduler_tpu.store.crd import (
    CRDError,
    LazyDemandCRDWatcher,
    ensure_resource_reservations_crd,
)


def _rr() -> ResourceReservation:
    return ResourceReservation(
        name="app-1",
        namespace="ns",
        labels={"spark-app-id": "app-1"},
        resource_version=7,
        spec=ReservationSpec(
            {
                "driver": Reservation("n0", Resources(1000, 1024 * 1024, 0)),
                "executor-1": Reservation("n1", Resources(2000, 2 * 1024 * 1024, 1000)),
            }
        ),
        status=ReservationStatus({"driver": "drv-pod"}),
    )


def _review(objects, desired):
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "ConversionReview",
        "request": {"uid": "u-1", "desiredAPIVersion": desired, "objects": objects},
    }


def test_rr_roundtrip_through_webhook_preserves_gpu():
    wire2 = rr_v1beta2_to_wire(_rr())
    # Downgrade to v1beta1 over the webhook...
    out = convert_review(_review([wire2], RR_V1BETA1))
    assert out["response"]["result"]["status"] == "Success"
    assert out["response"]["uid"] == "u-1"
    (old,) = out["response"]["convertedObjects"]
    assert old["apiVersion"] == RR_V1BETA1
    # v1beta1 is flat {node, cpu, memory}; GPU survives via the annotation.
    slot = old["spec"]["reservations"]["executor-1"]
    assert set(slot) == {"node", "cpu", "memory"}
    assert RESERVATION_SPEC_ANNOTATION in old["metadata"]["annotations"]
    # ...and back up: lossless round-trip (conversion_resource_reservation.go:29-121).
    back = convert_review(_review([old], RR_V1BETA2))
    (new,) = back["response"]["convertedObjects"]
    rr2 = rr_v1beta2_from_wire(new)
    assert rr2.spec.reservations["executor-1"].resources.gpu_milli == 1000
    assert rr2.spec.reservations["executor-1"].node == "n1"
    assert rr2.status.pods == {"driver": "drv-pod"}
    # The round-trip carrier annotation is consumed on upgrade.
    assert RESERVATION_SPEC_ANNOTATION not in rr2.annotations


def test_demand_downgrade_and_upgrade():
    d = Demand(
        name="demand-pod-1",
        namespace="ns",
        spec=DemandSpec(
            instance_group="ig",
            units=[
                DemandUnit(
                    Resources(500, 1024, 0),
                    count=3,
                    pod_names_by_namespace={"ns": ["pod-1"]},
                )
            ],
            enforce_single_zone_scheduling=True,
            zone="z1",
        ),
        status=DemandStatus(phase="pending"),
    )
    wire = demand_v1alpha2_to_wire(d)
    out = convert_review(_review([wire], DEMAND_V1ALPHA1))
    (old,) = out["response"]["convertedObjects"]
    assert old["apiVersion"] == DEMAND_V1ALPHA1
    assert old["spec"]["units"][0]["count"] == 3
    back = convert_review(_review([old], DEMAND_V1ALPHA2))
    (new,) = back["response"]["convertedObjects"]
    assert new["spec"]["units"][0]["resources"]["cpu"] == "500m"
    assert new["status"]["phase"] == "pending"
    # Zone affinity is a v1alpha2-only concept: lost on downgrade, absent
    # after the round trip (v1alpha1 has no carrier annotation).
    assert "zone" not in new["spec"]


def test_reference_shaped_v1beta1_upgrades_losslessly():
    """A v1beta1 object exactly as the reference webhook would write it —
    fully-qualified reservation-spec annotation holding the marshaled
    v1beta2 spec (conversion_resource_reservation.go ConvertFrom) plus full
    ObjectMeta — upgrades with GPU recovered and metadata preserved."""
    ref_obj = {
        "apiVersion": RR_V1BETA1,
        "kind": "ResourceReservation",
        "metadata": {
            "name": "app-9",
            "namespace": "ns",
            "uid": "3f2c-uid",
            "creationTimestamp": "2026-01-05T10:00:00Z",
            "generation": 4,
            "resourceVersion": "42",
            "labels": {"spark-app-id": "app-9"},
            "ownerReferences": [
                {"apiVersion": "v1", "kind": "Pod", "name": "drv", "uid": "p-uid"}
            ],
            "finalizers": ["example.com/protect"],
            "annotations": {
                RESERVATION_SPEC_ANNOTATION: (
                    '{"reservations":{"driver":{"node":"n0","resources":'
                    '{"cpu":"1","memory":"1Gi","nvidia.com/gpu":"2"}}}}'
                )
            },
        },
        "spec": {
            "reservations": {"driver": {"node": "n0", "cpu": "1", "memory": "1Gi"}}
        },
        "status": {"pods": {"driver": "drv"}},
    }
    out = convert_review(_review([ref_obj], RR_V1BETA2))
    assert out["response"]["result"]["status"] == "Success"
    (new,) = out["response"]["convertedObjects"]
    # GPU recovered from the reference-format stash; cpu/mem from flat fields.
    res = new["spec"]["reservations"]["driver"]["resources"]
    assert res["cpu"] == "1" and res["nvidia.com/gpu"] == "2"
    assert res["memory"] == f"{1024 * 1024}Ki"
    # Immutable metadata preserved verbatim; stash annotation removed.
    meta = new["metadata"]
    assert meta["uid"] == "3f2c-uid"
    assert meta["creationTimestamp"] == "2026-01-05T10:00:00Z"
    assert meta["generation"] == 4
    assert meta["ownerReferences"][0]["name"] == "drv"
    assert meta["finalizers"] == ["example.com/protect"]
    assert RESERVATION_SPEC_ANNOTATION not in (meta.get("annotations") or {})


def test_reference_shaped_demand_v1alpha2_roundtrip():
    """A reference-format v1alpha2 Demand (kebab-case tags, RFC3339
    last-transition-time; types_demand.go:82-122) survives downgrade to
    v1alpha1 and back with GPU, phase and transition time intact."""
    ref_demand = {
        "apiVersion": DEMAND_V1ALPHA2,
        "kind": "Demand",
        "metadata": {
            "name": "demand-pod-7",
            "namespace": "ns",
            "uid": "d-uid",
            "creationTimestamp": "2026-02-01T00:00:00Z",
        },
        "spec": {
            "units": [
                {
                    "resources": {
                        "cpu": "2",
                        "memory": "4Gi",
                        "nvidia.com/gpu": "1",
                    },
                    "count": 5,
                    "pod-names-by-namespace": {"ns": ["pod-7"]},
                }
            ],
            "instance-group": "ig-a",
            "is-long-lived": True,
            "enforce-single-zone-scheduling": False,
        },
        "status": {
            "phase": "pending",
            "last-transition-time": "2026-02-01T12:30:45Z",
        },
    }
    out = convert_review(_review([ref_demand], DEMAND_V1ALPHA1))
    assert out["response"]["result"]["status"] == "Success"
    (old,) = out["response"]["convertedObjects"]
    # v1alpha1 units are flat cpu/memory/gpu (v1alpha1/types_demand.go:57-62).
    assert old["spec"]["units"][0]["gpu"] == "1"
    assert old["spec"]["instance-group"] == "ig-a"
    assert old["spec"]["is-long-lived"] is True
    assert old["status"]["last-transition-time"] == "2026-02-01T12:30:45Z"
    assert old["metadata"]["uid"] == "d-uid"
    # Back up to storage version: everything v1alpha1 can carry survives.
    back = convert_review(_review([old], DEMAND_V1ALPHA2))
    (new,) = back["response"]["convertedObjects"]
    assert new["spec"]["units"][0]["resources"]["nvidia.com/gpu"] == "1"
    assert new["spec"]["instance-group"] == "ig-a"
    assert new["status"]["phase"] == "pending"
    assert new["status"]["last-transition-time"] == "2026-02-01T12:30:45Z"
    assert new["metadata"]["creationTimestamp"] == "2026-02-01T00:00:00Z"


def test_same_version_passthrough_and_unknown_version_fails():
    wire = rr_v1beta2_to_wire(_rr())
    out = convert_review(_review([wire], RR_V1BETA2))
    assert out["response"]["convertedObjects"] == [wire]

    bad = dict(wire, apiVersion="sparkscheduler.palantir.com/v9")
    out = convert_review(_review([bad, wire], RR_V1BETA2))
    assert out["response"]["result"]["status"] == "Failed"
    assert "v9" in out["response"]["result"]["message"]
    assert out["response"]["convertedObjects"] == []


def test_webhook_over_http_inproc_and_standalone():
    import json
    import urllib.request

    from spark_scheduler_tpu.server.http import ConversionWebhookServer

    srv = ConversionWebhookServer(port=0)
    srv.start()
    try:
        review = _review([rr_v1beta2_to_wire(_rr())], RR_V1BETA1)
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/convert",
            data=json.dumps(review).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["kind"] == "ConversionReview"
        assert body["response"]["result"]["status"] == "Success"
        (obj,) = body["response"]["convertedObjects"]
        assert obj["apiVersion"] == RR_V1BETA1
    finally:
        srv.stop()


def test_ensure_reservation_crd_creates_and_verifies():
    backend = InMemoryBackend()
    backend.unregister_crd("resourcereservations.sparkscheduler.palantir.com")
    assert not backend.crd_exists(
        "resourcereservations.sparkscheduler.palantir.com"
    )
    ensure_resource_reservations_crd(backend)
    assert backend.crd_exists("resourcereservations.sparkscheduler.palantir.com")


def test_ensure_crd_deletes_on_failed_verify():
    class NeverEstablished(InMemoryBackend):
        def register_crd(self, name, definition=None):
            pass  # create "succeeds" but never reports Established

        def crd_exists(self, name):
            return False

    unregistered = []
    backend = NeverEstablished()
    backend.unregister_crd = lambda name: unregistered.append(name)
    try:
        ensure_resource_reservations_crd(
            backend, name="rr-crd", timeout_s=0.01, sleep=lambda s: None
        )
        raise AssertionError("expected CRDError")
    except CRDError:
        pass
    assert unregistered == ["rr-crd"]  # half-created CRD torn down


def test_lazy_demand_watcher_fires_once_on_crd_arrival():
    backend = InMemoryBackend()  # no demand CRD registered yet
    watcher = LazyDemandCRDWatcher(backend, DEMAND_CRD, poll_interval_s=0.01)
    fired = []
    watcher.on_ready(lambda: fired.append("a"))
    assert not watcher.check_now() and fired == []

    watcher.start()
    backend.register_crd(DEMAND_CRD)
    assert watcher.wait_ready(timeout=5.0)
    watcher.stop()
    assert fired == ["a"]
    # Late registration fires immediately; ready callbacks never re-fire.
    watcher.on_ready(lambda: fired.append("b"))
    assert fired == ["a", "b"]
    assert watcher.check_now()


def test_lazy_watcher_callbacks_race_free():
    backend = InMemoryBackend()
    watcher = LazyDemandCRDWatcher(backend, DEMAND_CRD, poll_interval_s=0.001)
    fired = []
    for i in range(8):
        watcher.on_ready(lambda i=i: fired.append(i))
    threads = [threading.Thread(target=watcher.check_now) for _ in range(8)]
    backend.register_crd(DEMAND_CRD)
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert sorted(fired) == list(range(8))  # each callback exactly once
