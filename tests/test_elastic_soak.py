"""Elastic invariant soak — the autoscaler in the randomized loop.

Same engine as tests/test_invariant_soak.py plus the elastic ops:
`elastic_burst` submits gangs too big for current capacity (demand ->
provision -> retried placement) and `autoscaler_tick` jumps the clock
across the drain TTL so provisioned nodes cordon and drain mid-run. Node
count therefore churns across the solver's padding buckets
(`_bucket(capacity, 8)`) under load — every recompile boundary crossed on
the 8-device CPU mesh — while the four standing invariants PLUS the
drain-safety invariant (no node holding a hard or soft reservation is
ever drained) are asserted as it goes.

Fast by design (non-slow): CI runs it on every PR. ELASTIC_SOAK_STEPS
scales it up for dedicated jobs.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from spark_scheduler_tpu.testing.soak import Soak

STEPS = int(os.environ.get("ELASTIC_SOAK_STEPS", "400"))
# Base (static-fleet) roster size; ELASTIC_SOAK_NODES=1000000 is the
# million-node family (ISSUE 11) — elastic capacity provisions on top.
NODES = int(os.environ.get("ELASTIC_SOAK_NODES", "10"))


@pytest.mark.parametrize(
    "strategy", ["tightly-pack", "single-az-tightly-pack"]
)
def test_elastic_soak(strategy):
    soak = Soak(
        np.random.default_rng(20260803), strategy, n_nodes=NODES, elastic=True
    )
    soak.run(STEPS // 2)
    # The elastic loop actually closed: demands were consumed, nodes were
    # provisioned AND handed back, and at least one burst rode autoscaled
    # capacity (invariants — including drain safety — asserted in-engine).
    counts = soak.h.autoscaler.metrics.counts()
    assert soak.op_counts.get("elastic_burst"), soak.op_counts
    assert counts["demands_fulfilled"] > 0, counts
    assert counts["nodes_added"] > 0, counts
    assert counts["nodes_drained"] > 0, counts
    assert soak.h.autoscaler.metrics.scaleup_latency_samples()
