"""Chaos-matrix soak (ISSUE 9): the randomized invariant-soak workload
run under seeded FaultPlans, one per surface family — {backend, kube,
wal, device, lease} — through the unified FaultInjector.

Per leg the engine asserts zero double placements, zero reservation
over-commits, zero silently-dropped write-back work, bounded per-step
latency, and per-surface recovery (WAL replay equals live truth after
append faults; the device path recovers after its greedy-fallback
window; a healthy lease holder is never deposed by store blips).

The replay tests pin the determinism contract the whole subsystem is
built on: same seed => same fault schedule => same soak verdict.

Step count: CHAOS_MATRIX_STEPS env (default 120 per leg so tier-1 stays
fast; CI's chaos-matrix job runs every leg at a higher budget).
"""

from __future__ import annotations

import os

import pytest

from spark_scheduler_tpu.testing.soak import ChaosMatrixSoak

MATRIX_STEPS = int(os.environ.get("CHAOS_MATRIX_STEPS", "120"))
# Roster size of the matrix legs; CHAOS_MATRIX_NODES=1000000 is the
# million-node family (ISSUE 11).
MATRIX_NODES = int(os.environ.get("CHAOS_MATRIX_NODES", "12"))


@pytest.mark.parametrize("surface", ChaosMatrixSoak.SURFACES)
def test_chaos_matrix_surface(surface, tmp_path):
    soak = ChaosMatrixSoak(
        surface, seed=9, n_nodes=MATRIX_NODES,
        wal_path=str(tmp_path / "wal.log"),
    )
    verdict = soak.run(MATRIX_STEPS)
    # The run itself asserted the invariants; pin that the plan actually
    # exercised its surface — a leg whose faults never fired tested
    # nothing.
    assert verdict["fired"], (surface, soak.injector.stats())
    assert verdict["write_back"]["dropped"] == 0
    assert verdict["apps"] > 0


@pytest.mark.parametrize("surface", ("backend", "kube", "wal", "device"))
def test_chaos_matrix_replay_deterministic(surface, tmp_path):
    """Same seed => same fault schedule => same verdict, field for field.
    (The lease leg's verdict is deterministic too but its surface fires
    on wall-clock-free renew ticks already covered above.)"""
    runs = []
    for i in range(2):
        soak = ChaosMatrixSoak(
            surface, seed=1234, wal_path=str(tmp_path / f"wal{i}.log")
        )
        runs.append(soak.run(80))
    a, b = runs
    assert a["schedule"] == b["schedule"]
    assert a == b


def test_chaos_matrix_different_seed_different_schedule(tmp_path):
    """The seed is load-bearing: a different seed must reshuffle the
    p-mode schedule (not merely re-label it)."""
    v1 = ChaosMatrixSoak("backend", seed=1).run(60)
    v2 = ChaosMatrixSoak("backend", seed=2).run(60)
    assert v1["schedule"] != v2["schedule"]
