"""Active-active HA subsystem tests (ISSUE 8).

Covers the lease (acquire / renew / expiry takeover / epoch fencing), the
fenced commit path, warm-standby tailing, promotion (reconcile-before-
serve), FailoverReconciler idempotency under racing replicas, the
configurable resync gap, instance-group sharding equivalence, the HTTP
role surfaces, and the tier-1 smoke CI keys on: leader + standby over a
shared DurableBackend WAL, leader killed, standby promotes within the
lease TTL and serves.
"""

from __future__ import annotations

import copy

import pytest

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.ha import (
    BackendLeaseStore,
    FencedBackend,
    FencingError,
    FileLeaseStore,
    LeaseManager,
    ShardMap,
)
from spark_scheduler_tpu.ha.replica import ShardedServingGroup, build_replica
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend
from spark_scheduler_tpu.testing.harness import (
    INSTANCE_GROUP_LABEL,
    Harness,
    new_node,
    static_allocation_spark_pods,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _config(ttl: float = 3.0, **kw) -> InstallConfig:
    kw.setdefault("fifo", True)
    kw.setdefault("binpack_algo", "tightly-pack")
    return InstallConfig(
        instance_group_label=INSTANCE_GROUP_LABEL,
        sync_writes=True,
        ha_enabled=True,
        ha_lease_ttl_s=ttl,
        **kw,
    )


# ------------------------------------------------------------------- lease


class TestLease:
    def _mgr(self, backend, holder, clock, ttl=3.0):
        return LeaseManager(
            BackendLeaseStore(backend), holder, ttl_s=ttl, clock=clock
        )

    def test_acquire_renew_takeover_epochs(self):
        backend = InMemoryBackend()
        clock = FakeClock()
        a = self._mgr(backend, "a", clock)
        b = self._mgr(backend, "b", clock)
        assert a.try_acquire() and a.acquired_epoch == 1
        assert a.is_held()
        # A live lease blocks takeover.
        assert not b.try_acquire() and b.acquired_epoch == 0
        # Renewal keeps the epoch.
        clock.advance(2.0)
        assert a.renew() and a.acquired_epoch == 1
        # Expiry enables takeover, which BUMPS the epoch.
        clock.advance(4.0)
        assert not a.is_held()
        assert b.try_acquire() and b.acquired_epoch == 2
        # The deposed holder cannot renew its stale epoch.
        assert not a.renew()
        with pytest.raises(FencingError):
            a.check_fence()
        b.check_fence()  # the live holder passes

    def test_release_enables_immediate_takeover_with_epoch_bump(self):
        backend = InMemoryBackend()
        clock = FakeClock()
        a = self._mgr(backend, "a", clock)
        b = self._mgr(backend, "b", clock)
        assert a.try_acquire()
        a.release()
        # No TTL wait needed — but the epoch still advances past a's term.
        assert b.try_acquire() and b.acquired_epoch == 2

    def test_file_lease_store_cas(self, tmp_path):
        path = str(tmp_path / "wal.lease")
        clock = FakeClock()
        a = LeaseManager(FileLeaseStore(path), "a", ttl_s=3.0, clock=clock)
        b = LeaseManager(FileLeaseStore(path), "b", ttl_s=3.0, clock=clock)
        assert a.try_acquire() and a.acquired_epoch == 1
        assert not b.try_acquire()
        clock.advance(10.0)
        assert b.try_acquire() and b.acquired_epoch == 2
        with pytest.raises(FencingError):
            a.check_fence()

    def test_file_takeover_cas_loses_to_interleaved_renewal(self, tmp_path):
        """Standby reads the lease just as the TTL lapses; the leader's
        delayed heartbeat then lands. The takeover CAS carries a stale
        renewed_at and must LOSE — renewals move ONLY renewed_at, so a
        CAS comparing just holder+epoch would depose a healthy leader
        mid-term."""
        from spark_scheduler_tpu.ha.lease import LeaseRecord

        path = str(tmp_path / "wal.lease")
        clock = FakeClock()
        a = LeaseManager(FileLeaseStore(path), "a", ttl_s=3.0, clock=clock)
        b = LeaseManager(FileLeaseStore(path), "b", ttl_s=3.0, clock=clock)
        assert a.try_acquire()
        clock.advance(3.5)
        stale = b._store.read()  # b observes an expired record...
        assert stale.expired(clock())
        assert a.renew()  # ...but the delayed heartbeat lands first
        assert not b._store.compare_and_swap(
            stale, LeaseRecord("b", stale.epoch + 1, clock(), 3.0)
        )
        assert not b.try_acquire()  # fresh read: unexpired again
        assert a.is_held()


# ----------------------------------------------------------------- fencing


class TestFencing:
    def test_fenced_backend_rejects_deposed_writer(self):
        backend = InMemoryBackend()
        clock = FakeClock()
        a = LeaseManager(BackendLeaseStore(backend), "a", 3.0, clock)
        b = LeaseManager(BackendLeaseStore(backend), "b", 3.0, clock)
        rejects = []
        fenced = FencedBackend(
            backend, a.check_fence, on_reject=rejects.append
        )
        assert a.try_acquire()
        # Pod/node writes are NEVER fenced (observed state must flow).
        fenced.add_node(new_node("n0"))
        from spark_scheduler_tpu.models.demands import (
            Demand,
            DemandSpec,
            DemandStatus,
        )

        d = Demand(
            name="d1", namespace="ns",
            spec=DemandSpec(units=[], instance_group="g"),
            status=DemandStatus(phase="pending"),
        )
        fenced.create("demands", d)  # live holder passes
        clock.advance(10.0)
        assert b.try_acquire()  # epoch 2: a is deposed
        d2 = copy.deepcopy(d)
        d2.name = "d2"
        with pytest.raises(FencingError):
            fenced.create("demands", d2)
        assert rejects == ["demands"]
        assert backend.get("demands", "ns", "d2") is None
        # Unfenced kinds still pass for the corpse (watch-state ingest).
        fenced.add_node(new_node("n1"))


# ----------------------------------------------------- standby warm state


class TestStandbyTailer:
    def test_standby_caches_and_usage_stay_hot(self):
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        leader = build_replica(backend, "r0", config=_config(), clock=clock)
        standby = build_replica(backend, "r1", config=_config(), clock=clock)
        assert leader.lease.try_acquire()
        leader.promote()
        names = [f"n{i}" for i in range(4)]
        for n in names:
            backend.add_node(new_node(n))
        pods = static_allocation_spark_pods("hot-app", 2)
        backend.add_pod(pods[0])
        res = leader.app.extender.predicate(
            ExtenderArgs(pod=pods[0], node_names=names)
        )
        assert res.ok
        # The standby's cache absorbed the leader's commit...
        rr = standby.app.rr_cache.get("namespace", "hot-app")
        assert rr is not None
        assert rr.spec == leader.app.rr_cache.get("namespace", "hot-app").spec
        # ...and its delta-maintained usage aggregate matches the leader's.
        assert (
            standby.app.reservation_manager.get_reserved_resources()
            == leader.app.reservation_manager.get_reserved_resources()
        )
        assert standby.tailer.applied > 0
        # The leader's OWN tailer deduped its own write (rv match).
        assert leader.tailer.applied == 0
        assert leader.tailer.skipped_own > 0
        # Deletes propagate too.
        leader.app.rr_cache.delete("namespace", "hot-app")
        assert standby.app.rr_cache.get("namespace", "hot-app") is None

    def test_standby_absorbs_updates_of_existing_objects(self):
        """UPDATE of an object the standby already holds: the cache's own
        watch subscription fast-forwards the stored rv BEFORE the tailer
        runs, so rv-equality would misread every external update as an
        own write and keep the stale content forever (the promoted leader
        would then schedule against pre-update usage). Content equality
        is the dedup — this pins the update path the create/delete tests
        never exercise."""
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        leader = build_replica(backend, "r0", config=_config(), clock=clock)
        standby = build_replica(backend, "r1", config=_config(), clock=clock)
        assert leader.lease.try_acquire()
        leader.promote()
        names = [f"n{i}" for i in range(4)]
        for n in names:
            backend.add_node(new_node(n))
        pods = static_allocation_spark_pods("upd-app", 2)
        backend.add_pod(pods[0])
        assert leader.app.extender.predicate(
            ExtenderArgs(pod=pods[0], node_names=names)
        ).ok
        # Executors bind: the leader UPDATES the existing reservation
        # (status/spec move), the standby must absorb the new content.
        for ex in pods[1:]:
            backend.add_pod(ex)
            assert leader.app.extender.predicate(
                ExtenderArgs(pod=ex, node_names=names)
            ).ok
        lrr = leader.app.rr_cache.get("namespace", "upd-app")
        srr = standby.app.rr_cache.get("namespace", "upd-app")
        assert srr is not None and srr.spec == lrr.spec
        assert srr.status == lrr.status
        assert (
            standby.app.reservation_manager.get_reserved_resources()
            == leader.app.reservation_manager.get_reserved_resources()
        )

    def test_warm_promotion_serves_executor_on_restored_reservation(self):
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        leader = build_replica(backend, "r0", config=_config(), clock=clock)
        standby = build_replica(backend, "r1", config=_config(), clock=clock)
        assert leader.lease.try_acquire()
        leader.promote()
        names = [f"n{i}" for i in range(4)]
        for n in names:
            backend.add_node(new_node(n))
        pods = static_allocation_spark_pods("surv", 2)
        backend.add_pod(pods[0])
        res = leader.app.extender.predicate(
            ExtenderArgs(pod=pods[0], node_names=names)
        )
        assert res.ok
        backend.bind_pod(pods[0], res.node_names[0])
        # Crash + takeover.
        leader.kill()
        clock.advance(5.0)
        assert standby.run_election_once() == "leader"
        assert standby.is_serving()
        # An executor binds onto the RESTORED reservation — warm state is
        # live, not cosmetic.
        backend.add_pod(pods[1])
        res1 = standby.app.extender.predicate(
            ExtenderArgs(pod=pods[1], node_names=names)
        )
        assert res1.ok
        rr = standby.app.rr_cache.get("namespace", "surv")
        reserved = {
            r.node for k, r in rr.spec.reservations.items() if k != "driver"
        }
        assert res1.node_names[0] in reserved


# ------------------------------------------------------ deposed recovery


class TestDeposedRecovery:
    def test_transient_lease_read_failure_is_not_terminal(self):
        """One flaky lease-store read deposes the leader (serving stops
        that tick) but must NOT park it forever: the next tick rejoins
        the election as a standby — here the record is still ours and
        unexpired, so re-affirmation promotes straight back."""
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        runtime = build_replica(backend, "r0", config=_config(), clock=clock)
        assert runtime.lease.try_acquire()
        runtime.promote()
        assert runtime.role == "leader"
        store = runtime.lease._store
        real_read = store.read
        store.read = lambda: None  # transient EIO/torn sidecar read
        assert runtime.run_election_once() == "deposed"
        assert not runtime.is_serving()
        store.read = real_read
        assert runtime.run_election_once() == "leader"
        assert runtime.is_serving()
        runtime.app.stop()


# ------------------------------------------------ reconciler idempotency


class TestReconcilerIdempotency:
    def _stale_state(self):
        """Admit two gangs, bind everything, then wipe the reservations —
        the new-leader stale-pod scenario reconciliation exists for."""
        h = Harness(binpack_algo="tightly-pack", fifo=True)
        names = [f"n{i}" for i in range(6)]
        h.add_nodes(*(new_node(n) for n in names))
        for i in range(2):
            pods = static_allocation_spark_pods(f"stale-{i}", 2)
            for p in pods:
                assert h.schedule(p, names).ok
        for i in range(2):
            rr = h.get_reservation("namespace", f"stale-{i}")
            h.app.rr_cache.delete(rr.namespace, rr.name)
        return h

    def test_second_pass_is_a_no_op(self):
        h = self._stale_state()
        first = h.app.reconciler.sync_resource_reservations_and_demands()
        assert first["created"] == 2
        rrs_after_first = {
            rr.name: (copy.deepcopy(rr.spec), copy.deepcopy(rr.status))
            for rr in h.app.rr_cache.list()
        }
        second = h.app.reconciler.sync_resource_reservations_and_demands()
        assert second["stale_apps"] == 0
        assert second["created"] == 0
        assert second["patched"] == 0
        assert second["soft_added"] == 0
        rrs_after_second = {
            rr.name: (rr.spec, rr.status) for rr in h.app.rr_cache.list()
        }
        assert rrs_after_first == rrs_after_second

    def test_racing_replicas_produce_no_duplicates(self):
        """Two replicas over one backend both reconcile (the takeover race
        window): one creates, the other — warm via its tailer — finds
        nothing stale; state converges to exactly one RR per app."""
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        a = build_replica(backend, "ra", config=_config(), clock=clock)
        b = build_replica(backend, "rb", config=_config(), clock=clock)
        assert a.lease.try_acquire()
        a.promote()
        names = [f"n{i}" for i in range(6)]
        for n in names:
            backend.add_node(new_node(n))
        pods = static_allocation_spark_pods("race", 2)
        backend.add_pod(pods[0])
        res = a.app.extender.predicate(
            ExtenderArgs(pod=pods[0], node_names=names)
        )
        assert res.ok
        backend.bind_pod(pods[0], res.node_names[0])
        # Wipe the reservation: BOTH replicas now see a stale bound driver.
        a.app.rr_cache.delete("namespace", "race")
        s1 = a.app.reconciler.sync_resource_reservations_and_demands()
        s2 = b.app.reconciler.sync_resource_reservations_and_demands()
        assert s1["created"] == 1
        # b's tailer absorbed a's repair before b's pass scanned.
        assert s2["created"] == 0 and s2["patched"] == 0
        rrs = backend.list("resourcereservations")
        assert len(rrs) == 1 and rrs[0].name == "race"
        assert (
            rrs[0].spec.reservations["driver"].node == pods[0].node_name
        )


# -------------------------------------------------------- resync heuristic


class TestResyncGap:
    def _counting_harness(self, **kw):
        h = Harness(binpack_algo="tightly-pack", fifo=False, **kw)
        h.add_nodes(new_node("n0"))
        calls = []
        real = h.app.reconciler.sync_resource_reservations_and_demands
        h.app.reconciler.sync_resource_reservations_and_demands = (
            lambda: (calls.append(1), real())[1]
        )
        return h, calls

    def test_resync_gap_is_configurable(self):
        h, calls = self._counting_harness(resync_gap_seconds=40.0)
        ext = h.app.extender
        assert ext._config.resync_gap_seconds == 40.0
        pods = static_allocation_spark_pods("gap", 1)
        ext._last_request = ext._clock() - 30.0  # > default 15, < 40
        h.schedule(pods[0], ["n0"])
        assert not calls
        ext._last_request = ext._clock() - 50.0  # > 40
        h.schedule(pods[1], ["n0"])
        assert len(calls) == 1

    def test_yaml_key_extender_resync_gap(self):
        cfg = InstallConfig.from_dict(
            {"extender": {"resync-gap-seconds": "2m"}}
        )
        assert cfg.resync_gap_seconds == 120.0
        assert InstallConfig.from_dict({}).resync_gap_seconds == 15.0

    def test_heuristic_skipped_while_lease_held(self):
        h, calls = self._counting_harness()
        ext = h.app.extender
        backend = InMemoryBackend()
        clock = FakeClock()
        lease = LeaseManager(BackendLeaseStore(backend), "me", 3.0, clock)
        assert lease.try_acquire()
        ext.ha_lease = lease
        pods = static_allocation_spark_pods("held", 1)
        ext._last_request = ext._clock() - 1e6  # any gap
        h.schedule(pods[0], ["n0"])
        assert not calls  # held lease: heuristic skipped
        # Lease lost -> the heuristic re-engages.
        clock.advance(10.0)
        ext._last_request = ext._clock() - 1e6
        h.schedule(pods[1], ["n0"])
        assert len(calls) == 1


# ---------------------------------------------------------------- sharding


class TestShardedServing:
    def test_shard_map_stable(self):
        m = ShardMap(2)
        groups = [f"g{i}" for i in range(16)]
        owners = [m.owner(g) for g in groups]
        assert owners == [ShardMap(2).owner(g) for g in groups]
        assert set(owners) == {0, 1}  # 16 groups spread over both

    def _two_group_workload(self, ga: str, gb: str):
        """Nodes + an interleaved driver/executor request sequence across
        two instance groups (deep-copied so two backends never alias)."""
        nodes = [
            new_node(f"a{i}", instance_group=ga) for i in range(4)
        ] + [new_node(f"b{i}", instance_group=gb) for i in range(4)]
        apps = []
        for i in range(3):
            apps.append((static_allocation_spark_pods(
                f"app-a{i}", 2, instance_group=ga), ga))
            apps.append((static_allocation_spark_pods(
                f"app-b{i}", 2, instance_group=gb), gb))
        return nodes, apps

    def test_sharded_decisions_byte_identical_per_group(self):
        m = ShardMap(2)
        groups = iter(f"group-{i}" for i in range(64))
        ga = next(g for g in groups if m.owner(g) == 0)
        gb = next(g for g in groups if m.owner(g) == 1)
        nodes, apps = self._two_group_workload(ga, gb)
        node_names = [n.name for n in nodes]

        # Control: ONE unsharded replica serves the interleaved sequence.
        control = Harness(binpack_algo="tightly-pack", fifo=True)
        control.add_nodes(*(copy.deepcopy(n) for n in nodes))
        control_results = []
        for pods, _g in apps:
            for p in pods:
                control_results.append(
                    (p.name, control.schedule(copy.deepcopy(p), node_names))
                )

        # Sharded: 2 active replicas over one shared backend, requests
        # arriving at the WRONG member half the time (forwarding).
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        group = ShardedServingGroup(
            backend, 2, config_factory=lambda i: _config(), clock=clock
        )
        group.start()
        for n in nodes:
            backend.add_node(copy.deepcopy(n))
        sharded_results = []
        for k, (pods, _g) in enumerate(apps):
            for p in pods:
                p = copy.deepcopy(p)
                backend.add_pod(p)
                # Everything arrives at replica 0: group-B requests are
                # wrong-shard there and must be forwarded to replica 1.
                res = group.predicate(
                    ExtenderArgs(pod=p, node_names=list(node_names)),
                    via=0,
                )
                sharded_results.append((p.name, res))
                if res.ok:
                    backend.bind_pod(p, res.node_names[0])

        assert group.forwarded > 0  # wrong-shard arrivals were forwarded
        for (name_c, rc), (name_s, rs) in zip(
            control_results, sharded_results
        ):
            assert name_c == name_s
            assert rc.ok == rs.ok, (name_c, rc, rs)
            assert rc.node_names == rs.node_names, (name_c, rc, rs)
            assert rc.outcome == rs.outcome, (name_c, rc, rs)
        # Durable reservations byte-identical per group.
        control_rrs = {
            rr.name: rr.spec
            for rr in control.backend.list("resourcereservations")
        }
        sharded_rrs = {
            rr.name: rr.spec
            for rr in backend.list("resourcereservations")
        }
        assert control_rrs == sharded_rrs
        group.stop()

    def test_remove_member_remaps_and_fences(self):
        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        group = ShardedServingGroup(
            backend, 3, config_factory=lambda i: _config(), clock=clock
        )
        group.start()
        groups = [f"group-{i}" for i in range(32)]
        victim = 2
        owned = [g for g in groups if group.shard_map.owner(g) == victim]
        assert owned  # 32 groups cover all 3 members
        with pytest.raises(ValueError):
            group.remove_member(0)  # the lease holder fails over, not drains
        removed = group.replicas[victim]
        before = {g: group.shard_map.owner(g) for g in groups}
        group.remove_member(victim)
        # ONLY the victim's groups remapped (a surviving member's window
        # in flight must not silently lose ownership mid-commit); the
        # member stopped serving.
        for g in groups:
            after = group.shard_map.owner(g)
            assert after != victim
            if before[g] != victim:
                assert after == before[g]
        assert not removed.is_serving()
        # A commit it still had in flight rejects instead of racing the
        # new owner (the member-group analog of the fencing epoch).
        from spark_scheduler_tpu.models.demands import (
            Demand,
            DemandSpec,
            DemandStatus,
        )

        late = Demand(
            name="late", namespace="ns",
            spec=DemandSpec(units=[], instance_group=owned[0]),
            status=DemandStatus(phase="pending"),
        )
        with pytest.raises(FencingError):
            removed.app.backend.create("demands", late)
        assert backend.get("demands", "ns", "late") is None
        # The remapped shard still serves: a request for a formerly
        # victim-owned group lands on a survivor and places.
        g = owned[0]
        for i in range(2):
            backend.add_node(new_node(f"rm{i}", instance_group=g))
        pod = static_allocation_spark_pods("app-rm", 1, instance_group=g)[0]
        backend.add_pod(pod)
        res = group.predicate(
            ExtenderArgs(pod=pod, node_names=["rm0", "rm1"]), via=0
        )
        assert res.ok
        group.stop()


# ------------------------------------------------------------ HTTP surface


class TestHTTPRoleSurfaces:
    def test_readiness_reflects_role_and_debug_ha(self):
        import http.client
        import json

        from spark_scheduler_tpu.server.http import SchedulerHTTPServer

        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        cfg = _config()
        cfg.ha_heartbeat_s = 3600.0  # no auto-tick during the test
        runtime = build_replica(backend, "web-r0", config=cfg, clock=clock)
        backend.add_node(new_node("n0"))
        server = SchedulerHTTPServer(
            runtime.app, host="127.0.0.1", port=0, ha=runtime
        )
        server.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port)

            def get(path):
                conn.request("GET", path)
                resp = conn.getresponse()
                return resp.status, json.loads(resp.read())

            status, body = get("/status/readiness")
            assert status == 503
            assert body == {"ready": False, "role": "standby"}
            status, body = get("/debug/ha")
            assert status == 200
            assert body["role"] == "standby" and not body["serving"]
            assert body["lease"]["lease_epoch"] == 0
            # Election: the replica promotes and readiness flips.
            assert runtime.run_election_once() == "leader"
            status, body = get("/status/readiness")
            assert status == 200
            assert body == {"ready": True, "role": "leader"}
            status, body = get("/debug/ha")
            assert body["role"] == "leader"
            assert body["lease"]["lease_epoch"] == 1
            assert body["promotion_ms"] is not None
            conn.close()
        finally:
            server.stop()

    def test_tailed_cluster_state_flips_readiness(self):
        """An HA replica's cluster state arrives by TAILING the shared
        backend — never via the PUT /state/nodes that flips `ready` on a
        standalone server — so readiness must observe the backend
        directly once a serving role is held. (Two-process failover: a
        standby promoted after the leader's SIGKILL would otherwise
        answer 503 forever and kube would never route to it.)"""
        import http.client
        import json

        from spark_scheduler_tpu.server.http import SchedulerHTTPServer

        backend = InMemoryBackend()
        backend.register_crd(DEMAND_CRD)
        clock = FakeClock()
        cfg = _config()
        cfg.ha_heartbeat_s = 3600.0
        runtime = build_replica(backend, "web-r1", config=cfg, clock=clock)
        server = SchedulerHTTPServer(
            runtime.app, host="127.0.0.1", port=0, ha=runtime
        )
        server.start()  # backend still empty: not ready
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port)

            def get(path):
                conn.request("GET", path)
                resp = conn.getresponse()
                return resp.status, json.loads(resp.read())

            assert runtime.run_election_once() == "leader"
            status, body = get("/status/readiness")
            assert status == 503  # serving role but no cluster state yet
            backend.add_node(new_node("n0"))  # arrives via the shared log
            status, body = get("/status/readiness")
            assert status == 200
            assert body == {"ready": True, "role": "leader"}
            conn.close()
        finally:
            server.stop()


# ------------------------------------------------------- tier-1 HA smoke


class TestDurableHASmoke:
    def test_leader_kill_standby_promotes_within_ttl_and_serves(self, tmp_path):
        """The CI smoke leg: leader + warm standby over ONE shared WAL
        (two backend instances, the standby in follower mode), leader
        killed mid-life, standby promotes within the lease TTL and serves
        — an executor binds onto the restored reservation and a fresh
        driver admission lands in the WAL as the new writer's append."""
        path = str(tmp_path / "state.jsonl")
        ttl = 2.0
        clock = FakeClock()

        from spark_scheduler_tpu.store.durable import DurableBackend

        leader_b = DurableBackend(path)
        leader_b.register_crd(DEMAND_CRD)
        lease_a = LeaseManager(
            FileLeaseStore(path + ".lease"), "r0", ttl_s=ttl, clock=clock
        )
        leader = build_replica(
            leader_b, "r0", config=_config(ttl), lease=lease_a, clock=clock
        )
        assert leader.run_election_once() == "leader"
        names = [f"n{i}" for i in range(4)]
        for n in names:
            leader_b.add_node(new_node(n))
        pods = static_allocation_spark_pods("walapp", 2)
        leader_b.add_pod(pods[0])
        res = leader.app.extender.predicate(
            ExtenderArgs(pod=pods[0], node_names=names)
        )
        assert res.ok
        leader_b.bind_pod(pods[0], res.node_names[0])

        # Warm standby over the SAME log, follower mode.
        standby_b = DurableBackend(path, follow=True)
        lease_b = LeaseManager(
            FileLeaseStore(path + ".lease"), "r1", ttl_s=ttl, clock=clock
        )
        standby = build_replica(
            standby_b, "r1", config=_config(ttl), lease=lease_b, clock=clock
        )
        assert standby.run_election_once() == "standby"  # lease is live
        # The follower tailed the leader's appends: caches are warm.
        assert standby.app.rr_cache.get("namespace", "walapp") is not None
        assert len(standby_b.list_nodes()) == 4

        # Crash. The lease expires; the standby's next tick promotes.
        leader.kill()
        leader_b.close()
        clock.advance(ttl * 1.5)
        assert standby.run_election_once() == "leader"
        assert standby.last_promotion_ms is not None
        assert standby.last_promotion_ms < ttl * 1000.0  # within the TTL

        # Serves immediately: executor onto the restored reservation...
        standby_b.add_pod(pods[1])
        res1 = standby.app.extender.predicate(
            ExtenderArgs(pod=pods[1], node_names=names)
        )
        assert res1.ok
        rr = standby.app.rr_cache.get("namespace", "walapp")
        reserved = {
            r.node for k, r in rr.spec.reservations.items() if k != "driver"
        }
        assert res1.node_names[0] in reserved
        # ...and a fresh gang admission APPENDS to the WAL as the new
        # writer (promote_to_writer flipped the follower).
        pods2 = static_allocation_spark_pods("walapp2", 1)
        standby_b.add_pod(pods2[0])
        res2 = standby.app.extender.predicate(
            ExtenderArgs(pod=pods2[0], node_names=names)
        )
        assert res2.ok
        standby_b.close()
        third = DurableBackend(path)
        assert third.get("resourcereservations", "namespace", "walapp2") is not None
        third.close()
