"""Fused multi-window device dispatch on resident carry state.

The engine (core/solver.py pack_windows_dispatch + extender
predicate_windows_dispatch + the PredicateBatcher's fused claim) solves K
queued serving windows in ONE device program — one h2d of K window blobs,
one jitted dispatch, one d2h of K placements — with the committed base
carried on-device between windows. These tests pin:

  - fused K-window decisions BYTE-IDENTICAL to sequential single-window
    dispatch across randomized usage churn, K in {1, 2, 4, 8}, with and
    without domain partitioning (device pool);
  - the RTT amortization property, structurally, via the simulated-RTT
    device shim (testing/rtt_shim.py): K fused windows fire ONE h2d and
    ONE d2h where K sequential dispatches fire K each;
  - restart-leak hygiene: close()/discard_pipeline() release the fused
    [K, ...] staging buffers and cancel queued work, and a later fetch of
    a released dispatch fails fast;
  - the non-ICI node-shards startup warning;
  - tier-1 smoke: a 2-device pool server with fuse-windows=4 boots,
    serves a concurrent burst, and exports the
    foundry.spark.scheduler.solver.dispatch.* gauges at /metrics with
    fused_k/dispatch_id on the flight-recorder records.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.core.solver import (
    FusedWindowView,
    PlacementSolver,
    WindowRequest,
)
from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.testing.harness import (
    Harness,
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)
from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT

ONE = Resources.from_quantities("1", "1Gi")
TWO = Resources.from_quantities("2", "2Gi")


def _nodes(n, groups=1):
    out = []
    for i in range(n):
        labels = {ZONE_LABEL: f"z{i % 2}"}
        out.append(
            Node(
                name=f"n{i:03d}",
                allocatable=Resources.from_quantities(
                    "8", "8Gi", "1", round_up=False
                ),
                labels=labels,
            )
        )
    return out


def _random_windows(rng, nodes, k, per, *, domains=None, fifo_rows=False):
    """K windows of `per` WindowRequests each. `domains` = list of
    disjoint node-name lists to cycle through (the partition topology);
    fifo_rows adds hypothetical earlier-driver prefixes."""
    names = [n.name for n in nodes]
    windows = []
    r = 0
    for _ in range(k):
        reqs = []
        for _ in range(per):
            rows = []
            if fifo_rows:
                for _ in range(int(rng.integers(0, 3))):
                    rows.append(
                        (ONE, ONE, int(rng.integers(1, 3)),
                         bool(rng.random() < 0.5))
                    )
            res = TWO if rng.random() < 0.3 else ONE
            rows.append((res, ONE, int(rng.integers(1, 4)), False))
            if domains is not None:
                dom = domains[r % len(domains)]
                cand = dom
            else:
                dom, cand = None, names
            reqs.append(
                WindowRequest(
                    rows=rows,
                    driver_candidate_names=cand,
                    domain_node_names=dom,
                )
            )
            r += 1
        windows.append(reqs)
    return windows


def _random_usage(rng, nodes):
    """Randomized external churn: a usage map debiting a few nodes."""
    usage = {}
    for n in nodes:
        if rng.random() < 0.3:
            usage[n.name] = Resources.from_quantities(
                str(int(rng.integers(1, 4))), "1Gi"
            )
    return usage


def _run_sequential(solver, nodes, batches, usages, strategy):
    """The serving loop's own order: inside a batch, dispatch every window
    back-to-back (pipelined — the next build applies zero external delta),
    then fetch all; churn lands between batches."""
    out = []
    for usage, wins in zip(usages, batches):
        handles = []
        for w in wins:
            t = solver.build_tensors_pipelined(nodes, usage, {})
            handles.append(solver.pack_window_dispatch(strategy, t, w))
        for h in handles:
            out.extend(solver.pack_window_fetch(h))
    return out


def _run_fused(solver, nodes, batches, usages, strategy):
    out = []
    for usage, wins in zip(usages, batches):
        t = solver.build_tensors_pipelined(nodes, usage, {})
        views = solver.pack_windows_dispatch(strategy, t, wins)
        assert all(isinstance(v, FusedWindowView) for v in views)
        assert len({v.dispatch_id for v in views}) == 1
        for v in views:
            out.extend(solver.pack_window_fetch(v))
    return out


@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_fused_matches_sequential_with_churn(k):
    rng = np.random.default_rng(100 + k)
    nodes = _nodes(16)
    n_batches = 3
    batches = [
        _random_windows(rng, nodes, k, 2, fifo_rows=True)
        for _ in range(n_batches)
    ]
    usages = [{}] + [_random_usage(rng, nodes) for _ in range(n_batches - 1)]

    seq = _run_sequential(
        PlacementSolver(use_native=False), nodes, batches, usages,
        "tightly-pack",
    )
    fused = _run_fused(
        PlacementSolver(use_native=False), nodes, batches, usages,
        "tightly-pack",
    )
    assert len(seq) == len(fused) == n_batches * k * 2
    for i, (a, b) in enumerate(zip(seq, fused)):
        assert a == b, f"decision {i} diverged: {a} vs {b}"


def test_fused_matches_sequential_single_az_strategy():
    """The single-AZ plug-board strategies ride the same segmented scan —
    one fused case pins them too."""
    rng = np.random.default_rng(7)
    nodes = _nodes(12)
    batches = [_random_windows(rng, nodes, 4, 2)]
    seq = _run_sequential(
        PlacementSolver(use_native=False), nodes, batches, [{}],
        "single-az-tightly-pack",
    )
    fused = _run_fused(
        PlacementSolver(use_native=False), nodes, batches, [{}],
        "single-az-tightly-pack",
    )
    assert seq == fused


@pytest.mark.parametrize("k", [2, 4])
def test_fused_pooled_partitioned_matches_single_device(k):
    """Fused dispatch on a 2-slot device pool, windows pinned to two
    disjoint instance-group domains (the partition topology): decisions
    byte-identical to the sequential single-device path."""
    rng = np.random.default_rng(30 + k)
    nodes = _nodes(16)
    half = [n.name for n in nodes[:8]], [n.name for n in nodes[8:]]
    batches = [
        _random_windows(rng, nodes, k, 2, domains=half) for _ in range(2)
    ]
    usages = [{}, _random_usage(rng, nodes)]
    seq = _run_sequential(
        PlacementSolver(use_native=False), nodes, batches, usages,
        "tightly-pack",
    )
    pooled = PlacementSolver(use_native=False, device_pool=2)
    assert pooled.pool_size == 2
    fused = _run_fused(pooled, nodes, batches, usages, "tightly-pack")
    assert seq == fused


def test_rtt_shim_amortizes_round_trips():
    """The structural amortization claim: K sequential window dispatches
    fire K h2d and K d2h boundaries; ONE fused dispatch of the same K
    windows fires exactly one of each — same decisions."""
    rng = np.random.default_rng(5)
    nodes = _nodes(12)
    batches = [_random_windows(rng, nodes, 4, 2)]

    shim = SimulatedRTT(rtt_ms=2.0)
    with shim:
        seq = _run_sequential(
            PlacementSolver(use_native=False), nodes, batches, [{}],
            "tightly-pack",
        )
    seq_counts = dict(shim.counts)
    assert seq_counts["h2d"] == 4
    assert seq_counts["d2h"] == 4

    shim2 = SimulatedRTT(rtt_ms=2.0)
    with shim2:
        fused = _run_fused(
            PlacementSolver(use_native=False), nodes, batches, [{}],
            "tightly-pack",
        )
    assert shim2.counts["h2d"] == 1
    assert shim2.counts["d2h"] == 1
    assert seq == fused


def test_close_releases_fused_staging_buffers():
    """The restart-leak contract extended to fused batches: close() must
    release the [K, ...] staging blob and fail later fetches fast, even
    while view handles are still parked outside the solver."""
    rng = np.random.default_rng(11)
    nodes = _nodes(8)
    solver = PlacementSolver(use_native=False)
    t = solver.build_tensors_pipelined(nodes, {}, {})
    views = solver.pack_windows_dispatch(
        "tightly-pack", t, _random_windows(rng, nodes, 3, 1)
    )
    owner = views[0].owner
    solver.close()
    assert owner.released
    assert owner.blob is None
    assert not solver._inflight_futures
    with pytest.raises(RuntimeError, match="discarded"):
        solver.pack_window_fetch(views[1])


def test_discard_pipeline_releases_fused_staging_buffers():
    rng = np.random.default_rng(12)
    nodes = _nodes(8)
    solver = PlacementSolver(use_native=False)
    t = solver.build_tensors_pipelined(nodes, {}, {})
    views = solver.pack_windows_dispatch(
        "tightly-pack", t, _random_windows(rng, nodes, 2, 1)
    )
    solver.discard_pipeline()
    assert views[0].owner.released
    assert views[0].owner.blob is None
    with pytest.raises(RuntimeError, match="discarded"):
        solver.pack_window_fetch(views[0])
    # The pipeline rebuilds from host truth and serves fresh windows.
    t2 = solver.build_tensors_pipelined(nodes, {}, {})
    views2 = solver.pack_windows_dispatch(
        "tightly-pack", t2, _random_windows(rng, nodes, 2, 1)
    )
    decisions = [d for v in views2 for d in solver.pack_window_fetch(v)]
    assert all(d.admitted for d in decisions)


def test_close_releases_fused_pooled_dispatch():
    """Pooled fused dispatch: close() cancels part futures and releases
    per-slot resident state (the PR 4 pool contract, fused path)."""
    rng = np.random.default_rng(13)
    nodes = _nodes(16)
    half = [n.name for n in nodes[:8]], [n.name for n in nodes[8:]]
    solver = PlacementSolver(use_native=False, device_pool=2)
    t = solver.build_tensors_pipelined(nodes, {}, {})
    views = solver.pack_windows_dispatch(
        "tightly-pack", t, _random_windows(rng, nodes, 2, 2, domains=half)
    )
    solver.close()
    assert views[0].owner.released
    for slot in solver._pool.slots:
        assert slot.statics is None and not slot.sub_statics
    with pytest.raises(RuntimeError):
        solver.pack_window_fetch(views[0])


def test_mesh_warning_on_non_ici_backend():
    """node-shards > 1 on a CPU backend used to degrade silently
    (measured 0.5x in PR 4); now it warns at startup. A plain pool of
    un-sharded devices stays silent."""
    with pytest.warns(RuntimeWarning, match="node-shards"):
        PlacementSolver(use_native=False, mesh=(1, 2))
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        PlacementSolver(use_native=False, device_pool=2)


def test_extender_fused_windows_dispatch_matches_sequential():
    """Extender-level equivalence through the full staging path
    (in-flight dedup, FIFO rows, domains, reservations): a fused 2-window
    dispatch places every gang exactly where the sequential pipelined
    dispatch does, and the flight recorder carries fused_k/dispatch_id."""

    def build(fuse):
        h = Harness(binpack_algo="tightly-pack", fifo=True)
        h.add_nodes(
            *[new_node(f"en{i}", zone=f"zone{i % 2}") for i in range(10)]
        )
        names = [f"en{i}" for i in range(10)]
        argss = []
        for j in range(8):
            pod = static_allocation_spark_pods(f"fx-{fuse}-{j}", 2)[0]
            h.add_pods(pod)
            argss.append(ExtenderArgs(pod=pod, node_names=names))
        return h, argss

    h_seq, args_seq = build("seq")
    tickets = [
        h_seq.extender.predicate_window_dispatch(args_seq[i : i + 4])
        for i in (0, 4)
    ]
    seq_results = [
        r for t in tickets for r in h_seq.extender.predicate_window_complete(t)
    ]

    h_fused, args_fused = build("fused")
    fused_tickets = h_fused.extender.predicate_windows_dispatch(
        [args_fused[:4], args_fused[4:]]
    )
    assert len(fused_tickets) == 2
    fused_results = [
        r
        for t in fused_tickets
        for r in h_fused.extender.predicate_window_complete(t)
    ]
    assert [r.node_names for r in seq_results] == [
        r.node_names for r in fused_results
    ]
    assert all(r.ok for r in fused_results)
    # Every fused decision shares one dispatch id and reports fused_k=2.
    recs = h_fused.app.recorder.query(role="driver", limit=16)
    fused_recs = [r for r in recs if r.get("fused_k")]
    assert fused_recs and all(r["fused_k"] == 2 for r in fused_recs)
    assert len({r["dispatch_id"] for r in fused_recs}) == 1


def test_extender_fused_dedups_inflight_apps_across_subwindows():
    """The same app submitted in two sub-windows of one fused claim: the
    duplicate defers to the post-window solo loop of its own ticket,
    which serves the reserved node via the idempotent-retry branch —
    exactly the pipelined cross-window behavior."""
    h = Harness(binpack_algo="tightly-pack", fifo=False)
    h.add_nodes(*[new_node(f"dd{i}") for i in range(4)])
    names = [f"dd{i}" for i in range(4)]
    pod = static_allocation_spark_pods("fx-dup", 1)[0]
    h.add_pods(pod)
    args = ExtenderArgs(pod=pod, node_names=names)
    other = static_allocation_spark_pods("fx-other", 1)[0]
    h.add_pods(other)
    tickets = h.extender.predicate_windows_dispatch(
        [[args, ExtenderArgs(pod=other, node_names=names)], [args]]
    )
    res = [
        r for t in tickets for r in h.extender.predicate_window_complete(t)
    ]
    assert all(r.ok for r in res), res
    # Both submissions of the duplicate got the SAME reserved node.
    assert res[0].node_names == res[2].node_names


def test_fused_claim_without_drivers_skips_featurize():
    """An executor-heavy fused claim with no driver anywhere must not pay
    the shared snapshot/tensor build (or risk a spurious
    PipelineDrainRequired) — the sequential path gates on driver_ids the
    same way."""
    h = Harness(binpack_algo="tightly-pack", fifo=False)
    h.add_nodes(*[new_node(f"xe{i}") for i in range(4)])
    names = [f"xe{i}" for i in range(4)]
    # Two sub-windows of non-spark pods (roles resolve to neither driver
    # nor executor): no device work should be provoked.
    from spark_scheduler_tpu.models.kube import Container, Pod

    def plain(name):
        p = Pod(
            name=name, namespace="namespace",
            containers=[Container(requests=ONE)],
        )
        h.add_pods(p)
        return ExtenderArgs(pod=p, node_names=names)

    before = dict(h.app.solver.device_state_stats)
    tickets = h.extender.predicate_windows_dispatch(
        [[plain("px-0"), plain("px-1")], [plain("px-2"), plain("px-3")]]
    )
    assert all(t.handle is None for t in tickets)
    assert h.app.solver.device_state_stats == before
    res = [
        r for t in tickets for r in h.extender.predicate_window_complete(t)
    ]
    assert all(r.outcome == "failure-non-spark-pod" for r in res)


def test_server_smoke_fused_pool_exports_dispatch_gauges():
    """Tier-1 smoke: 2-device CPU pool + fuse-windows=4 server boots,
    serves a concurrent burst (the simulated-RTT shim keeps windows in
    flight long enough for the backlog to fuse), and the
    foundry.spark.scheduler.solver.dispatch.* series reach /metrics."""
    from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s
    from spark_scheduler_tpu.store.backend import InMemoryBackend

    backend = InMemoryBackend()
    group_names = {}
    for g in range(2):
        group_names[g] = []
        for i in range(6):
            n = new_node(
                f"fg{g}-n{i}", zone=f"zone{i % 2}", instance_group=f"fgroup-{g}"
            )
            backend.add_node(n)
            group_names[g].append(n.name)
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_device_pool=2,
            solver_fuse_windows=4,
            predicate_max_window=2,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    assert app.solver.pool_size == 2
    server = SchedulerHTTPServer(
        app, registry, host="127.0.0.1", port=0, request_timeout_s=120.0
    )
    server.start()
    shim = SimulatedRTT(rtt_ms=0.0, h2d_ms=25.0, d2h_ms=50.0)
    shim.install()
    n_clients = 12
    errors: list = []
    results = [None] * n_clients

    def client(i):
        try:
            g = i % 2
            pod = static_allocation_spark_pods(
                f"fsrv-{i}", 2, instance_group=f"fgroup-{g}"
            )[0]
            backend.add_pod(pod)
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=120
            )
            body = json.dumps(
                {"Pod": pod_to_k8s(pod), "NodeNames": group_names[g]}
            ).encode()
            conn.request("POST", "/predicates", body=body)
            results[i] = json.loads(conn.getresponse().read())
            conn.close()
        except Exception as exc:  # surfaced after join
            errors.append(exc)

    try:
        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
            time.sleep(0.002)
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        for i, r in enumerate(results):
            assert r and r.get("NodeNames"), (i, r)
            assert r["NodeNames"][0] in group_names[i % 2]
        assert server.batcher.fused_dispatches >= 1, server.batcher.stats()

        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=60
        )
        conn.request("GET", "/metrics")
        snap = json.loads(conn.getresponse().read())
        conn.close()
        prefix = "foundry.spark.scheduler.solver.dispatch."
        dispatch_series = sorted(
            name for name in snap if name.startswith(prefix)
        )
        assert prefix + "fused.k" in dispatch_series, sorted(snap)
        assert prefix + "amortized.rtt.ms" in dispatch_series
        assert prefix + "overlap.occupancy" in dispatch_series
        # Flight-recorder records of the fused windows carry the grouping.
        recs = app.recorder.query(role="driver", limit=64)
        assert any((r.get("fused_k") or 1) > 1 for r in recs)
    finally:
        shim.uninstall()
        server.stop()
    # stop() -> solver.close(): fused staging + pool replicas released.
    assert app.solver._pipe is None
    for slot in app.solver._pool.slots:
        assert slot.statics is None and not slot.sub_statics
