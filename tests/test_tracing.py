"""Tracing + safe-param logging tests (SURVEY.md §5.1, VERDICT #8/#9).

Covers span structure (predicate -> solve nesting, write-back), b3
propagation from caller headers, the /debug/traces route, svc1log safe
params, and the JAX profiler capture producing an artifact.
"""

from __future__ import annotations

import http.client
import io
import json
import os

from spark_scheduler_tpu.tracing import (
    Svc1Logger,
    Tracer,
    demand_safe_params,
    pod_safe_params,
    rr_safe_params,
    set_svc1log,
    set_tracer,
    start_jax_profile,
    stop_jax_profile,
    tracer,
)


class TestTracer:
    def test_span_nesting_and_ring_buffer(self):
        t = Tracer()
        with t.span("outer", a=1) as outer_span:
            with t.span("inner") as inner_span:
                assert t.current() is inner_span.span
            assert t.current() is outer_span.span
        spans = t.finished_spans()
        names = [s["name"] for s in spans]
        assert names == ["inner", "outer"]  # finish order
        inner, outer = spans
        assert inner["traceId"] == outer["traceId"]
        assert inner["parentId"] == outer["id"]
        assert outer["tags"] == {"a": 1}

    def test_b3_header_extraction_and_injection(self):
        t = Tracer()
        headers = {"X-B3-TraceId": "beef" * 8, "X-B3-SpanId": "cafe" * 4}
        with t.root_from_headers(headers, "srv") as root:
            assert root.span.trace_id == "beef" * 8
            assert root.span.parent_id == "cafe" * 4
            out = t.inject_headers()
            assert out["X-B3-TraceId"] == "beef" * 8
            assert out["X-B3-SpanId"] == root.span.span_id
        # single-header form
        with t.root_from_headers({"b3": "aa-bb-1"}, "srv") as root:
            assert root.span.trace_id == "aa"
            assert root.span.parent_id == "bb"
        # unsampled traces are not recorded
        t.clear()
        with t.root_from_headers({"b3": "aa-bb-0"}, "srv"):
            pass
        assert t.finished_spans() == []
        # lone deny form "b3: 0" also suppresses recording
        with t.root_from_headers({"b3": "0"}, "srv"):
            pass
        assert t.finished_spans() == []

    def test_error_tagged(self):
        t = Tracer()
        try:
            with t.span("boom"):
                raise ValueError("x")
        except ValueError:
            pass
        (span,) = t.finished_spans()
        assert "ValueError" in span["tags"]["error"]

    def test_ring_buffer_overflow_keeps_newest(self):
        """The finished-span ring is bounded: overflow evicts oldest-first
        and never grows past capacity."""
        t = Tracer(capacity=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        spans = t.finished_spans()
        assert len(spans) == 4
        assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]
        # clear() empties it; the ring keeps working afterwards
        t.clear()
        assert t.finished_spans() == []
        with t.span("after"):
            pass
        assert [s["name"] for s in t.finished_spans()] == ["after"]

    def test_b3_single_header_round_trip(self):
        """`b3: {trace}-{span}-{sampled}` extraction round-trips through
        inject_headers and back through a downstream extraction, for both
        the sampled and unsampled decisions."""
        trace, upstream_span = "ab" * 16, "cd" * 8
        t = Tracer()
        with t.root_from_headers(
            {"b3": f"{trace}-{upstream_span}-1"}, "srv"
        ) as root:
            assert root.span.trace_id == trace
            assert root.span.parent_id == upstream_span
            hdrs = t.inject_headers()
            assert hdrs["X-B3-TraceId"] == trace
            assert hdrs["X-B3-Sampled"] == "1"
            single = (
                f"{hdrs['X-B3-TraceId']}-{hdrs['X-B3-SpanId']}"
                f"-{hdrs['X-B3-Sampled']}"
            )
        t2 = Tracer()
        with t2.root_from_headers({"b3": single}, "downstream") as child:
            assert child.span.trace_id == trace
            assert child.span.parent_id == root.span.span_id
            assert child.span.sampled
        assert [s["name"] for s in t2.finished_spans()] == ["downstream"]

        # Unsampled: the deny decision survives the round trip AND
        # suppresses recording on both hops.
        t3 = Tracer()
        with t3.root_from_headers(
            {"b3": f"{trace}-{upstream_span}-0"}, "srv"
        ):
            hdrs0 = t3.inject_headers()
            assert hdrs0["X-B3-Sampled"] == "0"
            single0 = (
                f"{hdrs0['X-B3-TraceId']}-{hdrs0['X-B3-SpanId']}-0"
            )
        assert t3.finished_spans() == []
        t4 = Tracer()
        with t4.root_from_headers({"b3": single0}, "downstream") as child0:
            assert child0.span.trace_id == trace
            assert not child0.span.sampled
        assert t4.finished_spans() == []


class TestServingTrace:
    def test_predicate_trace_structure_and_debug_route(self):
        """HTTP predicate produces a predicate -> select-node -> solve chain
        joined by one traceId, honoring the caller's b3 trace id."""
        from spark_scheduler_tpu.server.app import build_scheduler_app
        from spark_scheduler_tpu.server.config import InstallConfig
        from spark_scheduler_tpu.server.http import SchedulerHTTPServer
        from spark_scheduler_tpu.server.kube_io import pod_to_k8s
        from spark_scheduler_tpu.store.backend import InMemoryBackend
        from spark_scheduler_tpu.testing.harness import (
            INSTANCE_GROUP_LABEL,
            new_node,
            static_allocation_spark_pods,
        )

        t = set_tracer(Tracer())
        log_stream = io.StringIO()
        set_svc1log(Svc1Logger(stream=log_stream))
        try:
            backend = InMemoryBackend()
            names = []
            for i in range(4):
                n = new_node(f"n{i}")
                backend.add_node(n)
                names.append(n.name)
            app = build_scheduler_app(
                backend,
                InstallConfig(
                    fifo=True,
                    sync_writes=True,
                    instance_group_label=INSTANCE_GROUP_LABEL,
                ),
            )
            server = SchedulerHTTPServer(
                app, host="127.0.0.1", port=0, debug_routes=True
            )
            server.start()
            try:
                pods = static_allocation_spark_pods("trace-app", 2)
                backend.add_pod(pods[0])
                conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
                trace_id = "12" * 16
                conn.request(
                    "POST",
                    "/predicates",
                    body=json.dumps(
                        {"Pod": pod_to_k8s(pods[0]), "NodeNames": names}
                    ).encode(),
                    headers={"X-B3-TraceId": trace_id, "X-B3-SpanId": "ab" * 8},
                )
                resp = json.loads(conn.getresponse().read())
                assert resp["NodeNames"], resp

                conn.request("GET", "/debug/traces")
                spans = json.loads(conn.getresponse().read())["spans"]
                conn.close()
            finally:
                server.stop()
            by_name = {s["name"]: s for s in spans}
            assert {"predicate", "select-node", "solve"} <= set(by_name)
            # one joined trace, continuing the caller's id
            assert {s["traceId"] for s in spans} == {trace_id}
            assert by_name["predicate"]["parentId"] == "ab" * 8
            # A lone driver rides the WINDOW path: select-node (the
            # decision apply) and solve (the decision pull) are siblings
            # under the request's predicate span.
            assert by_name["select-node"]["parentId"] == by_name["predicate"]["id"]
            assert by_name["solve"]["parentId"] == by_name["predicate"]["id"]
            assert by_name["select-node"]["tags"]["outcome"] == "success"
            assert by_name["predicate"]["tags"]["outcome"] == "success"
            assert by_name["solve"]["tags"]["batched"] is True
            # write-back ran under the trace too (sync_writes drains inline)
            assert "write-back" in by_name
            # svc1log carried safe params + trace join
            logs = [json.loads(line) for line in log_stream.getvalue().splitlines()]
            entry = next(e for e in logs if e["message"] == "predicate")
            assert entry["params"]["podName"] == pods[0].name
            assert entry["params"]["outcome"] == "success"
            assert entry["traceId"] == trace_id
        finally:
            set_tracer(Tracer())
            set_svc1log(Svc1Logger())


class TestDebugRouteGating:
    def test_debug_routes_disabled_by_default(self):
        from spark_scheduler_tpu.server.app import build_scheduler_app
        from spark_scheduler_tpu.server.config import InstallConfig
        from spark_scheduler_tpu.server.http import SchedulerHTTPServer
        from spark_scheduler_tpu.store.backend import InMemoryBackend
        from spark_scheduler_tpu.testing.harness import new_node

        backend = InMemoryBackend()
        backend.add_node(new_node("n0"))
        app = build_scheduler_app(backend, InstallConfig(sync_writes=True))
        server = SchedulerHTTPServer(app, host="127.0.0.1", port=0)
        server.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
            for method, path in (
                ("GET", "/debug/traces"),
                ("POST", "/debug/profile/start"),
                ("POST", "/debug/profile/stop"),
            ):
                conn.request(method, path)
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 404, (method, path)
            conn.close()
        finally:
            server.stop()


class TestSafeParams:
    def test_pod_demand_rr_safe_params(self):
        from spark_scheduler_tpu.models.demands import (
            Demand,
            DemandSpec,
            DemandUnit,
        )
        from spark_scheduler_tpu.models.reservations import (
            Reservation,
            ReservationSpec,
            ReservationStatus,
            ResourceReservation,
        )
        from spark_scheduler_tpu.models.resources import Resources
        from spark_scheduler_tpu.testing.harness import static_allocation_spark_pods

        pod = static_allocation_spark_pods("sp-app", 1)[0]
        p = pod_safe_params(pod)
        assert p == {
            "podName": pod.name,
            "podNamespace": pod.namespace,
            "podSparkRole": "driver",
            "podSparkAppID": "sp-app",
        }
        d = Demand(
            name="demand-x",
            namespace="ns",
            spec=DemandSpec(
                units=[DemandUnit(resources=Resources.from_quantities("1", "1Gi"), count=2)],
                instance_group="ig",
            ),
        )
        dp = demand_safe_params(d)
        assert dp["demandUnits"] == [{"count": 2, "cpu": 1000, "memoryKib": 1048576}]
        rr = ResourceReservation(
            name="app",
            namespace="ns",
            spec=ReservationSpec(
                {"driver": Reservation("n1", Resources.from_quantities("1", "1Gi"))}
            ),
            status=ReservationStatus({"driver": "app-driver"}),
        )
        rp = rr_safe_params(rr)
        assert rp["reservationNodes"] == ["n1"]
        assert rp["reservationPodNames"] == ["app-driver"]


class TestJaxProfiler:
    def test_failed_flush_does_not_wedge_profiler(self, tmp_path):
        """stop_trace raising (unwritable dir) must not leave jax's internal
        profile state 'started' — the next capture must work end to end."""
        import jax.numpy as jnp
        import pytest as _pytest

        assert start_jax_profile("/proc/nonexistent-dir/x")
        (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
        with _pytest.raises(Exception):
            stop_jax_profile()
        good = str(tmp_path / "recovered")
        assert start_jax_profile(good), "profiler wedged after failed flush"
        (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
        assert stop_jax_profile() == good

    def test_profile_capture_produces_artifact(self, tmp_path):
        import jax.numpy as jnp

        log_dir = str(tmp_path / "trace")
        assert start_jax_profile(log_dir)
        assert not start_jax_profile(log_dir)  # already running -> False
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        out = stop_jax_profile()
        assert out == log_dir
        assert stop_jax_profile() is None  # idempotent
        # an xplane artifact exists somewhere under the trace dir
        found = [
            f
            for root, _, files in os.walk(log_dir)
            for f in files
            if f.endswith(".xplane.pb") or f.endswith(".trace.json.gz")
        ]
        assert found, f"no trace artifact under {log_dir}"
