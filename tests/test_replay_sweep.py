"""Batched multi-arm sweep equivalence (ISSUE 18).

The correctness bar for replay/sweep.py: a stacked M-arm sweep is a pure
reorganization of M sequential replays — every arm's verdicts, placements,
denials, and mismatch census must be bit-identical to its own sequential
`replay_trace()` under the same config, for strategy arms, prune arms, and
their mixes, at M in {2, 4, 8}. On top of the equivalence pin:

  * sweep determinism — same trace + same grid twice gives identical
    `decision_summary()` documents (wall-clock fields excluded);
  * shared-build accounting — dedup collapses identity-pinned-only
    differences into one stream, stacked dispatches actually happen, and
    every lane boots exactly one roster build / full snapshot (the
    zero-per-arm-rebuild pin CI's sweep smoke leg re-asserts at 10k
    nodes);
  * `what_if()` is a thin 2-arm wrapper over the sweep (its base arm is
    the sweep's stream 0), keeping the ISSUE 17 diff schema intact.
"""

import pytest

from spark_scheduler_tpu.replay import generate, replay_trace, run_sweep
from spark_scheduler_tpu.replay.sweep import grid_arms


@pytest.fixture(scope="module")
def bursty_trace(tmp_path_factory):
    """One small generated bursty trace shared by every sweep test: big
    enough for multi-window pipelining and strategy divergence, small
    enough to replay in seconds per arm."""
    path = str(tmp_path_factory.mktemp("sweep") / "bursty.jsonl")
    generate("bursty", path, seed=11, n_nodes=24, bursts=6)
    return path


def _assert_arm_equiv(arm, rep, seq):
    assert rep.placements == seq.placements, arm
    assert rep.verdict_counts == seq.verdict_counts, arm
    assert rep.denials == seq.denials, arm
    assert rep.decisions == seq.decisions, arm
    assert rep.utilization == seq.utilization, arm
    assert rep.fragmentation == seq.fragmentation, arm
    assert len(rep.mismatches) == len(seq.mismatches), arm


ARM_SETS = {
    2: [
        {},
        {"binpack_algo": "distribute-evenly"},
    ],
    4: [
        {},
        {"binpack_algo": "distribute-evenly"},
        {"binpack_algo": "minimal-fragmentation"},
        {"solver_prune_top_k": 4, "solver_prune_slack": 0.75},
    ],
    8: [
        {},
        {"binpack_algo": "distribute-evenly"},
        {"binpack_algo": "minimal-fragmentation"},
        {"binpack_algo": "single-az-tightly-pack"},
        {"binpack_algo": "single-az-minimal-fragmentation"},
        {"binpack_algo": "az-aware-tightly-pack"},
        {"solver_prune_top_k": 4, "solver_prune_slack": 0.75},
        {
            "binpack_algo": "distribute-evenly",
            "solver_prune_top_k": 4,
            "solver_prune_slack": 0.75,
        },
    ],
}


@pytest.mark.parametrize("m", sorted(ARM_SETS))
def test_sweep_bit_identical_to_sequential_per_arm(bursty_trace, m):
    """The tentpole contract: every arm of an M-arm sweep equals its own
    sequential replay — strategies, prune on/off, and dedup'd duplicates
    alike."""
    arms = ARM_SETS[m]
    sweep = run_sweep(bursty_trace, arms)
    assert len(sweep.reports) == m
    for arm, rep in zip(arms, sweep.reports):
        seq = replay_trace(bursty_trace, overrides=arm or None)
        _assert_arm_equiv(arm, rep, seq)
    # the sweep never had to bail out of lockstep
    assert sweep.telemetry["forced_resolves"] == 0


def test_sweep_accelerate_off_still_bit_identical(bursty_trace):
    """`accelerate=False` opts out of injected certified pruning; decisions
    are the same either way (that's what 'certified' means) but the opt-out
    path must hold the same equivalence bar."""
    arms = ARM_SETS[2]
    sweep = run_sweep(bursty_trace, arms, accelerate=False)
    for arm, rep in zip(arms, sweep.reports):
        _assert_arm_equiv(arm, rep, replay_trace(bursty_trace, overrides=arm or None))
    assert sweep.telemetry["lane_pruned_windows"] == [0, 0]


def test_sweep_determinism(bursty_trace):
    """Same trace + same grid -> identical decision documents (wall-clock
    fields are excluded by decision_summary; everything else must match to
    the byte)."""
    arms = ARM_SETS[4]
    a = run_sweep(bursty_trace, arms).decision_summary()
    b = run_sweep(bursty_trace, arms).decision_summary()
    assert a == b


def test_stream_dedup_and_shared_build_accounting(bursty_trace):
    """Arms differing only in identity-pinned knobs share one decision
    stream; each stream boots exactly one roster build and one full
    snapshot (everything arm-invariant is built once per stream, never per
    arm); compatible windows actually stack."""
    arms = [
        {},
        {"solver_prune_top_k": 4, "solver_prune_slack": 0.75},  # dedup -> 0
        {"binpack_algo": "distribute-evenly"},
        {"binpack_algo": "minimal-fragmentation"},
    ]
    sweep = run_sweep(bursty_trace, arms)
    t = sweep.telemetry
    assert t["arms"] == 4 and t["streams"] == 3 and t["dedup_arms"] == 1
    assert sweep.arms[0]["stream"] == sweep.arms[1]["stream"]
    # one roster build / full snapshot per LANE, zero per extra arm
    assert t["lane_roster_rebuilds"] == [1] * t["streams"]
    assert t["lane_full_snapshots"] == [1] * t["streams"]
    assert t["stacked_dispatches"] > 0
    assert t["stacked_arm_windows"] >= 2 * t["stacked_dispatches"]
    assert t["windows"] == t["stacked_arm_windows"] + t["lane_fallbacks"]
    # dedup'd arms still get independent (deep-copied) reports
    assert sweep.reports[0] is not sweep.reports[1]
    assert sweep.reports[0].placements == sweep.reports[1].placements


def test_sweep_report_shapes(bursty_trace):
    """summary() / markdown() are the CLI's output surface — keep them
    well-formed (one row per arm, telemetry tail present)."""
    sweep = run_sweep(bursty_trace, ARM_SETS[2])
    s = sweep.summary()
    assert [a["name"] for a in s["arms"]] == [
        "base",
        "binpack_algo=distribute-evenly",
    ]
    assert all("report" in a for a in s["arms"])
    assert s["telemetry"]["arms"] == 2
    md = sweep.markdown()
    assert md.count("\n") >= 3 and "| arm |" in md
    assert "stacked dispatches" in md


def test_grid_arms_cartesian():
    arms = grid_arms(
        {"binpack-algo": ["a", "b"], "solver_prune_top_k": [0, 64]},
        base={"fifo": True},
    )
    assert len(arms) == 4
    assert all(a["fifo"] is True for a in arms)
    assert {(a["binpack_algo"], a["solver_prune_top_k"]) for a in arms} == {
        ("a", 0), ("a", 64), ("b", 0), ("b", 64)
    }


def test_what_if_is_a_two_arm_sweep(bursty_trace, monkeypatch):
    """what_if() delegates to run_sweep with exactly [base, variant] and
    keeps the ISSUE 17 schema."""
    from spark_scheduler_tpu.replay import engine as engine_mod
    from spark_scheduler_tpu.replay import sweep as sweep_mod

    seen = {}
    real = sweep_mod.run_sweep

    def spy(trace, arms, **kw):
        seen["arms"] = list(arms)
        return real(trace, arms, **kw)

    monkeypatch.setattr(sweep_mod, "run_sweep", spy)
    diff = engine_mod.what_if(
        bursty_trace, {"binpack-algo": "distribute-evenly"}
    )
    assert seen["arms"][0] == {}
    assert seen["arms"][1] == {"binpack-algo": "distribute-evenly"}
    for key in (
        "trace", "overrides", "decisions", "verdicts", "denials",
        "placements", "latency_ms", "utilization", "fragmentation",
        "overcommit", "base_mismatches",
    ):
        assert key in diff, key
    assert diff["decisions"]["base"] == diff["decisions"]["variant"]


def test_device_pool_and_autoscaler_grid_shares_one_stream(bursty_trace):
    """ISSUE 19 satellite: `solver.device-pool` and the autoscaler policy
    knobs are sweepable `grid_arms` fields — identity-pinned (pooling
    moves wall time, never decision bytes, per the multi-device parity
    suites; replay forces the autoscaler off), so a pool x idle-ttl grid
    collapses to ONE decision stream, the topology knobs are neutralized
    inside the lane (no pooled solver is built for a sweep), and every
    arm's report is bit-identical to a sequential replay."""
    arms = grid_arms(
        {
            "solver-device-pool": [1, 2],
            "autoscaler-idle-ttl-s": [60.0, 300.0],
        }
    )
    assert len(arms) == 4
    sweep = run_sweep(bursty_trace, arms)
    t = sweep.telemetry
    assert t["arms"] == 4 and t["streams"] == 1 and t["dedup_arms"] == 3
    assert len({a["stream"] for a in sweep.arms}) == 1
    # one roster build / one full snapshot TOTAL: the whole grid rode one
    # lane
    assert t["lane_roster_rebuilds"] == [1] and t["lane_full_snapshots"] == [1]
    seq = replay_trace(bursty_trace)
    for arm, rep in zip(sweep.arms, sweep.reports):
        _assert_arm_equiv(arm, rep, seq)
