"""Byte-identity pin (ISSUE 16 acceptance): with the policy subsystem
DISABLED — and equally with it enabled but configured to the reference
semantics (ordering=fifo, preemption/defrag off) — the scheduler produces
byte-identical decisions and reservations to the pre-policy FIFO path, on
both the solo predicate and the coalesced window. This is the default-off
guarantee the plug-board promises; CI runs this file as the identity leg."""

import copy
import json

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.server.conversion import rr_v1beta2_to_wire
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    static_allocation_spark_pods,
)


class ManualClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        self.t += 0.25  # deterministic monotone ticks
        return self.t


def _scenario_pods():
    """One fixed workload, pods stamped with deterministic timestamps so
    every run sees identical inputs regardless of the module-level pod
    counter."""
    groups = {
        "solo-a": static_allocation_spark_pods("solo-a", 2),
        "solo-big": static_allocation_spark_pods("solo-big", 20),  # no fit
        "solo-dyn": dynamic_allocation_spark_pods("solo-dyn", 2, 4),
        "win-a": static_allocation_spark_pods("win-a", 3),
        "win-b": static_allocation_spark_pods("win-b", 2),
        "win-big": static_allocation_spark_pods("win-big", 30),  # no fit
    }
    for i, (_, pods) in enumerate(sorted(groups.items())):
        for p in pods:
            p.creation_timestamp = 100.0 + i
    return groups


def _res_key(res):
    return (
        res.outcome,
        tuple(res.node_names),
        tuple(sorted(res.failed_nodes.items())),
    )


def _run(scenario, **kw):
    g = copy.deepcopy(scenario)
    h = Harness(clock=ManualClock(), resync_gap_seconds=1e12, **kw)
    h.add_nodes(
        new_node("n1", zone="zone1"),
        new_node("n2", zone="zone1"),
        new_node("n3", zone="zone2"),
        new_node("n4", zone="zone2"),
    )
    names = ["n1", "n2", "n3", "n4"]
    transcript = []

    def note(pod, res):
        transcript.append((pod.name, _res_key(res)))

    # Solo path: sequential gangs, including a fit denial mid-stream.
    for app in ("solo-a", "solo-big", "solo-dyn"):
        for p in g[app]:
            note(p, h.schedule(p, names))
        if app == "solo-big":
            # Retire the unfittable gang, else it FIFO-blocks (identically
            # in both runs, but leaving nothing downstream to compare).
            for p in g[app]:
                h.delete_pod(p)
    # Windowed path: one coalesced driver window, then the executors.
    drivers = [g["win-a"][0], g["win-b"][0], g["win-big"][0]]
    h.add_pods(*drivers)
    t = h.app.extender.predicate_window_dispatch(
        [ExtenderArgs(pod=p, node_names=names) for p in drivers]
    )
    for p, res in zip(drivers, h.app.extender.predicate_window_complete(t)):
        note(p, res)
        if res.ok:
            h.backend.bind_pod(p, res.node_names[0])
    for app in ("win-a", "win-b"):
        for p in g[app][1:]:
            note(p, h.schedule(p, names))

    wires = sorted(
        json.dumps(rr_v1beta2_to_wire(rr), sort_keys=True)
        for rr in h.app.rr_cache.list()
    )
    policy = h.app.extender._policy
    h.app.stop()
    return transcript, wires, policy


def test_policy_disabled_and_neutral_config_are_byte_identical():
    scenario = _scenario_pods()
    base_t, base_w, base_p = _run(scenario)
    assert base_p is None  # reference path: no engine constructed
    # Enabled-but-neutral: the engine is live yet must not perturb a bit.
    neut_t, neut_w, neut_p = _run(
        scenario,
        policy_enabled=True,
        policy_ordering="fifo",
        policy_preemption=False,
        policy_defrag=False,
    )
    assert neut_p is not None and neut_p.preemption is None
    assert neut_t == base_t
    assert neut_w == base_w
    # Sanity: the scenario actually exercised both admits and denials.
    outcomes = {k[0] for _, k in base_t}
    assert "success" in outcomes and "failure-fit" in outcomes
    assert len(base_w) >= 4


def test_policy_disabled_sequential_fallback_identical():
    """Same pin on the sequential (non-batched) admission branch."""
    scenario = _scenario_pods()
    base_t, base_w, _ = _run(scenario, batched_admission=False)
    neut_t, neut_w, neut_p = _run(
        scenario, batched_admission=False, policy_enabled=True
    )
    assert neut_p is not None
    assert neut_t == base_t
    assert neut_w == base_w
