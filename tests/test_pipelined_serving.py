"""Pipelined windowed serving: dispatching window k+1 BEFORE completing
window k must change nothing about the decisions.

The pipeline's correctness rests on three mechanisms, each pinned here:
  - the device-side committed-base thread + additive external deltas
    (solver.build_tensors_pipelined): an overlapped dispatch sees exactly
    the availability a serialized server would have shown it;
  - the in-flight app set (extender): an app whose admission is still in
    flight is deferred to its own window's post-apply solo loop, where the
    idempotent-retry branch answers;
  - mirror self-correction: a gang the kernel admitted but whose
    reservation the host failed to create is restored to the device
    automatically by the next delta.

Nodes are the harness standard 8 CPU / 8 GiB / 1 GPU
(extender_test_utils.go:225-257); static-allocation apps cost
(1 + num_executors) CPU / GiB.
"""

import threading

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.core.solver import PipelineDrainRequired
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)

NS = "namespace"


def _mk_harness(n_nodes=12, fifo=True):
    h = Harness(binpack_algo="tightly-pack", fifo=fifo)
    h.add_nodes(*[new_node(f"n{i}", zone=f"zone{i % 2}") for i in range(n_nodes)])
    return h, [f"n{i}" for i in range(n_nodes)]


def _driver_args(h, app_id, execs, node_names):
    driver = static_allocation_spark_pods(app_id, execs)[0]
    h.add_pods(driver)
    return driver, ExtenderArgs(pod=driver, node_names=list(node_names))


def _assert_reservations_consistent(
    h, *, expected_apps, slots_per_app, node_names, placed=None
):
    """Shared end-state invariants for the HTTP workload tests: one
    reservation per app with every slot filled and bound, bound nodes match
    the reserved slots (when the test recorded `placed`: pod name -> node),
    and no node's reserved CPU/memory exceeds the 8 CPU / 8 GiB harness
    node."""
    rrs = h.backend.list("resourcereservations")
    assert len(rrs) == expected_apps, [rr.name for rr in rrs]
    usage: dict[str, list[int]] = {}
    valid_nodes = set(node_names)
    for rr in rrs:
        assert len(rr.spec.reservations) == slots_per_app, rr.name
        bound = rr.status.pods if rr.status else {}
        assert len(bound) == slots_per_app, (rr.name, bound)
        for slot_name, slot in rr.spec.reservations.items():
            assert slot.node in valid_nodes, (rr.name, slot_name, slot.node)
            if placed is not None:
                pod_name = bound.get(slot_name)
                assert pod_name in placed, (rr.name, slot_name, pod_name)
                assert placed[pod_name] == slot.node, (
                    "pod bound off its reserved slot",
                    rr.name, slot_name, pod_name, placed[pod_name], slot.node,
                )
            u = usage.setdefault(slot.node, [0, 0])
            u[0] += slot.resources.cpu_milli
            u[1] += slot.resources.mem_kib
    for node, (cpu, kib) in usage.items():
        assert cpu <= 8000 and kib <= 8 * 1024 * 1024, (node, cpu, kib)


def test_pipelined_windows_match_serialized_decisions():
    """Dispatch w2 while w1 is un-fetched; the combined decisions must equal
    a serialized server's (same stream, complete-before-dispatch)."""
    streams = []
    for mode in ("pipelined", "serial"):
        h, node_names = _mk_harness()
        ext = h.extender
        w1 = [_driver_args(h, f"app-a{i}", 3, node_names) for i in range(3)]
        w2 = [_driver_args(h, f"app-b{i}", 3, node_names) for i in range(3)]
        t1 = ext.predicate_window_dispatch([a for _, a in w1])
        if mode == "pipelined":
            # Overlap: w2 dispatched before w1 is completed.
            t2 = ext.predicate_window_dispatch([a for _, a in w2])
            r1 = ext.predicate_window_complete(t1)
            r2 = ext.predicate_window_complete(t2)
        else:
            r1 = ext.predicate_window_complete(t1)
            t2 = ext.predicate_window_dispatch([a for _, a in w2])
            r2 = ext.predicate_window_complete(t2)
        placements = [res.node_names for res in r1 + r2]
        outcomes = [res.outcome for res in r1 + r2]
        streams.append((placements, outcomes))
        for (pod, _), res in zip(w1 + w2, r1 + r2):
            assert res.node_names, (mode, pod.name, res)
    assert streams[0] == streams[1]


def test_pipelined_capacity_is_threaded_not_double_booked():
    """Two overlapped windows on a cluster that fits exactly one window's
    gangs: the second window must see the first's (un-applied) admissions
    via the device-side thread and reject."""
    # 2 nodes x 8 CPU; one 7-executor app = 8 CPU = one full node.
    h, node_names = _mk_harness(n_nodes=2, fifo=False)
    ext = h.extender
    w1 = [_driver_args(h, f"fit-{i}", 7, node_names) for i in range(2)]
    w2 = [_driver_args(h, f"over-{i}", 7, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w1])
    t2 = ext.predicate_window_dispatch([a for _, a in w2])
    r1 = ext.predicate_window_complete(t1)
    r2 = ext.predicate_window_complete(t2)
    assert all(res.node_names for res in r1), r1
    assert not any(res.node_names for res in r2), (
        "second window double-booked capacity the first window's in-flight "
        f"admissions already hold: {r2}"
    )


def test_inflight_app_defers_to_idempotent_retry():
    """The same app submitted in two overlapped windows: the second request
    must not be re-admitted by the kernel — it resolves after the first
    window applies, to the SAME node."""
    h, node_names = _mk_harness()
    ext = h.extender
    driver, args = _driver_args(h, "dup-app", 3, node_names)
    _, oargs1 = _driver_args(h, "other-1", 3, node_names)
    _, oargs2 = _driver_args(h, "other-2", 3, node_names)
    t1 = ext.predicate_window_dispatch([args, oargs1])
    # window 2 carries a duplicate of dup-app while window 1 is in flight
    dup_args = ExtenderArgs(pod=driver, node_names=list(node_names))
    t2 = ext.predicate_window_dispatch([dup_args, oargs2])
    assert (NS, "dup-app") in ext._inflight_apps
    r1 = ext.predicate_window_complete(t1)
    assert (NS, "dup-app") not in ext._inflight_apps
    r2 = ext.predicate_window_complete(t2)
    assert r1[0].node_names and r2[0].node_names
    assert r1[0].node_names == r2[0].node_names, "idempotent retry diverged"
    # only ONE reservation exists for the app
    assert ext._rrm.get_resource_reservation("dup-app", NS) is not None
    rrs = h.backend.list("resourcereservations")
    assert sum(1 for rr in rrs if rr.name == "dup-app") == 1


def test_reservation_failure_restores_device_capacity():
    """A gang admitted by the kernel whose reservation the host fails to
    create must get its capacity back on device at the next window (mirror
    self-correction), so a later app can use it."""
    # 1 node x 8 CPU: one 7-executor app fills it.
    h, node_names = _mk_harness(n_nodes=1, fifo=False)
    ext = h.extender
    rrm = ext._rrm
    from spark_scheduler_tpu.core.reservation_manager import ReservationError

    orig_create = rrm.create_reservations

    def flaky_create(pod, res, driver_node, exec_nodes):
        if pod.labels["spark-app-id"].startswith("fail"):
            raise ReservationError("injected write failure")
        return orig_create(pod, res, driver_node, exec_nodes)

    rrm.create_reservations = flaky_create
    wf = [_driver_args(h, f"fail-{i}", 7, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in wf])
    r1 = ext.predicate_window_complete(t1)
    # fail-0: kernel admitted, reservation write failed (internal error);
    # fail-1: no capacity left behind fail-0's in-window admission.
    assert all(not res.node_names for res in r1), r1

    # Next window: the failed gang's capacity must be back (device restored
    # by the mirror delta), so a fresh app fits.
    _, okargs = _driver_args(h, "recover", 7, node_names)
    _, okargs_b = _driver_args(h, "recover-b", 7, node_names)
    t2 = ext.predicate_window_dispatch([okargs, okargs_b])
    r2 = ext.predicate_window_complete(t2)
    assert r2[0].node_names, (
        f"capacity lost after reservation-write failure: {r2}"
    )
    assert not r2[1].node_names  # the node holds exactly one gang


def test_node_add_mid_flight_rides_the_static_delta():
    """Adding one node while a window is un-fetched no longer drains the
    pipeline (ISSUE 11): the changed static rows ship as a row-scatter
    delta, the in-flight window completes on its dispatch-time view, and
    the next dispatch sees the new node."""
    h, node_names = _mk_harness(n_nodes=4)
    ext = h.extender
    solver = h.app.solver
    w1 = [_driver_args(h, f"dr-{i}", 2, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w1])
    assert t1.handle is not None
    h.add_nodes(new_node("late-node", zone="zone0"))
    w2 = [
        _driver_args(h, f"dr2-{i}", 2, node_names + ["late-node"])
        for i in range(2)
    ]
    before = solver.device_state_stats["static_delta_uploads"]
    t2 = ext.predicate_window_dispatch([a for _, a in w2])
    assert solver.device_state_stats["static_delta_uploads"] > before
    r1 = ext.predicate_window_complete(t1)
    assert all(res.node_names for res in r1)
    r2 = ext.predicate_window_complete(t2)
    assert all(res.node_names for res in r2)
    # The new node is genuinely live on the resident state: fill it.
    _, late_args = _driver_args(h, "on-late", 7, ["late-node"])
    t3 = ext.predicate_window_dispatch([late_args])
    r3 = ext.predicate_window_complete(t3)
    assert r3[0].node_names == ["late-node"]


def test_topology_change_mid_flight_raises_drain_when_not_deltable():
    """A topology change the delta protocol cannot express — here the pad
    bucket growing, which changes every resident shape (and, with
    delta-statics disabled, ANY statics change) — still raises
    PipelineDrainRequired while a window is in flight; after completing
    the pending window the dispatch succeeds and sees the new nodes."""
    h, node_names = _mk_harness(n_nodes=4)
    ext = h.extender
    w1 = [_driver_args(h, f"dr-{i}", 2, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w1])
    assert t1.handle is not None
    # Cross the pad bucket (8): registry grows 4 -> 9 rows, shapes change.
    late = [new_node(f"late-{j}", zone="zone0") for j in range(5)]
    h.add_nodes(*late)
    w2 = [
        _driver_args(
            h, f"dr2-{i}", 2, node_names + [n.name for n in late]
        )
        for i in range(2)
    ]
    try:
        ext.predicate_window_dispatch([a for _, a in w2])
        raised = False
    except PipelineDrainRequired:
        raised = True
    assert raised
    r1 = ext.predicate_window_complete(t1)
    assert all(res.node_names for res in r1)
    t2 = ext.predicate_window_dispatch([a for _, a in w2])
    r2 = ext.predicate_window_complete(t2)
    assert all(res.node_names for res in r2)


def test_statics_change_mid_flight_drains_with_delta_statics_off():
    """solver.delta-statics=false restores the pre-ISSUE-11 contract:
    every mid-flight statics change drains."""
    h, node_names = _mk_harness(n_nodes=4)
    ext = h.extender
    h.app.solver._delta_statics = False
    w1 = [_driver_args(h, f"dr-{i}", 2, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w1])
    h.add_nodes(new_node("late-node", zone="zone0"))
    w2 = [
        _driver_args(h, f"dr2-{i}", 2, node_names + ["late-node"])
        for i in range(2)
    ]
    try:
        ext.predicate_window_dispatch([a for _, a in w2])
        raised = False
    except PipelineDrainRequired:
        raised = True
    assert raised
    r1 = ext.predicate_window_complete(t1)
    assert all(res.node_names for res in r1)
    t2 = ext.predicate_window_dispatch([a for _, a in w2])
    r2 = ext.predicate_window_complete(t2)
    assert all(res.node_names for res in r2)


def test_solo_solve_sees_inflight_window_gangs():
    """A solo predicate served while windows are in flight uses the
    pipelined device base, so it cannot take capacity an in-flight window's
    gang already holds."""
    h, node_names = _mk_harness(n_nodes=1, fifo=False)
    ext = h.extender
    # Window fills the node (7 execs + driver = 8 CPU).
    w = [_driver_args(h, f"w-{i}", 7, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w])
    # Solo request while the window is un-fetched: must see the in-flight
    # gang and reject.
    _, solo_args = _driver_args(h, "solo-late", 3, node_names)
    solo_res = ext.predicate(solo_args)
    assert not solo_res.node_names, solo_res
    r1 = ext.predicate_window_complete(t1)
    assert r1[0].node_names


def test_capacity_epoch_resolves_stale_window():
    """When a solo admission bypasses the pipelined view (topology-change
    fallback to a host-truth build), the epoch bump makes the in-flight
    window discard its stale decisions and re-solve — no double-booking."""
    h, node_names = _mk_harness(n_nodes=1, fifo=False)
    ext = h.extender
    solver = ext._solver
    w = [_driver_args(h, f"stale-{i}", 7, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w])

    # Simulate the drain-fallback: the solo solve builds from HOST truth
    # (blind to the in-flight gang) and admits onto the same node.
    orig_build = solver.build_tensors_pipelined

    def blind_build(nodes, usage, overhead, topo_version=None, **_kw):
        return solver.build_tensors(nodes, usage, overhead)

    solver.build_tensors_pipelined = blind_build
    try:
        _, solo_args = _driver_args(h, "solo-blind", 7, node_names)
        solo_res = ext.predicate(solo_args)
        assert solo_res.node_names, solo_res  # blind solve admits
    finally:
        solver.build_tensors_pipelined = orig_build

    # The window's stale decision (stale-0 admitted on the now-taken node)
    # must be discarded and re-solved: both window apps now fail.
    r1 = ext.predicate_window_complete(t1)
    assert not any(res.node_names for res in r1), (
        f"stale window decisions were applied despite the epoch change: {r1}"
    )
    # Exactly one reservation (the solo app) — node not oversubscribed.
    rrs = h.backend.list("resourcereservations")
    assert len(rrs) == 1 and rrs[0].name == "solo-blind"


def test_epoch_mismatch_resolve_invalidates_later_inflight_windows():
    """The discard/re-solve of an epoch-stale window is ITSELF a capacity
    change (advisor r3, high): the re-solve may move the window's gangs off
    the placements a LATER in-flight window's device base threads. That
    later window must also re-solve — applying its device decisions would
    double-book the moved gangs' nodes.

    Interleave (two 8-CPU nodes): window B (two 2-CPU gangs, both land on
    n0); solo app (6 CPU -> n1) bumps the epoch; window C dispatched
    against B-original + solo (sees n0=4, n1=6 used); B completes, detects
    the stale epoch, re-solves from host truth — now one of B's gangs
    prefers n1 (2 free next to the solo app) and MOVES; C completes. Before
    the fix C applied its device decisions (computed against B-original)
    and n1 ended 10/8 oversubscribed."""
    h, node_names = _mk_harness(n_nodes=2, fifo=False)
    ext = h.extender
    solver = ext._solver

    w_b = [_driver_args(h, f"b-{i}", 1, node_names) for i in range(2)]  # 2 CPU each
    t_b = ext.predicate_window_dispatch([a for _, a in w_b])
    assert t_b.handle is not None

    # Solo admission while B is in flight: 6 CPU only fits n1 (n0 would
    # have 4 free in the pipelined view but 6 > 4... actually n0 has
    # 8-4=4 free -> must go n1). Bumps the capacity epoch.
    _, solo_args = _driver_args(h, "solo-mid", 5, node_names)  # 6 CPU
    solo_res = ext.predicate(solo_args)
    assert solo_res.node_names, solo_res
    epoch_after_solo = ext._capacity_epoch

    # Window C dispatched at the post-solo epoch, device base threading
    # B's ORIGINAL placements.
    w_c = [
        _driver_args(h, "c-0", 3, node_names),  # 4 CPU
        _driver_args(h, "c-1", 1, node_names),  # 2 CPU
    ]
    t_c = ext.predicate_window_dispatch([a for _, a in w_c])
    assert t_c.epoch == epoch_after_solo

    # B completes: stale epoch -> discard + re-solve. The discard must
    # bump the epoch again so C re-solves too.
    r_b = ext.predicate_window_complete(t_b)
    assert ext._capacity_epoch > epoch_after_solo, (
        "discard/re-solve did not invalidate later in-flight windows"
    )
    r_c = ext.predicate_window_complete(t_c)

    # Accounting invariant: whatever got admitted, no node exceeds 8 CPU.
    usage: dict[str, int] = {}
    for rr in h.backend.list("resourcereservations"):
        for slot in rr.spec.reservations.values():
            usage[slot.node] = usage.get(slot.node, 0) + slot.resources.cpu_milli
    assert all(v <= 8000 for v in usage.values()), usage
    # Everything fits serially (2+2+6+4+2 = 16 = cluster), so a correct
    # re-solve chain admits all of it.
    for res in list(r_b) + list(r_c):
        assert res.node_names, (usage, r_b, r_c)


def test_fetch_failure_applies_surviving_windows_before_redispatch():
    """After window k's fetch fails (pipeline dropped), still-in-flight
    window k+1 must be applied before a new dispatch builds from the host
    view, or the new window would double-book k+1's capacity."""
    h, node_names = _mk_harness(n_nodes=2, fifo=False)
    ext = h.extender
    w1 = [_driver_args(h, f"k-{i}", 7, node_names) for i in range(2)]
    w2 = [_driver_args(h, f"k1-{i}", 7, node_names) for i in range(2)]
    t1 = ext.predicate_window_dispatch([a for _, a in w1])
    t2 = ext.predicate_window_dispatch([a for _, a in w2])

    class _Boom:
        def result(self):
            raise ConnectionError("injected")

    t1.handle.blob_future = _Boom()
    try:
        ext.predicate_window_complete(t1)
    except ConnectionError:
        pass
    assert ext._solver._pipe is None
    # Batcher contract: complete the surviving window BEFORE dispatching new.
    r2 = ext.predicate_window_complete(t2)
    # k admitted both gangs device-side; k's fetch failed so ITS gangs are
    # lost, but k+1's decisions were solved against a base that included
    # k's gangs -> k+1 saw no room and rejects. Crucially its apply ran
    # before the next dispatch.
    # Now a fresh window builds from host truth (k lost, k+1 applied):
    _, a1 = _driver_args(h, "fresh-0", 7, node_names)
    _, a2 = _driver_args(h, "fresh-1", 7, node_names)
    t3 = ext.predicate_window_dispatch([a1, a2])
    r3 = ext.predicate_window_complete(t3)
    # Accounting: reservations on any node never exceed 8 CPU.
    usage: dict[str, int] = {}
    for rr in h.backend.list("resourcereservations"):
        for slot in rr.spec.reservations.values():
            usage[slot.node] = usage.get(slot.node, 0) + slot.resources.cpu_milli
    assert all(v <= 8000 for v in usage.values()), usage


def test_fetch_failure_resets_pipeline_to_host_truth():
    """A failed decision fetch must not leak the window's gangs: the
    pipeline resets and the next build re-uploads from the host view, so
    the never-reserved capacity is usable again. Without a degraded-mode
    controller the slot-fatal failure PROPAGATES (pre-ISSUE-9 contract,
    still the behavior for bare solvers)."""
    h, node_names = _mk_harness(n_nodes=1, fifo=False)
    ext = h.extender
    ext._solver.degraded = None  # bare solver: no degraded policy wired
    _, args = _driver_args(h, "lost", 7, node_names)
    _, args_b = _driver_args(h, "lost-b", 7, node_names)
    t1 = ext.predicate_window_dispatch([args, args_b])

    class _Boom:
        def result(self):
            raise ConnectionError("injected transfer failure")

    t1.handle.blob_future = _Boom()
    try:
        ext.predicate_window_complete(t1)
        raised = False
    except ConnectionError:
        raised = True
    assert raised
    assert ext._solver._pipe is None  # pipeline dropped
    assert not ext._inflight_apps  # in-flight cleared despite the failure

    # Capacity was never reserved; a fresh window must be able to use it.
    _, okargs = _driver_args(h, "after-loss", 7, node_names)
    _, okargs_b = _driver_args(h, "after-loss-b", 7, node_names)
    t2 = ext.predicate_window_dispatch([okargs, okargs_b])
    r2 = ext.predicate_window_complete(t2)
    assert r2[0].node_names, r2


def test_fetch_failure_with_degraded_policy_serves_window_via_fallback():
    """ISSUE 9: with the degraded controller wired (the app default), a
    slot-fatal fetch failure no longer loses the window — its decisions
    re-solve exactly on the host greedy fallback (nothing was applied
    anywhere yet), the pipeline still resets to host truth, and the next
    healthy device window clears degraded."""
    h, node_names = _mk_harness(n_nodes=1, fifo=False)
    ext = h.extender
    assert ext._solver.degraded is not None  # wired by build_scheduler_app
    _, args = _driver_args(h, "kept", 7, node_names)
    _, args_b = _driver_args(h, "kept-b", 7, node_names)
    t1 = ext.predicate_window_dispatch([args, args_b])

    class _Boom:
        def result(self):
            raise ConnectionError("injected transfer failure")

    t1.handle.blob_future = _Boom()
    r1 = ext.predicate_window_complete(t1)
    assert r1[0].node_names, r1  # the window SERVED (host fallback)
    assert ext._solver._pipe is None  # pipeline still dropped
    snap = ext._solver.degraded.snapshot()
    assert snap["active"] and snap["fallback_decisions"] > 0

    # The fallback-served gang's reservation is REAL: a fresh 7-cpu
    # driver no longer fits the 8-cpu node (the capacity is genuinely
    # held, not leaked). The window still solves on the device, which
    # clears the degraded flag.
    _, okargs = _driver_args(h, "after", 7, node_names)
    t2 = ext.predicate_window_dispatch([okargs])
    r2 = ext.predicate_window_complete(t2)
    assert r2[0].outcome == "failure-fit", r2
    assert not ext._solver.degraded.active


def test_batcher_completes_solo_ticket_before_next_window():
    """A pending ticket with no dispatched solve (lone request -> solo path)
    must be completed BEFORE the next window dispatches: its reservation
    has to be visible to the window's solve (review finding: solo-path
    admissions were not pipeline-guarded)."""
    import queue as _q

    from spark_scheduler_tpu.server.http import PredicateBatcher

    events = []
    release_solo = threading.Event()

    from types import SimpleNamespace

    class StubTicket:
        def __init__(self, tag, handle):
            self.tag = tag
            self.handle = handle
            self.sync = handle is None

    class StubExtender:
        def predicate_window_dispatch(self, args_list):
            tag = args_list[0]
            handle = (
                SimpleNamespace(blob_future=None) if len(args_list) > 1 else None
            )
            events.append(("dispatch", tag, handle is not None))
            return StubTicket(tag, handle)

        def predicate_window_complete(self, ticket):
            if ticket.sync:
                release_solo.wait(5)
            events.append(("complete", ticket.tag, ticket.handle is not None))
            return ["ok"] * (1 if ticket.sync else 2)

    b = PredicateBatcher(StubExtender(), max_window=4, hold_ms=0)
    results = _q.Queue()

    def submit(tag):
        results.put(b.submit(tag))

    t_solo = threading.Thread(target=submit, args=("solo",))
    t_solo.start()
    # Give the dispatcher time to claim the solo request as a sync ticket.
    import time as _time

    _time.sleep(0.15)
    t_w1 = threading.Thread(target=submit, args=("w",))
    t_w2 = threading.Thread(target=submit, args=("w",))
    t_w1.start(), t_w2.start()
    _time.sleep(0.15)
    release_solo.set()
    for t in (t_solo, t_w1, t_w2):
        t.join(10)
    b.stop()
    # The solo ticket's COMPLETE must precede the window's DISPATCH.
    solo_done = events.index(("complete", "solo", False))
    win_disp = next(
        i for i, e in enumerate(events) if e[0] == "dispatch" and e[2]
    )
    assert solo_done < win_disp, events


import pytest


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_http_mixed_driver_executor_workload(transport):
    """Drivers and executors of MANY apps interleave through the HTTP
    batcher: each app's executors go in right after its driver binds, while
    OTHER apps' driver windows are still in flight — mixed batches hit the
    window path and the post-apply executor ladder together. Every gang
    must end fully bound ON ITS RESERVED NODES with no node
    oversubscribed. (An executor cannot race its OWN driver's un-applied
    admission here: driver responses only return after the window applies,
    matching kube-scheduler's ordering.)"""
    import http.client
    import json as _json

    from spark_scheduler_tpu.server.kube_io import pod_to_k8s
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer

    h, node_names = _mk_harness(n_nodes=24)
    server = SchedulerHTTPServer(
        h.app, host="127.0.0.1", port=0, request_timeout_s=120.0,
        transport=transport,
    )
    server.start()
    n_apps, execs_per_app = 6, 3
    errs: list = []
    placed: dict[str, str] = {}
    lock = threading.Lock()

    def run_app(ai):
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=120
            )
            pods = static_allocation_spark_pods(f"mix-{ai}", execs_per_app)
            for pod in pods:  # driver first, then its executors
                h.backend.add_pod(pod)
                conn.request(
                    "POST", "/predicates",
                    body=_json.dumps(
                        {"Pod": pod_to_k8s(pod), "NodeNames": node_names}
                    ).encode(),
                )
                resp = _json.loads(conn.getresponse().read())
                if not resp.get("NodeNames"):
                    raise RuntimeError(f"{pod.name}: {resp}")
                h.backend.bind_pod(pod, resp["NodeNames"][0])
                with lock:
                    placed[pod.name] = resp["NodeNames"][0]
            conn.close()
        except Exception as exc:  # surfaced after join
            errs.append(exc)

    threads = [
        threading.Thread(target=run_app, args=(ai,)) for ai in range(n_apps)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        if errs:
            raise errs[0]
        _assert_reservations_consistent(
            h,
            expected_apps=n_apps,
            slots_per_app=1 + execs_per_app,
            node_names=node_names,
            placed=placed,
        )
    finally:
        server.stop()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_http_pipelined_soak_consistent_reservations(transport):
    """Concurrent clients through the REAL HTTP server: every request lands
    and the final reservation state is consistent (each app exactly one
    reservation, executor slots on real nodes, no node over capacity)."""
    import http.client
    import json as _json

    from spark_scheduler_tpu.server.kube_io import pod_to_k8s
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer

    h, node_names = _mk_harness(n_nodes=40)
    server = SchedulerHTTPServer(
        h.app, host="127.0.0.1", port=0, request_timeout_s=120.0,
        transport=transport,
    )
    server.start()
    n_clients, rounds = 8, 5
    errs: list = []
    placed: dict[str, str] = {}
    lock = threading.Lock()

    def client(ci):
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=120
            )
            for r in range(rounds):
                driver = static_allocation_spark_pods(f"soak-{ci}-{r}", 2)[0]
                h.backend.add_pod(driver)
                body = _json.dumps(
                    {"Pod": pod_to_k8s(driver), "NodeNames": node_names}
                ).encode()
                conn.request("POST", "/predicates", body=body)
                resp = _json.loads(conn.getresponse().read())
                if not resp.get("NodeNames"):
                    raise RuntimeError(f"{ci}-{r}: {resp}")
                h.backend.bind_pod(driver, resp["NodeNames"][0])
                with lock:
                    placed[driver.name] = resp["NodeNames"][0]
            conn.close()
        except Exception as exc:  # surfaced after join
            errs.append(exc)

    threads = [
        threading.Thread(target=client, args=(ci,)) for ci in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        if errs:
            raise errs[0]
        assert len(placed) == n_clients * rounds
        # Drivers only in this soak: 1 driver slot bound per reservation;
        # the 2 executor slots exist but stay unbound (no executor pods
        # were submitted), so assert the shape directly + shared node
        # accounting.
        rrs = h.backend.list("resourcereservations")
        assert len(rrs) == n_clients * rounds
        usage: dict[str, list[int]] = {}
        valid_nodes = set(node_names)
        for rr in rrs:
            for slot in rr.spec.reservations.values():
                assert slot.node in valid_nodes
                u = usage.setdefault(slot.node, [0, 0])
                u[0] += slot.resources.cpu_milli
                u[1] += slot.resources.mem_kib
        for node, (cpu, kib) in usage.items():
            assert cpu <= 8000 and kib <= 8 * 1024 * 1024, (node, cpu, kib)
    finally:
        server.stop()
