"""Async event-loop transport: backpressure and framing edges the
threaded stack never had to express — max-connections 503 load shedding,
max-body-bytes 413 with keep-alive surviving, pipelined request framing
with in-order responses, batcher-queue-depth shedding, and the
foundry.spark.scheduler.server.* telemetry surface.

The load-shed smoke is the tier-1 guard for the ceiling-lift PR: saturate
past max-connections on CPU, assert the excess got clean 503s and ZERO
sockets hang.
"""

import json
import socket
import urllib.request

import pytest

from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import SchedulerHTTPServer
from spark_scheduler_tpu.store.backend import InMemoryBackend
from spark_scheduler_tpu.testing.harness import new_node


def _make_server(transport="async", **kw):
    backend = InMemoryBackend()
    backend.add_node(new_node("n0"))
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(sync_writes=True),
        metrics=SchedulerMetrics(registry, "instance-group"),
    )
    srv = SchedulerHTTPServer(
        app, registry, port=0, transport=transport, **kw
    )
    srv.start()
    return srv


def _read_response(sock, timeout=5.0):
    """Read exactly ONE response (headers + Content-Length body) so
    keep-alive reuse never races a partial read."""
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            return buf
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":", 1)[1].strip())
    while len(rest) < length:
        chunk = sock.recv(4096)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest


def _read_all(sock, timeout=5.0):
    sock.settimeout(timeout)
    buf, closed = b"", False
    try:
        while True:
            chunk = sock.recv(4096)
            if not chunk:
                closed = True
                break
            buf += chunk
    except socket.timeout:
        pass
    return buf, closed


def test_load_shed_past_max_connections_no_hung_sockets():
    """Saturate past max-connections: the excess connections get a canned
    503 + close (never a hang, never a silent drop), the admitted ones
    still serve, and the server stays healthy afterwards."""
    cap = 4
    srv = _make_server(max_connections=cap, request_timeout_s=5.0)
    try:
        port = srv.port
        admitted = [
            socket.create_connection(("127.0.0.1", port)) for _ in range(cap)
        ]
        # Nudge the loop so all opens are registered before the excess.
        for s in admitted:
            s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"200" in _read_response(s)
        shed_results = []
        for _ in range(8):
            s = socket.create_connection(("127.0.0.1", port))
            buf, closed = _read_all(s)
            shed_results.append((buf, closed))
            s.close()
        for buf, closed in shed_results:
            assert buf.startswith(b"HTTP/1.1 503"), buf[:80]
            assert b"connection limit reached" in buf
            assert closed, "shed socket was left hanging"
        # Admitted connections still work (keep-alive survived the storm).
        for s in admitted:
            s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"200" in _read_response(s)
            s.close()
        # Slots freed: a fresh connection is admitted again.
        s = socket.create_connection(("127.0.0.1", port))
        s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
        assert b"200" in _read_response(s)
        s.close()
        stats = srv.telemetry.stats()
        assert stats["connection_sheds"] >= 8
    finally:
        srv.stop()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_oversized_body_413_and_keepalive_survives(transport):
    """A body past max-body-bytes is answered 413 with the body DRAINED:
    the same connection must serve the next request (no desync, no
    close) on both transports."""
    srv = _make_server(transport=transport, max_body_bytes=1024)
    try:
        big = b"x" * 4096
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(
            b"POST /predicates HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(big)).encode() + b"\r\n\r\n" + big
        )
        resp = _read_response(s)
        assert resp.startswith(b"HTTP/1.1 413"), resp[:120]
        assert b"Connection: close" not in resp
        # Keep-alive survived: next request on the SAME socket frames
        # cleanly (the oversized body was drained, not left in the stream).
        s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
        follow = _read_response(s)
        assert follow.startswith(b"HTTP/1.1 200"), follow[:120]
        s.close()
        assert srv.telemetry.stats()["body_rejections"] == 1
    finally:
        srv.stop()


def test_pipelined_requests_answered_in_order():
    """Three pipelined requests in ONE write: three responses come back in
    request order on the persistent connection."""
    srv = _make_server()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(
            b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /nope HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /status/readiness HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
        )
        buf, closed = _read_all(s)
        s.close()
        import re

        # Bodies are framed by Content-Length (no trailing CRLF), so a
        # body can butt directly against the next status line — match the
        # status lines positionally instead of splitting on CRLF.
        statuses = re.findall(rb"HTTP/1\.1 (\d{3})", buf)
        # liveness 200, unknown 404, readiness 200 (node pre-seeded) —
        # strictly in request order.
        assert statuses == [b"200", b"404", b"200"], (statuses, buf[:400])
        assert closed  # the final Connection: close honored
    finally:
        srv.stop()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_queue_depth_load_shedding_503(transport, monkeypatch):
    """When the batcher backlog crosses shed-queue-depth, /predicates gets
    an immediate 503 instead of parking until the request timeout."""
    srv = _make_server(transport=transport, shed_queue_depth=1)
    try:
        monkeypatch.setattr(srv.batcher, "queue_depth", lambda: 99)
        body = json.dumps({"Pod": {"metadata": {}}, "NodeNames": ["n0"]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/predicates",
            data=body,
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as err:
            assert err.code == 503
            payload = json.loads(err.read())
            assert payload["error"] == "scheduler overloaded"
        assert srv.telemetry.stats()["queue_sheds"] >= 1
    finally:
        srv.stop()


def test_transport_metrics_surface():
    """GET /metrics exposes the transport's series: the JSON snapshot
    carries server_transport, the Prometheus exposition the
    foundry.spark.scheduler.server.* gauges."""
    srv = _make_server()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics"
        ) as resp:
            snap = json.loads(resp.read())
        st = snap["server_transport"]
        assert st["transport"] == "async"
        assert st["requests_total"] >= 1
        assert st["open_connections"] >= 1
        assert "keepalive_reuse_ratio" in st
        assert "parse_mean_ms" in st and "write_mean_ms" in st
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/metrics?format=prometheus"
        )
        with urllib.request.urlopen(req) as resp:
            text = resp.read().decode()
        assert "foundry_spark_scheduler_server_requests_total" in text.replace(
            ".", "_"
        ) or "foundry.spark.scheduler.server.requests_total" in text
    finally:
        srv.stop()


def test_keepalive_reuse_ratio_counts_reused_requests():
    srv = _make_server()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        for _ in range(4):
            s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"200" in _read_response(s)
        s.close()
        stats = srv.telemetry.stats()
        assert stats["requests_total"] >= 4
        assert stats["keepalive_requests"] >= 3
        assert stats["keepalive_reuse_ratio"] > 0.5
    finally:
        srv.stop()


def test_malformed_request_line_rejected_in_order():
    """A garbage request line gets a 400 + close — and when it arrives
    pipelined behind a valid request, the valid response still flushes
    FIRST (the reject rides the slot queue, never out of band)."""
    srv = _make_server()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(
            b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n"
            b"TOTAL GARBAGE\r\n\r\n"
        )
        buf, closed = _read_all(s)
        s.close()
        import re

        statuses = re.findall(rb"HTTP/1\.1 (\d{3})", buf)
        assert statuses == [b"200", b"400"], (statuses, buf[:300])
        assert closed
        # The server is healthy for the next connection.
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
        assert b"200" in _read_response(s)
        s.close()
    finally:
        srv.stop()
