"""Multi-device window-solve engine: tier-1 serving smoke + lifecycle.

The conftest forces an 8-device virtual CPU mesh
(`xla_force_host_platform_device_count=8`), so these run in CI without
accelerator hardware:

  - boot the REAL HTTP server with a 2-device pool, serve a concurrent
    burst of multi-group /predicates, and assert the per-device solver
    gauges (`foundry.spark.scheduler.solver.device.*`) reach /metrics
    with the foundry prefix and one series per pool slot;
  - close()/discard_pipeline() must release per-device resident state and
    cancel queued fetch work (repeated server restarts in one process
    must not leak device buffers or parked closures);
  - make_pool_slots clamps oversized pools instead of failing the boot.
"""

import http.client
import json
import threading

import pytest

from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import SchedulerHTTPServer
from spark_scheduler_tpu.server.kube_io import pod_to_k8s
from spark_scheduler_tpu.store.backend import InMemoryBackend
from spark_scheduler_tpu.testing.harness import (
    Harness,
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)

DEVICE_PREFIX = "foundry.spark.scheduler.solver.device."


def test_server_smoke_two_device_pool_exports_device_gauges():
    backend = InMemoryBackend()
    n_groups, nodes_per_group = 2, 6
    group_names = {}
    for g in range(n_groups):
        group_names[g] = []
        for i in range(nodes_per_group):
            n = new_node(
                f"g{g}-n{i}", zone=f"zone{i % 2}", instance_group=f"group-{g}"
            )
            backend.add_node(n)
            group_names[g].append(n.name)
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            solver_device_pool=2,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    assert app.solver.pool_size == 2
    server = SchedulerHTTPServer(
        app, registry, host="127.0.0.1", port=0, request_timeout_s=120.0
    )
    server.start()
    n_clients = 8
    errors: list = []
    results = [None] * n_clients

    def client(i):
        try:
            g = i % n_groups
            pod = static_allocation_spark_pods(
                f"md-{i}", 2, instance_group=f"group-{g}"
            )[0]
            backend.add_pod(pod)
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=120
            )
            body = json.dumps(
                {"Pod": pod_to_k8s(pod), "NodeNames": group_names[g]}
            ).encode()
            conn.request("POST", "/predicates", body=body)
            results[i] = json.loads(conn.getresponse().read())
            conn.close()
        except Exception as exc:  # surfaced after join
            errors.append(exc)

    try:
        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        for i, r in enumerate(results):
            assert r and r.get("NodeNames"), (i, r)
            # Gangs stay inside their group's nodes.
            assert r["NodeNames"][0] in group_names[i % n_groups]
        # The engine actually served windows (solo singletons aside).
        assert app.solver.window_path_counts.get("pool", 0) >= 1

        # ---- /metrics JSON: one device.* series per pool slot, prefixed.
        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=60
        )
        conn.request("GET", "/metrics")
        snap = json.loads(conn.getresponse().read())
        conn.close()
        device_series = {
            name: entries
            for name, entries in snap.items()
            if isinstance(entries, list) and name.startswith(DEVICE_PREFIX)
        }
        assert device_series, sorted(snap)
        uploads = snap.get(DEVICE_PREFIX + "uploads")
        assert uploads, sorted(device_series)
        devices_seen = {e["tags"]["device"] for e in uploads}
        assert len(devices_seen) >= 2, uploads
        assert snap.get(DEVICE_PREFIX + "solve.ms"), sorted(device_series)
        assert all(name.startswith("foundry.spark.scheduler.") for name in device_series)
    finally:
        server.stop()
    # stop() -> app.stop() -> solver.close(): resident state released.
    assert app.solver._pipe is None
    for slot in app.solver._pool.slots:
        assert slot.statics is None and not slot.sub_statics


def test_close_cancels_queued_fetch_work_and_releases_state():
    """After close(), queued-but-unrun pool futures are cancelled and every
    device-resident buffer is dropped — the restart-leak fix."""
    h = Harness(
        binpack_algo="tightly-pack", fifo=False, solver_device_pool=2
    )
    for g in range(2):
        h.add_nodes(
            *[
                new_node(f"g{g}-n{i}", instance_group=f"group-{g}")
                for i in range(4)
            ]
        )
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    args = []
    for g in range(2):
        pod = static_allocation_spark_pods(
            f"cl-{g}", 2, instance_group=f"group-{g}"
        )[0]
        h.add_pods(pod)
        args.append(
            ExtenderArgs(
                pod=pod, node_names=[f"g{g}-n{i}" for i in range(4)]
            )
        )
    results = h.extender.predicate_batch(args)
    assert all(r.ok for r in results)
    solver = h.app.solver
    assert any(s.statics or s.sub_statics for s in solver._pool.slots)
    solver.close()
    assert solver._pipe is None and solver._dev is None
    assert not solver._inflight_futures
    for slot in solver._pool.slots:
        assert slot.statics is None and not slot.sub_statics
    # Fresh (unreserved) drivers so the dispatch actually reaches the
    # solver instead of the idempotent-retry branch.
    fresh = []
    for g in range(2):
        pod = static_allocation_spark_pods(
            f"cl-fresh-{g}", 2, instance_group=f"group-{g}"
        )[0]
        h.add_pods(pod)
        fresh.append(
            ExtenderArgs(
                pod=pod, node_names=[f"g{g}-n{i}" for i in range(4)]
            )
        )
    with pytest.raises(RuntimeError, match="after shutdown"):
        h.extender.predicate_batch(fresh)


def test_discard_pipeline_releases_pool_replicas():
    h = Harness(
        binpack_algo="tightly-pack", fifo=False, solver_device_pool=2
    )
    for g in range(2):
        h.add_nodes(
            *[
                new_node(f"g{g}-n{i}", instance_group=f"group-{g}")
                for i in range(4)
            ]
        )
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    pod = static_allocation_spark_pods("dp-0", 2, instance_group="group-0")[0]
    h.add_pods(pod)
    r = h.extender.predicate_batch(
        [ExtenderArgs(pod=pod, node_names=[f"g0-n{i}" for i in range(4)])]
    )
    assert r[0].ok
    solver = h.app.solver
    solver.discard_pipeline()
    assert solver._pipe is None
    for slot in solver._pool.slots:
        assert slot.statics is None and not slot.sub_statics
    # And the next window full-uploads and serves fine.
    pod2 = static_allocation_spark_pods("dp-1", 2, instance_group="group-1")[0]
    h.add_pods(pod2)
    r2 = h.extender.predicate_batch(
        [ExtenderArgs(pod=pod2, node_names=[f"g1-n{i}" for i in range(4)])]
    )
    assert r2[0].ok


def test_make_pool_slots_clamps_to_available_devices():
    from spark_scheduler_tpu.parallel.mesh import make_pool_slots

    # conftest forces 8 virtual devices; a 64-slot config must clamp.
    slots = make_pool_slots(64)
    assert 1 <= len(slots) <= 8
    # Sub-mesh slots: 2 slots x 4 node shards consumes all 8 devices.
    mesh_slots = make_pool_slots(2, 4)
    assert len(mesh_slots) == 2
    assert all(hasattr(s, "devices") for s in mesh_slots)
    with pytest.raises(ValueError):
        make_pool_slots(1, 1024)  # node-shards beyond the device count
