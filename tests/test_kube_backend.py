"""KubeBackend tests: reservations/demands persisted THROUGH the apiserver.

The reference's deployment truth: CRDs in etcd are the durable store, the
scheduler's caches write back through rate-limited clients, and a new
leader lists them back and reconciles (SURVEY.md §3.5, §5.4). These tests
run the full scheduler against the fake apiserver with KubeBackend.
"""

from __future__ import annotations

import pytest

from spark_scheduler_tpu.kube.apiserver import FakeKubeAPIServer
from spark_scheduler_tpu.kube.backend import KubeBackend, TokenBucket
from spark_scheduler_tpu.store.backend import ConflictError, DEMAND_CRD
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    static_allocation_spark_pods,
)
from tests.test_kube_watch import k8s_node, wait_until


@pytest.fixture
def apiserver():
    server = FakeKubeAPIServer()
    server.start()
    yield server
    server.stop()


def _kube_harness(apiserver, n_nodes=4, **kw):
    backend = KubeBackend(apiserver.base_url, qps=1000, burst=1000)
    backend.start()
    assert backend.wait_synced(timeout=5.0)
    h = Harness(backend=backend, **kw)
    names = [f"n{i}" for i in range(n_nodes)]
    h.add_nodes(*(new_node(n) for n in names))
    return h, backend, names


class TestTokenBucket:
    def test_burst_then_refill(self):
        now = [0.0]
        waits = []
        bucket = TokenBucket(
            qps=10, burst=3, clock=lambda: now[0],
            sleep=lambda s: (waits.append(s), now.__setitem__(0, now[0] + s)),
        )
        for _ in range(3):
            bucket.acquire()  # burst: no waiting
        assert waits == []
        bucket.acquire()  # 4th must wait ~1/qps
        assert waits and abs(waits[0] - 0.1) < 1e-6
        now[0] += 1.0  # a second passes: tokens refill (capped at burst)
        for _ in range(3):
            bucket.acquire()
        assert len(waits) == 1


class TestApiserverPersistence:
    def test_gang_reservation_lands_in_apiserver(self, apiserver):
        h, backend, names = _kube_harness(apiserver)
        pods = static_allocation_spark_pods("kb-app", 2)
        result = h.schedule(pods[0], names)
        assert result.node_names, result
        for p in pods[1:]:
            assert h.schedule(p, names).node_names
        # the CR lives in the APISERVER, not just locally
        stored = apiserver.collections["resourcereservations"].objects
        assert ("namespace", "kb-app") in stored
        wire = stored[("namespace", "kb-app")]
        assert wire["status"]["pods"]["driver"] == pods[0].name
        assert len(wire["spec"]["reservations"]) == 3
        # schema was enforced on the write path (CRD registered via REST)
        assert "resourcereservations" in apiserver._crds
        h.app.stop()
        backend.stop()

    def test_demand_lands_in_apiserver(self, apiserver):
        # The autoscaler (not the scheduler) provides the Demand CRD.
        h, backend, names = _kube_harness(apiserver, n_nodes=1)
        backend.register_crd(DEMAND_CRD)
        h.app.demand_crd_watcher.check_now()
        big = static_allocation_spark_pods("kb-big", 50)
        result = h.schedule(big[0], names)
        assert not result.node_names  # cannot fit => demand
        stored = apiserver.collections["demands"].objects
        assert ("namespace", f"demand-{big[0].name}") in stored
        wire = stored[("namespace", f"demand-{big[0].name}")]
        assert wire["spec"]["instance-group"]  # kebab-case reference format
        h.app.stop()
        backend.stop()

    def test_conflict_maps_to_conflict_error(self, apiserver):
        h, backend, names = _kube_harness(apiserver)
        pods = static_allocation_spark_pods("kb-conf", 1)
        assert h.schedule(pods[0], names).node_names
        rr = backend.get("resourcereservations", "namespace", "kb-conf")
        # another writer bumps the rv behind our back
        import json

        raw = apiserver.collections["resourcereservations"].objects[
            ("namespace", "kb-conf")
        ]
        apiserver.update("resourcereservations", json.loads(json.dumps(raw)))
        stale = rr.copy()
        with pytest.raises(ConflictError):
            backend.update("resourcereservations", stale)
        h.app.stop()
        backend.stop()

    def test_external_modify_only_bumps_rv(self, apiserver):
        """Cache owner is the sole writer: an external MODIFIED must not
        replace the locally-owned object (cache.go:106-133)."""
        import json

        h, backend, names = _kube_harness(apiserver)
        pods = static_allocation_spark_pods("kb-rv", 1)
        assert h.schedule(pods[0], names).node_names
        # the locally-stored instance (backend.get on remote kinds does a
        # fresh REST GET — different object)
        (local_before,) = backend.list("resourcereservations")
        raw = json.loads(
            json.dumps(
                apiserver.collections["resourcereservations"].objects[
                    ("namespace", "kb-rv")
                ]
            )
        )
        raw["status"]["pods"] = {}  # external mutation we must NOT absorb
        apiserver.update("resourcereservations", raw)
        new_rv = int(raw["metadata"]["resourceVersion"])
        assert wait_until(
            lambda: backend.list("resourcereservations")[0].resource_version
            == new_rv
        )
        local_after = backend.list("resourcereservations")[0]
        assert local_after is local_before  # same object, rv fast-forwarded
        assert local_after.status.pods  # our state kept
        h.app.stop()
        backend.stop()


class TestAbsentCollections:
    def test_missing_collection_syncs_empty_and_polls(self):
        """A cluster without the Demand CRD must not hang startup or hammer
        the apiserver: the reflector syncs as empty and polls slowly
        (demand_informer.go:75-97 semantics)."""
        import threading

        from spark_scheduler_tpu.kube.reflector import (
            BackendSyncTarget,
            Reflector,
        )
        from spark_scheduler_tpu.server.kube_io import node_from_k8s
        from spark_scheduler_tpu.store.backend import InMemoryBackend

        # a server that 404s everything (no such collection)
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        hits = [0]

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                hits[0] += 1
                body = b'{"reason": "NotFound", "code": 404}'
                self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            reflector = Reflector(
                f"http://127.0.0.1:{srv.server_address[1]}",
                "/apis/scaler.palantir.com/v1alpha2/demands",
                node_from_k8s,
                BackendSyncTarget(InMemoryBackend(), "demands"),
                tolerate_absent=True,
                absent_poll_s=60.0,
            )
            reflector.start()
            try:
                # synced-as-empty, quickly — startup must not block
                assert reflector.wait_synced(timeout=5.0)
                import time as _t

                _t.sleep(0.5)
                # slow poll: one (maybe two) probes, not a 0.2s retry storm
                assert hits[0] <= 3, hits[0]
            finally:
                reflector.stop()
        finally:
            srv.shutdown()
            srv.server_close()


class TestFailover:
    def test_new_leader_restores_from_apiserver(self, apiserver):
        """Leader change: a fresh scheduler process lists reservations back
        from the apiserver and keeps scheduling — executor lands on its
        restored reservation (failover.go:35-72 + cache fill)."""
        h, backend, names = _kube_harness(apiserver)
        pods = static_allocation_spark_pods("kb-fo", 2)
        driver, execs = pods[0], pods[1:]
        assert h.schedule(driver, names).node_names
        assert h.schedule(execs[0], names).node_names
        h.app.stop()
        backend.stop()  # process death — nothing local survives

        backend2 = KubeBackend(apiserver.base_url, qps=1000, burst=1000)
        backend2.start()
        assert backend2.wait_synced(timeout=5.0)
        h2 = Harness(backend=backend2)
        h2.add_nodes(*(new_node(n) for n in names))
        # pods live in the apiserver's world; re-add them to the new
        # backend the way pod ingestion would
        for p in pods:
            h2.add_pods(h.backend.get("pods", p.namespace, p.name) or p)
        rrs = backend2.list("resourcereservations")
        assert len(rrs) == 1 and rrs[0].name == "kb-fo"
        h2.app.reconciler.sync_resource_reservations_and_demands()
        res = h2.schedule(execs[1], names)
        assert res.node_names, res
        reserved = {
            r.node
            for slot, r in rrs[0].spec.reservations.items()
            if slot != "driver"
        }
        assert res.node_names[0] in reserved
        h2.app.stop()
        backend2.stop()


class TestDynamicAllocationThroughApiserver:
    def test_compaction_updates_apiserver(self, apiserver):
        h, backend, names = _kube_harness(apiserver)
        pods = dynamic_allocation_spark_pods("kb-dyn", 1, 3)
        driver, execs = pods[0], pods[1:]
        assert h.schedule(driver, names).node_names
        for e in execs:
            assert h.schedule(e, names).node_names
        # extra executors beyond min ride soft reservations; DELETING the
        # hard-slot executor queues compaction, which promotes a soft
        # executor into the freed CRD slot — visible in the apiserver
        h.backend.delete_pod(execs[0])
        h.app.reservation_manager.compact_dynamic_allocation_applications()
        wire = apiserver.collections["resourcereservations"].objects[
            ("namespace", "kb-dyn")
        ]
        bound = set(wire["status"]["pods"].values())
        assert execs[0].name not in bound
        assert len(bound) == 2  # driver + the promoted executor
        h.app.stop()
        backend.stop()
