"""Fleet chaos soak (ISSUE 19) — the engine lives in
spark_scheduler_tpu/testing/soak.py (FleetSoak, shared with
hack/fleet_smoke.py's CI leg). A seeded random gang mix across 3
clusters with multi-homed instance groups, one cluster killed mid-run
and rejoined later. Invariants: zero double placements, zero
over-commits, aggregates == walk-oracle, every orphaned pending gang
re-routed off the dead cluster, and per-cluster byte-identity to a
standalone replay of the full soak's op stream.

Step count: FLEET_SOAK_STEPS env (default 40 keeps tier-1 fast; the CI
fleet job runs longer).
"""

from __future__ import annotations

import os

from spark_scheduler_tpu.testing.soak import FleetSoak

STEPS = int(os.environ.get("FLEET_SOAK_STEPS", "40"))


def test_fleet_chaos_soak():
    soak = FleetSoak(n_clusters=3, nodes_per_cluster=2, seed=1)
    try:
        soak.run(
            steps=STEPS,
            kill_at=max(2, STEPS * 5 // 8),
            rejoin_at=max(3, STEPS * 4 // 5),
        )
        v = soak.verdict()
    finally:
        soak.stop()
    assert v["double_placements"] == [], v["double_placements"]
    assert v["overcommit"] == [], v["overcommit"]
    assert v["oracle_mismatches"] == [], v["oracle_mismatches"]
    assert v["orphans_unrouted"] == [], v["orphans_unrouted"]
    # The chaos actually bit: traffic placed, capacity pressure spilled
    # gangs across clusters, and every cluster replayed byte-identical.
    assert v["placed"] > 0
    assert v["spillovers"] > 0, v
    assert all(r["identical"] for r in v["equivalence"].values())


def test_fleet_soak_orphans_leave_dead_cluster():
    """A seed whose kill point catches a pending backlog: the orphan
    re-route invariant is exercised, not vacuous."""
    soak = FleetSoak(n_clusters=3, nodes_per_cluster=2, seed=1)
    try:
        v = soak.run(steps=45, kill_at=25, rejoin_at=36).verdict()
    finally:
        soak.stop()
    assert v["orphans_at_kill"] > 0
    assert v["orphans_unrouted"] == [], v["orphans_unrouted"]
    assert v["double_placements"] == [] and v["overcommit"] == []
