"""HostFeatureStore: per-window featurize is O(changed), not O(nodes).

Three layers:
  - the tier-1 BUDGET test: a 10k-node store absorbs 50 incremental
    events and serves steady-state snapshots without a single O(nodes)
    roster re-walk (instrumented counters, not timing — timing guards
    flake on shared CI boxes; the counters ARE the loop evidence);
  - zero-copy semantics: unchanged state returns the same frozen arrays
    and roster tuples, object-identical across snapshots;
  - the satellite fixes that ride along: LRU eviction for the domain /
    candidate caches, frozen overhead views, and the snapshot's
    equivalence with the legacy per-window rebuild.
"""

import numpy as np
import pytest

from spark_scheduler_tpu.core.lru import LRUCache
from spark_scheduler_tpu.models.kube import Container, Pod
from spark_scheduler_tpu.models.resources import FrozenResources, Resources
from spark_scheduler_tpu.models.reservations import new_resource_reservation
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.store.backend import InMemoryBackend
from spark_scheduler_tpu.testing.harness import (
    INSTANCE_GROUP_LABEL,
    Harness,
    new_node,
    static_allocation_spark_pods,
)

NS = "namespace"


def _app_with_nodes(n_nodes):
    backend = InMemoryBackend()
    names = []
    for i in range(n_nodes):
        node = new_node(f"fs-n{i}", zone=f"zone{i % 4}")
        backend.add_node(node)
        names.append(node.name)
    app = build_scheduler_app(
        backend,
        InstallConfig(
            sync_writes=True, instance_group_label=INSTANCE_GROUP_LABEL
        ),
    )
    return backend, app, names


def _reservation(names, j, execs=2):
    driver = static_allocation_spark_pods(f"fs-app-{j}", execs)[0]
    return new_resource_reservation(
        names[j % len(names)],
        [names[(j + k + 1) % len(names)] for k in range(execs)],
        driver,
        Resources.from_quantities("1", "1Gi"),
        Resources.from_quantities("1", "1Gi"),
    )


# ----------------------------------------------------------- budget (tier-1)


def test_budget_10k_nodes_steady_state_featurize_is_o_changed():
    """THE regression guard for the optimisation: build a 10k-node store,
    apply 50 incremental events (reservation commits), and assert the
    steady-state snapshots did NO O(nodes) work — the roster-rebuild
    counter (the store's only O(nodes) Python walk) must not move, and
    the refresh counters must track exactly the events applied."""
    backend, app, names = _app_with_nodes(10_000)
    store = app.extender.features

    cold = store.snapshot()
    assert store.roster_rebuilds == 1  # the one cold build
    assert len(cold.nodes) == 10_000

    rebuilds_before = store.roster_rebuilds
    usage_refreshes_before = store.usage_refreshes
    for j in range(50):
        assert app.rr_cache.create(_reservation(names, j))
        snap = store.snapshot()
        # The roster was untouched: same tuple/dict objects, zero walks.
        assert snap.nodes is cold.nodes
        assert snap.by_name is cold.by_name
        assert snap.statics_epoch == cold.statics_epoch
    assert store.roster_rebuilds == rebuilds_before, (
        "steady-state featurize paid an O(nodes) roster re-walk"
    )
    # Usage refreshed once per dirty window as an O(changed) row PATCH
    # into the resident master (ISSUE 13) — zero full [cap,3] copies.
    assert store.usage_patches == 50
    assert store.usage_refreshes == usage_refreshes_before, (
        "steady-state usage refresh paid a full-array copy"
    )

    # The snapshots carried the commits: reserved rows are non-zero.
    assert snap.usage.any()

    # A node ADD rides the append patch (ISSUE 11): the roster grows
    # without an O(nodes) re-list/re-intern — the rebuild counter stays
    # flat and the add-patch counter moves instead.
    backend.add_node(new_node("fs-late", zone="zone0"))
    snap2 = store.snapshot()
    assert store.roster_rebuilds == rebuilds_before
    assert store.roster_add_patches == 1
    assert len(snap2.nodes) == 10_001
    assert snap2.by_name["fs-late"] is not None
    # A node DELETE rides the tombstone patch (ISSUE 12): swap-remove +
    # live-mask clear, no O(nodes) re-list — the rebuild counter stays
    # flat and the delete-patch counter moves instead.
    backend.delete("nodes", "", "fs-late")
    snap3 = store.snapshot()
    assert store.roster_rebuilds == rebuilds_before
    assert store.roster_delete_patches == 1
    assert len(snap3.nodes) == 10_000
    assert "fs-late" not in snap3.by_name
    # Bumps at least once for the roster walk (the re-masked overhead copy
    # may bump it again) — what matters is that the solver's epoch skip is
    # invalidated.
    assert snap2.statics_epoch > cold.statics_epoch
    app.stop()


def test_snapshot_is_zero_copy_when_clean():
    backend, app, names = _app_with_nodes(8)
    store = app.extender.features
    s1 = store.snapshot()
    s2 = store.snapshot()
    assert s2.nodes is s1.nodes
    assert s2.by_name is s1.by_name
    assert s2.usage is s1.usage
    assert s2.overhead is s1.overhead
    assert s2.epoch == s1.epoch
    # Frozen: the shared arrays cannot be scribbled on by a consumer.
    with pytest.raises(ValueError):
        s1.usage[0, 0] = 1
    with pytest.raises(ValueError):
        s1.overhead[0, 0] = 1
    app.stop()


def test_snapshot_matches_legacy_rebuild():
    """The snapshot's arrays must equal what the legacy per-window rebuild
    derived: usage == reserved_usage(), overhead rows == get_overhead
    map — through build_tensors the two views are byte-identical."""
    backend, app, names = _app_with_nodes(16)
    store, solver = app.extender.features, app.solver
    # Overhead: an unreserved non-spark pod bound to a node.
    backend.add_pod(
        Pod(
            name="ov-pod",
            namespace="kube-system",
            node_name=names[3],
            scheduler_name="default-scheduler",
            phase="Running",
            containers=[
                Container(requests=Resources.from_quantities("500m", "256Mi"))
            ],
        )
    )
    assert app.rr_cache.create(_reservation(names, 0))
    snap = store.snapshot()

    legacy_nodes = backend.list_nodes()
    legacy_usage = app.reservation_manager.reserved_usage()
    legacy_overhead = app.overhead_computer.get_overhead(legacy_nodes)

    rows = min(snap.usage.shape[0], legacy_usage.shape[0])
    assert np.array_equal(snap.usage[:rows], legacy_usage[:rows])

    t_snap = solver.build_tensors(
        snap.nodes, snap.usage, snap.overhead, full_node_list=True
    )
    t_legacy = solver.build_tensors(
        legacy_nodes, legacy_usage, legacy_overhead, full_node_list=True
    )
    for field in ("available", "schedulable", "zone_id", "valid"):
        assert np.array_equal(
            np.asarray(getattr(t_snap, field)),
            np.asarray(getattr(t_legacy, field)),
        ), field
    app.stop()


# ------------------------------------------------------------- LRU satellite


def test_lru_cache_65th_signature_keeps_the_64_hottest():
    """The domain-cache satellite: overflow evicts the LRU entry only —
    a 65th signature must keep the 64 hottest resident (the old
    `.clear()` wiped all of them)."""
    cache = LRUCache(64)
    for i in range(64):
        cache.put(("sig", i), i)
    # Touch 1..63 so ("sig", 0) is the coldest.
    for i in range(1, 64):
        assert cache.get(("sig", i)) == i
    cache.put(("sig", 64), 64)
    assert len(cache) == 64
    assert ("sig", 0) not in cache  # only the LRU entry fell out
    for i in range(1, 65):
        assert ("sig", i) in cache


def test_domain_cache_lru_in_extender():
    """Integration pin: the extender's affinity-domain memo survives an
    overflow — filling it past capacity does not clear the hot entries."""
    h = Harness(binpack_algo="tightly-pack", fifo=False)
    h.add_nodes(*[new_node(f"n{i}", zone=f"zone{i % 2}") for i in range(4)])
    ext = h.extender
    topo = h.backend.nodes_version
    for i in range(70):
        ext._domain_cache.put((("ig", f"group-{i}"),), (topo, [f"n{i % 4}"]))
    assert len(ext._domain_cache) == 64
    # The most recent 64 signatures survived.
    assert ((("ig", "group-69"),)) in ext._domain_cache
    assert ((("ig", "group-6"),)) in ext._domain_cache
    assert ((("ig", "group-5"),)) not in ext._domain_cache


# --------------------------------------------------------- frozen overheads


def test_get_overhead_returns_frozen_views():
    backend, app, names = _app_with_nodes(4)
    backend.add_pod(
        Pod(
            name="ov-pod",
            namespace="kube-system",
            node_name=names[0],
            scheduler_name="default-scheduler",
            phase="Running",
            containers=[
                Container(requests=Resources.from_quantities("1", "1Gi"))
            ],
        )
    )
    oc = app.overhead_computer
    overhead = oc.get_overhead(backend.list_nodes())
    assert names[0] in overhead
    view = overhead[names[0]]
    assert isinstance(view, FrozenResources)
    # Value-equal with plain Resources, both directions.
    expected = Resources.from_quantities("1", "1Gi")
    assert view == expected and expected == view
    with pytest.raises(TypeError):
        view.add(Resources.from_quantities("1", "1Gi"))
    with pytest.raises(TypeError):
        view.sub(expected)
    # copy() is the mutable escape hatch, and mutating it does not touch
    # the aggregate.
    mutable = view.copy()
    mutable.add(Resources.from_quantities("1", "0"))
    again = oc.get_overhead(backend.list_nodes())[names[0]]
    assert again == expected
    # Memoized: repeated queries reuse the same view object until the
    # aggregate changes.
    assert again is view
    oracle = oc.compute_node_overhead_oracle(names[0])[0]
    assert view == oracle
    app.stop()


def test_frozen_view_invalidated_on_aggregate_change():
    backend, app, names = _app_with_nodes(4)
    oc = app.overhead_computer

    def add_ov(name, node):
        backend.add_pod(
            Pod(
                name=name,
                namespace="kube-system",
                node_name=node,
                scheduler_name="default-scheduler",
                phase="Running",
                containers=[
                    Container(requests=Resources.from_quantities("1", "1Gi"))
                ],
            )
        )

    add_ov("ov-1", names[0])
    v1 = oc.get_overhead(backend.list_nodes())[names[0]]
    add_ov("ov-2", names[0])
    v2 = oc.get_overhead(backend.list_nodes())[names[0]]
    assert v2 is not v1
    assert v2 == Resources.from_quantities("2", "2Gi")
    # Dense mirror tracked the same deltas.
    version, dense = oc.overhead_snapshot(None)
    idx = app.solver.registry.index_of(names[0])
    assert Resources.from_array(dense[idx]) == v2
    app.stop()


def test_overhead_of_deleted_node_is_masked_like_the_legacy_dict():
    """A deleted node whose pods still exist keeps rows in the dense
    overhead aggregate; the legacy get_overhead(all_nodes) dict never
    surfaced them. The snapshot must match the dict exactly — non-live
    rows zeroed — or the soak's drained-mirror invariant (which rebuilds
    from the dict) would diverge from the serving path."""
    backend, app, names = _app_with_nodes(4)
    store = app.extender.features
    backend.add_pod(
        Pod(
            name="ghost-ov",
            namespace="kube-system",
            node_name=names[1],
            scheduler_name="default-scheduler",
            phase="Running",
            containers=[
                Container(requests=Resources.from_quantities("1", "1Gi"))
            ],
        )
    )
    idx = app.solver.registry.index_of(names[1])
    snap = store.snapshot()
    assert snap.overhead[idx].any()

    backend.delete("nodes", "", names[1])  # pod survives the node
    snap2 = store.snapshot()
    assert not snap2.overhead[idx].any(), (
        "dense overhead leaked a deleted node's row past the roster mask"
    )
    # And the raw aggregate still remembers it: re-adding the node
    # resurfaces the overhead, exactly like the dict would.
    backend.add_node(new_node(names[1], zone="zone1"))
    snap3 = store.snapshot()
    assert snap3.overhead[idx].any()
    app.stop()


def test_overhead_change_invalidates_statics_epoch():
    """Regression pin (review finding): `schedulable = allocatable -
    overhead` is a STATIC field of the cluster tensors, and overhead can
    change with NO node event (pod churn). The statics epoch must bump on
    overhead refreshes, or the solver's epoch skip would leave a stale
    schedulable tensor on device and window decisions could diverge from
    the reference path."""
    backend, app, names = _app_with_nodes(4)
    store, solver = app.extender.features, app.solver
    s1 = store.snapshot()
    t1 = solver.build_tensors_pipelined(
        s1.nodes, s1.usage, s1.overhead,
        topo_version=s1.nodes_version, statics_version=s1.statics_epoch,
    )
    # Overhead-only event: an unreserved pod binds to a node.
    backend.add_pod(
        Pod(
            name="stale-ov",
            namespace="kube-system",
            node_name=names[0],
            scheduler_name="default-scheduler",
            phase="Running",
            containers=[
                Container(requests=Resources.from_quantities("500m", "512Mi"))
            ],
        )
    )
    s2 = store.snapshot()
    assert s2.statics_epoch != s1.statics_epoch
    t2 = solver.build_tensors_pipelined(
        s2.nodes, s2.usage, s2.overhead,
        topo_version=s2.nodes_version, statics_version=s2.statics_epoch,
    )
    # The device-resident schedulable tensor followed host truth.
    idx = solver.registry.index_of(names[0])
    host_sched = np.asarray(getattr(t2, "host", t2).schedulable)
    dev_sched = np.asarray(t2.schedulable)
    assert np.array_equal(dev_sched[idx], host_sched[idx])
    assert dev_sched[idx][0] == 8000 - 500  # allocatable - overhead
    app.stop()


# ------------------------------------------------- node DELETE patch (ISSUE 12)


def test_delete_patch_matches_fresh_rebuild():
    """A node DELETE swap-removes through the patch path: the patched
    roster must equal a from-scratch rebuild as a SET (swap-remove
    permutes positions), the live-row mask must drop the deleted row,
    and the dirty hint must carry the deleted name for the solver's
    tombstone path."""
    backend, app, names = _app_with_nodes(12)
    store = app.extender.features
    store.snapshot()
    rebuilds = store.roster_rebuilds

    backend.delete("nodes", "", names[3])
    snap = store.snapshot()
    assert store.roster_rebuilds == rebuilds
    assert store.roster_delete_patches == 1
    assert {n.name for n in snap.nodes} == set(names) - {names[3]}
    assert names[3] not in snap.by_name
    assert len(snap.roster_rows) == len(snap.nodes)
    # roster_rows still names each node's registry row.
    reg = app.solver.registry
    for node, row in zip(snap.nodes, snap.roster_rows):
        assert reg.index_of(node.name) == row
    # The deleted row left the live mask (the overhead re-mask input).
    deleted_row = reg.index_of(names[3])
    assert not store._roster_mask[deleted_row]
    # Dirty hint carries the delete.
    assert snap.dirty_hint is not None and names[3] in snap.dirty_hint[2]
    app.stop()


def test_delete_then_serve_recycles_registry_row():
    """End-to-end delete satellite: serving across a DELETE takes the
    patch path on both layers (no roster rebuild, no arena re-walk), the
    tombstoned registry row recycles once nothing references it, and a
    later ADD reuses the freed index — the registry capacity does not
    grow past the high-water mark."""
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    backend, app, names = _app_with_nodes(16)
    ext = app.extender
    ext._last_request = float("inf")
    store = ext.features

    def serve(tag):
        d = static_allocation_spark_pods(f"del-{tag}", 1)[0]
        backend.add_pod(d)
        tok = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d, node_names=list(names))]
        )
        return ext.predicate_window_complete(tok)

    serve("warm")
    rebuilds = store.roster_rebuilds
    # Delete an idle node (no reservations landed on it yet).
    victim = names[-1]
    backend.delete("nodes", "", victim)
    serve("after-del")
    assert store.roster_rebuilds == rebuilds
    assert store.roster_delete_patches == 1
    serve("drain")  # tombstone released once no window is in flight
    assert app.solver.tombstones_recycled >= 1
    assert app.solver.registry.index_of(victim) is None
    cap_before = app.solver.registry.capacity
    # A new node reuses the freed registry row: capacity stays flat.
    backend.add_node(new_node("del-reborn", zone="zone0"))
    serve("after-add")
    assert app.solver.registry.capacity == cap_before
    assert store.roster_rebuilds == rebuilds
    app.stop()


# ----------------------------------- per-zone head-walk property (ISSUE 12)


def test_rank_headwalk_topk_matches_full_sort_under_churn():
    """Property test: the planner's head-walk top-K — the first K valid
    fitting rows of a zone's resident order — must equal the top-K of a
    from-scratch full sort, per zone, under randomized add/update/delete
    churn. Keys are drawn from a tiny value set so tie GROUPS straddle
    the K boundary (the order's row-index tiebreak must keep the
    incremental and rebuilt orders identical)."""
    from spark_scheduler_tpu.core.feature_store import RankIndex

    rng = np.random.default_rng(77)
    n, zb, k = 400, 4, 6
    avail = (rng.integers(0, 4, size=(n, 3)) * 8).astype(np.int32)
    name_rank = rng.permutation(n).astype(np.int32)
    zone_id = rng.integers(0, 3, size=n).astype(np.int32)
    valid = rng.random(n) < 0.9
    min_req = np.asarray([8, 8, 0], np.int32)

    idx = RankIndex()
    idx.rebuild(avail, name_rank, zone_id, zb)
    for step in range(40):
        op = int(rng.integers(0, 3))
        rows = rng.choice(n, size=int(rng.integers(1, 10)), replace=False)
        if op == 0:  # availability churn
            avail[rows] = (rng.integers(0, 4, size=(len(rows), 3)) * 8)
        elif op == 1:  # delete
            valid[rows] = False
        else:  # add / revive
            valid[rows] = True
            avail[rows] = (rng.integers(0, 4, size=(len(rows), 3)) * 8)
        idx.update_rows(avail, name_rank, rows)
        for z in range(zb):
            zo = idx.zone_order(z)
            zrows = zo[valid[zo]]
            fit = (avail[zrows] >= min_req).all(axis=1)
            head = zrows[fit][:k]
            cand = np.flatnonzero(
                valid
                & (zone_id == z)
                & (avail >= min_req).all(axis=1)
            )
            full = cand[np.lexsort((
                cand,
                name_rank[cand].astype(np.int64),
                avail[cand, 0].astype(np.int64),
                avail[cand, 1].astype(np.int64),
            ))]
            assert np.array_equal(head, full[:k]), (step, z)


def test_delete_then_readd_does_not_release_live_row():
    """Review regression: a node deleted while a window was in flight
    (release deferred) and then RE-ADDED must cancel its parked
    tombstone — releasing the row later would unmap a live node and
    hand its registry index to the free list."""
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    backend, app, names = _app_with_nodes(12)
    ext = app.extender
    ext._last_request = float("inf")

    def serve(tag):
        d = static_allocation_spark_pods(f"readd-{tag}", 1)[0]
        backend.add_pod(d)
        tok = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d, node_names=list(names))]
        )
        return ext.predicate_window_complete(tok)

    serve("warm")
    victim = names[-1]
    row = app.solver.registry.index_of(victim)
    # Delete + serve (the window in flight at build time defers release),
    # then re-add the SAME name and keep serving.
    backend.delete("nodes", "", victim)
    serve("deleted")
    backend.add_node(new_node(victim, zone="zone0"))
    serve("readded")
    serve("drain")
    assert app.solver.registry.index_of(victim) == row, (
        "live re-added node lost its registry row to a stale tombstone"
    )
    assert victim not in app.solver._pending_tombstones
    res = serve("place")
    assert res[0].node_names
    app.stop()
