"""Windowed serving (VERDICT r2 #1): coalesced /predicates windows must make
exactly the decisions sequential serving makes.

Three layers:
  - ops: the segmented scan (commit/reset/dup rows) vs per-segment masked
    solves threaded host-side;
  - extender: predicate_batch vs predicate-one-at-a-time on identical
    clusters, including FIFO blocking, failures, and single-AZ strategies;
  - server: concurrent HTTP clients are actually batched (window > 1) and
    produce a consistent reservation state.
"""

import copy
import dataclasses
import json
import http.client
import threading

import numpy as np
import pytest

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)

from tests.test_packing_golden import random_cluster

EMAX = 8
NUM_ZONES = 4


# --------------------------------------------------------------------- ops


def _random_segments(rng, n_requests, n):
    """Synthesized window: each request has 0-3 hypothetical earlier rows
    plus its own (committing) row."""
    segments = []
    for _ in range(n_requests):
        rows = []
        for _ in range(int(rng.integers(0, 4))):
            rows.append(
                (
                    rng.integers(1, 4, size=3).astype(np.int32),
                    rng.integers(1, 5, size=3).astype(np.int32),
                    int(rng.integers(1, EMAX + 1)),
                    bool(rng.random() < 0.3),
                )
            )
        rows.append(
            (
                rng.integers(1, 4, size=3).astype(np.int32),
                rng.integers(1, 5, size=3).astype(np.int32),
                int(rng.integers(1, EMAX + 1)),
                False,
            )
        )
        cand = rng.random(n) < 0.8
        dom = rng.random(n) < 0.9
        segments.append({"rows": rows, "cand": cand, "dom": dom})
    return segments


def _flatten_segments(segments, n):
    flat, commit, reset, cands, doms = [], [], [], [], []
    real_row_of = []
    for seg in segments:
        for j, row in enumerate(seg["rows"]):
            flat.append(row)
            commit.append(j == len(seg["rows"]) - 1)
            reset.append(j == 0)
            cands.append(seg["cand"])
            doms.append(seg["dom"])
        real_row_of.append(len(flat) - 1)
    return flat, commit, reset, cands, doms, real_row_of


def _segment_batch(segments, n):
    flat, commit, reset, cands, doms, real_row_of = _flatten_segments(
        segments, n
    )
    return (
        make_app_batch(
            np.stack([r[0] for r in flat]),
            np.stack([r[1] for r in flat]),
            np.asarray([r[2] for r in flat], np.int32),
            skippable=[r[3] for r in flat],
            driver_cand=np.stack(cands),
            domain=np.stack(doms),
            commit=commit,
            reset=reset,
        ),
        real_row_of,
    )


@pytest.mark.parametrize("fill", ["tightly-pack", "az-aware-tightly-pack"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_segmented_scan_matches_per_segment_solves(fill, seed):
    """The WINDOWING property: a multi-segment scan == solving each segment
    as its own ONE-segment window against the threaded base availability
    (segments are independent requests given the committed base)."""
    rng = np.random.default_rng(seed)
    c = random_cluster(rng, 32)
    n = 32
    segments = _random_segments(rng, 5, n)
    apps, real_row_of = _segment_batch(segments, n)
    got = batched_fifo_pack(c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES)

    base = np.asarray(c.available).copy()
    for s_idx, seg in enumerate(segments):
        rows = list(seg["rows"])
        sub, sub_real = _segment_batch([seg], n)
        ci = dataclasses.replace(c, available=base.astype(np.int32))
        want = batched_fifo_pack(ci, sub, fill=fill, emax=EMAX, num_zones=NUM_ZONES)
        last = sub_real[0]
        real = real_row_of[s_idx]
        assert bool(got.admitted[real]) == bool(want.admitted[last]), (
            f"segment {s_idx} admitted"
        )
        assert int(got.driver_node[real]) == int(want.driver_node[last]), (
            f"segment {s_idx} driver"
        )
        np.testing.assert_array_equal(
            np.asarray(got.executor_nodes[real]),
            np.asarray(want.executor_nodes[last]),
            err_msg=f"segment {s_idx} executors",
        )
        if bool(want.admitted[last]):
            drv = int(want.driver_node[last])
            base[drv] -= np.asarray(rows[-1][0])
            for e in np.asarray(want.executor_nodes[last]):
                if e >= 0:
                    base[e] -= np.asarray(rows[-1][1])
    live = np.asarray(c.valid)
    np.testing.assert_array_equal(
        np.asarray(got.available_after)[live], base[live]
    )


@pytest.mark.parametrize("seed", [0, 3, 9])
def test_segment_semantics_match_reference_greedy(seed):
    """Within a segment: orders are computed ONCE from the segment-start
    availability and reused for every row (the reference sorts once per
    request, resource.go:299, and fitEarlierDrivers reuses the orders while
    only availability mutates). Oracle: greedy fixed-order packing."""
    from tests import greedy_oracle as G

    rng = np.random.default_rng(seed)
    c = random_cluster(rng, 24)
    n = 24
    segments = _random_segments(rng, 4, n)
    apps, real_row_of = _segment_batch(segments, n)
    got = batched_fifo_pack(
        c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
    )

    base = np.asarray(c.available).astype(np.int64).copy()
    valid = np.asarray(c.valid)
    zone = np.asarray(c.zone_id)
    names = np.asarray(c.name_rank)
    row0 = 0
    for s_idx, seg in enumerate(segments):
        dom = seg["dom"] & valid
        d_elig = dom & seg["cand"]
        e_elig = dom & ~np.asarray(c.unschedulable) & np.asarray(c.ready)
        # Orders from the SEGMENT-START availability, fixed for the segment.
        d_order = G.greedy_priority_order(
            base, zone, names, d_elig, domain=dom,
            label_rank=np.asarray(c.label_rank_driver),
        )
        e_order = G.greedy_priority_order(
            base, zone, names, e_elig, domain=dom,
            label_rank=np.asarray(c.label_rank_executor),
        )
        avail = base.copy()
        blocked = False
        for j, row in enumerate(seg["rows"]):
            flat_j = row0 + j
            dreq = np.asarray(row[0], np.int64)
            ereq = np.asarray(row[1], np.int64)
            count = int(min(row[2], EMAX))
            drv, execs, ok, _ = G.greedy_spark_bin_pack(
                avail, dreq, ereq, count, d_order, e_order, "tightly-pack"
            )
            packed = ok and int(row[2]) <= EMAX
            admitted = packed and not blocked
            assert bool(got.packed[flat_j]) == packed, (s_idx, j)
            assert bool(got.admitted[flat_j]) == admitted, (s_idx, j)
            if admitted:
                assert int(got.driver_node[flat_j]) == drv, (s_idx, j)
                got_execs = [
                    int(x) for x in np.asarray(got.executor_nodes[flat_j]) if x >= 0
                ]
                assert got_execs == list(execs), (s_idx, j)
                avail[drv] -= dreq
                for nd in execs:
                    avail[nd] -= ereq
                if j == len(seg["rows"]) - 1:  # the committing request row
                    base[drv] -= dreq
                    for nd in execs:
                        base[nd] -= ereq
            if not packed and not row[3]:
                blocked = True
        row0 += len(seg["rows"])


def test_segmented_sharded_matches_unsharded():
    """Serving windows survive GSPMD node-axis sharding: the segmented scan
    (per-segment sorts via lax.cond, base threading, commit/reset rows)
    produces identical decisions on an 8-device virtual mesh."""
    from spark_scheduler_tpu.parallel import make_solver_mesh, sharded_fifo_pack

    rng = np.random.default_rng(21)
    c = random_cluster(rng, 64)  # divisible by the 8-device "nodes" axis
    segments = _random_segments(rng, 4, 64)
    apps, _ = _segment_batch(segments, 64)
    mesh = make_solver_mesh()
    want = batched_fifo_pack(
        c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
    )
    got = sharded_fifo_pack(
        mesh, c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
    )
    np.testing.assert_array_equal(
        np.asarray(got.driver_node), np.asarray(want.driver_node)
    )
    np.testing.assert_array_equal(
        np.asarray(got.executor_nodes), np.asarray(want.executor_nodes)
    )
    np.testing.assert_array_equal(np.asarray(got.admitted), np.asarray(want.admitted))
    np.testing.assert_array_equal(
        np.asarray(got.available_after), np.asarray(want.available_after)
    )


# ----------------------------------------------------------------- extender


def _make_harness(strategy, fifo, n_nodes, zones=2):
    h = Harness(binpack_algo=strategy, fifo=fifo)
    h.add_nodes(
        *[new_node(f"n{i}", zone=f"zone{i % zones}") for i in range(n_nodes)]
    )
    return h


@pytest.mark.parametrize("strategy", ["tightly-pack", "az-aware-tightly-pack"])
@pytest.mark.parametrize("fifo", [True, False])
def test_predicate_batch_matches_sequential(strategy, fifo):
    """predicate_batch on a window of concurrent driver requests ==
    predicate() one at a time in the same order, including failures (the
    cluster is sized so later gangs do not fit)."""
    pods_sets = [static_allocation_spark_pods(f"w-{strategy}-{fifo}-{i}", 4) for i in range(6)]
    drivers = [ps[0] for ps in pods_sets]
    names = [f"n{i}" for i in range(6)]

    h_seq = _make_harness(strategy, fifo, 6)
    seq_drivers = copy.deepcopy(drivers)
    for d in seq_drivers:
        h_seq.add_pods(d)
    seq_results = [
        h_seq.extender.predicate(ExtenderArgs(pod=d, node_names=list(names)))
        for d in seq_drivers
    ]

    h_win = _make_harness(strategy, fifo, 6)
    win_drivers = copy.deepcopy(drivers)
    for d in win_drivers:
        h_win.add_pods(d)
    win_results = h_win.extender.predicate_batch(
        [ExtenderArgs(pod=d, node_names=list(names)) for d in win_drivers]
    )

    assert len(seq_results) == len(win_results)
    for i, (s, w) in enumerate(zip(seq_results, win_results)):
        assert s.outcome == w.outcome, f"request {i}: {s.outcome} != {w.outcome}"
        assert s.node_names == w.node_names, f"request {i} node"
    # Reservation state (executor placements) must also match.
    for d in drivers:
        app_id = d.labels["spark-app-id"]
        rr_s = h_seq.get_reservation(d.namespace, app_id)
        rr_w = h_win.get_reservation(d.namespace, app_id)
        assert (rr_s is None) == (rr_w is None), app_id
        if rr_s is not None:
            assert {
                k: (v.node) for k, v in rr_s.spec.reservations.items()
            } == {k: (v.node) for k, v in rr_w.spec.reservations.items()}, app_id


def test_predicate_batch_mixed_roles_and_idempotent_retry():
    """A window mixing an already-reserved driver (idempotent retry), fresh
    drivers, an executor of a reserved app, and a non-spark pod."""
    h = _make_harness("tightly-pack", True, 8)
    names = [f"n{i}" for i in range(8)]

    first = static_allocation_spark_pods("mix-first", 2)
    h.schedule(first[0], names)  # reserve app mix-first

    fresh = [static_allocation_spark_pods(f"mix-{i}", 2) for i in range(2)]
    from spark_scheduler_tpu.models.kube import Container, Pod
    from spark_scheduler_tpu.models.resources import Resources

    non_spark = Pod(
        name="plain-pod",
        namespace="namespace",
        containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
    )
    batch = [
        ExtenderArgs(pod=first[0], node_names=list(names)),  # retry
        ExtenderArgs(pod=fresh[0][0], node_names=list(names)),
        ExtenderArgs(pod=first[1], node_names=list(names)),  # executor
        ExtenderArgs(pod=fresh[1][0], node_names=list(names)),
        ExtenderArgs(pod=non_spark, node_names=list(names)),
    ]
    for args in batch:
        h.add_pods(args.pod)
    results = h.extender.predicate_batch(batch)
    assert results[0].outcome == "success" and results[0].node_names
    assert results[1].outcome == "success"
    # executor binds onto one of mix-first's unbound reservation nodes
    assert results[2].outcome in ("success", "success-already-bound")
    assert results[3].outcome == "success"
    assert results[4].outcome == "failure-non-spark-pod"
    # retry returned the original reserved node
    rr = h.get_reservation("namespace", "mix-first")
    assert results[0].node_names[0] == rr.spec.reservations["driver"].node


def test_predicate_batch_duplicate_driver_submission():
    """The same driver pod submitted twice in one window (client retry):
    both answers must name the ONE reserved node, exactly as solo
    serialization's idempotent-retry branch would (resource.go:273-286)."""
    h = _make_harness("tightly-pack", True, 8)
    names = [f"n{i}" for i in range(8)]
    driver = static_allocation_spark_pods("dup-app", 2)[0]
    h.add_pods(driver)
    results = h.extender.predicate_batch(
        [
            ExtenderArgs(pod=driver, node_names=list(names)),
            ExtenderArgs(pod=copy.deepcopy(driver), node_names=list(names)),
            ExtenderArgs(pod=copy.deepcopy(driver), node_names=list(names)),
        ]
    )
    assert all(r.outcome == "success" for r in results)
    rr = h.get_reservation("namespace", "dup-app")
    reserved = rr.spec.reservations["driver"].node
    assert all(r.node_names == [reserved] for r in results)


def test_predicate_batch_fifo_blocking_window():
    """A window where an impossible earlier gang blocks later ones exactly
    as sequential FIFO would (resource.go:241-249)."""
    huge = static_allocation_spark_pods("huge", 500)[0]
    small = static_allocation_spark_pods("small", 1)[0]
    names = [f"n{i}" for i in range(4)]

    h_seq = _make_harness("tightly-pack", True, 4)
    h_seq.add_pods(copy.deepcopy(huge), copy.deepcopy(small))
    seq = [
        h_seq.extender.predicate(ExtenderArgs(pod=p, node_names=list(names)))
        for p in (copy.deepcopy(huge), copy.deepcopy(small))
    ]
    h_win = _make_harness("tightly-pack", True, 4)
    h_win.add_pods(copy.deepcopy(huge), copy.deepcopy(small))
    win = h_win.extender.predicate_batch(
        [
            ExtenderArgs(pod=copy.deepcopy(huge), node_names=list(names)),
            ExtenderArgs(pod=copy.deepcopy(small), node_names=list(names)),
        ]
    )
    assert [r.outcome for r in win] == [r.outcome for r in seq]
    assert win[0].outcome == "failure-fit"
    assert win[1].outcome == "failure-earlier-driver"


# ------------------------------------------------------------------- server


def test_http_concurrent_requests_are_batched():
    """Concurrent POST /predicates calls coalesce into windows (>1 request
    per solve), every gang lands with a consistent reservation state, and
    the window-size histogram reaches the metric registry."""
    from spark_scheduler_tpu.metrics.registry import MetricRegistry
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s

    registry = MetricRegistry()
    h = _make_harness("tightly-pack", True, 24)
    names = [f"n{i}" for i in range(24)]
    server = SchedulerHTTPServer(h.app, registry=registry, host="127.0.0.1", port=0)
    server.start()
    n_clients = 12
    results = [None] * n_clients
    errors = []

    def run_client(i):
        try:
            pods = static_allocation_spark_pods(f"conc-{i}", 2)
            h.backend.add_pod(pods[0])
            conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
            body = json.dumps(
                {"Pod": pod_to_k8s(pods[0]), "NodeNames": names}
            ).encode()
            conn.request("POST", "/predicates", body=body)
            results[i] = json.loads(conn.getresponse().read())
            conn.close()
        except Exception as exc:  # surface in the main thread
            errors.append(exc)

    try:
        threads = [
            threading.Thread(target=run_client, args=(i,)) for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        for i, r in enumerate(results):
            assert r and r.get("NodeNames"), (i, r)
        stats = server.batcher.stats()
        assert stats["requests_served"] == n_clients
        # every app got its gang reserved
        for i in range(n_clients):
            rr = h.get_reservation("namespace", f"conc-{i}")
            assert rr is not None and len(rr.spec.reservations) == 3
        # window sizes landed in the registry histogram
        snap = registry.snapshot()
        hist = snap.get("foundry.spark.scheduler.predicate.window")
        assert hist and hist[0].get("count", 0) >= 1, snap
    finally:
        server.stop()


# ------------------------------------------------- batched executor ladder


def _exec_equivalence(build_scenario, strategy="tightly-pack", n_nodes=8,
                      same_az_da=False):
    """Run `build_scenario(h) -> list[ExtenderArgs]` on two identical
    harnesses; serve the returned executor window via predicate_batch on
    one and via solo predicate() in the same order on the other; assert
    outcomes, nodes, and reservation state match."""
    hs = []
    for _ in range(2):
        h = Harness(
            binpack_algo=strategy,
            fifo=True,
            same_az_dynamic_allocation=same_az_da,
        )
        h.add_nodes(
            *[new_node(f"n{i}", zone=f"zone{i % 2}") for i in range(n_nodes)]
        )
        hs.append(h)
    h_win, h_seq = hs
    win_args = build_scenario(h_win)
    seq_args = build_scenario(h_seq)
    win_results = h_win.extender.predicate_batch(win_args)
    seq_results = [h_seq.extender.predicate(a) for a in seq_args]
    assert len(win_results) == len(seq_results)
    for k, (w, s) in enumerate(zip(win_results, seq_results)):
        assert w.outcome == s.outcome, f"request {k}: {w.outcome} != {s.outcome}"
        assert w.node_names == s.node_names, f"request {k} node"
    apps = {
        (a.pod.namespace, a.pod.labels.get("spark-app-id", ""))
        for a in win_args
    }
    for ns, app_id in apps:
        rr_w = h_win.get_reservation(ns, app_id)
        rr_s = h_seq.get_reservation(ns, app_id)
        assert (rr_w is None) == (rr_s is None), app_id
        if rr_w is not None:
            assert {
                k: v.node for k, v in rr_w.spec.reservations.items()
            } == {k: v.node for k, v in rr_s.spec.reservations.items()}, app_id
            assert rr_w.status.pods == rr_s.status.pods, app_id
    return h_win, h_seq


def test_executor_window_binds_match_sequential():
    """A window of executors binding onto their app's unbound reservations
    (the rung-2 hot path) + one over-count straggler -> failure-unbound."""
    names = [f"n{i}" for i in range(8)]

    def scenario(h):
        pods = static_allocation_spark_pods("xw-app", 4)
        h.schedule(pods[0], names)  # driver reserves 4 executor slots
        for p in pods[1:]:
            h.add_pods(p)
        extra = static_allocation_spark_pods("xw-app", 5)[5]
        h.add_pods(extra)
        return [
            ExtenderArgs(pod=p, node_names=list(names))
            for p in pods[1:] + [extra]
        ]

    _exec_equivalence(scenario)


def test_executor_window_reschedule_group_matches_sequential():
    """Executors whose reserved nodes are NOT offered (kube-scheduler
    filtered them) reschedule via ONE grouped solve; decisions must match
    solving them one at a time."""
    def scenario(h):
        pods = static_allocation_spark_pods("xr-app", 3)
        h.schedule(pods[0], [f"n{i}" for i in range(4)])  # reserve on n0-n3
        for p in pods[1:]:
            h.add_pods(p)
        # Offer ONLY nodes outside the reservation footprint.
        offered = ["n4", "n5", "n6", "n7"]
        return [
            ExtenderArgs(pod=p, node_names=list(offered)) for p in pods[1:]
        ]

    h_win, _ = _exec_equivalence(scenario)
    # The grouped path actually rescheduled (not bound to original slots).
    rr = h_win.get_reservation("namespace", "xr-app")
    rescheduled_nodes = {
        v.node for k, v in rr.spec.reservations.items() if k != "driver"
    }
    assert rescheduled_nodes <= {"n4", "n5", "n6", "n7"}


def test_executor_window_dynamic_allocation_extras_match_sequential():
    """Dynamic-allocation window: min executors bind hard slots, extras get
    soft reservations, over-max fails — all in one window."""
    from spark_scheduler_tpu.testing.harness import (
        dynamic_allocation_spark_pods,
    )

    names = [f"n{i}" for i in range(8)]

    def scenario(h):
        pods = dynamic_allocation_spark_pods("xd-app", 2, 4)
        h.schedule(pods[0], names)  # 2 hard slots + up to 2 soft
        execs = pods[1:] + [dynamic_allocation_spark_pods("xd-app", 2, 5)[5]]
        for p in execs:
            h.add_pods(p)
        return [ExtenderArgs(pod=p, node_names=list(names)) for p in execs]

    h_win, h_seq = _exec_equivalence(scenario)
    for h in (h_win, h_seq):
        sr, ok = h.app.soft_store.get_soft_reservation("xd-app")
        assert ok and len(sr.reservations) == 2, sr.reservations if ok else ok


def test_executor_window_mixed_apps_interleaved():
    """Executors of several apps interleaved in one window group per app
    without cross-talk."""
    names = [f"n{i}" for i in range(8)]

    def scenario(h):
        args = []
        pods_by_app = {}
        for a in range(3):
            pods = static_allocation_spark_pods(f"xm-{a}", 2)
            h.schedule(pods[0], names)
            pods_by_app[a] = pods
            for p in pods[1:]:
                h.add_pods(p)
        for k in range(2):
            for a in range(3):
                args.append(
                    ExtenderArgs(
                        pod=pods_by_app[a][1 + k], node_names=list(names)
                    )
                )
        return args

    _exec_equivalence(scenario)


def test_executor_window_contention_preserves_arrival_order():
    """Under capacity contention, reschedule stragglers must win spots in
    ARRIVAL order across apps — window [a1, b1, a2] with room for exactly
    two executors gives the spots to a1 and b1, like serial serving."""
    names = [f"n{i}" for i in range(8)]

    def scenario(h):
        # Fill n7 so exactly 2 executors (1cpu/1Gi each) still fit.
        filler = static_allocation_spark_pods("xc-filler", 5)
        h.schedule(filler[0], ["n7"])
        for p in filler[1:]:
            h.schedule(p, ["n7"])
        a = static_allocation_spark_pods("xc-a", 2)
        b = static_allocation_spark_pods("xc-b", 1)
        h.schedule(a[0], names[:4])
        h.schedule(b[0], names[:4])
        for p in a[1:] + b[1:]:
            h.add_pods(p)
        # Offer ONLY the nearly-full node: every executor needs a reschedule.
        return [
            ExtenderArgs(pod=a[1], node_names=["n7"]),
            ExtenderArgs(pod=b[1], node_names=["n7"]),
            ExtenderArgs(pod=a[2], node_names=["n7"]),
        ]

    h_win, _ = _exec_equivalence(scenario)


def test_executor_window_duplicate_submission_single_spot():
    """The same executor pod twice in one window (client retry coalesced):
    one reschedule, the retry resolves already-bound, ONE spot consumed."""
    names = [f"n{i}" for i in range(8)]

    def scenario(h):
        pods = static_allocation_spark_pods("xdup-app", 1)
        h.schedule(pods[0], names[:2])  # reserve on n0/n1
        h.add_pods(pods[1])
        offered = ["n4", "n5"]
        return [
            ExtenderArgs(pod=pods[1], node_names=list(offered)),
            ExtenderArgs(pod=pods[1], node_names=list(offered)),
        ]

    h_win, h_seq = _exec_equivalence(scenario)
    for h in (h_win, h_seq):
        rr = h.get_reservation("namespace", "xdup-app")
        bound = [
            k for k, v in rr.status.pods.items() if v == "xdup-app-exec-1"
        ]
        assert len(bound) == 1, rr.status.pods


def test_executor_window_driverless_reschedule_fails_internal():
    """Reschedule context failure (driver pod gone) fails ALL the app's
    spot-seeking executors failure-internal — including one classified
    no-spots by the pre-consumed budget — matching serial serving."""
    names = [f"n{i}" for i in range(8)]

    def scenario(h):
        pods = static_allocation_spark_pods("xgone-app", 1)
        h.schedule(pods[0], names[:2])
        h.add_pods(pods[1])
        dup = static_allocation_spark_pods("xgone-app", 2)[2]
        h.add_pods(dup)
        h.delete_pod(pods[0])  # driver vanishes
        offered = ["n4", "n5"]
        return [
            ExtenderArgs(pod=pods[1], node_names=list(offered)),
            ExtenderArgs(pod=dup, node_names=list(offered)),
        ]

    _exec_equivalence(scenario)


def test_fetch_pool_is_shared_across_solvers():
    """Regression: every solver used to lazily create its OWN 4-worker
    fetch pool, and harness-style callers (every test, every rebuilt app)
    never close the solver — a full test run accumulated 100+ leaked
    daemon threads and segfaulted in a native thread. The blob-fetch pool
    is process-shared now: N live solvers serving pipelined windows keep
    at most one pool's worth of fetch threads."""
    names = [f"n{i}" for i in range(4)]
    for k in range(6):
        h = Harness("tightly-pack", fifo=False)
        h.add_nodes(*[new_node(n) for n in names])
        pods = static_allocation_spark_pods(f"pool-{k}", 2)
        h.add_pods(pods[0])
        results = h.extender.predicate_batch(
            [ExtenderArgs(pod=pods[0], node_names=list(names))]
        )
        assert results[0].ok
    fetch_threads = [
        t for t in threading.enumerate()
        if t.name.startswith("window-blob-fetch")
    ]
    assert len(fetch_threads) <= 4, [t.name for t in fetch_threads]


# ------------------------------------------------- multi-device engine


def _multi_group_harness(n_groups=4, nodes_per_group=4, **kw):
    h = Harness(binpack_algo="tightly-pack", fifo=True, **kw)
    for g in range(n_groups):
        h.add_nodes(
            *[
                new_node(
                    f"g{g}-n{i}",
                    zone=f"zone{i % 2}",
                    instance_group=f"group-{g}",
                )
                for i in range(nodes_per_group)
            ]
        )
    return h


def _group_window_requests(rng, n_groups, nodes_per_group, n_requests):
    """Random WindowRequests pinned to per-group domains, with FIFO-style
    hypothetical prefix rows, in one interleaved arrival order."""
    from spark_scheduler_tpu.core.solver import WindowRequest
    from spark_scheduler_tpu.models.resources import Resources

    reqs = []
    for k in range(n_requests):
        g = int(rng.integers(0, n_groups))
        names = [f"g{g}-n{i}" for i in range(nodes_per_group)]
        rows = []
        for _ in range(int(rng.integers(0, 3))):  # hypothetical prefix
            rows.append(
                (
                    Resources.from_quantities("1", "1Gi"),
                    Resources.from_quantities("1", "1Gi"),
                    int(rng.integers(1, 4)),
                    bool(rng.random() < 0.5),
                )
            )
        rows.append(
            (
                Resources.from_quantities("1", "1Gi"),
                Resources.from_quantities("1", "1Gi"),
                int(rng.integers(1, 4)),
                False,
            )
        )
        reqs.append(
            WindowRequest(
                rows=rows,
                driver_candidate_names=list(names),
                domain_node_names=list(names),
            )
        )
    return reqs


@pytest.mark.parametrize(
    "engine_kw",
    [
        {"solver_device_pool": 4},  # pooled: partitioned across devices
        {"solver_mesh_groups": 1, "solver_mesh_node_shards": 4},  # sharded
    ],
    ids=["device-pool", "sharded-mesh"],
)
def test_multi_device_window_decisions_byte_identical(engine_kw):
    """THE engine equivalence pin: the same window stream solved through
    the device pool (disjoint-domain partitions solving concurrently on
    the 8-device virtual mesh) and through the GSPMD sharded mode produces
    WindowDecisions BYTE-IDENTICAL to the single-device serving path —
    every node name, admitted/blocked bit, and efficiency float. Two
    overlapped windows exercise the threaded committed base + priors."""
    decisions_by_mode = []
    for kw in ({}, engine_kw):
        h = _multi_group_harness(**kw)
        solver = h.app.solver
        nodes = h.backend.list_nodes()
        rng = np.random.default_rng(7)
        w1 = _group_window_requests(rng, 4, 4, 10)
        w2 = _group_window_requests(rng, 4, 4, 10)
        t1 = solver.build_tensors_pipelined(nodes, {}, {})
        h1 = solver.pack_window_dispatch("tightly-pack", t1, w1)
        # Overlap: dispatch w2 before fetching w1 (the pipelined loop).
        t2 = solver.build_tensors_pipelined(nodes, {}, {})
        h2 = solver.pack_window_dispatch("tightly-pack", t2, w2)
        d1 = solver.pack_window_fetch(h1)
        d2 = solver.pack_window_fetch(h2)
        decisions_by_mode.append(d1 + d2)
    single, multi = decisions_by_mode
    assert single == multi  # NamedTuple equality: every field, bit for bit


def test_pooled_serving_through_extender_matches_single_device():
    """End-to-end over the extender: a mixed multi-group driver window via
    predicate_batch lands identical outcomes, nodes, and reservation state
    with and without the device pool (windows partition by instance
    group), and pool-mode records attribute each decision to a slot."""
    streams = []
    for kw in ({}, {"solver_device_pool": 4}):
        h = _multi_group_harness(**kw)
        args = []
        for g in range(4):
            for a in range(2):
                pod = static_allocation_spark_pods(
                    f"mdx-{g}-{a}", 2, instance_group=f"group-{g}"
                )[0]
                h.add_pods(pod)
                args.append(
                    ExtenderArgs(
                        pod=pod,
                        node_names=[f"g{g}-n{i}" for i in range(4)],
                    )
                )
        results = h.extender.predicate_batch(args)
        rrs = {
            rr.name: {k: v.node for k, v in rr.spec.reservations.items()}
            for rr in h.backend.list("resourcereservations")
        }
        streams.append(
            ([(r.outcome, tuple(r.node_names)) for r in results], rrs)
        )
        if kw:
            assert h.app.solver.window_path_counts.get("pool", 0) >= 1
            info = h.app.solver.last_solve_info
            assert info["path"] == "pool" and info["partitions"] == 4
            rec = h.app.recorder.query(role="driver", limit=1)[0]
            assert rec["device_id"] and rec["device_id"].startswith("cpu:")
            assert rec["state_upload"] in ("full", "delta", "reuse")
    assert streams[0] == streams[1]


def test_pool_falls_back_whole_window_on_overlapping_domains():
    """Requests whose domains overlap (shared nodes) must NOT partition:
    the window solves whole on one slot and decisions still match the
    single-device path."""
    streams = []
    for kw in ({}, {"solver_device_pool": 2}):
        h = Harness(binpack_algo="tightly-pack", fifo=True, **kw)
        h.add_nodes(*[new_node(f"n{i}") for i in range(8)])
        names = [f"n{i}" for i in range(8)]
        args = []
        for a in range(4):
            pod = static_allocation_spark_pods(f"ovl-{a}", 2)[0]
            h.add_pods(pod)
            args.append(ExtenderArgs(pod=pod, node_names=list(names)))
        results = h.extender.predicate_batch(args)
        streams.append([(r.outcome, tuple(r.node_names)) for r in results])
        if kw:
            assert h.app.solver.last_solve_info["partitions"] == 1
    assert streams[0] == streams[1]


def test_donated_carry_not_reused_after_commit():
    """Buffer donation pin: the pipelined committed base is DONATED into
    the window solve — available_after updates it in place — so the
    consumed carry must be marked deleted and any reuse must raise instead
    of silently reading freed memory. The pipeline itself keeps working
    (it threads available_after forward, never the dead input)."""
    from spark_scheduler_tpu.core.solver import WindowRequest
    from spark_scheduler_tpu.models.resources import Resources

    h = Harness(binpack_algo="tightly-pack", fifo=False)
    h.add_nodes(*[new_node(f"n{i}") for i in range(4)])
    solver = h.app.solver
    nodes = h.backend.list_nodes()
    req = WindowRequest(
        rows=[
            (
                Resources.from_quantities("1", "1Gi"),
                Resources.from_quantities("1", "1Gi"),
                2,
                False,
            )
        ],
        driver_candidate_names=[f"n{i}" for i in range(4)],
    )
    t1 = solver.build_tensors_pipelined(nodes, {}, {})
    carry = t1.available
    handle = solver.pack_window_dispatch("tightly-pack", t1, [req])
    assert carry.is_deleted(), "committed-base carry was copied, not donated"
    with pytest.raises(Exception):
        np.asarray(carry)  # reuse of the donated carry must fail loudly
    decisions = solver.pack_window_fetch(handle)
    assert decisions[0].admitted
    # The pipeline threads the in-place-updated base forward unharmed.
    t2 = solver.build_tensors_pipelined(nodes, {}, {})
    h2 = solver.pack_window_dispatch("tightly-pack", t2, [req])
    assert solver.pack_window_fetch(h2)[0].admitted


def test_solver_close_fails_fast_on_pipelined_dispatch():
    """After close(), a pipelined dispatch must raise instead of enqueuing
    a Future nobody serves (ThreadPoolExecutor-after-shutdown semantics);
    the shared pool itself stays up for other solvers."""
    names = [f"n{i}" for i in range(4)]
    h = Harness("tightly-pack", fifo=False)
    h.add_nodes(*[new_node(n) for n in names])
    pods = static_allocation_spark_pods("pool-close", 2)
    h.add_pods(pods[0])
    h.app.solver.close()
    with pytest.raises(RuntimeError, match="after shutdown"):
        h.extender.predicate_batch(
            [ExtenderArgs(pod=pods[0], node_names=list(names))]
        )
