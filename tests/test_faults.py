"""Unit suite for the fault-tolerance subsystem (ISSUE 9).

Pins the exact contracts the rest of the repo builds on: RetryPolicy's
backoff sequence / jitter bounds / deadline abort / per-attempt timeout,
the circuit breaker's closed -> open -> half-open -> closed discipline,
FaultInjector determinism (same seed => same schedule) and its adapter
seams (backend hook compat, nested install/uninstall, lease wrapper),
the degraded-mode controller, the slot-failure classifier, and the
`async_client_retry_count` back-compat alias.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

import pytest

from spark_scheduler_tpu.faults import (
    AttemptTimeoutError,
    BreakerOpenError,
    CircuitBreaker,
    DegradedModeController,
    DeviceFaultError,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    FaultyLeaseStore,
    InjectedFault,
    RetryDeadlineExceeded,
    RetryPolicy,
    classify_slot_failure,
)
from spark_scheduler_tpu.faults.retry import CLOSED, HALF_OPEN, OPEN


# ---------------------------------------------------------------- RetryPolicy


def test_backoff_sequence_exponential_and_capped():
    p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=1.0)
    assert [p.backoff(i) for i in range(6)] == [
        0.1, 0.2, 0.4, 0.8, 1.0, 1.0
    ]


def test_full_jitter_bounds_and_determinism():
    p = RetryPolicy(base_delay_s=0.5, multiplier=2.0, max_delay_s=8.0)
    draws = [p.delay(i, random.Random(7)) for i in range(20) for _ in range(5)]
    for i in range(20):
        for d in draws[i * 5:(i + 1) * 5]:
            assert 0.0 <= d <= p.backoff(i)
    # Seeded rng => reproducible jitter (the chaos matrix relies on it).
    rng_a, rng_b = random.Random(11), random.Random(11)
    assert [p.delay(i, rng_a) for i in range(10)] == [
        p.delay(i, rng_b) for i in range(10)
    ]


def test_no_jitter_is_deterministic_backoff():
    p = RetryPolicy(jitter="none", base_delay_s=0.25, multiplier=3.0,
                    max_delay_s=10.0)
    assert p.delay(0) == 0.25
    assert p.delay(1) == 0.75
    assert p.delay(2) == 2.25


def test_call_retries_then_succeeds_with_recorded_pauses():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, multiplier=2.0,
                    max_delay_s=10.0, jitter="none")
    attempts = {"n": 0}
    pauses: list[float] = []
    retries: list[tuple[int, float]] = []

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 4:
            raise ValueError(f"boom {attempts['n']}")
        return "ok"

    out = p.call(
        flaky,
        sleep=pauses.append,
        on_retry=lambda n, exc, pause: retries.append((n, pause)),
    )
    assert out == "ok"
    assert attempts["n"] == 4
    assert pauses == [0.1, 0.2, 0.4]  # exact deterministic ladder
    assert retries == [(1, 0.1), (2, 0.2), (3, 0.4)]


def test_call_exhausts_attempts_and_raises_last_error():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter="none")
    attempts = {"n": 0}

    def always():
        attempts["n"] += 1
        raise ValueError(f"boom {attempts['n']}")

    with pytest.raises(ValueError, match="boom 3"):
        p.call(always, sleep=lambda s: None)
    assert attempts["n"] == 3  # max_attempts counts TOTAL tries


def test_call_retry_on_filters_exception_types():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.0, jitter="none")

    def wrong_type():
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        p.call(wrong_type, retry_on=(ValueError,), sleep=lambda s: None)


def test_deadline_aborts_between_attempts_and_chains_cause():
    # Virtual clock: each attempt "takes" 1s; deadline 2.5s => the third
    # retry pause would cross it.
    now = {"t": 0.0}

    def clock():
        return now["t"]

    def sleep(s):
        now["t"] += s

    def failing():
        now["t"] += 1.0
        raise ConnectionError("down")

    p = RetryPolicy(max_attempts=None, base_delay_s=0.5, multiplier=1.0,
                    max_delay_s=0.5, jitter="none", deadline_s=2.5)
    with pytest.raises(RetryDeadlineExceeded) as ei:
        p.call(failing, clock=clock, sleep=sleep)
    assert isinstance(ei.value.__cause__, ConnectionError)
    # Never slept past the deadline: the abort happens BEFORE the pause.
    assert now["t"] <= 2.5 + 1.0


def test_attempt_timeout_abandons_and_retries():
    p = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter="none",
                    attempt_timeout_s=0.05)
    release = threading.Event()
    calls = {"n": 0}

    def slow_then_fast():
        calls["n"] += 1
        if calls["n"] == 1:
            release.wait(5.0)  # hangs well past the per-attempt timeout
            return "late"
        return "fast"

    try:
        assert p.call(slow_then_fast, sleep=lambda s: None) == "fast"
    finally:
        release.set()
    assert calls["n"] == 2


def test_attempt_timeout_exhaustion_raises_attempt_timeout_error():
    p = RetryPolicy(max_attempts=1, attempt_timeout_s=0.02)
    release = threading.Event()
    try:
        with pytest.raises(AttemptTimeoutError):
            p.call(lambda: release.wait(5.0), sleep=lambda s: None)
    finally:
        release.set()


def test_unbounded_attempts_keep_retrying():
    p = RetryPolicy(max_attempts=None, base_delay_s=0.0, jitter="none")
    attempts = {"n": 0}

    def eventually():
        attempts["n"] += 1
        if attempts["n"] < 50:
            raise OSError("flap")
        return attempts["n"]

    assert p.call(eventually, sleep=lambda s: None) == 50


# ------------------------------------------------------------ CircuitBreaker


def _clocked_breaker(threshold=3, reset=10.0):
    now = {"t": 0.0}
    transitions: list[tuple[str, str]] = []
    b = CircuitBreaker(
        failure_threshold=threshold,
        reset_timeout_s=reset,
        clock=lambda: now["t"],
        on_transition=lambda old, new: transitions.append((old, new)),
        name="test",
    )
    return b, now, transitions


def test_breaker_opens_at_threshold_and_refuses():
    b, now, transitions = _clocked_breaker(threshold=3)
    for _ in range(2):
        assert b.allow()
        b.on_failure()
    assert b.state == CLOSED
    assert b.allow()
    b.on_failure()  # third consecutive failure
    assert b.state == OPEN
    assert not b.allow()
    assert transitions == [(CLOSED, OPEN)]
    assert b.opens == 1


def test_breaker_half_open_probe_success_closes():
    b, now, transitions = _clocked_breaker(threshold=1, reset=10.0)
    b.on_failure()
    assert b.state == OPEN and not b.allow()
    now["t"] = 10.0  # reset window elapsed
    assert b.allow()  # the half-open probe slot
    assert b.state == HALF_OPEN
    assert not b.allow()  # exactly ONE probe at a time
    b.on_success()
    assert b.state == CLOSED
    assert b.allow()
    assert transitions == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                           (HALF_OPEN, CLOSED)]


def test_breaker_half_open_probe_failure_reopens_and_rearms():
    b, now, _ = _clocked_breaker(threshold=1, reset=5.0)
    b.on_failure()
    now["t"] = 5.0
    assert b.allow()
    b.on_failure()  # the probe failed
    assert b.state == OPEN
    assert not b.allow()  # window re-armed from the re-open
    now["t"] = 10.0
    assert b.allow()  # next probe window
    assert b.opens == 2


def test_breaker_success_resets_failure_streak():
    b, _, _ = _clocked_breaker(threshold=3)
    b.on_failure()
    b.on_failure()
    b.on_success()
    b.on_failure()
    b.on_failure()
    assert b.state == CLOSED  # streak restarted after the success


def test_policy_call_with_breaker_raises_breaker_open():
    b, now, _ = _clocked_breaker(threshold=2, reset=30.0)
    p = RetryPolicy(max_attempts=5, base_delay_s=0.0, jitter="none")
    calls = {"n": 0}

    def failing():
        calls["n"] += 1
        raise ConnectionError("down")

    # The ladder feeds the breaker; once it opens mid-ladder the next
    # attempt is refused without touching fn.
    with pytest.raises(BreakerOpenError):
        p.call(failing, breaker=b, sleep=lambda s: None)
    assert calls["n"] == 2  # threshold, not the full attempt budget
    assert b.state == OPEN


# -------------------------------------------------------------- FaultInjector


def _plan(seed=1, **spec_kw):
    return FaultPlan(seed=seed, specs=[FaultSpec(**spec_kw)])


def test_injector_same_seed_same_schedule():
    plan = FaultPlan(
        seed=42,
        name="replay",
        specs=[
            FaultSpec(surface="backend.*", mode="error", p=0.3),
            FaultSpec(surface="device.*", mode="error", p=0.5),
        ],
    )
    surfaces = (
        ["backend.resourcereservations.create"] * 10
        + ["device.dispatch"] * 10
        + ["backend.demands.update"] * 10
    )

    def run():
        inj = FaultInjector(plan)
        for s in surfaces:
            try:
                inj.fire(s)
            except InjectedFault:
                pass
        return inj.schedule()

    first, second = run(), run()
    assert first == second
    assert first  # the plan actually fired something
    # A different seed moves the p-draws.
    other = FaultInjector(FaultPlan(seed=43, specs=plan.specs))
    for s in surfaces:
        try:
            other.fire(s)
        except InjectedFault:
            pass
    assert other.schedule() != first


def test_injector_at_every_limit_partition_triggers():
    at = FaultInjector(_plan(surface="a.*", at=[1, 3]))
    fired = []
    for i in range(5):
        try:
            at.fire("a.x")
        except InjectedFault:
            fired.append(i)
    assert fired == [1, 3]

    every = FaultInjector(_plan(surface="a.*", every=3, limit=2))
    fired = []
    for i in range(10):
        try:
            every.fire("a.x")
        except InjectedFault:
            fired.append(i)
    assert fired == [0, 3]  # every 3rd, capped by limit=2

    part = FaultInjector(_plan(surface="a.*", mode="partition", start=2,
                               length=3))
    fired = []
    for i in range(8):
        try:
            part.fire("a.x")
        except InjectedFault:
            fired.append(i)
    assert fired == [2, 3, 4]  # one contiguous outage window


def test_injector_latency_mode_sleeps_injected_duration():
    slept: list[float] = []
    inj = FaultInjector(
        _plan(surface="backend.*", mode="latency", latency_ms=25.0),
        sleep=slept.append,
    )
    inj.fire("backend.nodes.update")  # latency never raises
    assert slept == [0.025]
    assert inj.schedule()[0][3] == "latency"


def test_injector_device_surface_raises_slot_fatal():
    inj = FaultInjector(_plan(surface="device.*", limit=1))
    with pytest.raises(DeviceFaultError) as ei:
        inj.fire("device.d2h")
    assert classify_slot_failure(ei.value)
    # Non-device surfaces raise the plain InjectedFault.
    inj2 = FaultInjector(_plan(surface="wal.*", limit=1))
    with pytest.raises(InjectedFault) as ei2:
        inj2.fire("wal.append")
    assert not isinstance(ei2.value, DeviceFaultError)


def test_backend_hook_returns_exception_instead_of_raising():
    """The ad-hoc `backend.fault_injector` contract this subsumes: the
    hook RETURNS the exception (the backend raises it under its lock)."""
    inj = FaultInjector(_plan(surface="backend.resourcereservations.create",
                              limit=1))
    hook = inj.backend_hook()
    exc = hook("resourcereservations", "create", object())
    assert isinstance(exc, InjectedFault)
    assert hook("resourcereservations", "create", object()) is None  # limit
    assert hook("pods", "update", object()) is None  # surface mismatch


def test_install_backend_nests_and_uninstall_restores():
    class StubBackend:
        fault_injector = None

    b = StubBackend()
    prior_calls = []
    b.fault_injector = lambda *a: prior_calls.append(a) or None

    outer = FaultInjector(_plan(surface="backend.*", p=0.0))
    outer.install_backend(b)
    inner = FaultInjector(_plan(surface="backend.*", p=0.0))
    with inner:
        inner.install_backend(b)
        assert b.fault_injector is not None
        b.fault_injector("pods", "create", None)
        assert inner.counts.get("backend.pods.create") == 1
    # Inner uninstall hands the seam back to the OUTER injector.
    b.fault_injector("pods", "create", None)
    assert outer.counts.get("backend.pods.create") == 1
    outer.uninstall()
    # ... and outer hands it back to the original hook.
    b.fault_injector("pods", "create", None)
    assert len(prior_calls) == 1


def test_device_shim_composes_with_inner_and_uninstall_restores():
    from spark_scheduler_tpu.core import solver as solver_mod

    prior = solver_mod._DEVICE_SHIM
    inner_events: list[str] = []
    inj = FaultInjector(_plan(surface="device.dispatch", at=[0]))
    try:
        inj.install_device(inner=inner_events.append)
        with pytest.raises(DeviceFaultError):
            solver_mod._shim("dispatch")
        solver_mod._shim("h2d")  # surface mismatch: delegates only
        assert inner_events == ["h2d"]  # the raising fire skipped delegation
        assert inj.counts == {"device.dispatch": 1, "device.h2d": 1}
    finally:
        inj.uninstall()
    assert solver_mod._DEVICE_SHIM is prior


def test_faulty_lease_store_fires_lease_surfaces():
    class StubStore:
        def read(self):
            return "record"

        def compare_and_swap(self, expect, record):
            return True

    inj = FaultInjector(_plan(surface="lease.write", limit=1))
    store = FaultyLeaseStore(StubStore(), inj)
    assert store.read() == "record"
    with pytest.raises(InjectedFault):
        store.compare_and_swap(None, "r")
    assert store.compare_and_swap(None, "r")  # limit exhausted
    assert inj.counts == {"lease.read": 1, "lease.write": 2}


def test_plan_from_dict_round_trip():
    plan = FaultPlan.from_dict(
        {
            "seed": 9,
            "name": "matrix-backend",
            "specs": [
                {"surface": "backend.*", "mode": "latency",
                 "latency-ms": 5.0, "p": 0.2},
                {"surface": "wal.append", "at": [4]},
            ],
        }
    )
    assert plan.seed == 9 and plan.name == "matrix-backend"
    assert plan.specs[0].latency_ms == 5.0 and plan.specs[0].p == 0.2
    assert plan.specs[1].at == [4]


# ---------------------------------------------------------------- degraded


def test_degraded_controller_engage_clear_and_counts():
    now = {"t": 100.0}
    changes: list[bool] = []
    d = DegradedModeController(
        policy="greedy", clock=lambda: now["t"], on_change=changes.append
    )
    assert not d.active and not d.sheds
    d.engage("slot died")
    d.engage("slot died again")  # no double-count while active
    assert d.active and d.engagements == 1 and d.since == 100.0
    d.on_fallback_decision(3)
    d.clear()
    d.clear()
    assert not d.active
    assert changes == [True, False]
    snap = d.snapshot()
    assert snap["engagements"] == 1 and snap["fallback_decisions"] == 3
    assert snap["since"] is None


def test_degraded_controller_rejects_unknown_policy():
    with pytest.raises(ValueError, match="degraded-mode policy"):
        DegradedModeController(policy="panic")


def test_classify_slot_failure_taxonomy():
    class XlaRuntimeError(RuntimeError):
        pass

    assert classify_slot_failure(DeviceFaultError("device.d2h"))
    assert classify_slot_failure(ConnectionError("tunnel drop"))
    assert classify_slot_failure(TimeoutError("rpc deadline"))
    assert classify_slot_failure(OSError("broken pipe"))
    assert classify_slot_failure(XlaRuntimeError("device failed"))
    assert not classify_slot_failure(TypeError("programming error"))
    assert not classify_slot_failure(ValueError("bad shape"))
    assert not classify_slot_failure(InjectedFault("backend.pods.create"))


# ----------------------------------------------------- config + back-compat


def test_install_config_parses_retry_and_degraded_keys():
    from spark_scheduler_tpu.server.config import InstallConfig

    cfg = InstallConfig.from_dict(
        {
            "server": {
                "degraded-mode": "shed",
                "degraded-retry-after": "10s",
            },
            "solver": {"quarantine-probe": "2s"},
            "retry": {
                "base-delay": "50ms",
                "multiplier": 3.0,
                "max-delay": "4s",
                "breaker-failure-threshold": 4,
                "breaker-reset-timeout": "8s",
            },
            "async-client-retry-count": 7,
        }
    )
    assert cfg.degraded_mode == "shed"
    assert cfg.degraded_retry_after_s == 10.0
    assert cfg.quarantine_probe_s == 2.0
    assert cfg.retry_base_delay_s == 0.05
    assert cfg.retry_multiplier == 3.0
    assert cfg.retry_max_delay_s == 4.0
    assert cfg.breaker_failure_threshold == 4
    assert cfg.breaker_reset_timeout_s == 8.0
    assert cfg.async_client_retry_count == 7


def test_install_config_defaults_keep_greedy_policy():
    from spark_scheduler_tpu.server.config import InstallConfig

    cfg = InstallConfig.from_dict({})
    assert cfg.degraded_mode == "greedy"
    assert cfg.breaker_failure_threshold == 8


def test_async_client_retry_count_alias_still_bounds_requeues():
    """`async-client-retry-count` keeps working as the attempt budget:
    a write failing more than `count` times is dropped, exactly as
    before ISSUE 9 — the policy only supplies the DELAYS."""
    from spark_scheduler_tpu.models.reservations import (
        Reservation,
        ReservationSpec,
        ReservationStatus,
        ResourceReservation,
    )
    from spark_scheduler_tpu.models.resources import Resources
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.store.cache import ResourceReservationCache

    backend = InMemoryBackend()
    cache = ResourceReservationCache(
        backend, max_retries=2,
        retry_policy=RetryPolicy(base_delay_s=0.0, jitter="none"),
    )
    client = cache.client
    assert client._max_retries == 2
    dropped: list = []
    client._on_error = lambda req, exc: dropped.append((req, exc))
    rr = ResourceReservation(
        name="alias-app", namespace="ns", labels={}, owner_pod_uid="uid",
        spec=ReservationSpec(
            {"driver": Reservation("n0", Resources.from_quantities("1", "1Gi"))}
        ),
        status=ReservationStatus({"driver": "alias-app-driver"}),
    )
    # Every backend write fails: the request retries its bounded budget
    # then drops with the metric — never an unbounded loop.
    inj = FaultInjector(_plan(surface="backend.resourcereservations.*",
                              mode="error"))
    with inj:
        inj.install_backend(backend)
        cache.create(rr)
        client.drain_sync()
    m = client.metrics
    assert m.retries == 2  # exactly the alias budget
    assert m.dropped == 1  # then dropped — local store keeps the intent
    assert len(dropped) == 1
    assert backend.get("resourcereservations", "ns", "alias-app") is None
    # The injector gone, the same write path works again (the drop lost
    # this request only; nothing is wedged).
    rr2 = dataclasses.replace(rr, name="alias-app-2")
    cache.create(rr2)
    client.drain_sync()
    assert backend.get("resourcereservations", "ns", "alias-app-2") is not None


def _breaker_client(breaker):
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.store.cache import ResourceReservationCache

    backend = InMemoryBackend()
    cache = ResourceReservationCache(
        backend, max_retries=2,
        retry_policy=RetryPolicy(base_delay_s=0.0, jitter="none"),
        breaker=breaker,
    )
    return backend, cache, cache.client


def _reservation(name):
    from spark_scheduler_tpu.models.reservations import (
        Reservation,
        ReservationSpec,
        ReservationStatus,
        ResourceReservation,
    )
    from spark_scheduler_tpu.models.resources import Resources

    return ResourceReservation(
        name=name, namespace="ns", labels={}, owner_pod_uid="uid",
        spec=ReservationSpec(
            {"driver": Reservation("n0", Resources.from_quantities("1", "1Gi"))}
        ),
        status=ReservationStatus({"driver": f"{name}-driver"}),
    )


def _pop_one(client):
    for bucket in range(client._queue.num_buckets):
        req = client._queue.pop(bucket, timeout_s=0)
        if req is not None:
            return req
    return None


def test_breaker_refusal_requeues_without_consuming_budget():
    """A write refused by the OPEN breaker is the breaker's state, not the
    request's failure: it requeues with its retry budget INTACT (the 5-step
    ladder exhausts in well under reset_timeout, so consuming budget on
    refusals would drop every write queued while the breaker is open), and
    lands once the backend recovers."""
    b, now, _ = _clocked_breaker(threshold=1, reset=60.0)
    backend, cache, client = _breaker_client(b)
    b.on_failure()  # breaker OPEN
    assert b.state == OPEN
    cache.create(_reservation("refused-app"))
    # Background-worker path: the open breaker refuses, the request
    # requeues at the SAME retry_count, nothing drops.
    for _ in range(10):  # 10 refusals >> the 2-retry alias budget
        req = _pop_one(client)
        assert req is not None and req.retry_count == 0
        client.process(req, allow_backoff=True)
    assert client.metrics.dropped == 0
    assert backend.get("resourcereservations", "ns", "refused-app") is None
    # Reset window passes: the requeued write goes through and closes
    # the breaker — nothing was lost.
    now["t"] += 61.0
    req = _pop_one(client)
    client.process(req, allow_backoff=True)
    assert backend.get("resourcereservations", "ns", "refused-app") is not None
    assert b.state == CLOSED


def test_breaker_half_open_probe_freed_by_namespace_terminating():
    """NamespaceTerminatingError means the backend ANSWERED — a healthy
    dependency refusing one request. It must report success to the breaker:
    swallowing the outcome would leave the half-open probe slot taken
    forever, wedging every later write behind BreakerOpenError."""
    from spark_scheduler_tpu.store.backend import NamespaceTerminatingError

    b, now, _ = _clocked_breaker(threshold=1, reset=10.0)
    backend, cache, client = _breaker_client(b)
    b.on_failure()  # OPEN
    now["t"] += 11.0  # past the reset window: next allow() is the probe
    client.fault_hook = lambda req: (_ for _ in ()).throw(
        NamespaceTerminatingError("ns terminating")
    )
    cache.create(_reservation("terminating-app"))
    req = _pop_one(client)
    client.process(req, allow_backoff=True)
    # Dropped as non-retryable, AND the probe slot released: CLOSED.
    assert client.metrics.dropped == 1
    assert b.state == CLOSED
    client.fault_hook = None
    cache.create(_reservation("after-app"))
    req = _pop_one(client)
    client.process(req, allow_backoff=True)
    assert backend.get("resourcereservations", "ns", "after-app") is not None


def test_build_app_wires_retry_policy_from_config():
    from spark_scheduler_tpu.testing.harness import Harness

    h = Harness(
        binpack_algo="tightly-pack",
        fifo=False,
        async_client_retry_count=3,
        retry_base_delay_s=0.5,
        retry_multiplier=4.0,
        retry_max_delay_s=6.0,
        breaker_failure_threshold=2,
    )
    client = h.app.rr_cache.client
    p = client._retry_policy
    assert p.max_attempts == 4  # count + 1 (total tries)
    assert p.base_delay_s == 0.5 and p.multiplier == 4.0
    assert p.max_delay_s == 6.0
    assert client._breaker is not None
    assert client._breaker.failure_threshold == 2


def test_injector_on_fire_publishes_fault_telemetry():
    """FaultInjector.on_fire -> RetryTelemetry.fault_hook: every fired
    fault lands on foundry.spark.scheduler.faults.injected, tagged by
    surface and action."""
    from spark_scheduler_tpu.metrics import MetricRegistry
    from spark_scheduler_tpu.observability.telemetry import (
        FAULTS_INJECTED,
        RetryTelemetry,
    )

    registry = MetricRegistry()
    tel = RetryTelemetry(registry)
    inj = FaultInjector(
        _plan(surface="backend.*", limit=2), on_fire=tel.fault_hook()
    )
    for _ in range(3):
        try:
            inj.fire("backend.resourcereservations.create")
        except InjectedFault:
            pass
    counter = registry.counter(
        FAULTS_INJECTED,
        surface="backend.resourcereservations.create",
        action="error",
    )
    assert counter.value == 2  # limit capped the third fire
