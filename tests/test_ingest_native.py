"""Cross-lane ingest parity + native framer conformance.

The native ingest lane (`server.ingest: native`) must be INVISIBLE on the
wire: decisions and response bodies byte-identical to the python lane
across {python, native} x {threaded, async}, the binary predicate protocol
equivalent to the JSON schema, the native framer matching the Python
framer's RFC 7230 edges (malformed frames, oversize-body 413 with
keep-alive intact, pipelined in-order responses), and a toolchain-less
host degrading to the python lane with a RuntimeWarning instead of dying.

The native-runtime-dependent tests skip cleanly when g++ is absent; the
pure-Python pieces (binary codec, response-encoder byte-identity,
degrade-on-unavailable) always run.
"""

import json
import socket
import urllib.error
import urllib.request

import pytest

from spark_scheduler_tpu import native
from spark_scheduler_tpu.core.extender import ExtenderFilterResult
from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
from spark_scheduler_tpu.server import ingest
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import SchedulerHTTPServer
from spark_scheduler_tpu.server.kube_io import filter_result_to_k8s
from spark_scheduler_tpu.server.routing import encode_filter_result
from spark_scheduler_tpu.store.backend import InMemoryBackend

INSTANCE_GROUP_LABEL = "resource_channel"
GROUP = "batch-medium-priority"

needs_native = pytest.mark.skipif(
    not native.available(), reason="native runtime not built (g++ absent)"
)


def _k8s_node(name, zone="zone1"):
    return {
        "metadata": {
            "name": name,
            "labels": {
                "failure-domain.beta.kubernetes.io/zone": zone,
                INSTANCE_GROUP_LABEL: GROUP,
            },
        },
        "status": {
            "allocatable": {"cpu": "8", "memory": "8Gi", "nvidia.com/gpu": "1"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def _k8s_spark_pod(app_id, name, executors=2, cpu="1"):
    return {
        "metadata": {
            "name": name,
            "namespace": "ns",
            "uid": f"uid-{name}",
            "labels": {"spark-role": "driver", "spark-app-id": app_id},
            "annotations": {
                "spark-driver-cpu": cpu,
                "spark-driver-mem": "1Gi",
                "spark-executor-cpu": cpu,
                "spark-executor-mem": "1Gi",
                "spark-executor-count": str(executors),
            },
            "creationTimestamp": "2026-07-29T12:00:00Z",
        },
        "spec": {
            "schedulerName": "spark-scheduler",
            "nodeSelector": {INSTANCE_GROUP_LABEL: GROUP},
            "containers": [
                {
                    "name": "main",
                    "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}},
                }
            ],
        },
        "status": {"phase": "Pending"},
    }


def _make_server(transport, ingest_lane, **kw):
    backend = InMemoryBackend()
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    srv = SchedulerHTTPServer(
        app, registry, port=0, transport=transport, ingest=ingest_lane, **kw
    )
    srv.start()
    return srv


def _request(port, method, path, payload=None, raw=None,
             content_type="application/json"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=raw if raw is not None else (
            json.dumps(payload).encode() if payload is not None else None
        ),
        method=method,
        headers={"Content-Type": content_type},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def _drive_scenario(transport, ingest_lane):
    """One full serving scenario; returns the raw response bytes of every
    step (what the parity assertion compares across lanes)."""
    srv = _make_server(transport, ingest_lane)
    port = srv.port
    out = {}
    try:
        for i in range(4):
            _request(port, "PUT", "/state/nodes", _k8s_node(f"n{i}"))
        names = [f"n{i}" for i in range(4)]
        # Success: JSON schema.
        pod = _k8s_spark_pod("app-json", "drv-json")
        _request(port, "PUT", "/state/pods", pod)
        out["ok_json"] = _request(
            port, "POST", "/predicates", {"Pod": pod, "NodeNames": names}
        )
        # Success: binary protocol.
        pod_b = _k8s_spark_pod("app-bin", "drv-bin")
        _request(port, "PUT", "/state/pods", pod_b)
        out["ok_binary"] = _request(
            port, "POST", "/predicates",
            raw=ingest.encode_predicate_binary(pod_b, names),
            content_type=ingest.BINARY_CONTENT_TYPE,
        )
        # Failure-fit: a driver that can never fit -> uniform failure map
        # over every candidate (the fragment-cached encoding), twice so
        # the second hit serves from the cache.
        big = _k8s_spark_pod("app-big", "drv-big", executors=90, cpu="4")
        _request(port, "PUT", "/state/pods", big)
        out["fail_1"] = _request(
            port, "POST", "/predicates", {"Pod": big, "NodeNames": names}
        )
        out["fail_2"] = _request(
            port, "POST", "/predicates", {"Pod": big, "NodeNames": names}
        )
        # Fast-path deviations that must FALL BACK, not diverge: an escaped
        # node name and the lowercase "nodeNames" key.
        pod_e = _k8s_spark_pod("app-esc", "drv-esc")
        _request(port, "PUT", "/state/pods", pod_e)
        out["escaped"] = _request(
            port, "POST", "/predicates",
            raw=b'{"Pod": ' + json.dumps(pod_e).encode()
            + b', "NodeNames": ["n0", "n\\u0031", "n2", "n3"]}',
        )
        # Malformed JSON body: identical error mapping.
        out["garbage"] = _request(
            port, "POST", "/predicates", raw=b"{not json"
        )
        # Malformed binary body: identical error mapping.
        out["bad_binary"] = _request(
            port, "POST", "/predicates", raw=b"SPRDxxxx",
            content_type=ingest.BINARY_CONTENT_TYPE,
        )
        # Canned surfaces.
        out["liveness"] = _request(port, "GET", "/status/liveness")
        out["missing"] = _request(port, "GET", "/no/such/route")
        if ingest_lane == "native":
            stats = srv.ingest_stats()
            # JSON + binary successes and the two failure posts hit the
            # fast path; the escaped-name body must be a counted fallback.
            assert stats["decode_hits"] >= 4, stats
            assert stats["decode_fallbacks"] >= 1, stats
            assert stats["binary_requests"] >= 1, stats
    finally:
        srv.stop()
    return out


@pytest.mark.parametrize("transport", ["threaded", "async"])
@needs_native
def test_cross_lane_byte_parity(transport):
    """Same scenario, both ingest lanes, one transport: every response —
    decisions, failure maps, error mappings, canned bodies — must be
    byte-identical."""
    py = _drive_scenario(transport, "python")
    nat = _drive_scenario(transport, "native")
    assert py.keys() == nat.keys()
    for step in py:
        assert py[step] == nat[step], f"{transport}/{step} diverged"
    assert json.loads(py["ok_json"][1])["NodeNames"], py["ok_json"]
    assert not json.loads(py["fail_1"][1])["NodeNames"]
    assert json.loads(py["fail_1"][1])["FailedNodes"]


@needs_native
def test_cross_transport_byte_parity_native_lane():
    """The native lane itself is transport-agnostic: threaded (native body
    decode) and async (native framing + decode) serve identical bytes."""
    a = _drive_scenario("threaded", "native")
    b = _drive_scenario("async", "native")
    for step in a:
        assert a[step] == b[step], f"native/{step} diverged across transports"


# ------------------------------------------------ response-encoder parity


def _result(node_names, failed, outcome):
    return ExtenderFilterResult(
        node_names=node_names, failed_nodes=failed, outcome=outcome
    )


def test_encode_filter_result_matches_json_dumps():
    """The template-spliced/cached encoder must be byte-identical to the
    json.dumps(filter_result_to_k8s(...)) it replaced — including the
    fragment-cache hit on a repeated uniform failure map."""
    names = [f"node-{i}" for i in range(40)]
    cases = [
        (_result(["n1"], {}, "success"), None),
        (_result(["zone-a/n é"], {}, "success"), None),  # escaping
        (_result([], {n: "does not fit" for n in names}, "failure-fit"),
         names),
        (_result([], {n: "does not fit" for n in names}, "failure-fit"),
         names),  # second encode serves the cached fragment
        (_result([], {"n1": "a", "n2": "b"}, "failure-fit"), ["n1", "n2"]),
        (_result([], {n: "boom" for n in names}, "failure-internal"), names),
        (_result([], {}, "failure-internal"), None),
    ]
    for result, hint in cases:
        expect = json.dumps(filter_result_to_k8s(result)).encode()
        assert encode_filter_result(result, hint) == expect


def test_canned_bodies_match_json_dumps():
    from spark_scheduler_tpu.server import routing

    assert routing._NOT_FOUND_BODY == json.dumps({"error": "not found"}).encode()
    assert routing._LIVENESS_BODY == json.dumps({"status": "up"}).encode()
    assert routing._READY_BODY == json.dumps({"ready": True}).encode()
    assert routing._NOT_READY_BODY == json.dumps({"ready": False}).encode()
    assert (
        routing._SHED_PRE + b"7}"
        == json.dumps(
            {"error": "scheduler overloaded", "queue_depth": 7}
        ).encode()
    )


# ------------------------------------------------------- binary protocol


def test_binary_codec_roundtrip_pure_python():
    pod = _k8s_spark_pod("app", "drv")
    names = [f"n{i}" for i in range(100)] + ["zone-é/n"]
    body = ingest.encode_predicate_binary(pod, names)
    decoded_pod, decoded_names = ingest.decode_predicate_binary_py(body)
    assert decoded_names == names
    assert decoded_pod.name == "drv" and decoded_pod.namespace == "ns"


@pytest.mark.parametrize(
    "body",
    [
        b"",
        b"SPRD",
        b"XXXX\x01\x00\x00\x00\x00\x00\x00\x00\x00",
        b"SPRD\x02" + b"\x00" * 8,  # bad version
        b"SPRD\x01\xff\xff\xff\xff" + b"\x00" * 8,  # pod frame overrun
        b"SPRD\x01\x02\x00\x00\x00{}\x01\x00\x00\x00",  # truncated names
        b"SPRD\x01\x02\x00\x00\x00{}\x00\x00\x00\x00x",  # trailing bytes
    ],
)
def test_binary_codec_rejects_malformed(body):
    with pytest.raises(ingest.BinaryPredicateError):
        ingest.decode_predicate_binary_py(body)


@needs_native
def test_native_binary_decoder_hostile_frames():
    """Adversarial binary bodies must fall back (never crash, never
    mis-tokenize): a 13-byte body declaring a billion names (the reserve
    would otherwise bad_alloc across the C ABI and kill the process), and
    a NUL inside a name (which would alias the blob's separator format —
    the Python decoder represents 'a\\0b' faithfully as ONE name)."""
    import struct

    from spark_scheduler_tpu.native import PredicateSlot

    bomb = b"SPRD\x01" + struct.pack("<I", 0) + struct.pack("<I", 10**9)
    slot = PredicateSlot()
    assert not slot.decode_binary(bomb)
    nul = (
        b"SPRD\x01" + struct.pack("<I", 2) + b"{}"
        + struct.pack("<I", 1) + struct.pack("<H", 3) + b"a\x00b"
    )
    assert not slot.decode_binary(nul)
    _, names = ingest.decode_predicate_binary_py(nul)
    assert names == ["a\x00b"]


@needs_native
def test_native_json_fast_path_refuses_escaped_keys():
    """An escaped key that DECODES to "Pod" compares unequal on raw bytes:
    the fast path must fall back rather than hit with an empty pod."""
    from spark_scheduler_tpu.native import PredicateSlot

    body = (
        b'{"\\u0050od": {"metadata": {"name": "real"}}, "NodeNames": ["n1"]}'
    )
    slot = PredicateSlot()
    assert not slot.decode_json(body)
    codec = ingest.NativeIngestCodec()
    assert codec.decode_predicate_body(body, binary=False) is None


@needs_native
def test_native_framer_ignores_empty_transfer_encoding():
    """`headers.get("Transfer-Encoding")` truthiness parity: an empty TE
    value (first header wins) is ignored by the Python framer, so the
    native framer must frame the body normally too."""
    from spark_scheduler_tpu import native as n

    conn = n.IngestConn(None, 65536)
    conn.feed(
        b"POST /predicates HTTP/1.1\r\nTransfer-Encoding:\r\n"
        b"Content-Length: 2\r\n\r\n{}"
    )
    ev = conn.next()
    assert ev.kind == n.EV_REQUEST and ev.body_error == 0
    assert ev.body_len == 2


@needs_native
def test_async_native_miss_decodes_once():
    """A deviating JSON body on the async native lane is ONE counted
    fallback: the transport's attempt is flagged on the Request so the
    routing layer goes straight to the Python parser."""
    srv = _make_server("async", "native")
    try:
        port = srv.port
        _request(port, "PUT", "/state/nodes", _k8s_node("n0"))
        pod = _k8s_spark_pod("app-esc", "drv-esc")
        _request(port, "PUT", "/state/pods", pod)
        status, body = _request(
            port, "POST", "/predicates",
            raw=b'{"Pod": ' + json.dumps(pod).encode()
            + b', "NodeNames": ["n\\u0030"]}',
        )
        assert status == 200 and json.loads(body)["NodeNames"] == ["n0"]
        stats = srv.ingest_stats()
        assert stats["decode_fallbacks"] == 1, stats
        assert stats["decode_hits"] == 0, stats
    finally:
        srv.stop()


@needs_native
def test_native_binary_decode_matches_python():
    pod = _k8s_spark_pod("app", "drv")
    names = [f"n{i}" for i in range(50)]
    body = ingest.encode_predicate_binary(pod, names)
    codec = ingest.NativeIngestCodec()
    decoded = codec.decode_predicate_body(body, binary=True)
    assert decoded is not None
    npod, nnames = decoded
    ppod, pnames = ingest.decode_predicate_binary_py(body)
    assert list(nnames) == pnames
    assert npod == ppod


# ------------------------------------------------- NativeNodeNames ticket


@needs_native
def test_native_node_names_ticket_semantics():
    body = json.dumps(
        {"Pod": {"metadata": {"name": "p"}},
         "NodeNames": [f"n{i}" for i in range(100)]}
    ).encode()
    codec = ingest.NativeIngestCodec()
    _, names1 = codec.decode_predicate_body(body, binary=False)
    _, names2 = codec.decode_predicate_body(body, binary=False)
    assert isinstance(names1, ingest.NativeNodeNames)
    # Content-hashable BEFORE materialization: hash/eq ride the digest +
    # native memcmp, the lazy list stays unbuilt.
    assert hash(names1) == hash(names2)
    assert names1 == names2
    assert names1._list is None and names2._list is None
    # Sequence protocol.
    assert len(names1) == 100
    assert names1[3] == "n3" and names1[-1] == "n99"
    assert "n42" in names1 and "nope" not in names1
    assert list(names1) == [f"n{i}" for i in range(100)]
    assert names1[:3] == ["n0", "n1", "n2"]
    assert names1 == [f"n{i}" for i in range(100)]
    # Different content: same everything but the last name.
    _, other = codec.decode_predicate_body(
        body.replace(b'"n99"', b'"nXX"'), binary=False
    )
    assert names1 != other


@needs_native
def test_candidate_mask_cache_keys_on_ticket_digest():
    from spark_scheduler_tpu.core.solver import PlacementSolver
    from spark_scheduler_tpu.models.kube import Node
    from spark_scheduler_tpu.models.resources import Resources

    solver = PlacementSolver()
    nodes = [
        Node(name=f"n{i}", allocatable=Resources.from_quantities("8", "8Gi", "0"))
        for i in range(16)
    ]
    tensors = solver.build_tensors(nodes, {}, {})
    body = json.dumps(
        {"Pod": {}, "NodeNames": [f"n{i}" for i in range(0, 16, 2)]}
    ).encode()
    codec = ingest.NativeIngestCodec()
    _, t1 = codec.decode_predicate_body(body, binary=False)
    _, t2 = codec.decode_predicate_body(body, binary=False)
    m1 = solver.candidate_mask(tensors, t1)
    assert t1._list is not None  # cold miss materialized to build the mask
    m2 = solver.candidate_mask(tensors, t2)
    assert m2 is m1  # digest-keyed cache hit
    assert t2._list is None  # ...without materializing the second ticket
    import numpy as np

    mask_from_list = solver.candidate_mask(
        tensors, [f"n{i}" for i in range(0, 16, 2)]
    )
    assert np.array_equal(m1, mask_from_list)


# --------------------------------------------- native framer conformance


def _read_response(sock, timeout=5.0):
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            return buf
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":", 1)[1].strip())
    while len(rest) < length:
        chunk = sock.recv(4096)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest[:length], rest[length:]


@needs_native
@pytest.mark.parametrize(
    "payload",
    [
        b"GARBAGE\r\n\r\n",
        b"GET /status/liveness HTTP-WRONG\r\n\r\n",
        b"GET /x HTTP/1.1\r\nno-colon-header\r\n\r\n",
    ],
)
def test_native_framer_rejects_malformed_frames(payload):
    srv = _make_server("async", "native")
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(payload)
        resp, _ = _read_response(s)
        assert resp.startswith(b"HTTP/1.1 400"), resp
        assert b"Connection: close" in resp
        s.settimeout(5.0)
        # The framer stops parsing; the transport closes after the write.
        tail = b""
        try:
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                tail += chunk
        except socket.timeout:
            pytest.fail("connection left open after malformed frame")
        s.close()
    finally:
        srv.stop()


@needs_native
def test_native_framer_header_block_too_large():
    srv = _make_server("async", "native")
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        # Past the 64 KiB header cap with NO terminator in sight: the
        # framer must 431 rather than buffer without bound.
        s.sendall(b"GET / HTTP/1.1\r\nX-Junk: " + b"j" * 70000)
        resp, _ = _read_response(s)
        assert resp.startswith(b"HTTP/1.1 431"), resp
        s.close()
    finally:
        srv.stop()


@needs_native
def test_native_framer_oversize_body_413_keepalive_survives():
    srv = _make_server("async", "native", max_body_bytes=64)
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        body = b"x" * 200
        s.sendall(
            b"POST /predicates HTTP/1.1\r\nHost: x\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        resp, rest = _read_response(s)
        assert resp.startswith(b"HTTP/1.1 413"), resp
        assert b"max-body-bytes=64" in resp
        # The 200-byte body was drained: the next request on the SAME
        # socket frames cleanly.
        s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
        resp2, _ = _read_response(s)
        assert resp2.startswith(b"HTTP/1.1 200"), resp2
        assert resp2.endswith(b'{"status": "up"}')
        s.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.parametrize(
    "te_headers",
    [
        b"Transfer-Encoding: chunked\r\n",
        b"Content-Length: 5\r\nContent-Length: 6\r\n",
        b"Content-Length: -5\r\n",
        b"Content-Length: 1_6\r\n",
    ],
)
def test_native_framer_unframeable_bodies_400_and_close(te_headers):
    srv = _make_server("async", "native")
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(
            b"POST /predicates HTTP/1.1\r\nHost: x\r\n" + te_headers + b"\r\n"
        )
        resp, _ = _read_response(s)
        assert resp.startswith(b"HTTP/1.1 400"), resp
        assert b"Connection: close" in resp
        s.close()
    finally:
        srv.stop()


@needs_native
def test_native_framer_pipelined_keepalive_in_order():
    """Three pipelined requests in ONE write — distinct routes so the
    in-order flush is observable — then a second burst on the same socket
    (keep-alive reuse across bursts)."""
    srv = _make_server("async", "native")
    try:
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(
            b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /no/such/route HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /status/readiness HTTP/1.1\r\nHost: x\r\n\r\n"
        )
        r1, rest = _read_response(s)
        assert r1.startswith(b"HTTP/1.1 200") and b'"status": "up"' in r1
        r2, rest = _read_response_with(rest, s)
        assert r2.startswith(b"HTTP/1.1 404"), r2
        r3, _ = _read_response_with(rest, s)
        # No cluster state synced yet: readiness is an honest 503.
        assert r3.startswith(b"HTTP/1.1 503"), r3
        assert r3.endswith(b'{"ready": false}')
        s.sendall(b"GET /status/liveness HTTP/1.1\r\nHost: x\r\n\r\n")
        r4, _ = _read_response(s)
        assert r4.startswith(b"HTTP/1.1 200"), r4
        s.close()
        stats = srv.ingest_stats()
        assert stats["native_parse_ns_total"] > 0
    finally:
        srv.stop()


def _read_response_with(buffered, sock, timeout=5.0):
    """_read_response, but consuming already-buffered bytes first."""
    sock.settimeout(timeout)
    buf = buffered
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            return buf, b""
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":", 1)[1].strip())
    while len(rest) < length:
        chunk = sock.recv(4096)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest[:length], rest[length:]


# ---------------------------------------------------- fail-soft degrade


def test_native_unavailable_degrades_with_warning(monkeypatch):
    """server.ingest: native on a toolchain-less host: RuntimeWarning at
    construction, python lane serves, telemetry says degraded."""
    import spark_scheduler_tpu.server.ingest as ingest_mod

    monkeypatch.setattr(ingest_mod, "try_native_codec", lambda: None)
    backend = InMemoryBackend()
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    with pytest.warns(RuntimeWarning, match="degrading to the python"):
        srv = SchedulerHTTPServer(
            app, registry, port=0, transport="async", ingest="native"
        )
    srv.start()
    try:
        assert srv.ingest_name == "python"
        assert srv.ingest_codec is None
        stats = srv.ingest_stats()
        assert stats["degraded"] == 1
        _request(srv.port, "PUT", "/state/nodes", _k8s_node("n0"))
        pod = _k8s_spark_pod("app", "drv")
        _request(srv.port, "PUT", "/state/pods", pod)
        status, body = _request(
            srv.port, "POST", "/predicates",
            {"Pod": pod, "NodeNames": ["n0"]},
        )
        assert status == 200 and json.loads(body)["NodeNames"] == ["n0"]
    finally:
        srv.stop()


def test_unknown_ingest_rejected():
    backend = InMemoryBackend()
    app = build_scheduler_app(
        backend, InstallConfig(sync_writes=True)
    )
    with pytest.raises(ValueError, match="unknown server ingest"):
        SchedulerHTTPServer(app, port=0, ingest="rust")
    app.stop()


def test_install_config_parses_server_ingest():
    cfg = InstallConfig.from_dict({"server": {"ingest": "native"}})
    assert cfg.server_ingest == "native"
    assert InstallConfig.from_dict({}).server_ingest == "python"
