"""HA chaos soak (ISSUE 8 acceptance): kill the leader mid-burst, assert
zero double-placements, zero reservation-invariant violations, and a
bounded placement-latency spike — the engine lives in testing/soak.py so
this fast CI leg and bench.py's ha_failover section drive one
implementation. `HA_CHAOS_CYCLES` scales it up for the soak CI job."""

from __future__ import annotations

import os

import pytest

from spark_scheduler_tpu.testing.soak import HAChaosSoak

CYCLES = int(os.environ.get("HA_CHAOS_CYCLES", "3"))
# Roster size of the chaos family; HA_CHAOS_NODES=1000000 is the
# million-node family (ISSUE 11).
NODES = int(os.environ.get("HA_CHAOS_NODES", "16"))


@pytest.mark.parametrize("strategy", ["tightly-pack", "distribute-evenly"])
def test_ha_chaos_leader_kill_soak(strategy):
    soak = HAChaosSoak(strategy=strategy, n_nodes=NODES, ttl_s=2.0)
    stats = soak.run(cycles=CYCLES, burst=4)
    assert stats["promotions"] == CYCLES
    assert stats["fenced_drops"] >= CYCLES  # every cycle fenced its orphan
    assert stats["apps_placed"] >= CYCLES * 6
    # The per-cycle invariants (no double placement, no over-commit,
    # bounded spike) asserted inside run_cycle; re-assert the final state.
    soak.check_invariants()


def test_ha_chaos_on_durable_backend(tmp_path):
    """Same chaos over a WAL-backed shared store: the surviving state is
    durable — a fresh replay holds exactly the surviving placements."""
    from spark_scheduler_tpu.store.durable import DurableBackend

    path = str(tmp_path / "chaos.jsonl")
    backend = DurableBackend(path)
    soak = HAChaosSoak(strategy="tightly-pack", n_nodes=12, backend=backend)
    soak.run(cycles=2, burst=3)
    backend.close()
    replayed = DurableBackend(path)
    rrs = {rr.name: rr for rr in replayed.list("resourcereservations")}
    assert set(rrs) == set(soak.placed)
    for app_id, node in soak.placed.items():
        assert rrs[app_id].spec.reservations["driver"].node == node
    replayed.close()


def test_ha_chaos_kill_schedule_rides_fault_plan():
    """The leader kill is a FaultPlan decision (replica.kill surface,
    ISSUE 9): an every-2nd-cycle plan kills half the cycles and runs the
    other half's staged windows to completion on the live leader, and
    lease.* specs in the same plan blip the lease store THROUGH the
    takeover — absorbed by the LeaseManager's retry ladder, never a
    spurious deposition."""
    from spark_scheduler_tpu.faults import FaultPlan, FaultSpec

    plan = FaultPlan(
        seed=7, name="ha-kill-alternate",
        specs=[
            FaultSpec(surface="replica.kill", mode="error", every=2),
            FaultSpec(surface="lease.read", mode="error", p=0.1, limit=6),
        ],
    )
    soak = HAChaosSoak(
        strategy="tightly-pack", n_nodes=16, ttl_s=2.0, fault_plan=plan
    )
    stats = soak.run(cycles=4, burst=3)
    assert stats["kills"] == 2 and stats["spared_cycles"] == 2
    assert stats["promotions"] == 2
    assert stats["fault_stats"]["fired"].get("replica.kill") == 2
    soak.check_invariants()
