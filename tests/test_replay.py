"""Decision-trace codec, deterministic replay, and what-if (ISSUE 17).

Pins the tentpole contracts:
  * trace round-trip is byte-identical (write -> read -> re-dump);
  * the reader tolerates a torn tail silently and counts mid-file
    corruption (durable.py's discipline);
  * generators are seed-deterministic to the byte;
  * a generated trace run with binding re-captures to a full trace whose
    strict replay is bit-identical (the closed generate -> run -> verify
    loop);
  * a recorded invariant-soak trace replays bit-identically — decision
    for decision — through the real extender (CI scales this leg to 10k+
    decisions via REPLAY_SOAK_STEPS / REPLAY_MIN_DECISIONS);
  * what-if under a different binpack strategy produces a well-formed,
    non-degenerate diff.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from spark_scheduler_tpu.replay import (
    TraceReader,
    config_fingerprint,
    config_from_fingerprint,
    generate,
    replay_trace,
    what_if,
)
from spark_scheduler_tpu.replay.trace import dumps_event
from spark_scheduler_tpu.server.config import InstallConfig


@pytest.fixture(scope="module")
def churn_run(tmp_path_factory):
    """One generated churn trace run through the engine with re-capture:
    (input_path, captured_path) shared by the loop + what-if tests."""
    d = tmp_path_factory.mktemp("replay")
    gen = str(d / "churn.jsonl")
    cap = str(d / "churn_run.jsonl")
    generate("churn", gen, seed=3, n_nodes=12, steps=60)
    rep = replay_trace(gen, record_path=cap)
    assert rep.decisions > 0
    return gen, cap


# ------------------------------------------------------------------- codec


def test_roundtrip_byte_identity(churn_run):
    """write -> read -> re-dump reproduces every line verbatim: the codec
    has ONE canonical encoding."""
    for path in churn_run:
        reader = TraceReader(path)
        raw = reader.raw_lines()
        assert raw, path
        redumped = [dumps_event(json.loads(line)) for line in raw]
        assert redumped == raw
        assert reader.header["v"] == 1


def test_torn_tail_tolerated_and_midfile_corruption_counted(
    churn_run, tmp_path
):
    gen, _ = churn_run
    with open(gen, encoding="utf-8") as fh:
        lines = fh.read().splitlines()

    # torn tail: a crash mid-append leaves a half-written last line
    torn = tmp_path / "torn.jsonl"
    torn.write_text("\n".join(lines) + '\n{"k":"pod","op":"ad')
    r = TraceReader(str(torn))
    events = list(r.events())
    assert r.torn_tail and r.malformed == 0
    assert len(events) == len(lines) - 1  # all real events survive

    # mid-file corruption: counted, skipped, rest still replays
    corrupt = list(lines)
    corrupt[len(corrupt) // 2] = "#### not json ####"
    bad = tmp_path / "corrupt.jsonl"
    bad.write_text("\n".join(corrupt) + "\n")
    r = TraceReader(str(bad))
    events = list(r.events())
    assert r.malformed == 1 and not r.torn_tail
    assert len(events) == len(lines) - 2

    # a non-header first line is rejected outright
    headless = tmp_path / "headless.jsonl"
    headless.write_text("\n".join(lines[1:]) + "\n")
    with pytest.raises(ValueError):
        TraceReader(str(headless))


def test_config_fingerprint_roundtrip():
    cfg = InstallConfig(
        fifo=True, binpack_algo="distribute-evenly", sync_writes=True
    )
    fp = config_fingerprint(cfg)
    rebuilt = config_from_fingerprint(fp)
    assert dataclasses.asdict(rebuilt) == fp
    # overrides accept dashes; unknown fields are a loud error
    over = config_from_fingerprint(fp, overrides={"binpack-algo": "tightly-pack"})
    assert over.binpack_algo == "tightly-pack"
    with pytest.raises(KeyError):
        config_from_fingerprint(fp, overrides={"no-such-field": 1})
    # unknown fingerprint keys (a newer build's trace) are dropped
    fp2 = dict(fp, field_from_the_future=42)
    assert config_from_fingerprint(fp2).binpack_algo == "distribute-evenly"


# -------------------------------------------------------------- generators


def test_generator_seed_determinism(tmp_path):
    for kind, sizing in (
        ("diurnal", dict(n_nodes=8, apps=6)),
        ("bursty", dict(n_nodes=8, bursts=2)),
        ("churn", dict(n_nodes=8, steps=15)),
    ):
        a, b, c = (str(tmp_path / f"{kind}-{i}.jsonl") for i in "abc")
        generate(kind, a, seed=7, **sizing)
        generate(kind, b, seed=7, **sizing)
        generate(kind, c, seed=8, **sizing)
        assert open(a).read() == open(b).read(), kind
        assert open(a).read() != open(c).read(), kind


def test_unknown_generator_is_an_error(tmp_path):
    with pytest.raises(ValueError, match="unknown generator"):
        generate("nope", str(tmp_path / "x.jsonl"), seed=0)


# ------------------------------------------------------------------ replay


def test_generated_trace_closes_the_loop(churn_run):
    """generate -> run (re-capture) -> strict verify: the captured trace
    replays bit-identically, and a second re-capture is byte-identical."""
    gen, cap = churn_run
    rep = replay_trace(cap, strict=True)
    assert rep.mismatches == [] and rep.compared == rep.decisions > 0
    assert rep.uncompared_windows == 0 and rep.overcommit == 0


def test_soak_trace_replays_bit_identically(tmp_path):
    """The headline acceptance test: a recorded invariant-soak session —
    churn, teardowns, reconciles, idempotent retries, pipelined windows —
    replays decision-for-decision. CI runs this with
    REPLAY_SOAK_STEPS=12000 / REPLAY_MIN_DECISIONS=10000 (the soak
    records ~0.9 decisions per step)."""
    from spark_scheduler_tpu.testing.soak import Soak

    steps = int(os.environ.get("REPLAY_SOAK_STEPS", "150"))
    min_decisions = int(os.environ.get("REPLAY_MIN_DECISIONS", "50"))
    path = str(tmp_path / "soak.jsonl")
    soak = Soak(
        np.random.default_rng(5), "single-az-tightly-pack", trace_path=path
    )
    soak.run(steps)
    soak.h.app.stop()

    rep = replay_trace(path, strict=True)
    assert rep.mismatches == []
    assert rep.compared == rep.decisions >= min_decisions, (
        rep.decisions, min_decisions
    )
    assert rep.uncompared_windows == 0
    # the trace captured a representative mix, not a monoculture
    assert rep.verdict_counts.get("success", 0) > 0
    assert not rep.torn_tail and rep.malformed == 0


def test_what_if_strategy_diff_is_well_formed(churn_run):
    """What-if smoke: tightly-pack vs distribute-evenly on the same trace
    must yield a clean base replay and a non-degenerate placement diff."""
    _, cap = churn_run
    diff = what_if(cap, {"binpack-algo": "distribute-evenly"})
    assert diff["base_mismatches"] == 0
    p = diff["placements"]
    assert p["same"] + p["changed"] > 0
    # spreading vs packing MUST move something on a multi-node cluster
    assert p["changed"] > 0
    assert diff["decisions"]["base"] == diff["decisions"]["variant"]
    for arm in ("base", "variant"):
        assert diff["latency_ms"][arm]["p50"] is not None
        assert diff["fragmentation"][arm]["cpu"] is not None
    assert isinstance(diff["denials"]["delta"], int)
