"""Observability: scheduler metrics through a real scheduling flow, periodic
reporters, waste phase attribution, and business events.
"""

import numpy as np

from spark_scheduler_tpu.events import EventEmitter
from spark_scheduler_tpu.metrics import (
    CacheReporter,
    MetricRegistry,
    QueueReporter,
    SchedulerMetrics,
    SoftReservationReporter,
    UsageReporter,
    WasteReporter,
)
from spark_scheduler_tpu.metrics import reporters as R
from spark_scheduler_tpu.metrics import scheduler_metrics as SM
from spark_scheduler_tpu.metrics.waste import SCHEDULING_WASTE
from spark_scheduler_tpu.testing.harness import (
    Harness,
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _scheduled_harness(metrics=None, events=None):
    h = Harness(metrics=metrics, events=events)
    h.add_nodes(*[new_node(f"n{i}") for i in range(4)])
    pods = static_allocation_spark_pods("app-1", 2)
    names = [f"n{i}" for i in range(4)]
    results = h.schedule_app(pods, names)
    assert all(r.ok for r in results)
    return h


def test_schedule_flow_populates_metrics():
    metrics = SchedulerMetrics(instance_group_label=INSTANCE_GROUP_LABEL)
    _scheduled_harness(metrics=metrics)
    snap = metrics.registry.snapshot()

    requests = snap[SM.REQUEST_COUNTER]
    by_role = {(e["tags"]["sparkrole"], e["tags"]["outcome"]): e["value"] for e in requests}
    assert by_role[("driver", "success")] == 1
    assert by_role[("executor", "success")] == 2
    assert all(
        e["tags"]["instance-group"] == "batch-medium-priority" for e in requests
    )
    assert snap[SM.SCHEDULE_TIME][0]["count"] >= 1
    # Packing efficiency histograms exist for all four dimensions.
    dims = {e["tags"]["dimension"] for e in snap[SM.PACKING_EFFICIENCY]}
    assert dims == {"CPU", "Memory", "GPU", "Max"}
    # One-zone cluster: pairs exist, none cross-zone.
    total = next(e["value"] for e in snap[SM.TOTAL_TRAFFIC])
    cross = next(e["value"] for e in snap[SM.CROSS_AZ_TRAFFIC])
    assert total == 3 and cross == 0  # driver+2 executors = C(3,2) pairs


def test_failed_attempt_then_success_marks_retry_time():
    clock = FakeClock()
    metrics = SchedulerMetrics(instance_group_label=INSTANCE_GROUP_LABEL, clock=clock)
    h = Harness(metrics=metrics)
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("big-app", 40)  # doesn't fit
    r = h.schedule(pods[0], ["n0"])
    assert not r.ok
    clock.advance(30.0)
    # Capacity arrives; retry succeeds.
    h.add_nodes(*[new_node(f"m{i}") for i in range(8)])
    r2 = h.schedule(pods[0], ["n0"] + [f"m{i}" for i in range(8)])
    assert r2.ok
    snap = metrics.registry.snapshot()
    retry = [
        e for e in snap[SM.RETRY_TIME] if e["tags"]["outcome"] == "success"
    ]
    assert retry and abs(retry[0]["max"] - 30.0) < 1e-6


def test_usage_cache_soft_reporters():
    registry = MetricRegistry()
    h = _scheduled_harness()
    usage_reporter = UsageReporter(registry, h.app.reservation_manager)
    usage_reporter.report_once()
    CacheReporter(
        registry, {"resourcereservations": h.app.rr_cache}
    ).report_once()
    SoftReservationReporter(registry, h.app.soft_store).report_once()
    snap = registry.snapshot()
    # 3 pods x (1 CPU = 1000 milli) on some nodes.
    cpu_total = sum(e["value"] for e in snap[R.USAGE_CPU])
    assert cpu_total == 3000
    assert next(e["value"] for e in snap[R.CACHED_OBJECTS]) == 1  # one RR
    assert next(e["value"] for e in snap[R.SOFT_RESERVATION_COUNT]) == 0

    # Reservation goes away (app finished, RR deleted) -> the per-node usage
    # series must be unregistered on the next tick (usage.go:96-113).
    for rr in h.app.rr_cache.list():
        h.app.rr_cache.delete(rr.namespace, rr.name)
    usage_reporter.report_once()
    snap2 = registry.snapshot()
    assert R.USAGE_CPU not in snap2 or not snap2[R.USAGE_CPU]
    assert R.USAGE_MEMORY not in snap2 or not snap2[R.USAGE_MEMORY]


def test_histogram_stats_p99_and_min():
    """Histogram.stats() exposes min/p99 alongside p50/p95 — bench.py and
    the autoscaler report p99, so the registry view must carry it too."""
    from spark_scheduler_tpu.metrics.registry import Histogram

    h = Histogram()
    for v in range(1, 101):
        h.update(float(v))
    s = h.stats()
    assert s["min"] == 1.0 and s["max"] == 100.0
    assert s["p50"] == 51.0 and s["p95"] == 96.0 and s["p99"] == 100.0
    assert s["count"] == 100
    # min is exact over ALL samples even after reservoir replacement
    h2 = Histogram(cap=4)
    for v in (5.0, 9.0, 1.0, 7.0, 8.0, 6.0):
        h2.update(v)
    assert h2.stats()["min"] == 1.0
    # the exact running sum rides along (Prometheus _sum must be monotone,
    # which a mean*count reconstruction is not)
    assert s["sum"] == sum(range(1, 101))
    # empty histogram reports zeros, not errors
    empty = Histogram().stats()
    assert empty == {
        "count": 0, "max": 0.0, "min": 0.0, "sum": 0.0, "mean": 0.0,
        "p50": 0.0, "p95": 0.0, "p99": 0.0,
    }


def test_queue_reporter_lifecycles():
    clock = FakeClock(t=100.0)
    registry = MetricRegistry()
    h = Harness()
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-q", 30)  # will not fit
    r = h.schedule(pods[0], ["n0"])
    assert not r.ok
    rep = QueueReporter(registry, h.backend, INSTANCE_GROUP_LABEL, clock=clock)
    clock.advance(5.0)
    rep.report_once()
    snap = registry.snapshot()
    queued = [
        e for e in snap[R.LIFECYCLE_COUNT] if e["tags"]["lifecycle"] == "queued"
    ]
    assert queued and queued[0]["value"] == 1
    # p99/min ride along with p50/p95/max; a single queued pod makes them
    # all equal its age.
    by_name = {
        name: next(
            e for e in snap[name] if e["tags"]["lifecycle"] == "queued"
        )["value"]
        for name in (
            R.LIFECYCLE_P50, R.LIFECYCLE_P95, R.LIFECYCLE_P99,
            R.LIFECYCLE_MIN, R.LIFECYCLE_MAX,
        )
    }
    assert len(set(by_name.values())) == 1, by_name
    stuck = []
    rep2 = QueueReporter(
        registry, h.backend, INSTANCE_GROUP_LABEL, clock=clock,
        on_stuck_pod=lambda pod, lc, age: stuck.append(pod.name),
    )
    clock.advance(13 * 3600.0)
    rep2.report_once()
    assert stuck == [pods[0].name]


def test_waste_reporter_phases():
    clock = FakeClock(t=0.0)
    w = WasteReporter(instance_group_label=INSTANCE_GROUP_LABEL, clock=clock)
    pods = static_allocation_spark_pods("app-w", 1)
    driver = pods[0]
    w.mark_failed_scheduling_attempt(driver, "failure-fit")
    clock.advance(10.0)  # 10s before demand creation
    w.on_demand_created(driver.key)
    clock.advance(20.0)
    w.on_demand_fulfilled(driver.key)
    clock.advance(5.0)  # 5s after fulfillment, no further failures
    w.on_pod_scheduled(driver)
    snap = w.registry.snapshot()
    by_type = {e["tags"]["wastetype"]: e for e in snap[SCHEDULING_WASTE]}
    assert abs(by_type["before-demand-creation"]["max"] - 10.0) < 1e-6
    assert abs(by_type["after-demand-fulfilled"]["max"] - 5.0) < 1e-6
    assert "after-demand-fulfilled-no-failures" in by_type
    assert "total-time-no-demand" not in by_type

    # No-demand path.
    w2 = WasteReporter(instance_group_label=INSTANCE_GROUP_LABEL, clock=clock)
    w2.mark_failed_scheduling_attempt(driver, "failure-fit")
    clock.advance(7.0)
    w2.on_pod_scheduled(driver)
    snap2 = w2.registry.snapshot()
    types2 = {e["tags"]["wastetype"] for e in snap2[SCHEDULING_WASTE]}
    assert types2 == {"total-time-no-demand"}


def test_queue_reporter_clears_stale_series():
    clock = FakeClock(t=100.0)
    registry = MetricRegistry()
    h = Harness()
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-s", 30)
    h.schedule(pods[0], ["n0"])  # fails -> queued
    rep = QueueReporter(registry, h.backend, INSTANCE_GROUP_LABEL, clock=clock)
    rep.report_once()
    assert any(
        e["tags"]["lifecycle"] == "queued"
        for e in registry.snapshot()[R.LIFECYCLE_COUNT]
    )
    h.delete_pod(pods[0])  # queue empties
    rep.report_once()
    assert R.LIFECYCLE_COUNT not in registry.snapshot()


def test_waste_reporter_wired_through_app():
    """The production wiring: demand creation, demand fulfillment (external
    autoscaler), and pod scheduling all feed the waste reporter."""
    clock = FakeClock(t=0.0)
    w = WasteReporter(instance_group_label=INSTANCE_GROUP_LABEL, clock=clock)
    h = Harness(waste=w)
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-ww", 20)
    r = h.schedule(pods[0], ["n0"])
    assert not r.ok  # failed attempt + demand created, via the wiring
    clock.advance(4.0)
    # External autoscaler fulfills the demand.
    demand = h.demands()[0]
    import dataclasses as dc

    from spark_scheduler_tpu.models.demands import PHASE_FULFILLED

    updated = dc.replace(demand)
    updated.status = dc.replace(demand.status, phase=PHASE_FULFILLED)
    h.backend.update("demands", updated)
    clock.advance(6.0)
    # Capacity arrives; driver schedules -> waste attributed.
    h.add_nodes(*[new_node(f"w{i}") for i in range(8)])
    r2 = h.schedule(pods[0], ["n0"] + [f"w{i}" for i in range(8)])
    assert r2.ok
    snap = w.registry.snapshot()
    by_type = {e["tags"]["wastetype"]: e for e in snap[SCHEDULING_WASTE]}
    assert abs(by_type["after-demand-fulfilled"]["max"] - 6.0) < 1e-6


def test_events_emitted():
    events = []
    emitter = EventEmitter(
        sink=events.append, instance_group_label=INSTANCE_GROUP_LABEL
    )
    h = Harness(events=emitter)
    h.add_nodes(*[new_node(f"n{i}") for i in range(2)])
    pods = static_allocation_spark_pods("app-e", 1)
    h.schedule_app(pods, ["n0", "n1"])
    names = [e["event"] for e in events]
    assert "foundry.spark.scheduler.application_scheduled" in names
    sched = next(e for e in events if e["event"].endswith("application_scheduled"))
    assert sched["sparkAppID"] == "app-e"
    assert sched["minExecutorCount"] == 1

    # Demand events: app that does not fit creates a demand.
    big = static_allocation_spark_pods("app-big", 50)
    r = h.schedule(big[0], ["n0", "n1"])
    assert not r.ok
    assert any(e["event"].endswith("demand_created") for e in events)


def test_cache_drift_detection():
    """VERDICT r4 missing #2: an unexplained cache-vs-backend size skew
    (beyond inflight writes + the informer-delay buffer) emits the
    cache.unexplained.difference gauge and per-object warnings; an
    explained skew emits 0."""
    import io
    import json as _json

    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.store.cache import ResourceReservationCache
    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log, svc1log
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )
    from spark_scheduler_tpu.models.resources import Resources
    from spark_scheduler_tpu.models.kube import Pod

    backend = InMemoryBackend()
    cache = ResourceReservationCache(backend, sync_writes=True)
    cache.start()
    registry = MetricRegistry()
    stream = io.StringIO()
    old_logger = svc1log()
    set_svc1log(Svc1Logger(stream=stream))
    try:
        # 7 reservations in the backend the cache never saw: skew 7 > 0+5.
        for i in range(7):
            driver = Pod(
                name=f"drift-{i}-driver", namespace="ns",
                labels={"spark-app-id": f"drift-{i}"},
            )
            backend.create(
                "resourcereservations",
                new_resource_reservation(
                    "n0", ["n0"], driver,
                    Resources.from_quantities("1", "1Gi"),
                    Resources.from_quantities("1", "1Gi"),
                ),
            )
        CacheReporter(
            registry, {"resourcereservations": cache}, backend=backend
        ).report_once()
    finally:
        set_svc1log(old_logger)
    snap = registry.snapshot()
    drift = snap[R.UNEXPLAINED_DIFFERENCE]
    assert drift and drift[0]["value"] == 7, drift
    by_source = {
        e["tags"]["source"]: e["value"] for e in snap[R.CACHED_OBJECTS]
    }
    assert by_source == {"cache": 0, "lister": 7}, by_source
    lines = [_json.loads(l) for l in stream.getvalue().splitlines()]
    warns = [l for l in lines if l["level"] == "WARN"]
    assert any(
        l["message"] == "found unexplained cache size difference"
        for l in warns
    )
    assert (
        sum(1 for l in warns if l["message"] == "object only exists in backend")
        == 7
    )

    # Heal the cache (it now sees the same 7): gauge returns to 0.
    registry2 = MetricRegistry()
    for rr in backend.list("resourcereservations"):
        cache._store.put(rr)
    CacheReporter(
        registry2, {"resourcereservations": cache}, backend=backend
    ).report_once()
    drift2 = registry2.snapshot()[R.UNEXPLAINED_DIFFERENCE]
    assert drift2 and drift2[0]["value"] == 0, drift2
