"""Component scenarios through the full scheduler (reference:
internal/extender/resource_test.go, unschedulablepods_test.go) — real
caches, real reservation manager, real packing kernels, in-memory backend.
"""

import pytest

from spark_scheduler_tpu.core.extender import (
    FAILURE_EARLIER_DRIVER,
    FAILURE_FIT,
    FAILURE_UNBOUND,
    SUCCESS,
    SUCCESS_ALREADY_BOUND,
    SUCCESS_SCHEDULED_EXTRA_EXECUTOR,
)
from spark_scheduler_tpu.models.kube import Container, Pod
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    static_allocation_spark_pods,
)


def test_gang_schedule_then_reject_extra_executor():
    """resource_test.go:26-47: schedule driver+2 executors, a third executor
    of the same app is rejected with failure-unbound."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = static_allocation_spark_pods("app-1", 2)
    results = h.schedule_app(pods, ["n1"])
    assert all(r.ok for r in results), [r.outcome for r in results]

    rr = h.get_reservation("namespace", "app-1")
    assert rr is not None
    assert set(rr.spec.reservations) == {"driver", "executor-1", "executor-2"}
    assert rr.status.pods == {
        "driver": "app-1-driver",
        "executor-1": "app-1-exec-1",
        "executor-2": "app-1-exec-2",
    }
    # persisted through async write-back to the backend
    assert h.backend.get("resourcereservations", "namespace", "app-1") is not None

    extra = Pod(
        name="app-1-exec-extra",
        namespace="namespace",
        labels=dict(pods[1].labels),
        scheduler_name=pods[1].scheduler_name,
        node_selector=dict(pods[1].node_selector),
        containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
    )
    result = h.schedule(extra, ["n1"])
    assert not result.ok
    assert result.outcome == FAILURE_UNBOUND


def test_replace_reservation_after_termination():
    """resource_test.go:49-69: a replacement executor takes over the dead
    executor's reservation slot."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = static_allocation_spark_pods("app-2", 2)
    results = h.schedule_app(pods, ["n1"])
    assert all(r.ok for r in results)

    h.terminate_pod(pods[2])  # exec-2 dies
    replacement = Pod(
        name="app-2-exec-replacement",
        namespace="namespace",
        labels=dict(pods[2].labels),
        scheduler_name=pods[2].scheduler_name,
        node_selector=dict(pods[2].node_selector),
        containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
    )
    result = h.schedule(replacement, ["n1"])
    assert result.ok and result.outcome == SUCCESS
    rr = h.get_reservation("namespace", "app-2")
    assert "app-2-exec-replacement" in rr.status.pods.values()
    assert "app-2-exec-2" not in rr.status.pods.values()


def test_executor_retry_is_idempotent():
    """Scheduling the same executor twice returns the already-bound node
    (success-already-bound, resource.go:377-388)."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = static_allocation_spark_pods("app-3", 1)
    assert all(r.ok for r in h.schedule_app(pods, ["n1"]))
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    again = h.extender.predicate(ExtenderArgs(pod=pods[1], node_names=["n1"]))
    assert again.ok and again.outcome == SUCCESS_ALREADY_BOUND


def test_driver_retry_returns_reserved_node():
    h = Harness()
    h.add_nodes(new_node("n1"), new_node("n2"))
    pods = static_allocation_spark_pods("app-4", 1)
    first = h.schedule(pods[0], ["n1", "n2"])
    assert first.ok
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    again = h.extender.predicate(ExtenderArgs(pod=pods[0], node_names=["n1", "n2"]))
    assert again.ok and again.node_names == first.node_names


def test_gang_does_not_fit_creates_demand():
    """failure-fit on too-large gang + Demand CR creation (resource.go:342-345,
    demand.go:82-108): driver unit + min-executor unit."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = static_allocation_spark_pods("app-5", 100)
    result = h.schedule(pods[0], ["n1"])
    assert not result.ok and result.outcome == FAILURE_FIT
    demands = h.demands()
    assert len(demands) == 1
    d = demands[0]
    assert d.name == "demand-app-5-driver"
    assert [u.count for u in d.spec.units] == [1, 100]
    # demand deleted when the driver later fits (cluster grows)
    for i in range(2, 15):
        h.add_nodes(new_node(f"n{i}"))
    result = h.schedule(pods[0], [f"n{i}" for i in range(1, 15)])
    assert result.ok
    assert h.demands() == []


def test_fifo_earlier_driver_blocks_later_driver():
    """resource.go:304-314: an older driver that can't fit blocks newer ones
    (failure-earlier-driver) when FIFO is on."""
    h = Harness(fifo=True)
    h.add_nodes(new_node("n1"))
    big = static_allocation_spark_pods("app-old", 20)  # will never fit
    small = static_allocation_spark_pods("app-new", 1)
    h.add_pods(*big)
    r = h.schedule(big[0], ["n1"])
    assert not r.ok and r.outcome == FAILURE_FIT
    r = h.schedule(small[0], ["n1"])
    assert not r.ok and r.outcome == FAILURE_EARLIER_DRIVER
    # the blocked driver also creates a demand for itself
    names = {d.name for d in h.demands()}
    assert "demand-app-new-driver" in names


def test_fifo_age_gate_skips_young_drivers():
    """fifoConfig age gate (resource.go:260-270): young unfitting drivers are
    skipped from FIFO consideration."""
    import time

    h = Harness(fifo=True)
    h.app.config.fifo_config.enforce_after_pod_age_s = 3600.0
    h.extender._config.fifo_config.enforce_after_pod_age_s = 3600.0
    h.add_nodes(new_node("n1"))
    big = static_allocation_spark_pods("app-old2", 20)
    big[0].creation_timestamp = time.time() - 10  # young
    small = static_allocation_spark_pods("app-new2", 1)
    small[0].creation_timestamp = time.time()
    h.add_pods(*big)
    assert not h.schedule(big[0], ["n1"]).ok
    r = h.schedule(small[0], ["n1"])
    assert r.ok, r.outcome


def test_dynamic_allocation_soft_reservation_over_min():
    """Dynamic allocation min=1 max=2 (resource_test.go:71-271): executor
    over min gets a soft reservation; over max is rejected."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = dynamic_allocation_spark_pods("app-da", 1, 2)
    driver, exec1, exec2 = pods
    assert h.schedule(driver, ["n1"]).ok
    rr = h.get_reservation("namespace", "app-da")
    assert set(rr.spec.reservations) == {"driver", "executor-1"}

    r1 = h.schedule(exec1, ["n1"])
    assert r1.ok and r1.outcome == SUCCESS

    r2 = h.schedule(exec2, ["n1"])
    assert r2.ok and r2.outcome == SUCCESS_SCHEDULED_EXTRA_EXECUTOR
    sr = h.soft_reservations()["app-da"]
    assert set(sr.reservations) == {"app-da-exec-2"}
    assert sr.reservations["app-da-exec-2"].node == "n1"

    extra = Pod(
        name="app-da-exec-3",
        namespace="namespace",
        labels=dict(exec1.labels),
        scheduler_name=exec1.scheduler_name,
        node_selector=dict(exec1.node_selector),
        containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
    )
    r3 = h.schedule(extra, ["n1"])
    assert not r3.ok and r3.outcome == FAILURE_UNBOUND


def test_dynamic_allocation_compaction_takes_over_dead_hard_slot():
    """When the hard-reserved executor dies, the soft-reserved one compacts
    into the freed hard slot (resourcereservations.go:238-316)."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    driver, exec1, exec2 = dynamic_allocation_spark_pods("app-da2", 1, 2)
    assert h.schedule(driver, ["n1"]).ok
    assert h.schedule(exec1, ["n1"]).ok
    assert h.schedule(exec2, ["n1"]).ok

    h.delete_pod(exec1)  # hard-slot executor dies -> queues compaction
    # next predicate call triggers compaction (resource.go:148)
    probe = static_allocation_spark_pods("probe", 0)
    h.schedule(probe[0], ["n1"])

    rr = h.get_reservation("namespace", "app-da2")
    assert rr.status.pods["executor-1"] == "app-da2-exec-2"
    sr = h.soft_reservations()["app-da2"]
    assert sr.reservations == {}
    assert sr.status.get("app-da2-exec-2") is False or "app-da2-exec-2" not in sr.reservations


def test_unschedulable_marker_capacity_check():
    """unschedulablepods_test.go:23-77: 2-exec app fits an empty cluster,
    100-exec app doesn't."""
    h = Harness()
    h.add_nodes(new_node("n1"), new_node("n2"))
    small = static_allocation_spark_pods("app-small", 2)[0]
    big = static_allocation_spark_pods("app-big", 100)[0]
    h.add_pods(small, big)
    marker = h.app.unschedulable_marker
    assert marker.does_pod_exceed_cluster_capacity(small) is False
    assert marker.does_pod_exceed_cluster_capacity(big) is True


def test_unschedulable_marker_gpu_shortage():
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = static_allocation_spark_pods("app-gpu", 2)
    pods[0].annotations["spark-executor-nvidia.com/gpu"] = "2"  # > 1 GPU/node
    h.add_pods(pods[0])
    assert h.app.unschedulable_marker.does_pod_exceed_cluster_capacity(pods[0]) is True


def test_failover_reconciliation_rebuilds_reservations():
    """failover.go:41-155: after losing the RR (simulating lost async
    writes), reconciliation rebuilds it from bound pods."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    pods = static_allocation_spark_pods("app-fo", 2)
    assert all(r.ok for r in h.schedule_app(pods, ["n1"]))

    # simulate lost write: nuke the RR from cache AND backend
    h.app.rr_cache.delete("namespace", "app-fo")
    h.app.rr_cache.flush()
    assert h.get_reservation("namespace", "app-fo") is None

    h.app.reconciler.sync_resource_reservations_and_demands()
    rr = h.get_reservation("namespace", "app-fo")
    assert rr is not None
    assert rr.spec.reservations["driver"].node == "n1"
    assert set(rr.status.pods.values()) == {
        "app-fo-driver",
        "app-fo-exec-1",
        "app-fo-exec-2",
    }


def test_failover_rebuilds_soft_reservations():
    """failover.go:164-231: extra executors (beyond min) are re-registered
    as soft reservations after state loss."""
    h = Harness()
    h.add_nodes(new_node("n1"))
    driver, exec1, exec2 = dynamic_allocation_spark_pods("app-fo2", 1, 2)
    assert h.schedule(driver, ["n1"]).ok
    assert h.schedule(exec1, ["n1"]).ok
    assert h.schedule(exec2, ["n1"]).ok

    # wipe the soft store (in-memory state lost on leader change)
    h.app.soft_store.remove_driver_reservation("app-fo2")
    h.app.reconciler.sync_resource_reservations_and_demands()
    sr = h.soft_reservations()["app-fo2"]
    assert set(sr.reservations) == {"app-fo2-exec-2"}


@pytest.mark.parametrize(
    "algo",
    [
        "tightly-pack",
        "distribute-evenly",
        "minimal-fragmentation",
        "single-az-tightly-pack",
        "single-az-minimal-fragmentation",
        "az-aware-tightly-pack",
    ],
)
def test_all_binpack_algos_schedule_end_to_end(algo):
    h = Harness(binpack_algo=algo)
    h.add_nodes(new_node("n1", zone="zone1"), new_node("n2", zone="zone2"))
    pods = static_allocation_spark_pods(f"app-{algo}", 3)
    results = h.schedule_app(pods, ["n1", "n2"])
    assert all(r.ok for r in results), [r.outcome for r in results]


def _run_fifo_scenario(batched: bool):
    """A FIFO scenario with a mixed queue: one blocked driver, a skippable
    young driver, admits before and after. Returns (outcomes, reservations)
    for comparison across admission paths."""
    h = Harness(binpack_algo="tightly-pack", fifo=True, batched_admission=batched)
    h.add_nodes(*(new_node(f"n{i}") for i in range(4)))
    nodes = [f"n{i}" for i in range(4)]

    outcomes = []
    # App A: fits (driver+2 execs) and is admitted.
    a = static_allocation_spark_pods("app-a", 2)
    outcomes.append(h.schedule(a[0], nodes).outcome)
    # App B driver arrives but is NOT scheduled yet (pending; joins FIFO).
    b = static_allocation_spark_pods("app-b", 30)  # cannot ever fit
    h.add_pods(b[0])
    # App C: later driver; B is pending-unschedulable ahead of it and not
    # skippable => failure-earlier-driver.
    c = static_allocation_spark_pods("app-c", 1)
    outcomes.append(h.schedule(c[0], nodes).outcome)
    # Remove B; C retries and is admitted.
    h.delete_pod(b[0])
    outcomes.append(h.schedule(c[0], nodes).outcome)
    # Executors of A and C bind.
    for p in a[1:]:
        outcomes.append(h.schedule(p, nodes).outcome)
    for p in c[1:]:
        outcomes.append(h.schedule(p, nodes).outcome)

    reservations = {}
    for app in ("app-a", "app-c"):
        rr = h.get_reservation("namespace", app)
        reservations[app] = (
            {k: (v.node, v.resources.as_tuple()) for k, v in rr.spec.reservations.items()},
            dict(rr.status.pods),
        ) if rr is not None else None
    return outcomes, reservations


def test_batched_admission_matches_sequential_path():
    """VERDICT r1 #1 'done' criterion: the windowed/batched driver admission
    produces exactly the decisions of the per-request sequential path."""
    got_b = _run_fifo_scenario(batched=True)
    got_s = _run_fifo_scenario(batched=False)
    assert got_b == got_s
    outcomes, reservations = got_b
    assert outcomes[0] == SUCCESS
    assert outcomes[1] == FAILURE_EARLIER_DRIVER
    assert outcomes[2] == SUCCESS
    assert reservations["app-a"] is not None
    assert reservations["app-c"] is not None
