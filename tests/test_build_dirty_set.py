"""O(K + changed) tensor build equivalence (ISSUE 13).

The per-window tensor build no longer runs the dense
`(mirror != host.available)` sweep or the full arena materialization:
the feature store journals EXACTLY which rows' availability inputs
changed, the resident build patches those rows in place, and the
pipelined mirror syncs by scattering them. Pinned here:

  - dirty-set build == dense-compare oracle BIT-IDENTICAL decisions
    under randomized add/update/delete churn (including the
    delete-tombstone + row-recycle interleavings of ISSUE 12) x
    {pruned, unpruned} x device pool {1, 2}, with the in-build oracle
    (`solver.build-oracle`) armed on the dirty-set side — a missed row
    fails the build itself, not just the comparison;
  - in-flight reconstruction: a window dispatched BEFORE external churn
    patched the resident buffer escalates/reconstructs against its
    dispatch-time view (the undo journal), byte-identical to the
    dense twin;
  - the steady-state serving loop runs ZERO dense mirror sweeps
    (`mirror_rows_compared` stays 0 — the counter the CI scale smoke
    pins at the million-node tier);
  - lazy warm start: a discard_pipeline restart re-serves without
    re-paying the planner's O(N log N) cold rebuild, and decisions
    after the restart still match the dense twin;
  - amortized roster growth: an ADD burst reallocates no resident
    buffer (`array_grows` 0) and pays zero roster rebuilds.
"""

import dataclasses

import numpy as np
import pytest

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)


def _mk(pool, prune, *, dirty: bool, n0: int):
    kw = dict(binpack_algo="tightly-pack", fifo=False)
    if pool > 1:
        kw["solver_device_pool"] = pool
    if prune:
        kw["solver_prune_top_k"] = prune
        kw["solver_prune_slack"] = 0.75
    h = Harness(**kw)
    h.add_nodes(
        *[new_node(f"n{i:03d}", zone=f"zone{i % 2}") for i in range(n0)]
    )
    if dirty:
        # The in-build oracle: every dirty-set mirror sync re-runs the
        # dense compare and raises on a missed row.
        h.app.solver.build_oracle = True
    else:
        # The DENSE twin: withholding the journal sends every build down
        # the full-materialization + dense-compare path (the pre-ISSUE-13
        # semantics, byte for byte).
        h.app.extender.features.journal_enabled = False
    return h


def _serve(h, live, seq, n_req=2):
    names = list(live)
    drivers = []
    for _ in range(n_req):
        d = static_allocation_spark_pods(f"bds-{next(seq)}", 2)[0]
        h.add_pods(d)
        drivers.append(d)
    t = h.extender.predicate_window_dispatch(
        [ExtenderArgs(pod=d, node_names=names) for d in drivers]
    )
    return [tuple(r.node_names) for r in h.extender.predicate_window_complete(t)]


def _churn_event(h, rng, live, spare, deleted):
    """One seeded node event; `deleted` names become re-addable, so the
    stream exercises delete-tombstone -> row-recycle interleavings."""
    op = rng.random()
    if op < 0.3 and (spare or deleted):
        # Re-adding a recently deleted name reuses its recycled registry
        # row through the tombstone-release path.
        name = deleted.pop() if deleted and rng.random() < 0.5 else (
            spare.pop() if spare else deleted.pop()
        )
        h.add_nodes(new_node(name, zone=f"zone{len(live) % 2}"))
        live.append(name)
        return ("add", name)
    if op < 0.75 and live:
        name = live[int(rng.integers(0, len(live)))]
        cur = h.backend.get_node(name)
        h.backend.update(
            "nodes",
            dataclasses.replace(cur, unschedulable=not cur.unschedulable),
        )
        return ("update", name)
    if len(live) > 8:
        name = live.pop(int(rng.integers(0, len(live))))
        h.backend.delete("nodes", "", name)
        deleted.append(name)
        return ("delete", name)
    return ("noop", None)


@pytest.mark.parametrize("pool,prune", [(1, 0), (1, 4), (2, 0), (2, 4)])
def test_dirty_set_build_matches_dense_oracle_under_churn(pool, prune):
    n0 = 48
    h_dirty = _mk(pool, prune, dirty=True, n0=n0)
    h_dense = _mk(pool, prune, dirty=False, n0=n0)
    live_a = [f"n{i:03d}" for i in range(n0)]
    live_b = list(live_a)
    spare_a = [f"x{j:02d}" for j in range(20, 0, -1)]
    spare_b = list(spare_a)
    del_a: list = []
    del_b: list = []
    rng_a = np.random.default_rng(20813)
    rng_b = np.random.default_rng(20813)
    seq = iter(range(100_000))
    for step in range(18):
        ev_a = _churn_event(h_dirty, rng_a, live_a, spare_a, del_a)
        ev_b = _churn_event(h_dense, rng_b, live_b, spare_b, del_b)
        assert ev_a == ev_b  # identical seeded streams
        start = next(seq)
        a = _serve(h_dirty, live_a, iter(range(start, start + 2)))
        b = _serve(h_dense, live_b, iter(range(start, start + 2)))
        assert a == b, f"step {step} ({ev_a}): {a} vs {b}"
    bs = h_dirty.app.solver.build_stats
    if prune and pool == 1:
        # The dirty-set sync actually served (the oracle checked it).
        # Pooled fetches debit the mirror densely (their placements
        # reassemble across partitions), so the pool arm legitimately
        # rides the dense fallback — the equivalence above is the claim
        # there.
        assert bs["dirty_rows"] > 0, bs
        assert bs["oracle_checks"] > 0, bs
    # The dense twin never took the dirty path.
    assert h_dense.app.solver.build_stats["dirty_rows"] == 0
    h_dirty.app.stop()
    h_dense.app.stop()


def test_steady_state_runs_zero_dense_mirror_sweeps():
    """After the cold build, a no-event pruned serving loop performs ZERO
    dense mirror sweeps — the `mirror_rows_compared` claim the CI scale
    smoke pins at 1M, asserted here at tier-1 scale."""
    h = _mk(1, 4, dirty=True, n0=64)
    live = [f"n{i:03d}" for i in range(64)]
    seq = iter(range(1000))
    _serve(h, live, seq)  # cold build + full upload
    bs = h.app.solver.build_stats
    compared0 = bs["mirror_rows_compared"]
    dense0 = bs["mirror_dense_syncs"]
    for _ in range(8):
        out = _serve(h, live, seq)
        assert all(out), out
    assert bs["mirror_rows_compared"] == compared0, bs
    assert bs["mirror_dense_syncs"] == dense0, bs
    assert bs["incremental_builds"] >= 8, bs
    h.app.stop()


def test_inflight_churn_escalation_reconstructs_dispatch_time_view():
    """A window dispatched, THEN external usage churn patches the resident
    availability in place, THEN the window fetches with a starved-K
    certificate (escalation): the re-solve must run against the
    dispatch-time view (undo journal), byte-identical to the dense twin
    whose buffers froze naturally."""
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )
    from spark_scheduler_tpu.models.resources import Resources

    outs = {}
    for mode in ("dirty", "dense"):
        kw = dict(
            binpack_algo="tightly-pack", fifo=False,
            solver_prune_top_k=1, solver_prune_slack=0.01,
        )
        h = Harness(**kw)
        h.add_nodes(
            *[new_node(f"n{i:03d}", zone=f"zone{i % 2}") for i in range(32)]
        )
        if mode == "dirty":
            h.app.solver.build_oracle = True
        else:
            h.app.extender.features.journal_enabled = False
        live = [f"n{i:03d}" for i in range(32)]
        seq = iter(range(100))
        _serve(h, live, seq)  # warm
        ext = h.extender
        names = list(live)
        d1 = static_allocation_spark_pods(f"if-{mode}-1", 2)[0]
        h.add_pods(d1)
        t1 = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d1, node_names=names)]
        )
        # External churn lands between t1's dispatch and its fetch: a
        # reservation created outside the window path patches the
        # resident availability (tracker delta -> journal -> in-place
        # patch during t2's build).
        blocker = static_allocation_spark_pods(f"if-{mode}-blk", 1)[0]
        h.backend.add_pod(blocker)
        rr = new_resource_reservation(
            "n005", ["n005"], blocker,
            Resources.from_quantities("2", "2Gi"),
            Resources.from_quantities("1", "1Gi"),
        )
        h.app.rr_cache.create(rr)
        d2 = static_allocation_spark_pods(f"if-{mode}-2", 2)[0]
        h.add_pods(d2)
        t2 = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d2, node_names=names)]
        )
        r1 = [tuple(r.node_names) for r in ext.predicate_window_complete(t1)]
        r2 = [tuple(r.node_names) for r in ext.predicate_window_complete(t2)]
        outs[mode] = (r1, r2)
        if mode == "dirty":
            # The starved K actually escalated (the reconstruction ran).
            assert h.app.solver.prune_stats["escalations"] > 0, (
                h.app.solver.prune_stats
            )
        h.app.stop()
    assert outs["dirty"] == outs["dense"], outs


def test_warm_restart_persists_planner():
    """discard_pipeline (the warm-restart analog) keeps the planner's
    resident per-zone orders: zero index rebuilds across the restart, and
    post-restart decisions equal the dense twin's."""
    h = _mk(1, 4, dirty=True, n0=64)
    live = [f"n{i:03d}" for i in range(64)]
    seq = iter(range(1000))
    for _ in range(3):
        _serve(h, live, seq)
    planner = h.app.solver._planner
    assert planner is not None
    rebuilds = planner.index.rebuilds
    h.app.solver.discard_pipeline()
    out = _serve(h, live, seq)
    assert all(out), out
    assert planner.index.rebuilds == rebuilds, (
        "warm restart re-paid the planner cold rebuild"
    )
    # Control: with lazy warm start OFF the restart invalidates.
    h2 = Harness(
        binpack_algo="tightly-pack", fifo=False,
        solver_prune_top_k=4, solver_prune_slack=0.75,
        solver_lazy_warm_start=False,
    )
    h2.add_nodes(
        *[new_node(f"n{i:03d}", zone=f"zone{i % 2}") for i in range(64)]
    )
    for _ in range(3):
        _serve(h2, live, seq)
    planner2 = h2.app.solver._planner
    rebuilds2 = planner2.index.rebuilds
    h2.app.solver.discard_pipeline()
    _serve(h2, live, seq)
    assert planner2.index.rebuilds == rebuilds2 + 1, (
        "lazy-warm-start=false must keep the hard invalidate"
    )
    h.app.stop()
    h2.app.stop()


def test_add_burst_zero_reallocations_and_rebuilds():
    """A node-ADD burst inside the capacity bucket reallocates NO resident
    buffer (`array_grows`) and pays zero roster rebuilds — the amortized
    growth claim as counters."""
    h = _mk(1, 0, dirty=True, n0=40)
    live = [f"n{i:03d}" for i in range(40)]
    seq = iter(range(1000))
    _serve(h, live, seq)
    store = h.app.extender.features
    grows0 = store.array_grows
    rebuilds0 = store.stats()["roster_rebuilds"]
    # 40 -> 60 nodes stays inside the 64-bucket: zero reallocations.
    for j in range(20):
        name = f"zadd{j:02d}"
        h.add_nodes(new_node(name, zone=f"zone{j % 2}"))
        live.append(name)
        out = _serve(h, live, seq, n_req=1)
        assert all(out), out
    st = store.stats()
    assert store.array_grows == grows0, st
    assert st["roster_rebuilds"] == rebuilds0, st
    assert st["roster_add_patches"] >= 20, st
    h.app.stop()


def test_delete_between_dispatch_and_complete_keeps_old_roster_view():
    """A node DELETE landing between a window's dispatch and its
    completion must not tear the ticket's parked snapshot: the delete
    patch copies-on-write the roster list AND the by-name map (an
    in-place pop would KeyError the completion's domain lookup)."""
    h = _mk(1, 4, dirty=True, n0=32)
    live = [f"n{i:03d}" for i in range(32)]
    seq = iter(range(100))
    _serve(h, live, seq)  # warm
    ext = h.extender
    d1 = static_allocation_spark_pods("dl-1", 2)[0]
    h.add_pods(d1)
    t1 = ext.predicate_window_dispatch(
        [ExtenderArgs(pod=d1, node_names=list(live))]
    )
    # Delete while W1 is in flight, and force a refresh that applies it
    # (W2's dispatch snapshots).
    h.backend.delete("nodes", "", "n030")
    d2 = static_allocation_spark_pods("dl-2", 2)[0]
    h.add_pods(d2)
    t2 = ext.predicate_window_dispatch(
        [ExtenderArgs(pod=d2, node_names=[n for n in live if n != "n030"])]
    )
    r1 = [tuple(r.node_names) for r in ext.predicate_window_complete(t1)]
    r2 = [tuple(r.node_names) for r in ext.predicate_window_complete(t2)]
    assert all(r1) and all(r2), (r1, r2)
    assert h.app.extender.features.stats()["roster_delete_patches"] >= 1
    h.app.stop()


def test_dense_fallback_on_journal_gap_is_exact():
    """A journal break mid-stream (simulated by toggling journal_enabled)
    downgrades to the dense compare for those builds and back — decisions
    stay identical to an always-dense twin."""
    h_a = _mk(1, 4, dirty=True, n0=48)
    h_b = _mk(1, 4, dirty=False, n0=48)
    live = [f"n{i:03d}" for i in range(48)]
    seq = iter(range(10_000))
    for step in range(9):
        if step == 3:
            h_a.app.extender.features.journal_enabled = False
        if step == 6:
            h_a.app.extender.features.journal_enabled = True
        start = next(seq)
        a = _serve(h_a, live, iter(range(start, start + 2)))
        b = _serve(h_b, live, iter(range(start, start + 2)))
        assert a == b, f"step {step}: {a} vs {b}"
    h_a.app.stop()
    h_b.app.stop()
