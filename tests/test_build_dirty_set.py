"""O(K + changed) tensor build equivalence (ISSUE 13).

The per-window tensor build no longer runs the dense
`(mirror != host.available)` sweep or the full arena materialization:
the feature store journals EXACTLY which rows' availability inputs
changed, the resident build patches those rows in place, and the
pipelined mirror syncs by scattering them. Pinned here:

  - dirty-set build == dense-compare oracle BIT-IDENTICAL decisions
    under randomized add/update/delete churn (including the
    delete-tombstone + row-recycle interleavings of ISSUE 12) x
    {pruned, unpruned} x device pool {1, 2}, with the in-build oracle
    (`solver.build-oracle`) armed on the dirty-set side — a missed row
    fails the build itself, not just the comparison;
  - in-flight reconstruction: a window dispatched BEFORE external churn
    patched the resident buffer escalates/reconstructs against its
    dispatch-time view (the undo journal), byte-identical to the
    dense twin;
  - the steady-state serving loop runs ZERO dense mirror sweeps
    (`mirror_rows_compared` stays 0 — the counter the CI scale smoke
    pins at the million-node tier);
  - lazy warm start: a discard_pipeline restart re-serves without
    re-paying the planner's O(N log N) cold rebuild, and decisions
    after the restart still match the dense twin;
  - amortized roster growth: an ADD burst reallocates no resident
    buffer (`array_grows` 0) and pays zero roster rebuilds.
"""

import dataclasses

import numpy as np
import pytest

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)


def _mk(pool, prune, *, dirty: bool, n0: int):
    kw = dict(binpack_algo="tightly-pack", fifo=False)
    if pool > 1:
        kw["solver_device_pool"] = pool
    if prune:
        kw["solver_prune_top_k"] = prune
        kw["solver_prune_slack"] = 0.75
    h = Harness(**kw)
    h.add_nodes(
        *[new_node(f"n{i:03d}", zone=f"zone{i % 2}") for i in range(n0)]
    )
    if dirty:
        # The in-build oracle: every dirty-set mirror sync re-runs the
        # dense compare and raises on a missed row.
        h.app.solver.build_oracle = True
    else:
        # The DENSE twin: withholding the journal sends every build down
        # the full-materialization + dense-compare path (the pre-ISSUE-13
        # semantics, byte for byte).
        h.app.extender.features.journal_enabled = False
    return h


def _serve(h, live, seq, n_req=2):
    names = list(live)
    drivers = []
    for _ in range(n_req):
        d = static_allocation_spark_pods(f"bds-{next(seq)}", 2)[0]
        h.add_pods(d)
        drivers.append(d)
    t = h.extender.predicate_window_dispatch(
        [ExtenderArgs(pod=d, node_names=names) for d in drivers]
    )
    return [tuple(r.node_names) for r in h.extender.predicate_window_complete(t)]


def _churn_event(h, rng, live, spare, deleted):
    """One seeded node event; `deleted` names become re-addable, so the
    stream exercises delete-tombstone -> row-recycle interleavings."""
    op = rng.random()
    if op < 0.3 and (spare or deleted):
        # Re-adding a recently deleted name reuses its recycled registry
        # row through the tombstone-release path.
        name = deleted.pop() if deleted and rng.random() < 0.5 else (
            spare.pop() if spare else deleted.pop()
        )
        h.add_nodes(new_node(name, zone=f"zone{len(live) % 2}"))
        live.append(name)
        return ("add", name)
    if op < 0.75 and live:
        name = live[int(rng.integers(0, len(live)))]
        cur = h.backend.get_node(name)
        h.backend.update(
            "nodes",
            dataclasses.replace(cur, unschedulable=not cur.unschedulable),
        )
        return ("update", name)
    if len(live) > 8:
        name = live.pop(int(rng.integers(0, len(live))))
        h.backend.delete("nodes", "", name)
        deleted.append(name)
        return ("delete", name)
    return ("noop", None)


@pytest.mark.parametrize("pool,prune", [(1, 0), (1, 4), (2, 0), (2, 4)])
def test_dirty_set_build_matches_dense_oracle_under_churn(pool, prune):
    n0 = 48
    h_dirty = _mk(pool, prune, dirty=True, n0=n0)
    h_dense = _mk(pool, prune, dirty=False, n0=n0)
    live_a = [f"n{i:03d}" for i in range(n0)]
    live_b = list(live_a)
    spare_a = [f"x{j:02d}" for j in range(20, 0, -1)]
    spare_b = list(spare_a)
    del_a: list = []
    del_b: list = []
    rng_a = np.random.default_rng(20813)
    rng_b = np.random.default_rng(20813)
    seq = iter(range(100_000))
    for step in range(18):
        ev_a = _churn_event(h_dirty, rng_a, live_a, spare_a, del_a)
        ev_b = _churn_event(h_dense, rng_b, live_b, spare_b, del_b)
        assert ev_a == ev_b  # identical seeded streams
        start = next(seq)
        a = _serve(h_dirty, live_a, iter(range(start, start + 2)))
        b = _serve(h_dense, live_b, iter(range(start, start + 2)))
        assert a == b, f"step {step} ({ev_a}): {a} vs {b}"
    bs = h_dirty.app.solver.build_stats
    if prune:
        # The dirty-set sync actually served (the oracle checked it) —
        # on the pool arms too: pooled fetches debit the mirror SPARSELY
        # since ISSUE 15 (the union of partition debit rows rides the
        # pending ledger), so every arm stays on the event-fed sync.
        assert bs["dirty_rows"] > 0, bs
        assert bs["oracle_checks"] > 0, bs
    # The dirty twin never fell back to a dense [N] mirror sweep — the
    # pooled arms included (ISSUE 15 tentpole (a)).
    assert bs["mirror_dense_syncs"] == 0, bs
    # The dense twin never took the dirty path.
    assert h_dense.app.solver.build_stats["dirty_rows"] == 0
    h_dirty.app.stop()
    h_dense.app.stop()


def test_steady_state_runs_zero_dense_mirror_sweeps():
    """After the cold build, a no-event pruned serving loop performs ZERO
    dense mirror sweeps — the `mirror_rows_compared` claim the CI scale
    smoke pins at 1M, asserted here at tier-1 scale."""
    h = _mk(1, 4, dirty=True, n0=64)
    live = [f"n{i:03d}" for i in range(64)]
    seq = iter(range(1000))
    _serve(h, live, seq)  # cold build + full upload
    bs = h.app.solver.build_stats
    compared0 = bs["mirror_rows_compared"]
    dense0 = bs["mirror_dense_syncs"]
    for _ in range(8):
        out = _serve(h, live, seq)
        assert all(out), out
    assert bs["mirror_rows_compared"] == compared0, bs
    assert bs["mirror_dense_syncs"] == dense0, bs
    assert bs["incremental_builds"] >= 8, bs
    h.app.stop()


def test_inflight_churn_escalation_reconstructs_dispatch_time_view():
    """A window dispatched, THEN external usage churn patches the resident
    availability in place, THEN the window fetches with a starved-K
    certificate (escalation): the re-solve must run against the
    dispatch-time view (undo journal), byte-identical to the dense twin
    whose buffers froze naturally."""
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )
    from spark_scheduler_tpu.models.resources import Resources

    outs = {}
    for mode in ("dirty", "dense"):
        kw = dict(
            binpack_algo="tightly-pack", fifo=False,
            solver_prune_top_k=1, solver_prune_slack=0.01,
        )
        h = Harness(**kw)
        h.add_nodes(
            *[new_node(f"n{i:03d}", zone=f"zone{i % 2}") for i in range(32)]
        )
        if mode == "dirty":
            h.app.solver.build_oracle = True
        else:
            h.app.extender.features.journal_enabled = False
        live = [f"n{i:03d}" for i in range(32)]
        seq = iter(range(100))
        _serve(h, live, seq)  # warm
        ext = h.extender
        names = list(live)
        d1 = static_allocation_spark_pods(f"if-{mode}-1", 2)[0]
        h.add_pods(d1)
        t1 = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d1, node_names=names)]
        )
        # External churn lands between t1's dispatch and its fetch: a
        # reservation created outside the window path patches the
        # resident availability (tracker delta -> journal -> in-place
        # patch during t2's build).
        blocker = static_allocation_spark_pods(f"if-{mode}-blk", 1)[0]
        h.backend.add_pod(blocker)
        rr = new_resource_reservation(
            "n005", ["n005"], blocker,
            Resources.from_quantities("2", "2Gi"),
            Resources.from_quantities("1", "1Gi"),
        )
        h.app.rr_cache.create(rr)
        d2 = static_allocation_spark_pods(f"if-{mode}-2", 2)[0]
        h.add_pods(d2)
        t2 = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d2, node_names=names)]
        )
        r1 = [tuple(r.node_names) for r in ext.predicate_window_complete(t1)]
        r2 = [tuple(r.node_names) for r in ext.predicate_window_complete(t2)]
        outs[mode] = (r1, r2)
        if mode == "dirty":
            # The starved K actually escalated (the reconstruction ran).
            assert h.app.solver.prune_stats["escalations"] > 0, (
                h.app.solver.prune_stats
            )
        h.app.stop()
    assert outs["dirty"] == outs["dense"], outs


def test_warm_restart_persists_planner():
    """discard_pipeline (the warm-restart analog) keeps the planner's
    resident per-zone orders: zero index rebuilds across the restart, and
    post-restart decisions equal the dense twin's."""
    h = _mk(1, 4, dirty=True, n0=64)
    live = [f"n{i:03d}" for i in range(64)]
    seq = iter(range(1000))
    for _ in range(3):
        _serve(h, live, seq)
    planner = h.app.solver._planner
    assert planner is not None
    rebuilds = planner.index.rebuilds
    h.app.solver.discard_pipeline()
    out = _serve(h, live, seq)
    assert all(out), out
    assert planner.index.rebuilds == rebuilds, (
        "warm restart re-paid the planner cold rebuild"
    )
    # Control: with lazy warm start OFF the restart invalidates.
    h2 = Harness(
        binpack_algo="tightly-pack", fifo=False,
        solver_prune_top_k=4, solver_prune_slack=0.75,
        solver_lazy_warm_start=False,
    )
    h2.add_nodes(
        *[new_node(f"n{i:03d}", zone=f"zone{i % 2}") for i in range(64)]
    )
    for _ in range(3):
        _serve(h2, live, seq)
    planner2 = h2.app.solver._planner
    rebuilds2 = planner2.index.rebuilds
    h2.app.solver.discard_pipeline()
    _serve(h2, live, seq)
    assert planner2.index.rebuilds == rebuilds2 + 1, (
        "lazy-warm-start=false must keep the hard invalidate"
    )
    h.app.stop()
    h2.app.stop()


def test_add_burst_zero_reallocations_and_rebuilds():
    """A node-ADD burst inside the capacity bucket reallocates NO resident
    buffer (`array_grows`) and pays zero roster rebuilds — the amortized
    growth claim as counters."""
    h = _mk(1, 0, dirty=True, n0=40)
    live = [f"n{i:03d}" for i in range(40)]
    seq = iter(range(1000))
    _serve(h, live, seq)
    store = h.app.extender.features
    grows0 = store.array_grows
    rebuilds0 = store.stats()["roster_rebuilds"]
    # 40 -> 60 nodes stays inside the 64-bucket: zero reallocations.
    for j in range(20):
        name = f"zadd{j:02d}"
        h.add_nodes(new_node(name, zone=f"zone{j % 2}"))
        live.append(name)
        out = _serve(h, live, seq, n_req=1)
        assert all(out), out
    st = store.stats()
    assert store.array_grows == grows0, st
    assert st["roster_rebuilds"] == rebuilds0, st
    assert st["roster_add_patches"] >= 20, st
    h.app.stop()


def test_delete_between_dispatch_and_complete_keeps_old_roster_view():
    """A node DELETE landing between a window's dispatch and its
    completion must not tear the ticket's parked snapshot: the delete
    patch copies-on-write the roster list AND the by-name map (an
    in-place pop would KeyError the completion's domain lookup)."""
    h = _mk(1, 4, dirty=True, n0=32)
    live = [f"n{i:03d}" for i in range(32)]
    seq = iter(range(100))
    _serve(h, live, seq)  # warm
    ext = h.extender
    d1 = static_allocation_spark_pods("dl-1", 2)[0]
    h.add_pods(d1)
    t1 = ext.predicate_window_dispatch(
        [ExtenderArgs(pod=d1, node_names=list(live))]
    )
    # Delete while W1 is in flight, and force a refresh that applies it
    # (W2's dispatch snapshots).
    h.backend.delete("nodes", "", "n030")
    d2 = static_allocation_spark_pods("dl-2", 2)[0]
    h.add_pods(d2)
    t2 = ext.predicate_window_dispatch(
        [ExtenderArgs(pod=d2, node_names=[n for n in live if n != "n030"])]
    )
    r1 = [tuple(r.node_names) for r in ext.predicate_window_complete(t1)]
    r2 = [tuple(r.node_names) for r in ext.predicate_window_complete(t2)]
    assert all(r1) and all(r2), (r1, r2)
    assert h.app.extender.features.stats()["roster_delete_patches"] >= 1
    h.app.stop()


def test_dense_fallback_on_journal_gap_is_exact():
    """A journal break mid-stream (simulated by toggling journal_enabled)
    downgrades to the dense compare for those builds and back — decisions
    stay identical to an always-dense twin."""
    h_a = _mk(1, 4, dirty=True, n0=48)
    h_b = _mk(1, 4, dirty=False, n0=48)
    live = [f"n{i:03d}" for i in range(48)]
    seq = iter(range(10_000))
    for step in range(9):
        if step == 3:
            h_a.app.extender.features.journal_enabled = False
        if step == 6:
            h_a.app.extender.features.journal_enabled = True
        start = next(seq)
        a = _serve(h_a, live, iter(range(start, start + 2)))
        b = _serve(h_b, live, iter(range(start, start + 2)))
        assert a == b, f"step {step}: {a} vs {b}"
    h_a.app.stop()
    h_b.app.stop()


# -------------------------- pooled / partitioned serving (ISSUE 15) ----------


def _mk_grouped(pool, prune, *, dirty: bool, n0: int):
    """Harness with nodes split across TWO instance groups, so 2-request
    cross-group windows PARTITION across the device pool."""
    kw = dict(binpack_algo="tightly-pack", fifo=False)
    if pool > 1:
        kw["solver_device_pool"] = pool
    if prune:
        kw["solver_prune_top_k"] = prune
        kw["solver_prune_slack"] = 0.75
    h = Harness(**kw)
    h.add_nodes(
        *[
            new_node(
                f"n{i:03d}", zone=f"zone{i % 2}",
                instance_group=f"ig{i % 2}",
            )
            for i in range(n0)
        ]
    )
    if dirty:
        h.app.solver.build_oracle = True
    else:
        h.app.extender.features.journal_enabled = False
    return h


def _serve_grouped(h, live, seq):
    """One 2-request window with the requests pinned to DIFFERENT
    instance groups — the pooled partition path."""
    names = list(live)
    drivers = []
    for g in ("ig0", "ig1"):
        d = static_allocation_spark_pods(
            f"pgd-{next(seq)}", 2, instance_group=g
        )[0]
        h.add_pods(d)
        drivers.append(d)
    t = h.extender.predicate_window_dispatch(
        [ExtenderArgs(pod=d, node_names=names) for d in drivers]
    )
    return [
        tuple(r.node_names)
        for r in h.extender.predicate_window_complete(t)
    ]


@pytest.mark.parametrize("prune", [0, 4])
def test_pooled_partitioned_churn_zero_dense_mirror_syncs(prune):
    """Pool-2 partitioned serving under node-update churn debits the
    mirror SPARSELY (ISSUE 15 tentpole (a)): decisions bit-match the
    dense twin, `mirror_dense_syncs` stays 0, the pending ledger carries
    the partition debit rows, and (pruned arm) the per-domain plan
    contexts re-serve kept sets and gathered statics per partition."""
    n0 = 48
    h_dirty = _mk_grouped(2, prune, dirty=True, n0=n0)
    h_dense = _mk_grouped(2, prune, dirty=False, n0=n0)
    live = [f"n{i:03d}" for i in range(n0)]
    # Lockstep per-harness app-id sequences: both twins see identical
    # pod names, and no id is ever reused within a twin.
    seq_a = iter(range(100_000))
    seq_b = iter(range(100_000))
    rng_a = np.random.default_rng(4051)
    rng_b = np.random.default_rng(4051)
    # Warm: cold featurize + the per-domain cold sweeps.
    for _ in range(2):
        a = _serve_grouped(h_dirty, live, seq_a)
        b = _serve_grouped(h_dense, live, seq_b)
        assert a == b
    st = h_dirty.app.solver.prune_stats
    sweep_after_warm = st["planner_sweep_rows"]
    for step in range(8):
        for h, rng in ((h_dirty, rng_a), (h_dense, rng_b)):
            name = live[int(rng.integers(0, len(live)))]
            cur = h.backend.get_node(name)
            h.backend.update(
                "nodes",
                dataclasses.replace(
                    cur, unschedulable=not cur.unschedulable
                ),
            )
        for _ in range(2):
            a = _serve_grouped(h_dirty, live, seq_a)
            b = _serve_grouped(h_dense, live, seq_b)
            assert a == b, f"step {step}: {a} vs {b}"
    bs = h_dirty.app.solver.build_stats
    assert bs["mirror_dense_syncs"] == 0, bs
    assert bs["pooled_debit_rows"] > 0, bs
    paths = h_dirty.app.solver.window_path_counts
    assert paths.get("pool", 0) > 0, paths
    if prune:
        # Per-partition plan/gather reuse engaged (tentpole (b)), and
        # churn never re-paid a per-domain O(N) sweep after the cold
        # context builds.
        assert st["windows"] > 0, st
        assert st["plan_reuse"] > 0, st
        assert st["gather_reuse"] > 0, st
        assert st["planner_sweep_rows"] == sweep_after_warm, st
        assert st["escalations"] == 0, st
    h_dirty.app.stop()
    h_dense.app.stop()


def test_pooled_slot_failure_redispatch_keeps_sparse_debits():
    """A slot dying mid-burst re-dispatches its partition on the
    survivor byte-identically (ISSUE 9 contract) — and the recovery
    never downgrades the mirror sync to a dense sweep (ISSUE 15)."""
    from spark_scheduler_tpu.faults import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )

    h = _mk_grouped(2, 4, dirty=True, n0=32)
    h2 = _mk_grouped(2, 4, dirty=False, n0=32)
    live = [f"n{i:03d}" for i in range(32)]
    seq_a = iter(range(0, 1000))
    seq_b = iter(range(0, 1000))
    outs_a, outs_b = [], []
    for _ in range(2):  # warm: 2 partitioned windows = 4 dispatch events
        outs_a.append(_serve_grouped(h, live, seq_a))
    # `at` indexes the surface's MATCHING events from injector install:
    # the first faulted window's second partition solve dies mid-burst.
    plan = FaultPlan(
        seed=0, name="pool-slot-kill",
        specs=[
            FaultSpec(
                surface="device.dispatch", mode="error", at=[1], limit=1
            )
        ],
    )
    with FaultInjector(plan) as inj:
        inj.install_device()
        for _ in range(2):
            outs_a.append(_serve_grouped(h, live, seq_a))
    outs_a.append(_serve_grouped(h, live, seq_a))
    for _ in range(5):
        outs_b.append(_serve_grouped(h2, live, seq_b))
    assert outs_a == outs_b, "slot-failure recovery diverged"
    assert h.app.solver.redispatch_count >= 1
    bs = h.app.solver.build_stats
    assert bs["mirror_dense_syncs"] == 0, bs
    h.app.stop()
    h2.app.stop()


@pytest.mark.parametrize("blocker_node", ["n004", "n005"])
def test_pooled_partition_escalation_interleaving_matches_dense(
    blocker_node,
):
    """In-flight churn between a partitioned pooled window's dispatch
    and fetch starves its certificate: the partition escalates to the
    exact re-solve, decisions still bit-match the unpruned single-device
    twin, and the mirror never dense-sweeps. Parametrized over the
    blocker's instance group so BOTH part orders run — in particular the
    second-part escalation, where the first partition's sparse commits
    must back-fill the lazily-materialized dense placements (a later
    in-flight window subtracts them as priors; regression for the
    double-booking found in review)."""
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )
    from spark_scheduler_tpu.models.resources import Resources

    outs = {}
    for mode in ("dirty", "dense"):
        kw = dict(binpack_algo="tightly-pack", fifo=False)
        if mode == "dirty":
            kw.update(
                solver_device_pool=2,
                solver_prune_top_k=1,
                solver_prune_slack=0.01,
            )
        h = Harness(**kw)
        h.add_nodes(
            *[
                new_node(
                    f"n{i:03d}", zone=f"zone{i % 2}",
                    instance_group=f"ig{i % 2}",
                )
                for i in range(32)
            ]
        )
        if mode == "dirty":
            h.app.solver.build_oracle = True
        live = [f"n{i:03d}" for i in range(32)]
        seq = iter(range(100))
        _serve_grouped(h, live, seq)  # warm
        ext = h.extender
        drivers = []
        for g in ("ig0", "ig1"):
            d = static_allocation_spark_pods(
                f"pe-{mode}-{g}", 2, instance_group=g
            )[0]
            h.add_pods(d)
            drivers.append(d)
        t1 = ext.predicate_window_dispatch(
            [ExtenderArgs(pod=d, node_names=list(live)) for d in drivers]
        )
        # External churn between t1's dispatch and its fetch.
        blocker = static_allocation_spark_pods(f"pe-{mode}-blk", 1)[0]
        h.backend.add_pod(blocker)
        rr = new_resource_reservation(
            blocker_node, [blocker_node], blocker,
            Resources.from_quantities("2", "2Gi"),
            Resources.from_quantities("1", "1Gi"),
        )
        h.app.rr_cache.create(rr)
        r1 = [
            tuple(r.node_names)
            for r in ext.predicate_window_complete(t1)
        ]
        r2 = _serve_grouped(h, live, seq)
        outs[mode] = (r1, r2)
        if mode == "dirty":
            assert h.app.solver.prune_stats["escalations"] > 0, (
                h.app.solver.prune_stats
            )
            assert h.app.solver.build_stats["mirror_dense_syncs"] == 0
        h.app.stop()
    assert outs["dirty"] == outs["dense"], outs


def test_pool_slot_mirror_catches_up_by_row_scatter():
    """Per-slot availability mirrors (ISSUE 15): a whole-window pooled
    dispatch landing on a LAGGING slot catches up by scattering the
    journaled rows instead of re-shipping the full [N,3] base — and the
    fetch patches an unknowable epoch with its exact commit rows so
    later catch-ups can cross it. Solver-level (the mirror is device
    machinery, independent of the host journal)."""
    from spark_scheduler_tpu.core.solver import (
        PlacementSolver,
        WindowRequest,
    )
    from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
    from spark_scheduler_tpu.models.resources import Resources

    one = Resources.from_quantities("1", "1Gi")
    nodes = [
        Node(
            name=f"m{i:03d}",
            allocatable=Resources.from_quantities(
                "8", "8Gi", "1", round_up=False
            ),
            labels={ZONE_LABEL: f"z{i % 2}"},
        )
        for i in range(32)
    ]
    names = [n.name for n in nodes]
    rng = np.random.default_rng(3)
    wins = [
        [
            WindowRequest(
                rows=[(one, one, int(rng.integers(1, 3)), False)],
                driver_candidate_names=names,
            )
            for _ in range(3)
        ]
        for _ in range(8)
    ]

    def run(solver):
        res = []
        for w in wins:
            t = solver.build_tensors_pipelined(nodes, {}, {})
            h = solver.pack_window_dispatch("tightly-pack", t, w)
            res.extend(solver.pack_window_fetch(h))
        return res

    base = run(PlacementSolver(use_native=False))
    pooled = PlacementSolver(use_native=False, device_pool=2)
    assert run(pooled) == base, "pooled decisions diverged"
    mirrors = {
        k: v["mirror"] for k, v in pooled.device_pool_stats().items()
    }
    catchups = sum(m["catchup"] for m in mirrors.values())
    delta_rows = sum(m["delta_rows"] for m in mirrors.values())
    dense = sum(m["dense"] for m in mirrors.values())
    assert catchups >= 1, mirrors
    assert delta_rows >= 1, mirrors
    # Only the cold first touch of a slot may pay the full re-ship.
    assert dense <= 2, mirrors


def test_boundary_add_inserts_into_kept_set_without_rescan():
    """A node ADD whose key beats a zone's kept boundary is INSERTED
    into the kept order in O(K) — the old K-th row evicts into the
    excluded summaries — instead of forcing the historical O(zone)
    re-scan (ISSUE 15 tentpole (c)); the resulting plan equals a fresh
    cold build's."""
    from spark_scheduler_tpu.core.prune import PrunePlanner
    from spark_scheduler_tpu.models.cluster import ClusterTensors

    n, zb = 24, 2

    def mk_host(valid):
        return ClusterTensors(
            available=avail,
            schedulable=avail.copy(),
            zone_id=zone_id,
            name_rank=name_rank,
            label_rank_driver=np.zeros(n, np.int32),
            label_rank_executor=np.zeros(n, np.int32),
            unschedulable=np.zeros(n, bool),
            ready=np.ones(n, bool),
            valid=valid,
        )

    avail = np.full((n, 3), 32, np.int32)  # equal keys: name rank decides
    zone_id = (np.arange(n) % 2).astype(np.int32)
    name_rank = (np.arange(n) + 10).astype(np.int32)
    valid = np.ones(n, bool)
    j = n - 1
    valid[j] = False  # the future ADD
    drv = np.asarray([[2, 4, 0]], np.int32)
    exc = np.asarray([[1, 2, 0]], np.int32)
    counts = np.asarray([2], np.int32)
    cand = [np.ones(n, bool)]

    planner = PrunePlanner()
    host = mk_host(valid)
    planner.sync(host, zb)
    plan = planner.plan_full_domain(
        host, cand_per_req=cand, drv_arr=drv, exc_arr=exc,
        counts=counts, num_zones=zb, top_k=4, slack=0.3,
    )
    assert plan is not None
    rescans0 = planner.stats["planner_zone_rescans"]
    scanned0 = planner.stats["planner_rows_scanned"]

    # The ADD: row j becomes valid with the BEST name rank in its zone.
    valid[j] = True
    name_rank[j] = 0
    planner.note_static(np.asarray([j]))
    host2 = mk_host(valid)
    planner.sync(host2, zb)
    plan2 = planner.plan_full_domain(
        host2, cand_per_req=cand, drv_arr=drv, exc_arr=exc,
        counts=counts, num_zones=zb, top_k=4, slack=0.3,
    )
    assert plan2 is not None
    st = planner.stats
    assert st["planner_boundary_inserts"] >= 1, st
    assert st["planner_zone_rescans"] == rescans0, st
    assert st["planner_rows_scanned"] == scanned0, st
    keep2 = plan2.keep[: plan2.k_real]
    assert j in keep2, keep2

    # Exactness oracle: the inserted plan equals a fresh cold build.
    fresh = PrunePlanner()
    fresh.sync(host2, zb)
    planf = fresh.plan_full_domain(
        host2, cand_per_req=cand, drv_arr=drv, exc_arr=exc,
        counts=counts, num_zones=zb, top_k=4, slack=0.3,
    )
    assert np.array_equal(keep2, planf.keep[: planf.k_real])
    assert np.array_equal(plan2.zone_mem, planf.zone_mem)
    assert np.array_equal(plan2.zone_cpu, planf.zone_cpu)
    for a, b in zip(plan2.zone_base, planf.zone_base):
        assert np.array_equal(a, b)
    assert np.array_equal(plan2.e_cnt_exec > 0, planf.e_cnt_exec > 0)
    assert np.array_equal(plan2.e_key_exec, planf.e_key_exec)
    assert np.array_equal(plan2.e_max_exec, planf.e_max_exec)
    assert np.array_equal(plan2.e_cnt_drv > 0, planf.e_cnt_drv > 0)
    assert np.array_equal(plan2.e_key_drv, planf.e_key_drv)
    assert np.array_equal(plan2.e_max_drv, planf.e_max_drv)
