"""Device-resident cluster state (VERDICT r2 #3): the delta-updated device
copy must stay bit-identical to a from-scratch rebuild through a randomized
mutate/serve soak, and the serving path must actually hit the delta/reuse
fast paths instead of re-uploading full tensors per request.
"""

import dataclasses

import numpy as np

import jax

from spark_scheduler_tpu.core.solver import PlacementSolver
from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import Resources


def _mk_node(i, cpu="8", mem="8Gi", gpu="1", zone=None, ready=True):
    return Node(
        name=f"dev-n{i}",
        allocatable=Resources.from_quantities(cpu, mem, gpu, round_up=False),
        labels={"topology.kubernetes.io/zone": zone or f"z{i % 3}"},
        ready=ready,
    )


def test_device_state_soak_matches_rebuild():
    rng = np.random.default_rng(7)
    solver = PlacementSolver()
    nodes = [_mk_node(i) for i in range(24)]
    usage: dict[str, Resources] = {}
    overhead: dict[str, Resources] = {}

    for step in range(60):
        # Random mutation mix: usage deltas (common), overhead drift,
        # node additions, node attribute flips (rare).
        r = rng.random()
        if r < 0.6:
            name = f"dev-n{int(rng.integers(0, len(nodes)))}"
            cur = usage.get(name, Resources.zero())
            cur = cur.copy()
            cur.add(Resources.from_quantities("1", "1Gi"))
            usage[name] = cur
        elif r < 0.75:
            name = f"dev-n{int(rng.integers(0, len(nodes)))}"
            overhead[name] = Resources.from_quantities(
                str(int(rng.integers(0, 3))), "512Mi"
            )
        elif r < 0.9 and step > 5:
            nodes.append(_mk_node(len(nodes)))
        else:
            i = int(rng.integers(0, len(nodes)))
            nodes[i] = _mk_node(i, ready=bool(rng.random() < 0.8))

        cached = solver.build_tensors_cached(nodes, dict(usage), dict(overhead))
        fresh = solver.build_tensors(nodes, dict(usage), dict(overhead))
        got = jax.device_get(
            dataclasses.asdict(
                dataclasses.replace(cached)
            )
        )
        for field in (
            "available",
            "schedulable",
            "zone_id",
            "name_rank",
            "label_rank_driver",
            "label_rank_executor",
            "unschedulable",
            "ready",
            "valid",
        ):
            np.testing.assert_array_equal(
                np.asarray(got[field]),
                np.asarray(getattr(fresh, field)),
                err_msg=f"step {step} field {field} diverged from rebuild",
            )
            np.testing.assert_array_equal(
                np.asarray(getattr(cached.host, field)),
                np.asarray(getattr(fresh, field)),
                err_msg=f"step {step} host mirror {field}",
            )

    stats = solver.device_state_stats
    # The soak is dominated by availability deltas: the delta path must have
    # fired, and full uploads must be the exception (topology changes only).
    assert stats["delta_uploads"] > 10, stats
    assert stats["full_uploads"] < 30, stats


def test_serving_path_uses_delta_updates():
    """Through the real extender: repeated driver admissions against a fixed
    topology must hit the delta/reuse fast paths after the first upload."""
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.testing.harness import (
        Harness,
        new_node,
        static_allocation_spark_pods,
    )

    h = Harness(binpack_algo="tightly-pack", fifo=True)
    h.add_nodes(*[new_node(f"n{i}") for i in range(16)])
    names = [f"n{i}" for i in range(16)]
    for i in range(6):
        driver = static_allocation_spark_pods(f"dev-soak-{i}", 2)[0]
        res = h.schedule(driver, names)
        assert res.ok, res
    stats = h.app.solver.device_state_stats
    assert stats["full_uploads"] <= 2, stats  # first build (+1 tolerance)
    assert stats["delta_uploads"] + stats["reuse_hits"] >= 4, stats
