"""Fleet federation (ISSUE 19): FleetFacade two-level placement over F
independent per-cluster stacks, demand spillover, kill/rejoin chaos, and
the per-cluster byte-identity contract — every cluster's decisions and
durable reservation state must match a standalone cluster replaying the
same op stream, under randomized churn, across solver configurations.
"""

import json
import urllib.request

import numpy as np
import pytest

from spark_scheduler_tpu.core.membership import StableMembership
from spark_scheduler_tpu.fleet import (
    ClusterStack,
    FleetFacade,
    verify_cluster_equivalence,
)
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.testing.harness import (
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)


def _config(**kw):
    return InstallConfig(
        fifo=True,
        sync_writes=True,
        instance_group_label=INSTANCE_GROUP_LABEL,
        **kw,
    )


# ------------------------------------------------------ membership core


class TestStableMembership:
    def test_owner_is_stable_for_survivors_across_removal(self):
        m = StableMembership(4)
        keys = [f"app-{i}" for i in range(64)]
        before = {k: m.owner(k) for k in keys}
        m.remove(2)
        after = {k: m.owner(k) for k in keys}
        # Only keys the victim owned move; every survivor's keys stay put.
        for k in keys:
            if before[k] != 2:
                assert after[k] == before[k], k
            else:
                assert after[k] != 2
                assert m.is_live(after[k])

    def test_rejoin_restores_original_assignment(self):
        m = StableMembership(4)
        keys = [f"app-{i}" for i in range(64)]
        before = {k: m.owner(k) for k in keys}
        m.remove(1)
        m.rejoin(1)
        assert {k: m.owner(k) for k in keys} == before
        assert m.live() == [0, 1, 2, 3]

    def test_cannot_remove_last_member(self):
        m = StableMembership(2)
        m.remove(0)
        with pytest.raises(ValueError):
            m.remove(1)

    def test_owned_by_partitions_keys(self):
        m = StableMembership(3)
        keys = [f"k{i}" for i in range(30)]
        shards = [m.owned_by(i, keys) for i in range(3)]
        assert sorted(k for s in shards for k in s) == sorted(keys)
        d = m.describe(keys)
        assert d["slots"] == 3 and d["live"] == [0, 1, 2]


# ------------------------------------------- two-level routing + facade


class TestFleetRouting:
    def _fleet(self, n=3, record_ops=True, **cfg_kw):
        f = FleetFacade(n, _config(**cfg_kw), record_ops=record_ops)
        for c in range(n):
            for i in range(2):
                f.add_node(c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}"))
        return f

    def test_hosting_pick_routes_to_group_host(self):
        f = self._fleet()
        try:
            pods = static_allocation_spark_pods(
                "app-host", 2, instance_group="ig-1"
            )
            d = f.schedule(pods[0])
            assert d.ok and d.cluster == 1
            assert f.router.picks["hosting"] == 1
            # Executors ride the driver's affinity — same cluster.
            for p in pods[1:]:
                dd = f.schedule(p)
                assert dd.ok and dd.cluster == 1
            assert f.router.picks["affinity"] == 2
        finally:
            f.stop()

    def test_headroom_pick_prefers_emptier_host(self):
        f = FleetFacade(2, _config(), record_ops=True)
        try:
            # Both clusters host the group; cluster 1 has more headroom.
            f.add_node(0, new_node("c0-n0", instance_group="ig-s"))
            for i in range(2):
                f.add_node(1, new_node(f"c1-n{i}", instance_group="ig-s"))
            d = f.schedule(
                static_allocation_spark_pods("app-hr", 1, instance_group="ig-s")[0]
            )
            assert d.ok and d.cluster == 1
            assert f.router.picks["headroom"] == 1
        finally:
            f.stop()

    def test_hash_pick_for_unhosted_group_is_stable(self):
        f = self._fleet()
        try:
            home, reason = f.router.route("ghost-app", "ig-nowhere")
            assert reason == "hash"
            f.router.unbind("ghost-app")
            again, _ = f.router.route("ghost-app", "ig-nowhere")
            assert again == home
        finally:
            f.stop()

    def test_wrong_cluster_call_is_forwarded_and_identical(self):
        f = self._fleet()
        try:
            pod = static_allocation_spark_pods(
                "app-fwd", 1, instance_group="ig-0"
            )[0]
            wrong = 2  # ig-0 is hosted by cluster 0
            d = f.schedule(pod, via=wrong)
            assert d.ok and d.cluster == 0
            assert f.forwarded == 1
            # The decision is the owner's: the node lives in cluster 0.
            assert d.result.node_names[0].startswith("c0-")
            verify_cluster_equivalence(f)
        finally:
            f.stop()


# ------------------------------------------------------------ spillover


class TestSpillover:
    def _two_homes(self, max_hops=1):
        """Two clusters both hosting ig-s: one node each."""
        f = FleetFacade(2, _config(), record_ops=True, max_spillover_hops=max_hops)
        f.add_node(0, new_node("c0-n0", instance_group="ig-s"))
        f.add_node(1, new_node("c1-n0", instance_group="ig-s"))
        return f

    def _fill(self, f, cluster, app_id, executors=6):
        """Occupy 7 of the node's 8 CPUs so a 3-pod gang cannot fit."""
        pods = static_allocation_spark_pods(
            app_id, executors, instance_group="ig-s"
        )
        f.router.bind(app_id, cluster)
        for p in pods:
            assert f.schedule(p).ok

    def test_denied_driver_spills_to_sibling_and_executors_follow(self):
        f = self._two_homes()
        try:
            self._fill(f, 0, "filler")
            pods = static_allocation_spark_pods(
                "spill-app", 2, instance_group="ig-s"
            )
            f.router.bind("spill-app", 0)  # force home = the full cluster
            d = f.schedule(pods[0])
            assert d.ok and d.cluster == 1 and d.spilled_from == 0
            assert f.spillover.spilled == 1
            # Affinity re-bound: the gang's executors land beside the
            # driver on the sibling.
            for p in pods[1:]:
                dd = f.schedule(p)
                assert dd.ok and dd.cluster == 1 and dd.spilled_from is None
            # Home cleanup: neither the pod nor its demand remain in
            # cluster 0 — the demand was fulfilled by a sibling, not an
            # autoscaler.
            home = f.stacks[0]
            assert home.backend.get("pods", "ns", pods[0].name) is None
            assert not [
                dm for dm in home.backend.list("demands")
                if "spill-app" in dm.name
            ]
            # The hand-off is journaled in the home cluster's recorder.
            recs = home.app.recorder.query(app="spill-app")
            assert recs and recs[0]["verdict"] == "spillover"
            assert "sibling cluster 1" in recs[0]["message"]
            # Both clusters stay byte-identical to standalone replays —
            # the sibling saw ordinary schedule ops, the home saw its
            # denial + release.
            verify_cluster_equivalence(f)
        finally:
            f.stop()

    def test_spillover_denied_everywhere_leaves_home_demand(self):
        f = self._two_homes()
        try:
            self._fill(f, 0, "filler-a")
            self._fill(f, 1, "filler-b")
            pods = static_allocation_spark_pods(
                "doomed-app", 2, instance_group="ig-s"
            )
            f.router.bind("doomed-app", 0)
            d = f.schedule(pods[0])
            assert not d.ok and d.cluster == 0
            assert d.spillover_attempts == 1 and f.spillover.denied == 1
            # The home demand STANDS — the autoscaler path takes over.
            assert [
                dm for dm in f.stacks[0].backend.list("demands")
                if "doomed-app" in dm.name
            ]
            # The sibling's failed copy left through release: no pod, no
            # demand, and its op stream still replays byte-identically.
            assert f.stacks[1].backend.get("pods", "ns", pods[0].name) is None
            verify_cluster_equivalence(f)
        finally:
            f.stop()

    def test_zero_hops_disables_spillover(self):
        f = self._two_homes(max_hops=0)
        try:
            self._fill(f, 0, "filler")
            pod = static_allocation_spark_pods(
                "capped-app", 2, instance_group="ig-s"
            )[0]
            f.router.bind("capped-app", 0)
            d = f.schedule(pod)
            assert not d.ok and d.spillover_attempts == 0
            assert f.spillover.spilled == 0
        finally:
            f.stop()


# ------------------------------------------------------- kill / rejoin


class TestKillRejoin:
    def test_placed_app_denies_while_home_down_and_never_double_places(self):
        f = FleetFacade(2, _config(), record_ops=True)
        try:
            for c in range(2):
                f.add_node(c, new_node(f"c{c}-n0", instance_group="ig-kr"))
            pods = static_allocation_spark_pods(
                "placed-app", 2, instance_group="ig-kr"
            )
            for p in pods[:2]:
                assert f.schedule(p).ok
            home = f.router.affinity_of("placed-app")
            f.kill_cluster(home)
            # The remaining executor targets a placed app on a dead
            # cluster: synthesized denial, NOT an op in any oplog.
            d = f.schedule(pods[2])
            assert not d.ok and d.unavailable
            assert f.unavailable_denials == 1
            holders = [
                s.index
                for s in f.stacks
                if any(
                    rr.name == "placed-app"
                    for rr in s.backend.list("resourcereservations")
                )
            ]
            assert holders == [home]  # exactly one cluster holds the gang
            # Rejoin: the same executor now serves at home, and the oplog
            # (which never saw the synthesized denial) replays clean.
            f.rejoin_cluster(home)
            d = f.schedule(pods[2])
            assert d.ok and d.cluster == home
            verify_cluster_equivalence(f)
        finally:
            f.stop()

    def test_pending_orphan_reroutes_to_survivor(self):
        f = FleetFacade(2, _config(), record_ops=True)
        try:
            # Both clusters host the group and BOTH are full: the gang is
            # denied at home and by spillover — a pending app.
            f.add_node(0, new_node("c0-n0", instance_group="ig-or"))
            f.add_node(1, new_node("c1-n0", instance_group="ig-or"))
            for fid, cluster in (("filler-a", 0), ("filler-b", 1)):
                f.router.bind(fid, cluster)
                for p in static_allocation_spark_pods(
                    fid, 6, instance_group="ig-or"
                ):
                    assert f.schedule(p).ok
            gang = static_allocation_spark_pods(
                "orphan-app", 2, instance_group="ig-or"
            )
            f.router.bind("orphan-app", 0)
            d = f.schedule(gang[0])
            assert not d.ok and d.cluster == 0
            # Home dies: the PENDING gang is an orphan — its affinity
            # drops so the next retry re-routes.
            assert f.kill_cluster(0) == 1
            assert f.router.affinity_of("orphan-app") is None
            # Capacity appears on the survivor; the retry routes there
            # (hosting pick among LIVE clusters) and the whole gang lands.
            f.add_node(1, new_node("c1-n1", instance_group="ig-or"))
            for p in gang:
                d = f.schedule(p)
                assert d.ok and d.cluster == 1
            # Exactly one cluster ever held the gang, and the survivor's
            # op stream still replays byte-identically. (The dead home's
            # replay is checked after rejoin-free shutdown too.)
            assert f.router.rerouted_orphans == 1
            verify_cluster_equivalence(f)
        finally:
            f.stop()


# ---------------------------- byte-identity under churn x solver configs


CHURN_CONFIGS = [
    pytest.param({}, id="default"),
    pytest.param({"solver_prune_top_k": 4}, id="pruned"),
    pytest.param({"solver_device_pool": 2}, id="pooled"),
]


class TestEquivalenceUnderChurn:
    @pytest.mark.parametrize("cfg_kw", CHURN_CONFIGS)
    def test_randomized_churn_replays_byte_identical(self, cfg_kw):
        rng = np.random.default_rng(11)
        f = FleetFacade(3, _config(**cfg_kw), record_ops=True)
        try:
            for c in range(3):
                for g in (c, (c + 1) % 3):
                    f.add_node(
                        c, new_node(f"c{c}-g{g}-n0", instance_group=f"ig-{g}")
                    )
            live = {}
            for step in range(25):
                roll = rng.random()
                if roll < 0.6 or not live:
                    app = f"churn-{step}"
                    group = f"ig-{int(rng.integers(0, 3))}"
                    pods = static_allocation_spark_pods(
                        app, int(rng.integers(1, 3)), instance_group=group
                    )
                    decisions = [f.schedule(p) for p in pods]
                    if decisions[0].ok:
                        live[app] = (decisions[0].cluster, pods)
                elif roll < 0.8 and live:
                    app = sorted(live)[int(rng.integers(0, len(live)))]
                    cluster, pods = live.pop(app)
                    for p in pods:
                        f.stacks[cluster].terminate_pod(p)
                else:
                    app = sorted(live)[int(rng.integers(0, len(live)))]
                    cluster, pods = live.pop(app)
                    for p in pods:
                        f.stacks[cluster].delete_pod(p)
                    f.router.unbind(app)
            report = verify_cluster_equivalence(f)
            assert set(report) == {0, 1, 2}
            assert all(r["identical"] for r in report.values())
            # Resident aggregates still equal a from-scratch walk.
            for s in f.stacks:
                assert s.aggregates.oracle_equals(), f"cluster {s.index}"
        finally:
            f.stop()


# -------------------------------------------------- aggregates oracle


class TestAggregatesOracle:
    def test_event_maintained_equals_walk_oracle(self):
        stack = ClusterStack(0, _config(), threaded=False)
        try:
            for i in range(4):
                stack.add_node(new_node(f"n{i}", instance_group="ig-a"))
            for k in range(3):
                for p in static_allocation_spark_pods(
                    f"agg-{k}", 2, instance_group="ig-a"
                ):
                    stack.schedule(p)
            agg = stack.aggregates
            assert agg.hosts_group("ig-a") and not agg.hosts_group("ig-x")
            assert agg.oracle_equals()
            # Churn: drop an app's pods, then a node.
            for p in static_allocation_spark_pods(
                "agg-0", 2, instance_group="ig-a"
            ):
                stack.delete_pod(p)
            stack.backend.delete("nodes", "", "n3")
            assert agg.oracle_equals()
            free = agg.free_total()
            assert free[0] > 0 and agg.top_node_free()[0] > 0
        finally:
            stack.stop()


# -------------------------------------------------- config + HTTP surface


class TestFleetConfigAndHTTP:
    def test_fleet_block_parses_with_defaults(self):
        cfg = InstallConfig.from_dict({})
        assert not cfg.fleet_enabled
        assert cfg.fleet_clusters == 2 and cfg.fleet_max_spillover_hops == 1
        cfg = InstallConfig.from_dict(
            {"fleet": {"enabled": True, "clusters": 4, "max-spillover-hops": 2}}
        )
        assert cfg.fleet_enabled and cfg.fleet_clusters == 4
        assert cfg.fleet_max_spillover_hops == 2

    def test_debug_fleet_and_cluster_tagged_predicate(self):
        from spark_scheduler_tpu.server.http import SchedulerHTTPServer

        f = FleetFacade(2, _config(), record_ops=True)
        for c in range(2):
            f.add_node(c, new_node(f"c{c}-n0", instance_group=f"ig-{c}"))
        server = SchedulerHTTPServer(
            f.stacks[0].app, host="127.0.0.1", port=0, fleet=f
        )
        server.start()
        try:
            def req(method, path, payload=None):
                r = urllib.request.Request(
                    f"http://127.0.0.1:{server.port}{path}",
                    data=(
                        json.dumps(payload).encode()
                        if payload is not None
                        else None
                    ),
                    method=method,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(r) as resp:
                    return resp.status, json.loads(resp.read())

            status, body = req("GET", "/debug/fleet")
            assert status == 200
            assert [c["live"] for c in body["clusters"]] == [True, True]
            # A predicate tagged with the WRONG cluster endpoint forwards
            # to the owner and returns the owner's decision bytes.
            pod = {
                "metadata": {
                    "name": "fleet-http-driver",
                    "namespace": "ns",
                    "uid": "uid-fh",
                    "labels": {
                        "spark-role": "driver",
                        "spark-app-id": "fleet-http",
                    },
                    "annotations": {
                        "spark-driver-cpu": "1",
                        "spark-driver-mem": "1Gi",
                        "spark-executor-cpu": "1",
                        "spark-executor-mem": "1Gi",
                        "spark-executor-count": "1",
                    },
                    "creationTimestamp": "2026-08-07T12:00:00Z",
                },
                "spec": {
                    "schedulerName": "spark-scheduler",
                    "nodeSelector": {INSTANCE_GROUP_LABEL: "ig-1"},
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {"cpu": "1", "memory": "1Gi"}
                            },
                        }
                    ],
                },
                "status": {"phase": "Pending"},
            }
            status, result = req(
                "POST", "/predicates?cluster=0", {"Pod": pod, "NodeNames": []}
            )
            assert status == 200 and result["NodeNames"] == ["c1-n0"]
            status, body = req("GET", "/debug/fleet")
            assert body["forwarded"] == 1
            assert body["router"]["picks"]["hosting"] == 1
        finally:
            server.stop()
            f.stop()
