"""Durable store + full CRD definition tests (VERDICT #7, SURVEY.md §5.4).

The reference's CRDs persist in etcd and survive leader changes; a new
leader refills caches from the apiserver and reconciles drift from pods
(cache/resourcereservations.go:53-60, failover.go:35-72). DurableBackend
gives the standalone deployment the same property via a JSONL write-ahead
log; these tests prove reservations survive process death.
"""

from __future__ import annotations

from spark_scheduler_tpu.models.crds import (
    DEMAND_CRD_NAME,
    RESERVATION_CRD_NAME,
    demand_crd,
    resource_reservation_crd,
    validate_custom_resource,
)
from spark_scheduler_tpu.models.demands import Demand, DemandSpec, DemandStatus, DemandUnit
from spark_scheduler_tpu.models.reservations import (
    Reservation,
    ReservationSpec,
    ReservationStatus,
    ResourceReservation,
)
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.server.conversion import (
    demand_v1alpha2_to_wire,
    rr_v1beta2_to_wire,
)
from spark_scheduler_tpu.store.backend import DEMAND_CRD, RESERVATION_CRD
from spark_scheduler_tpu.store.durable import DurableBackend
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)


def _sample_rr() -> ResourceReservation:
    return ResourceReservation(
        name="app-1",
        namespace="ns",
        labels={"a": "b"},
        owner_pod_uid="uid-driver",
        spec=ReservationSpec(
            {
                "driver": Reservation("n0", Resources.from_quantities("1", "1Gi")),
                "executor-1": Reservation("n1", Resources.from_quantities("2", "2Gi", "1")),
            }
        ),
        status=ReservationStatus({"driver": "app-1-driver"}),
    )


def _sample_demand() -> Demand:
    return Demand(
        name="demand-app-2-driver",
        namespace="ns",
        spec=DemandSpec(
            units=[
                DemandUnit(
                    resources=Resources.from_quantities("2", "4Gi"),
                    count=3,
                    pod_names_by_namespace={"ns": ["app-2-driver"]},
                )
            ],
            instance_group="ig1",
            is_long_lived=False,
        ),
        status=DemandStatus(phase="pending"),
    )


class TestCRDDefinitions:
    def test_reservation_crd_shape(self):
        crd = resource_reservation_crd()
        assert crd["metadata"]["name"] == RESERVATION_CRD_NAME == RESERVATION_CRD
        versions = {v["name"]: v for v in crd["spec"]["versions"]}
        assert versions["v1beta2"]["storage"] and versions["v1beta2"]["served"]
        assert versions["v1beta1"]["served"] and not versions["v1beta1"]["storage"]
        # schemas are structural: spec.reservations typed through
        schema = versions["v1beta2"]["schema"]["openAPIV3Schema"]
        res_schema = schema["properties"]["spec"]["properties"]["reservations"]
        assert res_schema["additionalProperties"]["required"] == ["node", "resources"]
        assert crd["spec"]["conversion"]["strategy"] == "None"

    def test_reservation_crd_webhook_strategy(self):
        crd = resource_reservation_crd(webhook_url="https://svc:8484/convert", ca_bundle="Q0E=")
        conv = crd["spec"]["conversion"]
        assert conv["strategy"] == "Webhook"
        assert conv["webhook"]["clientConfig"]["url"] == "https://svc:8484/convert"
        assert conv["webhook"]["clientConfig"]["caBundle"] == "Q0E="

    def test_demand_crd_shape(self):
        crd = demand_crd()
        assert crd["metadata"]["name"] == DEMAND_CRD_NAME == DEMAND_CRD
        versions = {v["name"]: v for v in crd["spec"]["versions"]}
        assert versions["v1alpha2"]["storage"]
        assert versions["v1alpha2"]["subresources"] == {"status": {}}
        phase = versions["v1alpha2"]["schema"]["openAPIV3Schema"]["properties"][
            "status"
        ]["properties"]["phase"]
        assert "cannot-fulfill" in phase["enum"]

    def test_wire_objects_validate_against_schemas(self):
        """The codecs' output passes the CRDs' structural validation — what
        a real apiserver would enforce on every write."""
        rr_wire = rr_v1beta2_to_wire(_sample_rr())
        assert validate_custom_resource(resource_reservation_crd(), rr_wire) == []
        d_wire = demand_v1alpha2_to_wire(_sample_demand())
        assert validate_custom_resource(demand_crd(), d_wire) == []

    def test_schema_rejects_malformed(self):
        rr_wire = rr_v1beta2_to_wire(_sample_rr())
        del rr_wire["spec"]["reservations"]["driver"]["node"]
        errors = validate_custom_resource(resource_reservation_crd(), rr_wire)
        assert any("node" in e for e in errors)
        d_wire = demand_v1alpha2_to_wire(_sample_demand())
        d_wire["status"]["phase"] = "bogus"
        errors = validate_custom_resource(demand_crd(), d_wire)
        assert any("enum" in e for e in errors)

    def test_fake_apiserver_enforces_schema(self):
        """A CRD registered with the fake apiserver makes its schema
        load-bearing: invalid CRs are rejected with 422 Invalid."""
        import pytest

        from spark_scheduler_tpu.kube.apiserver import FakeKubeAPIServer, ValidationError

        api = FakeKubeAPIServer()
        api.register_crd(resource_reservation_crd())
        good = rr_v1beta2_to_wire(_sample_rr())
        api.create("resourcereservations", good)
        bad = rr_v1beta2_to_wire(_sample_rr())
        bad["metadata"]["name"] = "app-bad"
        del bad["spec"]["reservations"]["driver"]["node"]
        with pytest.raises(ValidationError):
            api.create("resourcereservations", bad)

    def test_ensure_registers_full_definition(self):
        from spark_scheduler_tpu.store.backend import InMemoryBackend
        from spark_scheduler_tpu.store.crd import ensure_resource_reservations_crd

        backend = InMemoryBackend()
        ensure_resource_reservations_crd(
            backend, webhook_url="https://127.0.0.1:8484/convert"
        )
        definition = backend.get_crd_definition(RESERVATION_CRD)
        assert definition is not None
        assert definition["spec"]["conversion"]["strategy"] == "Webhook"


class TestDurableBackend:
    def test_object_round_trip(self, tmp_path):
        path = str(tmp_path / "state.jsonl")
        backend = DurableBackend(path)
        node = new_node("n0")
        backend.add_node(node)
        pods = static_allocation_spark_pods("app-rt", 1)
        for p in pods:
            backend.add_pod(p)
        backend.create("resourcereservations", _sample_rr())
        backend.register_crd(DEMAND_CRD)
        backend.create("demands", _sample_demand())
        backend.bind_pod(pods[0], "n0")
        backend.close()

        re_backend = DurableBackend(path)
        assert re_backend.get_node("n0") == node
        re_pod = re_backend.get("pods", pods[0].namespace, pods[0].name)
        assert re_pod.node_name == "n0"  # bind survived
        assert re_pod.annotations == pods[0].annotations
        assert re_pod.uid == pods[0].uid
        rr = re_backend.get("resourcereservations", "ns", "app-1")
        assert rr.spec == _sample_rr().spec
        assert rr.status == _sample_rr().status
        assert rr.owner_pod_uid == "uid-driver"
        d = re_backend.get("demands", "ns", "demand-app-2-driver")
        assert d.spec == _sample_demand().spec
        assert d.status.phase == "pending"
        assert re_backend.crd_exists(DEMAND_CRD)
        re_backend.close()

    def test_delete_survives(self, tmp_path):
        path = str(tmp_path / "state.jsonl")
        backend = DurableBackend(path)
        backend.add_node(new_node("n0"))
        backend.add_node(new_node("n1"))
        backend.delete("nodes", "", "n0")
        backend.close()
        re_backend = DurableBackend(path)
        assert re_backend.get_node("n0") is None
        assert re_backend.get_node("n1") is not None
        re_backend.close()

    def test_compaction_bounds_log(self, tmp_path):
        path = str(tmp_path / "state.jsonl")
        backend = DurableBackend(path)
        node = backend.add_node(new_node("n0"))
        for _ in range(50):
            backend.update("nodes", node)
        with open(path) as f:
            assert len(f.readlines()) > 50
        backend.compact()
        with open(path) as f:
            lines = f.readlines()
        # registry (1 reservation CRD entry) + 1 node
        assert len(lines) <= 3, lines
        re_backend = DurableBackend(path)
        assert re_backend.get_node("n0") is not None
        re_backend.close()
        backend.close()

    def test_torn_tail_write_truncated_with_warning(self, tmp_path):
        """Crash mid-append: replay must warn AND truncate the torn bytes
        — leaving them would corrupt the NEXT appended record (it lands on
        the same line)."""
        import os

        import pytest

        path = str(tmp_path / "state.jsonl")
        backend = DurableBackend(path, compact_on_load=False)
        backend.add_node(new_node("n0"))
        backend.close()
        good_size = os.path.getsize(path)
        with open(path, "a") as f:
            f.write('{"verb": "create", "kind": "nodes", "na')  # crash mid-write
        with pytest.warns(RuntimeWarning, match="torn trailing record"):
            re_backend = DurableBackend(path, compact_on_load=False)
        assert re_backend.get_node("n0") is not None
        # The file was repaired to the last complete record, so a new
        # append starts on a fresh line and survives the NEXT replay.
        assert os.path.getsize(path) == good_size
        re_backend.add_node(new_node("n1"))
        re_backend.close()
        third = DurableBackend(path, compact_on_load=False)
        assert third.get_node("n0") is not None
        assert third.get_node("n1") is not None
        third.close()

    def test_promotion_truncates_dead_writers_torn_tail(self, tmp_path):
        """Leader crashes mid-append; the promoted follower must truncate
        the partial line BEFORE its first append — welding its record
        onto the torn bytes would make ONE undecodable line, losing both
        on the next replay."""
        import os

        import pytest

        path = str(tmp_path / "state.jsonl")
        leader = DurableBackend(path, compact_on_load=False)
        leader.add_node(new_node("n0"))
        follower = DurableBackend(path, follow=True)
        assert follower.get_node("n0") is not None
        leader.close()
        with open(path, "a") as f:
            f.write('{"verb": "create", "kind": "nodes", "na')  # SIGKILL
        with pytest.warns(RuntimeWarning, match="torn mid-append tail"):
            follower.promote_to_writer()
        follower.add_node(new_node("n1"))
        follower.close()
        replayed = DurableBackend(path, compact_on_load=False)
        assert replayed.get_node("n0") is not None
        assert replayed.get_node("n1") is not None
        replayed.close()

    def test_promotion_keeps_complete_unterminated_tail(self, tmp_path):
        """The crash can land AFTER the record's bytes flushed but BEFORE
        its newline: cold-restart replay keeps that record (`for raw in
        f` parses an unterminated last line), so promotion must too —
        apply it, terminate the line, and append after it."""
        import warnings

        path = str(tmp_path / "state.jsonl")
        leader = DurableBackend(path, compact_on_load=False)
        leader.add_node(new_node("n0"))
        follower = DurableBackend(path, follow=True)
        leader.close()
        # Flush a COMPLETE node-create record with no trailing newline.
        with open(path) as f:
            template = f.readline().rstrip("\n")
        with open(path, "a") as f:
            f.write(template.replace('"n0"', '"n1"'))  # crash before \n
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            follower.promote_to_writer()  # no torn-tail warning
        assert follower.get_node("n1") is not None
        follower.add_node(new_node("n2"))
        follower.close()
        replayed = DurableBackend(path, compact_on_load=False)
        for n in ("n0", "n1", "n2"):
            assert replayed.get_node(n) is not None, n
        replayed.close()

    def test_follower_boot_silent_on_in_progress_append(self, tmp_path):
        """A standby booting while the LIVE writer is mid-append sees a
        healthy log, not damage: no corruption warning, no truncation —
        poll_log picks the record up once the writer completes it."""
        import os
        import warnings

        path = str(tmp_path / "state.jsonl")
        leader = DurableBackend(path, compact_on_load=False)
        leader.add_node(new_node("n0"))
        with open(path, "a") as f:
            f.write('{"verb": "create", "kind": "nodes", "na')  # mid-flush
        size = os.path.getsize(path)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            follower = DurableBackend(path, follow=True)
        assert follower.get_node("n0") is not None
        assert os.path.getsize(path) == size  # follower never truncates
        leader.close()

    def test_writer_killed_mid_record(self, tmp_path):
        """A real writer PROCESS killed mid-append: the child flushes half
        a record and parks; SIGKILL tears it exactly there. Replay warns,
        truncates, and keeps every complete record."""
        import os
        import signal
        import subprocess
        import sys

        import pytest

        path = str(tmp_path / "killed.jsonl")
        child = subprocess.Popen(
            [
                sys.executable,
                "-c",
                f"""
import json, sys
from spark_scheduler_tpu.store.durable import DurableBackend
from spark_scheduler_tpu.testing.harness import new_node
b = DurableBackend({path!r}, compact_on_load=False)
b.add_node(new_node("k0"))
b.add_node(new_node("k1"))
# Crash mid-append: half a record, flushed, no newline.
b._file.write(json.dumps({{"verb": "create", "kind": "nodes"}})[:21])
b._file.flush()
print("TORN", flush=True)
import time; time.sleep(60)
""",
            ],
            stdout=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            assert child.stdout.readline().strip() == b"TORN"
            child.send_signal(signal.SIGKILL)
            child.wait(timeout=30)
        finally:
            if child.poll() is None:
                child.kill()
        with pytest.warns(RuntimeWarning, match="torn trailing record"):
            backend = DurableBackend(path, compact_on_load=False)
        assert backend.get_node("k0") is not None
        assert backend.get_node("k1") is not None
        with open(path, "rb") as f:
            assert f.read().endswith(b"}\n")  # torn bytes are gone
        backend.close()


class TestRestartRecovery:
    def test_reservations_survive_restart(self, tmp_path):
        """Kill the scheduler after gang admission; a new process over the
        same log restores reservations, reconciles, and keeps scheduling —
        the executor rebind proves restored state is live, not cosmetic."""
        path = str(tmp_path / "state.jsonl")
        backend = DurableBackend(path)
        h = Harness(backend=backend)
        node_names = [f"n{i}" for i in range(4)]
        h.add_nodes(*(new_node(n) for n in node_names))
        pods = static_allocation_spark_pods("app-surv", 2)
        driver, execs = pods[0], pods[1:]
        result = h.schedule(driver, node_names)
        assert result.node_names, result
        driver_node = result.node_names[0]
        res0 = h.schedule(execs[0], node_names)
        assert res0.node_names
        h.app.stop()
        backend.close()

        # --- process death; new process over the same log ---
        backend2 = DurableBackend(path)
        h2 = Harness(backend=backend2)
        # the restart is a leader change: reconcile CRD state with pods
        h2.app.reconciler.sync_resource_reservations_and_demands()
        rrs = backend2.list("resourcereservations")
        assert len(rrs) == 1
        rr = rrs[0]
        assert rr.name == "app-surv"
        assert rr.status.pods["driver"] == driver.name
        # the second executor binds onto its restored reservation
        res1 = h2.schedule(execs[1], node_names)
        assert res1.node_names, res1
        reserved_nodes = {r.node for n, r in rr.spec.reservations.items() if n != "driver"}
        assert res1.node_names[0] in reserved_nodes
        assert backend2.get("pods", driver.namespace, driver.name).node_name == driver_node
        h2.app.stop()
        backend2.close()
