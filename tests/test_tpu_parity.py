"""On-TPU parity smoke gate (VERDICT r1 weak #5).

The main suite runs on a forced-CPU virtual mesh; this test executes the
golden oracle sweep on the REAL default backend by spawning a fresh
process without the CPU override. Opt-in (slow: remote-TPU compiles):

    SPARK_SCHEDULER_TPU_SMOKE=1 python -m pytest tests/test_tpu_parity.py -q
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(
    os.environ.get("SPARK_SCHEDULER_TPU_SMOKE") != "1",
    reason="set SPARK_SCHEDULER_TPU_SMOKE=1 to run the on-device parity smoke",
)
def test_parity_on_default_backend():
    env = {
        k: v
        for k, v in os.environ.items()
        # drop the suite's CPU pin so the child resolves the real backend
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "hack", "tpu_parity_smoke.py")],
        capture_output=True,
        text=True,
        timeout=1200,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["parity"] == "ok"
    assert verdict["cases_checked"] > 0
