"""Golden parity: the Pallas queue kernel == the XLA scan, decision for
decision.

`ops/pallas_fifo.fifo_pack_pallas` re-derives the executor fills as
iterative masked-argmin placement and runs the whole FIFO scan inside one
Mosaic kernel; these tests pin it bit-for-bit to `batched_fifo_pack` (which
is itself oracle-parity-tested in test_batched.py) across randomized
clusters and queues, in interpreter mode on the CPU suite. The same
comparison runs compiled on real silicon in hack/tpu_parity_smoke.py.
"""

import numpy as np
import pytest

from spark_scheduler_tpu.models.cluster import ClusterTensors
from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch
from spark_scheduler_tpu.ops.pallas_fifo import (
    PALLAS_FILLS,
    PALLAS_SINGLE_AZ,
    fifo_pack_auto,
    fifo_pack_pallas,
)

from tests.test_packing_golden import random_cluster

EMAX = 8
NUM_ZONES = 4


def random_apps(rng, b, pad_to=None):
    driver = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
    driver[:, 2] = rng.integers(0, 2, size=b)
    execs = rng.integers(1, 8, size=(b, 3)).astype(np.int32)
    execs[:, 2] = rng.integers(0, 2, size=b)
    counts = rng.integers(0, EMAX + 3, size=b).astype(np.int32)  # incl. too-big
    skip = rng.random(b) < 0.3
    return make_app_batch(driver, execs, counts, pad_to=pad_to, skippable=skip)


def assert_same(got, want):
    for field in ("driver_node", "executor_nodes", "admitted", "packed",
                  "available_after"):
        g = np.asarray(getattr(got, field))
        w = np.asarray(getattr(want, field))
        np.testing.assert_array_equal(g, w, err_msg=field)


@pytest.mark.parametrize("fill", sorted(PALLAS_FILLS))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_matches_xla_scan(fill, seed):
    rng = np.random.default_rng(seed)
    c = random_cluster(rng, 37, num_zones=NUM_ZONES)
    apps = random_apps(rng, 9, pad_to=12)
    want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES)
    got = fifo_pack_pallas(
        c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES, interpret=True
    )
    assert_same(got, want)


@pytest.mark.parametrize("fill", sorted(PALLAS_FILLS))
def test_pallas_strict_fifo_blocking(fill):
    """A huge non-skippable gang blocks everything behind it in both paths."""
    rng = np.random.default_rng(7)
    c = random_cluster(rng, 24, num_zones=NUM_ZONES)
    driver = np.ones((4, 3), np.int32)
    execs = np.ones((4, 3), np.int32)
    execs[1] = 1000  # unpackable
    counts = np.array([2, 8, 2, 2], np.int32)
    apps = make_app_batch(driver, execs, counts,
                          skippable=np.zeros(4, bool))
    want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES)
    got = fifo_pack_pallas(
        c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES, interpret=True
    )
    assert_same(got, want)
    assert not np.asarray(want.admitted)[2:].any()


def test_pallas_negative_availability_and_zero_count():
    """Overcommitted nodes (negative availability) and zero-executor gangs."""
    rng = np.random.default_rng(11)
    c = random_cluster(rng, 20, num_zones=NUM_ZONES)
    avail = np.asarray(c.available).copy()
    avail[3] = -5
    avail[7, 0] = -1
    import dataclasses

    c = dataclasses.replace(c, available=avail)
    driver = np.ones((3, 3), np.int32)
    execs = np.ones((3, 3), np.int32)
    counts = np.array([0, 3, 0], np.int32)
    apps = make_app_batch(driver, execs, counts)
    for fill in sorted(PALLAS_FILLS):
        want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX,
                                 num_zones=NUM_ZONES)
        got = fifo_pack_pallas(
            c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES, interpret=True
        )
        assert_same(got, want)


def test_pallas_sublane_folded_layout_matches():
    """Clusters at/above the fold threshold run the [8, cols] sublane
    layout; its decisions must equal the XLA scan exactly like the flat
    row's. The threshold is patched down so interpret mode stays fast —
    a fresh node count keeps the jit cache from reusing a flat-layout
    trace."""
    from spark_scheduler_tpu.ops import pallas_fifo as pf

    orig = pf._layout_rows
    pf._layout_rows = lambda n: pf._SUBLANES
    try:
        rng = np.random.default_rng(21)
        c = random_cluster(rng, 53, num_zones=NUM_ZONES)
        apps = random_apps(rng, 7)
        for fill in sorted(PALLAS_FILLS) + sorted(PALLAS_SINGLE_AZ):
            want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX,
                                     num_zones=NUM_ZONES)
            got = fifo_pack_pallas(
                c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES,
                interpret=True,
            )
            assert_same(got, want)
    finally:
        pf._layout_rows = orig


def test_pallas_single_az_gpu_scoring_parity():
    """Zone-efficiency scoring with GPU-bearing nodes: the per-node max
    includes the GPU ratio only where schedulable GPU exists
    (efficiency.go:139-144) — a GPU-heavy cluster exercises that branch of
    the in-kernel score."""
    rng = np.random.default_rng(37)
    c = random_cluster(rng, 29, num_zones=NUM_ZONES)
    import dataclasses

    sched = np.asarray(c.schedulable).copy()
    avail = np.asarray(c.available).copy()
    sched[::2, 2] = 4  # every other node carries schedulable GPU
    avail[::2, 2] = rng.integers(0, 5, size=len(avail[::2]))
    c = dataclasses.replace(
        c, schedulable=sched, available=np.minimum(avail, sched)
    )
    driver = np.ones((6, 3), np.int32)
    execs = np.ones((6, 3), np.int32)
    execs[:, 2] = rng.integers(0, 2, size=6)  # some gangs want GPUs
    counts = rng.integers(1, EMAX + 1, size=6).astype(np.int32)
    apps = make_app_batch(driver, execs, counts)
    for fill in sorted(PALLAS_SINGLE_AZ):
        want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX,
                                 num_zones=NUM_ZONES)
        got = fifo_pack_pallas(
            c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES,
            interpret=True,
        )
        assert_same(got, want)


@pytest.mark.parametrize("fill", sorted(PALLAS_SINGLE_AZ))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_single_az_matches_xla_scan(fill, seed):
    """The in-kernel per-zone pack + efficiency-scored zone pick (VERDICT
    r3 #4) equals the XLA scan's pack_one_app_single_az step, decision for
    decision — including az-aware's plain fallback and the
    minimal-fragmentation driver-only reservation quirk."""
    rng = np.random.default_rng(seed * 11 + 2)
    c = random_cluster(rng, 37, num_zones=NUM_ZONES)
    apps = random_apps(rng, 9, pad_to=12)
    want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES)
    got = fifo_pack_pallas(
        c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES, interpret=True
    )
    assert_same(got, want)


def test_pallas_single_az_rejects_when_no_zone_fits():
    """single-az (no fallback): a gang no single zone can hold is
    rejected; az-aware admits it via the plain fallback."""
    rng = np.random.default_rng(29)
    c = random_cluster(rng, 24, num_zones=NUM_ZONES)
    driver = np.ones((1, 3), np.int32)
    execs = np.ones((1, 3), np.int32) * 4
    counts = np.array([EMAX], np.int32)  # spread wider than any one zone
    apps = make_app_batch(driver, execs, counts)
    for fill in ("single-az-tightly-pack", "az-aware-tightly-pack"):
        want = batched_fifo_pack(c, apps, fill=fill, emax=EMAX,
                                 num_zones=NUM_ZONES)
        got = fifo_pack_pallas(
            c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES,
            interpret=True,
        )
        assert_same(got, want)


def test_pallas_rejects_masked():
    rng = np.random.default_rng(3)
    c = random_cluster(rng, 16, num_zones=NUM_ZONES)
    apps = random_apps(rng, 4)
    masked = apps._replace(domain=np.ones((4, 16), bool))
    with pytest.raises(ValueError):
        fifo_pack_pallas(c, masked, fill="tightly-pack",
                         emax=EMAX, num_zones=NUM_ZONES, interpret=True)


def test_pallas_empty_batch():
    """B=0 short-circuits (the grid would be empty): no admissions,
    availability unchanged — same as the XLA scan."""
    rng = np.random.default_rng(13)
    c = random_cluster(rng, 16, num_zones=NUM_ZONES)
    apps = make_app_batch(
        np.zeros((0, 3), np.int32), np.zeros((0, 3), np.int32),
        np.zeros(0, np.int32),
    )
    got = fifo_pack_pallas(
        c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES,
        interpret=True,
    )
    assert got.driver_node.shape == (0,)
    assert got.executor_nodes.shape == (0, EMAX)
    np.testing.assert_array_equal(
        np.asarray(got.available_after), np.asarray(c.available)
    )


def test_grouped_pallas_fast_path_interpret():
    """The per-group slicing/stacking of the single-chip fast path
    (_grouped_pallas) must reproduce the vmapped scan's decisions — driven
    through the Pallas interpreter so the CPU suite covers the wiring, not
    just the fallback."""
    from spark_scheduler_tpu.parallel import (
        grouped_fifo_pack,
        grouped_fifo_pack_auto,  # noqa: F401 — fallback covered below
        make_solver_mesh,
        stack_groups,
    )
    from spark_scheduler_tpu.parallel.solve import _grouped_pallas

    rng = np.random.default_rng(29)
    # 24 nodes: divisible by the virtual mesh's 8-way node axis (the
    # `want` side shards over it).
    clusters = [random_cluster(rng, 24, num_zones=NUM_ZONES) for _ in range(3)]
    batches = [random_apps(rng, 5) for _ in range(3)]
    sc, sa = stack_groups(clusters, batches)
    mesh = make_solver_mesh(n_groups=1)
    want = grouped_fifo_pack(mesh, sc, sa, fill="tightly-pack", emax=EMAX,
                             num_zones=NUM_ZONES)
    got = _grouped_pallas(sc, sa, fill="tightly-pack", emax=EMAX,
                          num_zones=NUM_ZONES, g=3, interpret=True)
    assert_same(got, want)


def test_grouped_auto_falls_back_on_cpu():
    """On the CPU mesh (no Mosaic) grouped_fifo_pack_auto must produce the
    vmapped scan's decisions; on a multi-device mesh it must always use the
    GSPMD path regardless of backend."""
    from spark_scheduler_tpu.parallel import (
        grouped_fifo_pack,
        grouped_fifo_pack_auto,
        make_solver_mesh,
        stack_groups,
    )

    rng = np.random.default_rng(17)
    clusters = [random_cluster(rng, 16, num_zones=NUM_ZONES) for _ in range(2)]
    batches = [random_apps(rng, 4) for _ in range(2)]
    sc, sa = stack_groups(clusters, batches)
    mesh = make_solver_mesh(n_groups=1)
    want = grouped_fifo_pack(mesh, sc, sa, fill="tightly-pack", emax=EMAX,
                             num_zones=NUM_ZONES)
    got = grouped_fifo_pack_auto(mesh, sc, sa, fill="tightly-pack",
                                 emax=EMAX, num_zones=NUM_ZONES)
    assert_same(got, want)


def test_auto_routing_falls_back_on_cpu():
    """On the CPU suite Mosaic is unavailable: fifo_pack_auto must still
    return correct decisions via the XLA scan."""
    rng = np.random.default_rng(5)
    c = random_cluster(rng, 16, num_zones=NUM_ZONES)
    apps = random_apps(rng, 5)
    want = batched_fifo_pack(c, apps, fill="tightly-pack", emax=EMAX,
                             num_zones=NUM_ZONES)
    got = fifo_pack_auto(c, apps, fill="tightly-pack", emax=EMAX,
                         num_zones=NUM_ZONES)
    assert_same(got, want)
