"""HTTPS transport tests (the witchcraft HTTPS slot, VERDICT weak #6).

The reference serves the extender protocol over HTTPS with cert/key and
client CAs from install config (examples/extender.yml:73-80) and probes
liveness/readiness over HTTPS (extender.yml:142-151).
"""

from __future__ import annotations

import http.client
import json
import socket
import ssl
import subprocess

import pytest

from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import ConversionWebhookServer, SchedulerHTTPServer
from spark_scheduler_tpu.store.backend import InMemoryBackend
from spark_scheduler_tpu.testing.harness import new_node


@pytest.fixture(scope="module")
def tls_material(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "server.crt"), str(d / "server.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", cert, "-days", "1",
            "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return cert, key


def _client_ctx(cert: str) -> ssl.SSLContext:
    ctx = ssl.create_default_context(cafile=cert)
    ctx.check_hostname = False
    return ctx


def _tls_server(tls_material, transport="threaded", **kw):
    cert, key = tls_material
    backend = InMemoryBackend()
    backend.add_node(new_node("n0"))
    app = build_scheduler_app(backend, InstallConfig(sync_writes=True))
    return SchedulerHTTPServer(
        app, host="127.0.0.1", port=0, cert_file=cert, key_file=key,
        transport=transport, **kw
    )


# Both transports must serve the same TLS surface: per-connection
# handshakes on the threaded stack, loop-level SSL on the async one.
@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_https_serving(tls_material, transport):
    cert, _ = tls_material
    server = _tls_server(tls_material, transport)
    server.start()
    try:
        assert server.tls
        conn = http.client.HTTPSConnection(
            "127.0.0.1", server.port, context=_client_ctx(cert), timeout=5
        )
        conn.request("GET", "/status/liveness")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        server.stop()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_plaintext_client_rejected_on_tls_server(tls_material, transport):
    server = _tls_server(tls_material, transport)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        with pytest.raises(Exception):  # TLS server drops a plaintext request
            conn.request("GET", "/status/liveness")
            resp = conn.getresponse()
            if resp.status:  # pragma: no cover - must not produce a response
                raise AssertionError("plaintext request succeeded")
        conn.close()
    finally:
        server.stop()


def test_conversion_webhook_https(tls_material):
    cert, key = tls_material
    server = ConversionWebhookServer(
        host="127.0.0.1", port=0, cert_file=cert, key_file=key
    )
    server.start()
    try:
        conn = http.client.HTTPSConnection(
            "127.0.0.1", server.port, context=_client_ctx(cert), timeout=5
        )
        review = {
            "request": {"uid": "u1", "desiredAPIVersion": "v1beta2", "objects": []}
        }
        conn.request("POST", "/convert", body=json.dumps(review).encode())
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["response"]["uid"] == "u1"
        conn.close()
    finally:
        server.stop()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_request_timeout_closes_stalled_connection(tls_material, transport):
    """A client that connects and never sends a request cannot pin a
    handler thread (threaded) or per-connection loop state (async) past
    the configured timeout."""
    backend = InMemoryBackend()
    backend.add_node(new_node("n0"))
    app = build_scheduler_app(backend, InstallConfig(sync_writes=True))
    server = SchedulerHTTPServer(
        app, host="127.0.0.1", port=0, request_timeout_s=0.5,
        transport=transport,
    )
    server.start()
    try:
        s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        s.settimeout(5)
        # send nothing; the server should close the connection after 0.5s
        data = s.recv(1)  # blocks until server closes -> b""
        assert data == b""
        s.close()
    finally:
        server.stop()


def test_config_parses_server_block():
    cfg = InstallConfig.from_dict(
        {
            "server": {
                "port": 9999,
                "cert-file": "/c.crt",
                "key-file": "/c.key",
                "client-ca-files": ["/ca.crt"],
                "transport": "async",
                "max-body-bytes": 1048576,
                "max-connections": 64,
                "shed-queue-depth": 32,
            },
            "request-timeout": "10s",
        }
    )
    assert cfg.port == 9999
    assert cfg.cert_file == "/c.crt"
    assert cfg.key_file == "/c.key"
    assert cfg.client_ca_files == ["/ca.crt"]
    assert cfg.request_timeout_s == 10.0
    assert cfg.server_transport == "async"
    assert cfg.max_body_bytes == 1048576
    assert cfg.max_connections == 64
    assert cfg.shed_queue_depth == 32
    # Defaults: threaded transport, backpressure knobs at their documented
    # values.
    dflt = InstallConfig.from_dict({})
    assert dflt.server_transport == "threaded"
    assert dflt.max_body_bytes == 16 * 1024 * 1024
    assert dflt.max_connections == 512
    assert dflt.shed_queue_depth == 256
