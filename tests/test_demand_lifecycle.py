"""Demand phase transitions and DemandGC races, end to end through the
in-process autoscaler: pending -> fulfilled, pending -> cannot-fulfill,
demand deleted when its pod schedules before the autoscaler acts, and
double-create idempotency."""

from __future__ import annotations

from spark_scheduler_tpu.models.demands import (
    PHASE_CANNOT_FULFILL,
    PHASE_EMPTY,
    PHASE_FULFILLED,
    PHASE_PENDING,
    demand_name_for_pod,
)
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _harness(**kw):
    kw.setdefault("autoscaler_max_cluster_size", 100)
    return Harness(autoscaler_enabled=True, clock=FakeClock(), **kw)


def _backend_demand(h, name, namespace="namespace"):
    return h.backend.get("demands", namespace, name)


def test_pending_to_fulfilled():
    h = _harness()
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-pf", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    name = demand_name_for_pod(pods[0])
    assert _backend_demand(h, name).status.phase == PHASE_EMPTY
    h.autoscaler.run_once()
    d = _backend_demand(h, name)
    # One pass both acks ("" -> pending) and fulfills; the transition time
    # is stamped and the latency anchor is the demand's creationTimestamp.
    assert d.status.phase == PHASE_FULFILLED
    assert d.status.last_transition_time > 0 or d.metadata_extra
    assert h.autoscaler.metrics.counts()["demands_fulfilled"] == 1


def test_pending_to_cannot_fulfill_at_cap():
    h = _harness(autoscaler_max_cluster_size=1)
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-cf", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    h.autoscaler.run_once()
    d = _backend_demand(h, demand_name_for_pod(pods[0]))
    assert d.status.phase == PHASE_CANNOT_FULFILL
    assert len(h.backend.list_nodes()) == 1  # nothing provisioned


def test_cap_limited_demand_retries_when_headroom_appears():
    """A demand refused at the cap is NOT starved forever: once headroom
    exists (cap raised here; drained capacity in production) the next pass
    re-acks it pending and fulfills it."""
    h = _harness(autoscaler_max_cluster_size=1)
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-fw", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    h.autoscaler.run_once()
    name = demand_name_for_pod(pods[0])
    assert _backend_demand(h, name).status.phase == PHASE_CANNOT_FULFILL
    h.autoscaler.run_once()  # still no headroom: refusal is stable
    assert _backend_demand(h, name).status.phase == PHASE_CANNOT_FULFILL
    h.autoscaler.max_cluster_size = 100
    h.autoscaler.run_once()
    assert _backend_demand(h, name).status.phase == PHASE_FULFILLED
    assert len(h.backend.list_nodes()) > 1


def test_demand_deleted_when_pod_schedules_first():
    """The DemandGC race: the pod gets capacity (another app tears down)
    and schedules before the autoscaler ever acts on its demand. The GC
    deletes the demand on the pod's scheduled transition, and the next
    autoscaler pass must cope with the demand being gone."""
    h = _harness()
    h.add_nodes(new_node("n0"))
    blocker = static_allocation_spark_pods("app-blocker", 6)
    for p in blocker:
        assert h.schedule(p, ["n0"]).ok
    pods = static_allocation_spark_pods("app-race", 1)
    assert not h.schedule(pods[0], ["n0"]).ok  # n0 full -> demand
    name = demand_name_for_pod(pods[0])
    assert _backend_demand(h, name) is not None
    # Blocker tears down; the pod schedules WITHOUT the autoscaler.
    for p in blocker:
        h.backend.delete_pod(h.backend.get("pods", p.namespace, p.name))
    rr = h.get_reservation("namespace", "app-blocker")
    h.app.rr_cache.delete(rr.namespace, rr.name)
    for p in pods:
        assert h.schedule(p, ["n0"]).ok
    assert _backend_demand(h, name) is None  # extender/GC deleted it
    summary = h.autoscaler.run_once()  # must not provision for a ghost
    assert summary["fulfilled"] == 0 and summary["nodes_added"] == 0


def test_demand_gc_on_externally_bound_pod():
    """demand_gc.go race cover: the demand's pod is bound by someone else
    entirely (no extender success path) — the GC subscription alone must
    delete the demand."""
    h = _harness()
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-gc", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    name = demand_name_for_pod(pods[0])
    assert _backend_demand(h, name) is not None
    h.backend.bind_pod(pods[0], "n0")  # kube-scheduler binds it anyway
    assert _backend_demand(h, name) is None


def test_double_create_idempotency():
    h = _harness()
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-dc", 20)
    # Two failed attempts -> create_demand_for_application twice.
    assert not h.schedule(pods[0], ["n0"]).ok
    first = _backend_demand(h, demand_name_for_pod(pods[0]))
    assert not h.schedule(pods[0], ["n0"]).ok
    demands = h.backend.list("demands")
    assert len(demands) == 1
    assert demands[0].resource_version == first.resource_version
    # And a pass fulfills ONE demand, once.
    h.autoscaler.run_once()
    assert h.autoscaler.metrics.counts()["demands_fulfilled"] == 1


def test_fulfilled_phase_feeds_waste_reporter():
    """The autoscaler's backend write is indistinguishable from the external
    autoscaler's: the waste reporter's on-update subscription sees it."""
    from spark_scheduler_tpu.metrics.waste import WasteReporter
    from spark_scheduler_tpu.testing.harness import INSTANCE_GROUP_LABEL

    clock = FakeClock()
    w = WasteReporter(instance_group_label=INSTANCE_GROUP_LABEL, clock=clock)
    h = Harness(
        autoscaler_enabled=True, autoscaler_max_cluster_size=100,
        clock=clock, waste=w,
    )
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-wf", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    clock.advance(4.0)
    h.autoscaler.run_once()
    clock.advance(6.0)
    names = [n.name for n in h.backend.list_nodes()]
    assert h.schedule(pods[0], names).ok
    snap = w.registry.snapshot()
    from spark_scheduler_tpu.metrics.waste import SCHEDULING_WASTE

    by_type = {e["tags"]["wastetype"]: e for e in snap[SCHEDULING_WASTE]}
    assert abs(by_type["after-demand-fulfilled"]["max"] - 6.0) < 1e-6


def test_phase_transition_stamps_time():
    clock = FakeClock(t=100.0)
    h = Harness(
        autoscaler_enabled=True, autoscaler_max_cluster_size=100, clock=clock
    )
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-ts", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    clock.advance(7.0)
    h.autoscaler.run_once()
    d = _backend_demand(h, demand_name_for_pod(pods[0]))
    assert d.status.phase == PHASE_FULFILLED
    assert d.status.last_transition_time == 107.0
    # Latency anchored on creationTimestamp (stamped at create, t=100).
    [latency] = h.autoscaler.metrics.scaleup_latency_samples()
    assert abs(latency - 7.0) < 1e-6


def test_ack_then_decision_are_separate_transitions():
    """"" -> pending (ownership ack) and pending -> fulfilled are distinct
    backend writes: an external dashboard watching resourceVersions sees
    both. Intercept via a demand-update subscription."""
    h = _harness()
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-pv", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    phases: list[str] = []
    h.backend.subscribe(
        "demands", on_update=lambda old, new: phases.append(new.status.phase)
    )
    h.autoscaler.run_once()
    assert phases == [PHASE_PENDING, PHASE_FULFILLED]


def test_impossible_unit_is_cannot_fulfill():
    """A demand unit larger than an empty template node can never be
    fulfilled by scale-up, whatever the cap."""
    h = _harness()  # template 8 cpu
    driver = static_allocation_spark_pods("app-imp", 1)[0]
    h.add_pods(driver)
    from spark_scheduler_tpu.models.resources import Resources

    d = h.app.demand_manager.create_demand_for_executor(
        driver, Resources.from_quantities("16", "1Gi", "0")
    )
    h.autoscaler.run_once()
    assert _backend_demand(h, d.name).status.phase == PHASE_CANNOT_FULFILL
    assert len(h.backend.list_nodes()) == 0
