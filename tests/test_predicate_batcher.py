"""PredicateBatcher contracts the serving transports lean on: timeout
shedding racing the dispatcher's claim, the claim_log hard bound, and the
callback-mode (submit_nowait) completion path the async transport uses.

All tests drive a stub extender — host-only, no solver — so the races can
be staged deterministically with events.
"""

import threading
import time

import pytest

from spark_scheduler_tpu.server.http import PredicateBatcher


class StubTicket:
    def __init__(self, batch_len):
        self.handle = None  # solo/sync path: complete immediately
        self.batch_len = batch_len


class StubExtender:
    """Synchronous stub: every window completes inline. `stall` (when set)
    blocks the dispatcher inside dispatch — after the claim, before
    completion — which is exactly the window the timeout race needs."""

    def __init__(self):
        self.stall = None  # threading.Event the dispatcher waits on
        self.dispatched = 0
        self.completed = 0

    def predicate_window_dispatch(self, args_list):
        self.dispatched += len(args_list)
        if self.stall is not None:
            assert self.stall.wait(10), "test stall never released"
        return StubTicket(len(args_list))

    def predicate_window_complete(self, ticket):
        self.completed += ticket.batch_len
        return ["ok"] * ticket.batch_len


def test_claim_log_is_hard_bounded():
    """The claim log must stop recording at CLAIM_LOG_CAP — a long soak
    cannot grow it unbounded (it is a forensic tail, not a history)."""
    ext = StubExtender()
    # max_window=1: every request is its own claim, so the log would reach
    # n entries without the bound.
    b = PredicateBatcher(ext, max_window=1, hold_ms=0)
    cap = PredicateBatcher.CLAIM_LOG_CAP
    n = cap + 150
    try:
        done = threading.Semaphore(0)
        errs = []

        def client(k):
            try:
                for _ in range(k):
                    assert b.submit("x", timeout=10) == "ok"
            except Exception as exc:  # pragma: no cover - surfaced below
                errs.append(exc)
            finally:
                done.release()

        n_threads = 8
        per = n // n_threads + 1
        for _ in range(n_threads):
            threading.Thread(target=client, args=(per,), daemon=True).start()
        for _ in range(n_threads):
            assert done.acquire(timeout=60)
        assert not errs, errs
        # Enough windows ran to cross the bound, and recording stopped
        # EXACTLY at it.
        assert b.windows_served > cap
        assert len(b.claim_log) == cap, len(b.claim_log)
    finally:
        b.stop()


def test_timeout_race_with_claimed_entry_completes_once_and_prunes():
    """A request that times out in submit() AFTER the dispatcher claimed
    its entry: the solve proceeds, the entry completes exactly once, and
    its slot does NOT linger in _claimed (regression: the lazy rebuild
    only ran on the next claim — on an idle server, never)."""
    ext = StubExtender()
    ext.stall = threading.Event()
    b = PredicateBatcher(ext, max_window=4, hold_ms=0)
    try:
        with pytest.raises(TimeoutError):
            b.submit("slow", timeout=0.15)
        # The dispatcher is stalled INSIDE dispatch — the entry was claimed,
        # so the timed-out submit couldn't remove it from the queue.
        assert b.queue_depth() == 0
        with b._cv:
            assert len(b._claimed) == 1
        ext.stall.set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with b._cv:
                if not b._claimed and b.requests_served == 1:
                    break
            time.sleep(0.01)
        with b._cv:
            assert b._claimed == [], "completed entry left its slot in _claimed"
        assert b.requests_served == 1  # completed exactly once
        # The batcher is healthy: a fresh request round-trips.
        ext.stall = None
        assert b.submit("next", timeout=5) == "ok"
    finally:
        ext.stall = None
        b.stop()


def test_timeout_unclaimed_entry_is_removed_from_queue():
    """A request that times out BEFORE the dispatcher claims it is shed
    from the queue — no window slot is burned solving for a client that
    already got an error."""
    ext = StubExtender()
    ext.stall = threading.Event()
    b = PredicateBatcher(ext, max_window=1, hold_ms=0)
    try:
        # First request parks the dispatcher inside dispatch...
        t1 = threading.Thread(
            target=lambda: b.submit("first", timeout=10), daemon=True
        )
        t1.start()
        deadline = time.monotonic() + 5
        while ext.dispatched == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        # ...so the second request stays UNCLAIMED in the queue and its
        # timeout must remove it.
        with pytest.raises(TimeoutError):
            b.submit("second", timeout=0.1)
        assert b.queue_depth() == 0
        ext.stall.set()
        t1.join(5)
        # Only the first request was ever dispatched/served.
        deadline = time.monotonic() + 5
        while ext.completed < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        time.sleep(0.05)  # would-be second window would dispatch by now
        assert ext.dispatched == 1
        assert b.requests_served == 1
    finally:
        ext.stall = None
        b.stop()


def test_submit_nowait_completion_callback():
    """Callback-mode submission (the async transport's path): done fires
    exactly once from the dispatcher with the entry's result."""
    ext = StubExtender()
    b = PredicateBatcher(ext, max_window=4, hold_ms=0)
    try:
        fired = []
        done_evt = threading.Event()

        def done(result, exc):
            fired.append((result, exc))
            done_evt.set()

        b.submit_nowait("x", done)
        assert done_evt.wait(5)
        assert fired == [("ok", None)]
        with b._cv:
            assert b._claimed == []
    finally:
        b.stop()


def test_abandon_unclaimed_nowait_entry_never_fires():
    ext = StubExtender()
    ext.stall = threading.Event()
    b = PredicateBatcher(ext, max_window=1, hold_ms=0)
    try:
        blocker_done = threading.Event()
        b.submit_nowait("blocker", lambda r, e: blocker_done.set())
        deadline = time.monotonic() + 5
        while ext.dispatched == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        fired = []
        entry = b.submit_nowait("victim", lambda r, e: fired.append((r, e)))
        assert b.abandon(entry) is True  # unclaimed: removed
        assert b.abandon(entry) is False  # idempotent
        ext.stall.set()
        assert blocker_done.wait(5)
        time.sleep(0.05)
        assert fired == []  # abandoned entry's callback never fired
        assert ext.dispatched == 1
    finally:
        ext.stall = None
        b.stop()


def test_stop_fails_pending_nowait_entries_via_callback():
    """Shutdown must flush callback entries with the shutting-down error —
    the async transport's in-flight requests get their error response
    instead of hanging."""
    ext = StubExtender()
    ext.stall = threading.Event()
    b = PredicateBatcher(ext, max_window=1, hold_ms=0)
    fired = []
    evt = threading.Event()
    b.submit_nowait("stuck", lambda r, e: (fired.append((r, e)), evt.set()))
    deadline = time.monotonic() + 5
    while ext.dispatched == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    stopper = threading.Thread(target=b.stop, daemon=True)
    stopper.start()
    # stop() joins the (stalled) dispatcher with a timeout, then fails the
    # claimed entry; the late release must be harmless (idempotent set).
    assert evt.wait(15)
    assert fired and fired[0][0] is None
    assert isinstance(fired[0][1], RuntimeError)
    ext.stall.set()
    stopper.join(10)
    assert not stopper.is_alive()
    assert len(fired) == 1  # a late dispatcher set() never double-fires
