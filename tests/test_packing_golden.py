"""Golden parity: vectorized packing kernels vs the greedy oracle.

Randomized clusters; every strategy must reproduce the oracle's placements
slot-for-slot (driver node, executor slot sequence, feasibility).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.ops import packing as P
from spark_scheduler_tpu.ops.sorting import priority_order, zone_ranks

from tests import greedy_oracle as G

EMAX = 24
NUM_ZONES = 4


def random_cluster(rng, n, num_zones=NUM_ZONES, with_labels=False):
    avail = rng.integers(0, 40, size=(n, 3)).astype(np.int32)
    avail[:, 1] = rng.integers(0, 64, size=n)  # memory
    avail[:, 2] = rng.integers(0, 3, size=n) * rng.integers(0, 2, size=n)  # gpu
    usage = rng.integers(0, 8, size=(n, 3)).astype(np.int32)
    schedulable = (avail + usage).astype(np.int32)
    zone_id = rng.integers(0, num_zones, size=n).astype(np.int32)
    name_rank = rng.permutation(n).astype(np.int32)
    if with_labels:
        lr_d = rng.choice([0, 1, 2, INT32_INF], size=n).astype(np.int32)
        lr_e = rng.choice([0, 1, INT32_INF], size=n).astype(np.int32)
    else:
        lr_d = np.full(n, INT32_INF, np.int32)
        lr_e = np.full(n, INT32_INF, np.int32)
    unschedulable = rng.random(n) < 0.1
    ready = rng.random(n) > 0.1
    valid = rng.random(n) > 0.05
    return ClusterTensors(
        available=avail,
        schedulable=schedulable,
        zone_id=zone_id,
        name_rank=name_rank,
        label_rank_driver=lr_d,
        label_rank_executor=lr_e,
        unschedulable=unschedulable,
        ready=ready,
        valid=valid,
    )


def oracle_orders(c: ClusterTensors, driver_mask, domain):
    avail = np.asarray(c.available)
    zone = np.asarray(c.zone_id)
    names = np.asarray(c.name_rank)
    valid = np.asarray(c.valid)
    dom = domain & valid
    d_elig = dom & driver_mask
    e_elig = dom & ~np.asarray(c.unschedulable) & np.asarray(c.ready)
    d_order = G.greedy_priority_order(
        avail, zone, names, d_elig, domain=dom, label_rank=np.asarray(c.label_rank_driver)
    )
    e_order = G.greedy_priority_order(
        avail, zone, names, e_elig, domain=dom, label_rank=np.asarray(c.label_rank_executor)
    )
    return d_order, e_order


def check_case(c, driver_req, exec_req, count, driver_mask, domain, fill):
    d_order, e_order = oracle_orders(c, driver_mask, domain)
    g_driver, g_execs, g_ok, _ = G.greedy_spark_bin_pack(
        np.asarray(c.available).astype(np.int64),
        driver_req.astype(np.int64),
        exec_req.astype(np.int64),
        count,
        d_order,
        e_order,
        fill,
    )
    got = P.spark_bin_pack(
        c,
        jnp.asarray(driver_req, jnp.int32),
        jnp.asarray(exec_req, jnp.int32),
        jnp.int32(count),
        jnp.asarray(driver_mask),
        jnp.asarray(domain),
        fill=fill,
        emax=EMAX,
        num_zones=NUM_ZONES,
    )
    assert bool(got.has_capacity) == g_ok, (fill, g_driver, g_execs)
    if g_ok:
        assert int(got.driver_node) == g_driver, (fill, g_driver, int(got.driver_node))
        got_execs = [int(x) for x in np.asarray(got.executor_nodes) if x >= 0]
        assert got_execs == list(g_execs), (fill, g_execs, got_execs)
    else:
        assert int(got.driver_node) == -1
        assert np.all(np.asarray(got.executor_nodes) == -1)


@pytest.mark.parametrize("fill", ["tightly-pack", "distribute-evenly", "minimal-fragmentation"])
def test_fill_strategies_match_oracle(fill):
    rng = np.random.default_rng(hash(fill) % 2**32)
    sizes = [1, 2, 3, 5, 9, 17]
    for trial in range(150):
        n = int(sizes[int(rng.integers(0, len(sizes)))])
        c = random_cluster(rng, n, with_labels=trial % 3 == 0)
        driver_req = rng.integers(0, 12, size=3).astype(np.int32)
        exec_req = rng.integers(0, 10, size=3).astype(np.int32)
        if trial % 7 == 0:
            exec_req[:] = 0  # zero-request edge: infinite capacity
        count = int(rng.integers(0, EMAX + 1))
        driver_mask = rng.random(n) < 0.7
        domain = rng.random(n) < 0.9
        check_case(c, driver_req, exec_req, count, driver_mask, domain, fill)


@pytest.mark.parametrize("fill", ["tightly-pack", "minimal-fragmentation"])
def test_single_az_matches_oracle(fill):
    rng = np.random.default_rng(42 if fill == "tightly-pack" else 43)
    kernel = (
        P.single_az_tightly_pack
        if fill == "tightly-pack"
        else P.single_az_minimal_fragmentation
    )
    sizes = [1, 3, 7, 15]
    for trial in range(120):
        n = int(sizes[int(rng.integers(0, len(sizes)))])
        c = random_cluster(rng, n)
        driver_req = rng.integers(0, 10, size=3).astype(np.int32)
        exec_req = rng.integers(1, 8, size=3).astype(np.int32)
        count = int(rng.integers(0, 12))
        driver_mask = rng.random(n) < 0.8
        domain = rng.random(n) < 0.95

        # Oracle (single_az.go:23-97): per-zone pack over zones in driver
        # first-appearance order; best avg efficiency wins, ties -> earliest.
        avail = np.asarray(c.available).astype(np.int64)
        sched = np.asarray(c.schedulable).astype(np.int64)
        zone = np.asarray(c.zone_id)
        valid = np.asarray(c.valid)
        dom = domain & valid
        d_order_all, e_order_all = oracle_orders(c, driver_mask, dom)
        zones_in_order = []
        for i in d_order_all:
            if zone[i] not in zones_in_order:
                zones_in_order.append(zone[i])
        best = None
        for z in zones_in_order:
            d_order = [i for i in d_order_all if zone[i] == z]
            e_order = [i for i in e_order_all if zone[i] == z]
            if not e_order:
                continue
            d, ex, ok, _ = G.greedy_spark_bin_pack(
                avail, driver_req.astype(np.int64), exec_req.astype(np.int64),
                count, d_order, e_order, fill,
            )
            if not ok:
                continue
            eff = G.greedy_avg_efficiency(
                avail, sched, d, ex, driver_req, exec_req,
                include_executors_in_reserved=(fill != "minimal-fragmentation"),
            )
            # chooseBestResult starts at Max=0.0 and replaces on strictly
            # greater, so zero-efficiency zones are rejected outright.
            if eff > (best[0] if best is not None else 0.0):
                best = (eff, d, ex)

        got = kernel(
            c,
            jnp.asarray(driver_req, jnp.int32),
            jnp.asarray(exec_req, jnp.int32),
            jnp.int32(count),
            jnp.asarray(driver_mask),
            jnp.asarray(domain),
            emax=EMAX,
            num_zones=NUM_ZONES,
        )
        if best is None:
            assert not bool(got.has_capacity)
            continue
        assert bool(got.has_capacity)
        got_driver = int(got.driver_node)
        got_execs = [int(x) for x in np.asarray(got.executor_nodes) if x >= 0]
        if (got_driver, got_execs) != (best[1], list(best[2])):
            # float32-vs-float64 efficiency tie: accept iff the kernel's pick
            # scores within 1e-5 of the oracle's best.
            got_eff = G.greedy_avg_efficiency(
                avail, sched, got_driver, got_execs, driver_req, exec_req,
                include_executors_in_reserved=(fill != "minimal-fragmentation"),
            )
            assert abs(got_eff - best[0]) < 1e-5, (
                fill, best, got_driver, got_execs, got_eff,
            )


def test_az_aware_fallback():
    rng = np.random.default_rng(7)
    sizes = [2, 6, 12]
    for _ in range(60):
        n = int(sizes[int(rng.integers(0, len(sizes)))])
        c = random_cluster(rng, n)
        driver_req = rng.integers(0, 8, size=3).astype(np.int32)
        exec_req = rng.integers(1, 6, size=3).astype(np.int32)
        count = int(rng.integers(0, 10))
        driver_mask = rng.random(n) < 0.8
        domain = np.ones(n, bool)
        az = P.single_az_tightly_pack(
            c, jnp.asarray(driver_req), jnp.asarray(exec_req), jnp.int32(count),
            jnp.asarray(driver_mask), jnp.asarray(domain), emax=EMAX, num_zones=NUM_ZONES,
        )
        plain = P.tightly_pack(
            c, jnp.asarray(driver_req), jnp.asarray(exec_req), jnp.int32(count),
            jnp.asarray(driver_mask), jnp.asarray(domain), emax=EMAX, num_zones=NUM_ZONES,
        )
        got = P.az_aware_tightly_pack(
            c, jnp.asarray(driver_req), jnp.asarray(exec_req), jnp.int32(count),
            jnp.asarray(driver_mask), jnp.asarray(domain), emax=EMAX, num_zones=NUM_ZONES,
        )
        if bool(az.has_capacity):
            assert int(got.driver_node) == int(az.driver_node)
            assert np.array_equal(np.asarray(got.executor_nodes), np.asarray(az.executor_nodes))
        else:
            assert bool(got.has_capacity) == bool(plain.has_capacity)
            if bool(plain.has_capacity):
                assert int(got.driver_node) == int(plain.driver_node)


def test_priority_order_matches_oracle():
    rng = np.random.default_rng(11)
    sizes = [1, 4, 11, 31]
    for trial in range(100):
        n = int(sizes[int(rng.integers(0, len(sizes)))])
        c = random_cluster(rng, n, with_labels=trial % 2 == 0)
        elig_np = (
            np.asarray(c.valid)
            & ~np.asarray(c.unschedulable)
            & np.asarray(c.ready)
            & (rng.random(n) < 0.9)
        )
        zr = zone_ranks(c, jnp.asarray(np.asarray(c.valid)), NUM_ZONES)
        order, cnt = priority_order(
            c, jnp.asarray(elig_np), zr, c.label_rank_executor
        )
        got = [int(x) for x in np.asarray(order)[: int(cnt)]]
        want = G.greedy_priority_order(
            np.asarray(c.available),
            np.asarray(c.zone_id),
            np.asarray(c.name_rank),
            elig_np,
            domain=np.asarray(c.valid),
            label_rank=np.asarray(c.label_rank_executor),
        )
        assert got == want


def test_efficiency_np_parity():
    """Host-side numpy efficiency (serving-path reporting) must match the
    jnp kernel (used inside the single-AZ packers) bit-for-float."""
    from spark_scheduler_tpu.ops.efficiency import (
        avg_packing_efficiency,
        avg_packing_efficiency_np,
    )

    rng = np.random.default_rng(7)
    for trial in range(20):
        c = random_cluster(rng, 40)
        driver_node = int(rng.integers(-1, 40))
        executor_nodes = rng.integers(-1, 40, size=8).astype(np.int32)
        driver_req = rng.integers(0, 4, size=3).astype(np.int32)
        exec_req = rng.integers(0, 4, size=3).astype(np.int32)
        jnp_eff = avg_packing_efficiency(
            c,
            jnp.int32(driver_node),
            jnp.asarray(executor_nodes),
            jnp.asarray(driver_req),
            jnp.asarray(exec_req),
        )
        np_eff = avg_packing_efficiency_np(
            c.schedulable, c.available, driver_node, executor_nodes,
            driver_req, exec_req,
        )
        for field in ("cpu", "memory", "gpu", "max"):
            assert float(getattr(jnp_eff, field)) == pytest.approx(
                float(getattr(np_eff, field)), abs=1e-5
            ), (trial, field)
