"""Batched FIFO gang admission: parity with a sequential oracle loop, strict
FIFO blocking semantics, and sharded == unsharded on the virtual device mesh.
"""

import numpy as np
import pytest

from spark_scheduler_tpu.models.cluster import ClusterTensors
from spark_scheduler_tpu.ops.batched import AppBatch, batched_fifo_pack, make_app_batch
from spark_scheduler_tpu.parallel import (
    grouped_fifo_pack,
    make_solver_mesh,
    sharded_fifo_pack,
    stack_groups,
)

from tests import greedy_oracle as G
from tests.test_packing_golden import random_cluster, oracle_orders

EMAX = 16
NUM_ZONES = 4


def random_apps(rng, b, pad_to=None):
    driver = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
    driver[:, 2] = rng.integers(0, 2, size=b)
    execs = rng.integers(1, 8, size=(b, 3)).astype(np.int32)
    execs[:, 2] = rng.integers(0, 2, size=b)
    # Occasionally exceed EMAX: oversized gangs must be rejected, not truncated.
    counts = rng.integers(0, EMAX + 4, size=b).astype(np.int32)
    skip = rng.random(b) < 0.3
    return make_app_batch(driver, execs, counts, pad_to=pad_to, skippable=skip)


def oracle_batched(c: ClusterTensors, apps: AppBatch, fill):
    """Sequential reference loop: pack each app in FIFO order against the
    mutating availability, orders fixed from the starting availability
    (fitEarlierDrivers semantics, resource.go:221-258)."""
    avail = np.asarray(c.available).astype(np.int64).copy()
    valid = np.asarray(c.valid)
    e_elig = valid & ~np.asarray(c.unschedulable) & np.asarray(c.ready)
    d_mask = e_elig.copy()
    d_order, e_order = oracle_orders(c, d_mask, valid)
    # oracle_orders applies eligibility itself; driver eligibility here is
    # the executor eligibility (queue mode, no kube candidate list).
    blocked = False
    out = []
    for i in range(len(apps.app_valid)):
        dreq = np.asarray(apps.driver_req[i], np.int64)
        ereq = np.asarray(apps.exec_req[i], np.int64)
        too_big = int(apps.exec_count[i]) > EMAX
        count = int(min(apps.exec_count[i], EMAX))
        drv, execs, ok, _ = G.greedy_spark_bin_pack(
            avail, dreq, ereq, count, d_order, e_order, fill
        )
        packed = ok and bool(apps.app_valid[i]) and not too_big
        admitted = packed and not blocked
        if admitted:
            avail[drv] -= dreq
            for n in execs:
                avail[n] -= ereq
        else:
            drv, execs = -1, []
        if bool(apps.app_valid[i]) and not packed and not bool(apps.skippable[i]):
            blocked = True
        out.append((drv, list(execs), admitted, packed))
    return out, avail


@pytest.mark.parametrize("fill", ["tightly-pack", "distribute-evenly", "minimal-fragmentation"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_batched_matches_sequential_oracle(fill, seed):
    rng = np.random.default_rng(seed)
    c = random_cluster(rng, 40)
    apps = random_apps(rng, 12, pad_to=16)
    got = batched_fifo_pack(c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES)
    want, want_avail = oracle_batched(c, apps, fill)
    for i, (drv, execs, admitted, packed) in enumerate(want):
        assert bool(got.admitted[i]) == admitted, f"app {i} admitted"
        assert bool(got.packed[i]) == packed, f"app {i} packed"
        assert int(got.driver_node[i]) == drv, f"app {i} driver"
        got_execs = [int(x) for x in np.asarray(got.executor_nodes[i]) if x >= 0]
        assert got_execs == execs, f"app {i} executors"
    live = np.asarray(c.valid)
    np.testing.assert_array_equal(
        np.asarray(got.available_after)[live], want_avail.astype(np.int32)[live]
    )


def random_masks(rng, b, n, p_cand=0.7, p_dom=0.85):
    """Per-app kube candidate lists + affinity domains, dense [B, N] bool."""
    dcand = rng.random((b, n)) < p_cand
    dom = rng.random((b, n)) < p_dom
    return dcand, dom


def oracle_masked(c: ClusterTensors, apps: AppBatch, fill):
    """Sequential serving-path oracle: each app is a standalone
    spark_bin_pack call with its own masks against the then-current
    availability (exactly what per-request /predicates does), with admitted
    usage subtracted between calls."""
    import dataclasses

    import jax.numpy as jnp

    from spark_scheduler_tpu.ops.packing import spark_bin_pack

    avail = np.asarray(c.available).copy()
    blocked = False
    out = []
    for i in range(len(apps.app_valid)):
        ci = dataclasses.replace(c, available=jnp.asarray(avail))
        count = int(apps.exec_count[i])
        p = spark_bin_pack(
            ci,
            jnp.asarray(apps.driver_req[i]),
            jnp.asarray(apps.exec_req[i]),
            jnp.int32(count),
            jnp.asarray(apps.driver_cand[i]),
            jnp.asarray(apps.domain[i]),
            fill=fill,
            emax=EMAX,
            num_zones=NUM_ZONES,
        )
        packed = bool(p.has_capacity) and bool(apps.app_valid[i])
        admitted = packed and not blocked
        if admitted:
            drv = int(p.driver_node)
            execs = [int(x) for x in np.asarray(p.executor_nodes) if int(x) >= 0]
            avail[drv] -= np.asarray(apps.driver_req[i])
            for nd in execs:
                avail[nd] -= np.asarray(apps.exec_req[i])
        else:
            drv, execs = -1, []
        if bool(apps.app_valid[i]) and not packed and not bool(apps.skippable[i]):
            blocked = True
        out.append((drv, execs, admitted, packed))
    return out, avail


@pytest.mark.parametrize("fill", ["tightly-pack", "distribute-evenly", "minimal-fragmentation"])
@pytest.mark.parametrize("seed", [0, 5])
def test_masked_batch_matches_sequential_spark_bin_pack(fill, seed):
    """VERDICT r1 #2: batched-with-masks == sequential spark_bin_pack with
    the same masks — the property that lets the serving path batch
    heterogeneous requests without changing any decision."""
    rng = np.random.default_rng(seed)
    c = random_cluster(rng, 40)
    n = np.asarray(c.available).shape[0]
    b = 10
    driver = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
    driver[:, 2] = rng.integers(0, 2, size=b)
    execs = rng.integers(1, 8, size=(b, 3)).astype(np.int32)
    execs[:, 2] = rng.integers(0, 2, size=b)
    counts = rng.integers(0, EMAX + 1, size=b).astype(np.int32)
    skip = rng.random(b) < 0.3
    dcand, dom = random_masks(rng, b, n)
    apps = make_app_batch(
        driver, execs, counts, skippable=skip, driver_cand=dcand, domain=dom,
        pad_to=16,
    )
    got = batched_fifo_pack(c, apps, fill=fill, emax=EMAX, num_zones=NUM_ZONES)
    want, want_avail = oracle_masked(c, apps, fill)
    for i, (drv, execs_w, admitted, packed) in enumerate(want):
        assert bool(got.admitted[i]) == admitted, f"app {i} admitted"
        assert bool(got.packed[i]) == packed, f"app {i} packed"
        assert int(got.driver_node[i]) == drv, f"app {i} driver"
        got_execs = [int(x) for x in np.asarray(got.executor_nodes[i]) if x >= 0]
        assert got_execs == execs_w, f"app {i} executors"
    live = np.asarray(c.valid)
    np.testing.assert_array_equal(
        np.asarray(got.available_after)[live], want_avail[live]
    )


from spark_scheduler_tpu.ops.batched import _SINGLE_AZ_INNER as _AZ_INNER

AZ_STRATEGIES = sorted(_AZ_INNER)


def greedy_single_az_candidates(
    avail, sched, zone, d_order, e_order, dreq, ereq, count, strategy
):
    """All reference-acceptable single-AZ outcomes for one app against the
    given availability and FIXED priority orders (single_az.go:23-97): the
    per-zone greedy results whose avg efficiency is within float32 tie
    distance of the best. Returns (acceptable [(driver, execs)], packed)."""
    inner = _AZ_INNER[strategy]
    zones_in_order = []
    for i in d_order:
        if zone[i] not in zones_in_order:
            zones_in_order.append(zone[i])
    results = []
    for z in zones_in_order:
        d_o = [i for i in d_order if zone[i] == z]
        e_o = [i for i in e_order if zone[i] == z]
        if not e_o:
            continue
        d, ex, ok, _ = G.greedy_spark_bin_pack(
            avail, dreq, ereq, count, d_o, e_o, inner
        )
        if not ok:
            continue
        eff = G.greedy_avg_efficiency(
            avail, sched, d, ex, dreq, ereq,
            include_executors_in_reserved=(inner != "minimal-fragmentation"),
        )
        if eff > 0.0:
            results.append((eff, d, list(ex)))
    if results:
        best = max(r[0] for r in results)
        acceptable = [(d, ex) for eff, d, ex in results if eff >= best - 1e-5]
        return acceptable, True
    if strategy == "az-aware-tightly-pack":
        d, ex, ok, _ = G.greedy_spark_bin_pack(
            avail, dreq, ereq, count, d_order, e_order, "tightly-pack"
        )
        if ok:
            return [(d, list(ex))], True
    return [], False


@pytest.mark.parametrize("strategy", AZ_STRATEGIES)
@pytest.mark.parametrize("seed", [0, 1])
def test_batched_single_az_matches_sequential_oracle(strategy, seed):
    """Queue mode: the batched single-AZ scan must per-step produce a
    reference-acceptable zone pick against the mutating availability, with
    orders fixed from the start (fitEarlierDrivers semantics). On float32
    efficiency near-ties, the oracle follows the kernel's choice."""
    rng = np.random.default_rng(seed)
    c = random_cluster(rng, 40)
    apps = random_apps(rng, 12, pad_to=16)
    got = batched_fifo_pack(c, apps, fill=strategy, emax=EMAX, num_zones=NUM_ZONES)

    avail = np.asarray(c.available).astype(np.int64).copy()
    sched = np.asarray(c.schedulable).astype(np.int64)
    zone = np.asarray(c.zone_id)
    valid = np.asarray(c.valid)
    e_elig = valid & ~np.asarray(c.unschedulable) & np.asarray(c.ready)
    d_order, e_order = oracle_orders(c, e_elig, valid)
    blocked = False
    for i in range(len(apps.app_valid)):
        dreq = np.asarray(apps.driver_req[i], np.int64)
        ereq = np.asarray(apps.exec_req[i], np.int64)
        too_big = int(apps.exec_count[i]) > EMAX
        count = int(min(apps.exec_count[i], EMAX))
        acceptable, ok = greedy_single_az_candidates(
            avail, sched, zone, d_order, e_order, dreq, ereq, count, strategy
        )
        packed = ok and bool(apps.app_valid[i]) and not too_big
        admitted = packed and not blocked
        assert bool(got.packed[i]) == packed, f"app {i} packed"
        assert bool(got.admitted[i]) == admitted, f"app {i} admitted"
        drv = int(got.driver_node[i])
        execs = [int(x) for x in np.asarray(got.executor_nodes[i]) if x >= 0]
        if admitted:
            assert (drv, execs) in acceptable, (
                f"app {i}: kernel pick {(drv, execs)} not reference-acceptable "
                f"{acceptable}"
            )
            avail[drv] -= dreq
            for nd in execs:
                avail[nd] -= ereq
        else:
            assert drv == -1 and not execs, f"app {i} must be unplaced"
        if bool(apps.app_valid[i]) and not packed and not bool(apps.skippable[i]):
            blocked = True
    live = np.asarray(c.valid)
    np.testing.assert_array_equal(
        np.asarray(got.available_after)[live], avail.astype(np.int32)[live]
    )


@pytest.mark.parametrize("strategy", AZ_STRATEGIES)
def test_masked_batch_single_az_matches_standalone(strategy):
    """Masked (serving) mode: each row of the batched single-AZ solve must
    match a standalone BINPACK_FUNCTIONS[strategy] call with the same masks
    against the then-current availability (float32 efficiency near-ties
    resolved in the kernel's favor, as test_single_az_matches_oracle)."""
    import dataclasses

    import jax.numpy as jnp

    from spark_scheduler_tpu.ops import BINPACK_FUNCTIONS

    rng = np.random.default_rng(9)
    c = random_cluster(rng, 40)
    n = np.asarray(c.available).shape[0]
    b = 10
    driver = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
    driver[:, 2] = rng.integers(0, 2, size=b)
    execs = rng.integers(1, 8, size=(b, 3)).astype(np.int32)
    execs[:, 2] = rng.integers(0, 2, size=b)
    counts = rng.integers(0, EMAX + 1, size=b).astype(np.int32)
    skip = rng.random(b) < 0.3
    dcand, dom = random_masks(rng, b, n)
    apps = make_app_batch(
        driver, execs, counts, skippable=skip, driver_cand=dcand, domain=dom,
        pad_to=16,
    )
    got = batched_fifo_pack(c, apps, fill=strategy, emax=EMAX, num_zones=NUM_ZONES)

    avail = np.asarray(c.available).copy()
    sched = np.asarray(c.schedulable).astype(np.int64)
    zone = np.asarray(c.zone_id)
    blocked = False
    for i in range(b):
        ci = dataclasses.replace(c, available=jnp.asarray(avail))
        p = BINPACK_FUNCTIONS[strategy](
            ci,
            jnp.asarray(apps.driver_req[i]),
            jnp.asarray(apps.exec_req[i]),
            jnp.int32(int(apps.exec_count[i])),
            jnp.asarray(apps.driver_cand[i]),
            jnp.asarray(apps.domain[i]),
            emax=EMAX,
            num_zones=NUM_ZONES,
        )
        packed = bool(p.has_capacity)
        admitted = packed and not blocked
        assert bool(got.packed[i]) == packed, f"app {i} packed"
        assert bool(got.admitted[i]) == admitted, f"app {i} admitted"
        drv = int(got.driver_node[i])
        got_execs = [int(x) for x in np.asarray(got.executor_nodes[i]) if x >= 0]
        if admitted:
            want_drv = int(p.driver_node)
            want_execs = [int(x) for x in np.asarray(p.executor_nodes) if x >= 0]
            if (drv, got_execs) != (want_drv, want_execs):
                # Different zone on a float32 efficiency tie: both picks must
                # score within tolerance.
                inner = _AZ_INNER[strategy]
                incl = inner != "minimal-fragmentation"
                eff_got = G.greedy_avg_efficiency(
                    avail.astype(np.int64), sched, drv, got_execs,
                    np.asarray(apps.driver_req[i], np.int64),
                    np.asarray(apps.exec_req[i], np.int64),
                    include_executors_in_reserved=incl,
                )
                eff_want = G.greedy_avg_efficiency(
                    avail.astype(np.int64), sched, want_drv, want_execs,
                    np.asarray(apps.driver_req[i], np.int64),
                    np.asarray(apps.exec_req[i], np.int64),
                    include_executors_in_reserved=incl,
                )
                assert abs(eff_got - eff_want) < 1e-5, (
                    f"app {i}: {(drv, got_execs)} vs {(want_drv, want_execs)}"
                )
            avail[drv] -= np.asarray(apps.driver_req[i])
            for nd in got_execs:
                avail[nd] -= np.asarray(apps.exec_req[i])
        if bool(apps.app_valid[i]) and not packed and not bool(apps.skippable[i]):
            blocked = True


def test_masked_sharded_matches_unsharded():
    """Per-step sorts + masks must survive GSPMD node-axis sharding."""
    rng = np.random.default_rng(17)
    c = random_cluster(rng, 64)
    n = np.asarray(c.available).shape[0]
    b = 6
    driver = rng.integers(1, 5, size=(b, 3)).astype(np.int32)
    execs = rng.integers(1, 6, size=(b, 3)).astype(np.int32)
    counts = rng.integers(1, 9, size=b).astype(np.int32)
    dcand, dom = random_masks(rng, b, n)
    apps = make_app_batch(driver, execs, counts, driver_cand=dcand, domain=dom)
    mesh = make_solver_mesh()
    want = batched_fifo_pack(c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES)
    got = sharded_fifo_pack(mesh, c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES)
    np.testing.assert_array_equal(np.asarray(got.driver_node), np.asarray(want.driver_node))
    np.testing.assert_array_equal(
        np.asarray(got.executor_nodes), np.asarray(want.executor_nodes)
    )
    np.testing.assert_array_equal(np.asarray(got.admitted), np.asarray(want.admitted))


def test_strict_fifo_blocking():
    rng = np.random.default_rng(7)
    c = random_cluster(rng, 20)
    # App 1 requests an impossible gang and is NOT skippable: apps 2.. must
    # be rejected even though they'd fit (resource.go:241-249).
    driver = np.ones((3, 3), np.int32)
    execs = np.ones((3, 3), np.int32)
    counts = np.array([1, 10**6, 1], np.int32)
    counts = np.minimum(counts, EMAX)
    execs[1] = 10**6  # impossible request instead
    apps = make_app_batch(driver, execs, counts, skippable=[False, False, False])
    got = batched_fifo_pack(c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES)
    assert bool(got.admitted[0])
    assert not bool(got.admitted[1])
    assert not bool(got.admitted[2])
    assert bool(got.packed[2])  # would fit; blocked only by FIFO

    # Same queue but app 1 skippable: app 2 goes through (resource.go:260-270).
    apps2 = make_app_batch(driver, execs, counts, skippable=[False, True, False])
    got2 = batched_fifo_pack(c, apps2, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES)
    assert bool(got2.admitted[2])


def _random_window_batch(rng, c, n_segments, pad_to=None):
    """A segmented WINDOW batch: each segment is 1-3 rows (hypothetical
    prefix + committing request row), with per-row candidate/domain
    masks — the shape core/solver.py pack_window dispatches."""
    n = c.available.shape[0]
    drv, exc, counts, skip, cand, dom, commit, reset = (
        [], [], [], [], [], [], [], [],
    )
    for _ in range(n_segments):
        seg_rows = int(rng.integers(1, 4))
        cand_mask = rng.random(n) < 0.8
        dom_mask = rng.random(n) < 0.9
        for j in range(seg_rows):
            d = rng.integers(1, 5, size=3).astype(np.int32)
            e = rng.integers(1, 5, size=3).astype(np.int32)
            d[2] = e[2] = 0
            drv.append(d)
            exc.append(e)
            counts.append(int(rng.integers(1, 5)))
            skip.append(bool(rng.random() < 0.4))
            cand.append(cand_mask)
            dom.append(dom_mask)
            commit.append(j == seg_rows - 1)
            reset.append(j == 0)
    return make_app_batch(
        np.stack(drv), np.stack(exc), np.asarray(counts, np.int32),
        pad_to=pad_to, skippable=skip,
        driver_cand=np.stack(cand), domain=np.stack(dom),
        commit=commit, reset=reset,
    )


def test_fuse_app_batches_matches_sequential_carry():
    """The fused multi-window identity at the ops layer: ONE scan over
    fuse_app_batches(K windows) == K sequential batched_fifo_pack calls
    with available_after threaded between them, row for row — including
    when the input batches carry padding rows that fusing must strip."""
    import dataclasses

    from spark_scheduler_tpu.ops.batched import fuse_app_batches

    rng = np.random.default_rng(21)
    c = random_cluster(rng, 24)
    batches = [
        _random_window_batch(rng, c, 3, pad_to=None),
        _random_window_batch(rng, c, 2, pad_to=9),  # padding rows stripped
        _random_window_batch(rng, c, 4, pad_to=None),
    ]

    # Sequential: thread the committed base across the K windows.
    cur = c
    seq = []
    for b in batches:
        out = batched_fifo_pack(
            cur, b, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
        )
        valid = np.asarray(b.app_valid)
        for i in np.flatnonzero(valid):
            seq.append(
                (
                    int(out.driver_node[i]),
                    [int(x) for x in np.asarray(out.executor_nodes[i])],
                    bool(out.admitted[i]),
                    bool(out.packed[i]),
                )
            )
        cur = dataclasses.replace(cur, available=out.available_after)
    seq_after = np.asarray(cur.available)

    fused = fuse_app_batches(batches)
    out = batched_fifo_pack(
        c, fused, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
    )
    got = [
        (
            int(out.driver_node[i]),
            [int(x) for x in np.asarray(out.executor_nodes[i])],
            bool(out.admitted[i]),
            bool(out.packed[i]),
        )
        for i in np.flatnonzero(np.asarray(fused.app_valid))
    ]
    assert got == seq
    np.testing.assert_array_equal(
        np.asarray(out.available_after), seq_after
    )


def test_fuse_app_batches_requires_segmented():
    from spark_scheduler_tpu.ops.batched import fuse_app_batches

    rng = np.random.default_rng(2)
    plain = random_apps(rng, 3)
    with pytest.raises(ValueError, match="segmented"):
        fuse_app_batches([plain])


def test_sharded_matches_unsharded():
    rng = np.random.default_rng(3)
    c = random_cluster(rng, 64)  # divisible by the 8-device "nodes" axis
    apps = random_apps(rng, 8)
    mesh = make_solver_mesh()  # all devices on "nodes"
    want = batched_fifo_pack(c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES)
    got = sharded_fifo_pack(mesh, c, apps, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES)
    np.testing.assert_array_equal(np.asarray(got.driver_node), np.asarray(want.driver_node))
    np.testing.assert_array_equal(
        np.asarray(got.executor_nodes), np.asarray(want.executor_nodes)
    )
    np.testing.assert_array_equal(np.asarray(got.admitted), np.asarray(want.admitted))
    np.testing.assert_array_equal(
        np.asarray(got.available_after), np.asarray(want.available_after)
    )


def test_grouped_2d_parallel_matches_per_group():
    rng = np.random.default_rng(11)
    clusters = [random_cluster(rng, 32) for _ in range(4)]
    batches = [random_apps(rng, 6, pad_to=8) for _ in range(4)]
    mesh = make_solver_mesh(n_groups=2, n_nodes_shards=4)
    stacked_c, stacked_a = stack_groups(clusters, batches)
    got = grouped_fifo_pack(
        mesh, stacked_c, stacked_a, fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
    )
    for gi in range(4):
        want = batched_fifo_pack(
            clusters[gi], batches[gi], fill="tightly-pack", emax=EMAX, num_zones=NUM_ZONES
        )
        np.testing.assert_array_equal(
            np.asarray(got.driver_node[gi]), np.asarray(want.driver_node)
        )
        np.testing.assert_array_equal(
            np.asarray(got.executor_nodes[gi]), np.asarray(want.executor_nodes)
        )
        np.testing.assert_array_equal(
            np.asarray(got.admitted[gi]), np.asarray(want.admitted)
        )


def test_grouped_pallas_sharded_matches_per_group():
    """The multi-chip Mosaic path (VERDICT r3 #5): groups sharded across
    the full 8-device mesh with the Pallas queue kernel running per device
    under shard_map (interpret mode on the CPU mesh) must equal the
    unsharded XLA scan group-for-group."""
    from spark_scheduler_tpu.parallel.solve import _grouped_pallas_sharded

    rng = np.random.default_rng(17)
    n_dev = 8
    clusters = [random_cluster(rng, 24) for _ in range(2 * n_dev)]
    batches = [random_apps(rng, 4, pad_to=4) for _ in range(2 * n_dev)]
    mesh = make_solver_mesh(n_groups=n_dev, n_nodes_shards=1)
    stacked_c, stacked_a = stack_groups(clusters, batches)
    got = _grouped_pallas_sharded(
        mesh, stacked_c, stacked_a, fill="tightly-pack", emax=EMAX,
        num_zones=NUM_ZONES, interpret=True,
    )
    for gi in range(2 * n_dev):
        want = batched_fifo_pack(
            clusters[gi], batches[gi], fill="tightly-pack", emax=EMAX,
            num_zones=NUM_ZONES,
        )
        for field in ("driver_node", "executor_nodes", "admitted", "packed",
                      "available_after"):
            np.testing.assert_array_equal(
                np.asarray(getattr(got, field)[gi]),
                np.asarray(getattr(want, field)),
                err_msg=f"group {gi} {field}",
            )
