"""End-to-end over the wire: state-sync a cluster, then gang-schedule a Spark
app through POST /predicates with real k8s-shaped ExtenderArgs JSON.
"""

import json
import urllib.request

import pytest

from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import SchedulerHTTPServer
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend

INSTANCE_GROUP_LABEL = "resource_channel"
GROUP = "batch-medium-priority"


def _k8s_node(name, zone="zone1"):
    return {
        "metadata": {
            "name": name,
            "labels": {
                "failure-domain.beta.kubernetes.io/zone": zone,
                INSTANCE_GROUP_LABEL: GROUP,
            },
        },
        "status": {
            "allocatable": {"cpu": "8", "memory": "8Gi", "nvidia.com/gpu": "1"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def _k8s_spark_pod(app_id, role, name, executors=2):
    annotations = {
        "spark-driver-cpu": "1",
        "spark-driver-mem": "1Gi",
        "spark-executor-cpu": "1",
        "spark-executor-mem": "1Gi",
        "spark-executor-count": str(executors),
    }
    return {
        "metadata": {
            "name": name,
            "namespace": "ns",
            "uid": f"uid-{name}",
            "labels": {"spark-role": role, "spark-app-id": app_id},
            "annotations": annotations,
            "creationTimestamp": "2026-07-29T12:00:00Z",
        },
        "spec": {
            "schedulerName": "spark-scheduler",
            "nodeSelector": {INSTANCE_GROUP_LABEL: GROUP},
            "containers": [
                {
                    "name": "main",
                    "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
                }
            ],
        },
        "status": {"phase": "Pending"},
    }


def _request(port, method, path, payload=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode() if payload is not None else None,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


@pytest.fixture()
def server():
    backend = InMemoryBackend()
    backend.register_crd(DEMAND_CRD)
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True,
            binpack_algo="single-az-tightly-pack",
            instance_group_label=INSTANCE_GROUP_LABEL,
            sync_writes=True,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    srv = SchedulerHTTPServer(app, registry, port=0)  # ephemeral port
    srv.start()
    yield srv
    srv.stop()


def test_gang_schedule_over_http(server):
    port = server.port
    status, body = _request(port, "GET", "/status/liveness")
    assert status == 200 and body["status"] == "up"
    # Not ready until cluster state has been synced (no nodes known yet);
    # gating kube-scheduler traffic on this avoids spurious failure-fit
    # demands against an empty cluster.
    status, body = _request(port, "GET", "/status/readiness")
    assert status == 503 and body["ready"] is False

    for i in range(4):
        status, _ = _request(port, "PUT", "/state/nodes", _k8s_node(f"n{i}"))
        assert status == 200

    status, body = _request(port, "GET", "/status/readiness")
    assert status == 200 and body["ready"] is True

    node_names = [f"n{i}" for i in range(4)]

    # Driver: gang admission over the extender protocol.
    driver = _k8s_spark_pod("app-http", "driver", "app-http-driver")
    _request(port, "PUT", "/state/pods", driver)
    status, result = _request(
        port, "POST", "/predicates", {"Pod": driver, "NodeNames": node_names}
    )
    assert status == 200
    assert result["NodeNames"], f"driver rejected: {result}"
    driver_node = result["NodeNames"][0]
    assert driver_node in node_names and not result["FailedNodes"]

    # Simulate the bind, then schedule both executors onto reserved slots.
    driver["spec"]["nodeName"] = driver_node
    driver["status"]["phase"] = "Running"
    _request(port, "PUT", "/state/pods", driver)
    for i in range(2):
        ex = _k8s_spark_pod("app-http", "executor", f"app-http-exec-{i}")
        _request(port, "PUT", "/state/pods", ex)
        status, result = _request(
            port, "POST", "/predicates", {"Pod": ex, "NodeNames": node_names}
        )
        assert status == 200 and result["NodeNames"], f"executor rejected: {result}"
        ex["spec"]["nodeName"] = result["NodeNames"][0]
        _request(port, "PUT", "/state/pods", ex)

    # An app too large for the cluster fails every node with failure-fit.
    big = _k8s_spark_pod("app-big", "driver", "app-big-driver", executors=100)
    _request(port, "PUT", "/state/pods", big)
    status, result = _request(
        port, "POST", "/predicates", {"Pod": big, "NodeNames": node_names}
    )
    assert status == 200 and not result["NodeNames"]
    assert set(result["FailedNodes"]) == set(node_names)

    # Metrics flowed.
    status, snap = _request(port, "GET", "/metrics")
    assert status == 200
    assert "foundry.spark.scheduler.requests" in snap


def test_non_spark_pod_rejected(server):
    port = server.port
    _request(port, "PUT", "/state/nodes", _k8s_node("n0"))
    pod = {
        "metadata": {"name": "web", "namespace": "ns", "labels": {}},
        "spec": {"containers": []},
    }
    status, result = _request(
        port, "POST", "/predicates", {"Pod": pod, "NodeNames": ["n0"]}
    )
    assert status == 200 and not result["NodeNames"]
