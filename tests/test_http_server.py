"""End-to-end over the wire: state-sync a cluster, then gang-schedule a Spark
app through POST /predicates with real k8s-shaped ExtenderArgs JSON.
"""

import json
import urllib.request

import pytest

from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import SchedulerHTTPServer
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend

INSTANCE_GROUP_LABEL = "resource_channel"
GROUP = "batch-medium-priority"


def _k8s_node(name, zone="zone1"):
    return {
        "metadata": {
            "name": name,
            "labels": {
                "failure-domain.beta.kubernetes.io/zone": zone,
                INSTANCE_GROUP_LABEL: GROUP,
            },
        },
        "status": {
            "allocatable": {"cpu": "8", "memory": "8Gi", "nvidia.com/gpu": "1"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def _k8s_spark_pod(app_id, role, name, executors=2):
    annotations = {
        "spark-driver-cpu": "1",
        "spark-driver-mem": "1Gi",
        "spark-executor-cpu": "1",
        "spark-executor-mem": "1Gi",
        "spark-executor-count": str(executors),
    }
    return {
        "metadata": {
            "name": name,
            "namespace": "ns",
            "uid": f"uid-{name}",
            "labels": {"spark-role": role, "spark-app-id": app_id},
            "annotations": annotations,
            "creationTimestamp": "2026-07-29T12:00:00Z",
        },
        "spec": {
            "schedulerName": "spark-scheduler",
            "nodeSelector": {INSTANCE_GROUP_LABEL: GROUP},
            "containers": [
                {
                    "name": "main",
                    "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
                }
            ],
        },
        "status": {"phase": "Pending"},
    }


def _request(port, method, path, payload=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode() if payload is not None else None,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


@pytest.fixture(params=["threaded", "async"])
def server(request):
    # The full scenario matrix runs against BOTH transports: the stdlib
    # thread-per-connection stack and the async event loop must be
    # byte-compatible on every route and framing edge.
    backend = InMemoryBackend()
    backend.register_crd(DEMAND_CRD)
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True,
            binpack_algo="single-az-tightly-pack",
            instance_group_label=INSTANCE_GROUP_LABEL,
            sync_writes=True,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    srv = SchedulerHTTPServer(
        app, registry, port=0, transport=request.param
    )  # ephemeral port
    srv.start()
    yield srv
    srv.stop()


def test_gang_schedule_over_http(server):
    port = server.port
    status, body = _request(port, "GET", "/status/liveness")
    assert status == 200 and body["status"] == "up"
    # Not ready until cluster state has been synced (no nodes known yet);
    # gating kube-scheduler traffic on this avoids spurious failure-fit
    # demands against an empty cluster.
    status, body = _request(port, "GET", "/status/readiness")
    assert status == 503 and body["ready"] is False

    for i in range(4):
        status, _ = _request(port, "PUT", "/state/nodes", _k8s_node(f"n{i}"))
        assert status == 200

    status, body = _request(port, "GET", "/status/readiness")
    assert status == 200 and body["ready"] is True

    node_names = [f"n{i}" for i in range(4)]

    # Driver: gang admission over the extender protocol.
    driver = _k8s_spark_pod("app-http", "driver", "app-http-driver")
    _request(port, "PUT", "/state/pods", driver)
    status, result = _request(
        port, "POST", "/predicates", {"Pod": driver, "NodeNames": node_names}
    )
    assert status == 200
    assert result["NodeNames"], f"driver rejected: {result}"
    driver_node = result["NodeNames"][0]
    assert driver_node in node_names and not result["FailedNodes"]

    # Simulate the bind, then schedule both executors onto reserved slots.
    driver["spec"]["nodeName"] = driver_node
    driver["status"]["phase"] = "Running"
    _request(port, "PUT", "/state/pods", driver)
    for i in range(2):
        ex = _k8s_spark_pod("app-http", "executor", f"app-http-exec-{i}")
        _request(port, "PUT", "/state/pods", ex)
        status, result = _request(
            port, "POST", "/predicates", {"Pod": ex, "NodeNames": node_names}
        )
        assert status == 200 and result["NodeNames"], f"executor rejected: {result}"
        ex["spec"]["nodeName"] = result["NodeNames"][0]
        _request(port, "PUT", "/state/pods", ex)

    # An app too large for the cluster fails every node with failure-fit.
    big = _k8s_spark_pod("app-big", "driver", "app-big-driver", executors=100)
    _request(port, "PUT", "/state/pods", big)
    status, result = _request(
        port, "POST", "/predicates", {"Pod": big, "NodeNames": node_names}
    )
    assert status == 200 and not result["NodeNames"]
    assert set(result["FailedNodes"]) == set(node_names)

    # Metrics flowed.
    status, snap = _request(port, "GET", "/metrics")
    assert status == 200
    assert "foundry.spark.scheduler.requests" in snap


def test_non_spark_pod_rejected(server):
    port = server.port
    _request(port, "PUT", "/state/nodes", _k8s_node("n0"))
    pod = {
        "metadata": {"name": "web", "namespace": "ns", "labels": {}},
        "spec": {"containers": []},
    }
    status, result = _request(
        port, "POST", "/predicates", {"Pod": pod, "NodeNames": ["n0"]}
    )
    assert status == 200 and not result["NodeNames"]


def _raw_exchange(port, request_bytes, timeout=5.0):
    """Send raw bytes, read until the server closes or the timeout fires.
    Returns (response_bytes, closed_cleanly)."""
    import socket

    s = socket.create_connection(("127.0.0.1", port))
    s.sendall(request_bytes)
    s.settimeout(timeout)
    resp, closed = b"", False
    try:
        while True:
            chunk = s.recv(4096)
            if not chunk:
                closed = True
                break
            resp += chunk
    except socket.timeout:
        pass
    s.close()
    return resp, closed


def test_chunked_transfer_encoding_rejected_and_connection_closed(server):
    """No chunked decoder: a Transfer-Encoding body must be answered with an
    explicit error (never a confidently wrong success computed from an empty
    body), the response must advertise Connection: close, and the socket must
    close so the unread chunk bytes can't desync a keep-alive follow-up."""
    port = server.port
    payload = b'{"Pod": {}, "NodeNames": ["n0"]}'
    req = (
        b"POST /predicates HTTP/1.1\r\nHost: x\r\n"
        b"Transfer-Encoding: chunked\r\nContent-Type: application/json\r\n\r\n"
        + hex(len(payload))[2:].encode() + b"\r\n" + payload + b"\r\n0\r\n\r\n"
    )
    resp, closed = _raw_exchange(port, req)
    first_line = resp.split(b"\r\n", 1)[0]
    assert first_line.startswith(b"HTTP/1.1 5") or first_line.startswith(
        b"HTTP/1.1 4"
    ), resp[:200]
    assert resp.count(b"HTTP/1.1") == 1  # exactly one response, no desync
    assert b"Transfer-Encoding not supported" in resp
    assert b"Connection: close" in resp
    assert closed

    # The server is still healthy for the next (fresh) connection.
    status, body = _request(port, "GET", "/status/liveness")
    assert status == 200 and body["status"] == "up"


def test_transfer_encoding_on_no_body_route_answers_fast(server):
    """A TE request to a route that never reads the body (404) must not block
    on a lying Content-Length; it gets its error response, then close."""
    import time

    t0 = time.monotonic()
    resp, closed = _raw_exchange(
        server.port,
        b"POST /nope HTTP/1.1\r\nHost: x\r\nTransfer-Encoding: chunked\r\n"
        b"Content-Length: 1000000\r\n\r\n2\r\n{}\r\n0\r\n\r\n",
        timeout=10.0,
    )
    assert resp.split(b"\r\n", 1)[0] == b"HTTP/1.1 404 Not Found"
    assert b"Connection: close" in resp and closed
    assert time.monotonic() - t0 < 5.0  # bounded drain, not a 30s stall


def test_garbage_content_length_rejected_and_closed(server):
    """Negative / non-numeric / mismatched-duplicate Content-Length cannot
    frame a body — the server answers 400 (not a success fabricated from an
    empty body, not a read(-1) to EOF) and closes the connection."""
    for headers in (
        b"Content-Length: -1\r\n",
        b"Content-Length: abc\r\n",
        # RFC 7230 3.3.2: differing duplicates must be rejected, else the
        # unread tail desyncs the next keep-alive request (smuggling).
        b"Content-Length: 4\r\nContent-Length: 28\r\n",
    ):
        # A real body rides along unread — the post-response drain must
        # consume it so close() sends FIN, not RST.
        resp, closed = _raw_exchange(
            server.port,
            b"POST /predicates HTTP/1.1\r\nHost: x\r\n" + headers
            + b"\r\n" + b'{"Pod": {}, "NodeNames": []}',
        )
        assert resp.split(b"\r\n", 1)[0] == b"HTTP/1.1 400 Bad Request", (
            headers, resp[:200])
        assert resp.count(b"HTTP/1.1") == 1, (headers, resp[:200])
        assert b"Connection: close" in resp and closed

    # Duplicate but IDENTICAL Content-Length values frame fine.
    body = b'{"Pod": {}, "NodeNames": []}'
    resp, _ = _raw_exchange(
        server.port,
        b"POST /predicates HTTP/1.1\r\nHost: x\r\nConnection: close\r\n"
        b"Content-Length: " + str(len(body)).encode() + b"\r\n"
        b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body,
    )
    assert resp.split(b"\r\n", 1)[0] == b"HTTP/1.1 200 OK", resp[:200]


def test_request_log_emits_structured_lines(server):
    """The req2log slot: with request-log enabled, every HTTP call emits one
    request.2 line with method, path, status, duration, and the caller's b3
    trace id."""
    import io
    import time

    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log, svc1log

    def _lines(stream):
        return [
            json.loads(l)
            for l in stream.getvalue().splitlines()
            if '"request.2"' in l
        ]

    stream = io.StringIO()
    old_logger = svc1log()
    set_svc1log(Svc1Logger(stream=stream))
    # Flip the flag on the RUNNING server (works on either transport).
    server.set_request_log(True)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/status/liveness",
            headers={"X-B3-TraceId": "abc123def456"},
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
        _request(server.port, "GET", "/nope")
        # Both transports emit the line AFTER writing the response bytes,
        # so the client can observe the response a beat before the log
        # lands — wait for it before swapping the logger back.
        deadline = time.monotonic() + 5.0
        while len(_lines(stream)) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        server.set_request_log(False)
        set_svc1log(old_logger)
    lines = _lines(stream)
    assert len(lines) == 2, stream.getvalue()
    live, missing = lines
    assert live["method"] == "GET" and live["path"] == "/status/liveness"
    assert live["status"] == 200 and live["duration"] >= 0
    assert live["traceId"] == "abc123def456"
    assert missing["status"] == 404 and missing["path"] == "/nope"
