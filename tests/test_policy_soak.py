"""Priority/preemption soak (ISSUE 16 satellite): sustained high-priority
pressure through the REAL policy-enabled extender must show preemption
working, bounded low-priority waits (age promotion), an untouchable
protected gang, and zero over-commit. CI runs the same scenario shortened
via `testing/soak.py policy` (see .github/workflows/ci.yml)."""

from spark_scheduler_tpu.testing.soak import PolicySoak


def test_policy_soak_no_starvation_and_protected_system_gang():
    soak = PolicySoak(n_low=3, n_nodes=3, promote_after_s=120.0, step_s=30.0)
    v = soak.run(steps=30)

    # Preemption actually fired, and never against the protected gang.
    assert v["evictions"] >= 1
    assert len(v["preemptions"]) >= 1
    for pre in v["preemptions"]:
        assert "system-app" not in pre["evicted"]
    assert v["system_rr_lost"] is False

    # High-priority pressure was real: some highs were denied once the
    # low gangs aged into the promotion cap and stopped being evictable.
    assert v["denied_high"] >= 1

    # No starvation: every low gang ends admitted, within the promotion
    # bound — 2 intervals promote "low" to the cap, plus scheduling slack.
    bound = 2 * soak.promote_after_s + 2 * soak.step_s
    for low_id, wait in v["low_waits_s"].items():
        assert wait is not None, f"{low_id} starved"
        assert wait <= bound, f"{low_id} waited {wait}s > {bound}s"

    # The over-commit invariant held at every step.
    assert v["overcommit"] == []

    # Decision records carry the full eviction audit trail.
    pre = v["preemptions"][0]
    assert pre["candidates"] >= 1 and pre["cost"] >= 1
    assert pre["search_ms"] >= 0.0
