"""Incremental overhead aggregates == the reference's per-query walk.

The OverheadComputer maintains per-node overhead via membership deltas
(pod/RR/soft events) instead of walking pods per query (overhead.go:120-168);
these tests prove the aggregates exact against the oracle walk through the
full scheduling lifecycle, including non-spark pods and unreserved pods of
other schedulers.
"""

from __future__ import annotations

from spark_scheduler_tpu.models.kube import Container, Pod
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    static_allocation_spark_pods,
)


def assert_overhead_consistent(h: Harness):
    oc = h.app.overhead_computer
    nodes = h.backend.list_nodes()
    inc = oc.get_overhead(nodes)
    inc_ns = oc.get_non_schedulable_overhead(nodes)
    for n in nodes:
        want, want_ns = oc.compute_node_overhead_oracle(n.name)
        got = inc.get(n.name, Resources.zero())
        got_ns = inc_ns.get(n.name, Resources.zero())
        assert got.as_tuple() == want.as_tuple(), f"overhead mismatch on {n.name}"
        assert got_ns.as_tuple() == want_ns.as_tuple(), (
            f"non-schedulable overhead mismatch on {n.name}"
        )


def other_scheduler_pod(name: str, node: str, cpu="2", mem="2Gi") -> Pod:
    return Pod(
        name=name,
        namespace="kube-system",
        node_name=node,
        phase="Running",
        scheduler_name="default-scheduler",
        containers=[Container(requests=Resources.from_quantities(cpu, mem))],
    )


def test_overhead_tracks_scheduling_lifecycle():
    h = Harness()
    h.add_nodes(*[new_node(f"n{i}") for i in range(5)])
    names = [f"n{i}" for i in range(5)]

    # Foreign pods (other scheduler, no reservations) are pure overhead.
    h.backend.add_pod(other_scheduler_pod("daemon-1", "n0"))
    h.backend.add_pod(other_scheduler_pod("daemon-2", "n3", cpu="1", mem="512Mi"))
    assert_overhead_consistent(h)

    # Spark pods gain reservations on admission -> leave overhead.
    pods = static_allocation_spark_pods("app-1", 3)
    assert all(r.ok for r in h.schedule_app(pods, names))
    assert_overhead_consistent(h)

    # Dynamic allocation: extras ride soft reservations (still reserved).
    dpods = dynamic_allocation_spark_pods("app-2", 1, 3)
    assert all(r.ok for r in h.schedule_app(dpods, names))
    assert_overhead_consistent(h)

    # Executor death + deletion: compaction moves soft->hard; pod leaves state.
    h.terminate_pod(pods[2])
    h.delete_pod(pods[2])
    assert_overhead_consistent(h)

    # Foreign pod deletion retracts its contribution.
    h.backend.delete("pods", "kube-system", "daemon-1")
    assert_overhead_consistent(h)


def test_overhead_counts_unreserved_spark_pod():
    """A spark pod bound WITHOUT a reservation (e.g. placed by another
    scheduler path) is overhead until a reservation appears."""
    h = Harness()
    h.add_nodes(new_node("n0"), new_node("n1"))
    pods = static_allocation_spark_pods("app-x", 1)
    driver = pods[0]
    # bind the driver directly, bypassing admission: no reservation exists
    h.backend.add_pod(driver)
    h.backend.bind_pod(driver, "n0")
    assert_overhead_consistent(h)
    oc = h.app.overhead_computer
    got = oc.get_overhead(h.backend.list_nodes()).get("n0")
    assert got is not None and got.cpu_milli > 0


def test_overhead_recomputes_are_delta_scoped():
    """Scheduling N apps must not trigger O(cluster) recomputes per request:
    recompute count stays linear in events, not apps x pods."""
    h = Harness()
    h.add_nodes(*[new_node(f"n{i}") for i in range(8)])
    names = [f"n{i}" for i in range(8)]
    oc = h.app.overhead_computer

    before = oc.recomputes
    pods = static_allocation_spark_pods("app-solo", 2)
    assert all(r.ok for r in h.schedule_app(pods, names))
    per_app = oc.recomputes - before

    before = oc.recomputes
    for i in range(4):
        extra = static_allocation_spark_pods(f"app-{i}", 2)
        assert all(r.ok for r in h.schedule_app(extra, names))
    # Each additional app costs about the same number of recomputes as the
    # first (its own pods' events), not an amount growing with cluster size.
    assert oc.recomputes - before <= 4 * (per_app + 4)
