"""Golden parity: the segmented-window Pallas path (ops/pallas_window) must
match the segmented XLA scan (ops/batched window mode) decision-for-decision
— same drivers, same executor slot sequences, same admitted/packed flags,
same committed base. The XLA scan is itself pinned to the greedy oracle, so
transitively the Mosaic path carries reference semantics
(resource.go:221-258 + binpack fills).

Runs the Pallas interpreter on CPU (tests/conftest.py pins jax to cpu); the
on-silicon equivalence runs inside every bench invocation
(hack/tpu_parity_smoke.py)."""

import numpy as np
import jax.numpy as jnp
import pytest

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch
from spark_scheduler_tpu.ops.pallas_window import (
    SegmentedWindow,
    make_segmented_window,
    window_pack_pallas,
)

FILLS = ("tightly-pack", "distribute-evenly", "minimal-fragmentation")
# All six (r5): the single-AZ wrappers run in-kernel on the window path too.
STRATEGIES = FILLS + (
    "single-az-tightly-pack",
    "single-az-minimal-fragmentation",
    "az-aware-tightly-pack",
)


def _cluster(rng, n, num_zones=4):
    avail = rng.integers(0, 24, size=(n, 3)).astype(np.int32)
    avail[:, 2] = rng.integers(0, 3, size=n)
    return ClusterTensors(
        available=jnp.asarray(avail),
        schedulable=jnp.asarray(avail.copy()),
        zone_id=jnp.asarray(rng.integers(0, num_zones, size=n), jnp.int32),
        name_rank=jnp.asarray(rng.permutation(n), jnp.int32),
        label_rank_driver=jnp.full(n, INT32_INF, jnp.int32),
        label_rank_executor=jnp.full(n, INT32_INF, jnp.int32),
        unschedulable=jnp.asarray(rng.random(n) < 0.1),
        ready=jnp.asarray(rng.random(n) > 0.05),
        valid=jnp.ones(n, bool),
    )


def _random_window(rng, n, n_requests, max_rows, emax):
    """Random segmented window: per-request FIFO rows + masks. Returns
    (xla AppBatch args, pallas SegmentedWindow, flat row map)."""
    requests = []
    cands, doms = [], []
    for _ in range(n_requests):
        rows = []
        for _ in range(rng.integers(1, max_rows + 1)):
            dr = rng.integers(0, 5, size=3).astype(np.int32)
            er = rng.integers(1, 4, size=3).astype(np.int32)
            dr[2] = 0
            er[2] = rng.integers(0, 2)
            cnt = int(rng.integers(0, emax + 1))
            rows.append((dr, er, cnt, bool(rng.random() < 0.3)))
        requests.append(rows)
        cands.append(rng.random(n) < (0.95 if rng.random() < 0.7 else 0.4))
        doms.append(rng.random(n) < (1.0 if rng.random() < 0.6 else 0.6))
    # Flat (XLA) layout
    flat = [row for rows in requests for row in rows]
    commit, reset, cand_rows, dom_rows = [], [], [], []
    for i, rows in enumerate(requests):
        for j in range(len(rows)):
            commit.append(j == len(rows) - 1)
            reset.append(j == 0)
            cand_rows.append(cands[i])
            dom_rows.append(doms[i])
    apps = make_app_batch(
        np.stack([r[0] for r in flat]),
        np.stack([r[1] for r in flat]),
        np.asarray([r[2] for r in flat], np.int32),
        skippable=[r[3] for r in flat],
        driver_cand=np.stack(cand_rows),
        domain=np.stack(dom_rows),
        commit=commit,
        reset=reset,
    )
    win = make_segmented_window(requests, cands, doms)
    flat_map = [
        (s, j) for s, rows in enumerate(requests) for j in range(len(rows))
    ]
    return apps, win, flat_map


@pytest.mark.parametrize("fill", STRATEGIES)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_window_pallas_matches_xla_scan(fill, seed):
    rng = np.random.default_rng(seed * 7 + 3)
    n, emax = 24, 8
    cluster = _cluster(rng, n)
    apps, win, flat_map = _random_window(
        rng, n, n_requests=5, max_rows=4, emax=emax
    )
    ref = batched_fifo_pack(cluster, apps, fill=fill, emax=emax, num_zones=4)
    meta, execs, base_after = window_pack_pallas(
        cluster, win, fill=fill, emax=emax, num_zones=4, interpret=True
    )
    meta = np.asarray(meta)
    execs = np.asarray(execs)
    ref_drivers = np.asarray(ref.driver_node)
    ref_execs = np.asarray(ref.executor_nodes)
    ref_admitted = np.asarray(ref.admitted)
    ref_packed = np.asarray(ref.packed)
    for bi, (s, j) in enumerate(flat_map):
        assert meta[s, j, 1] == ref_admitted[bi], (fill, seed, bi, "admitted")
        assert meta[s, j, 2] == ref_packed[bi], (fill, seed, bi, "packed")
        assert meta[s, j, 0] == ref_drivers[bi], (fill, seed, bi, "driver")
        np.testing.assert_array_equal(
            execs[s, j], ref_execs[bi], err_msg=f"{fill} seed={seed} row={bi}"
        )
    np.testing.assert_array_equal(
        np.asarray(base_after),
        np.asarray(ref.available_after),
        err_msg=f"{fill} seed={seed} base",
    )


def test_window_pallas_strict_fifo_blocking_is_segment_local():
    """A non-skippable failure blocks LATER rows of its own segment only;
    the next segment starts unblocked (each request's solo solve starts
    fresh)."""
    rng = np.random.default_rng(11)
    n, emax = 16, 8
    cluster = _cluster(rng, n)
    big = (np.full(3, 500, np.int32), np.ones(3, np.int32), 4, False)
    small = (np.ones(3, np.int32), np.ones(3, np.int32), 2, False)
    requests = [[big, small], [small]]
    cands = [np.ones(n, bool)] * 2
    doms = [np.ones(n, bool)] * 2
    win = make_segmented_window(requests, cands, doms)
    meta, _, _ = window_pack_pallas(
        cluster, win, fill="tightly-pack", emax=emax, num_zones=4,
        interpret=True,
    )
    meta = np.asarray(meta)
    assert meta[0, 0, 2] == 0  # big does not pack
    assert meta[0, 1, 1] == 0  # same-segment follower is FIFO-blocked
    assert meta[1, 0, 1] == 1  # next segment starts unblocked


def test_window_pallas_commit_rows_thread_the_base():
    """Only COMMIT rows persist into the base: two identical segments on a
    one-gang cluster -> first admits, second sees the committed usage and
    rejects; hypothetical rows never leak across segments."""
    n, emax = 8, 8
    avail = np.zeros((n, 3), np.int32)
    avail[0] = (4, 4, 0)
    cluster = ClusterTensors(
        available=jnp.asarray(avail),
        schedulable=jnp.asarray(avail.copy()),
        zone_id=jnp.zeros(n, jnp.int32),
        name_rank=jnp.arange(n, dtype=jnp.int32),
        label_rank_driver=jnp.full(n, INT32_INF, jnp.int32),
        label_rank_executor=jnp.full(n, INT32_INF, jnp.int32),
        unschedulable=jnp.zeros(n, bool),
        ready=jnp.ones(n, bool),
        valid=jnp.ones(n, bool),
    )
    gang = (np.ones(3, np.int32) * np.array([1, 1, 0], np.int32),
            np.array([1, 1, 0], np.int32), 3, False)
    requests = [[gang], [gang]]
    ones = [np.ones(n, bool)] * 2
    win = make_segmented_window(requests, ones, ones)
    meta, _, base_after = window_pack_pallas(
        cluster, win, fill="tightly-pack", emax=emax, num_zones=2,
        interpret=True,
    )
    meta = np.asarray(meta)
    assert meta[0, 0, 1] == 1  # first request admitted (1+3 = 4 CPU)
    assert meta[1, 0, 1] == 0  # second sees the committed base: full
    assert np.asarray(base_after)[0, 0] == 0


def test_window_pallas_empty_candidates_and_emax_edges():
    """A segment whose candidate mask excludes every node rejects without
    disturbing its neighbors; count == emax and count == 0 rows match the
    XLA scan exactly."""
    rng = np.random.default_rng(31)
    n, emax = 16, 8
    cluster = _cluster(rng, n)
    one = np.ones(3, np.int32)
    requests = [
        [(one, one, emax, False)],  # full-width gang
        [(one, one, 0, False)],  # zero-executor gang
        [(one, one, 2, False)],  # starved: empty candidate mask
    ]
    cands = [np.ones(n, bool), np.ones(n, bool), np.zeros(n, bool)]
    doms = [np.ones(n, bool)] * 3
    win = make_segmented_window(requests, cands, doms)
    # XLA twin
    flat = [r for rows in requests for r in rows]
    apps = make_app_batch(
        np.stack([r[0] for r in flat]),
        np.stack([r[1] for r in flat]),
        np.asarray([r[2] for r in flat], np.int32),
        skippable=[r[3] for r in flat],
        driver_cand=np.stack([cands[i] for i in range(3)]),
        domain=np.stack([doms[i] for i in range(3)]),
        commit=[True] * 3,
        reset=[True] * 3,
    )
    ref = batched_fifo_pack(
        cluster, apps, fill="tightly-pack", emax=emax, num_zones=4
    )
    meta, execs, base_after = window_pack_pallas(
        cluster, win, fill="tightly-pack", emax=emax, num_zones=4,
        interpret=True,
    )
    meta = np.asarray(meta)
    for bi in range(3):
        assert meta[bi, 0, 1] == np.asarray(ref.admitted)[bi], bi
        assert meta[bi, 0, 0] == np.asarray(ref.driver_node)[bi], bi
        np.testing.assert_array_equal(
            np.asarray(execs)[bi, 0], np.asarray(ref.executor_nodes)[bi]
        )
    assert meta[2, 0, 1] == 0  # starved segment rejected
    np.testing.assert_array_equal(
        np.asarray(base_after), np.asarray(ref.available_after)
    )


def test_solver_window_route_parity(monkeypatch):
    """The solver's Pallas window route (pack_window dispatch/fetch through
    _window_blob_pallas) returns byte-identical decisions to the XLA route
    for the same window."""
    import spark_scheduler_tpu.ops.pallas_window as pw
    from functools import partial as _p

    from spark_scheduler_tpu.core.solver import PlacementSolver, WindowRequest
    from spark_scheduler_tpu.models.kube import Node
    from spark_scheduler_tpu.models.resources import Resources

    def mk_solver():
        s = PlacementSolver(use_native=False)
        nodes = [
            Node(
                name=f"n{i}",
                allocatable=Resources.from_quantities("8", "8Gi"),
            )
            for i in range(12)
        ]
        t = s.build_tensors(nodes, {}, {})
        return s, t, [n.name for n in nodes]

    one = Resources.from_quantities("1", "1Gi")
    two = Resources.from_quantities("2", "2Gi")

    def mk_requests(names):
        return [
            WindowRequest(
                rows=[(one, one, 3, False)],
                driver_candidate_names=names,
            ),
            WindowRequest(
                rows=[(one, one, 3, False), (two, one, 2, False)],
                driver_candidate_names=names,
            ),
            WindowRequest(
                rows=[(one, two, 4, True), (one, one, 1, False)],
                driver_candidate_names=names[:8],
            ),
        ]

    s_x, t_x, names = mk_solver()
    ref = s_x.pack_window("tightly-pack", t_x, mk_requests(names))

    monkeypatch.setattr(pw, "window_pallas_eligible", lambda fill: True)
    monkeypatch.setattr(
        pw, "window_pack_pallas", _p(pw.window_pack_pallas, interpret=True)
    )
    s_p, t_p, names_p = mk_solver()
    got = s_p.pack_window("tightly-pack", t_p, mk_requests(names_p))
    assert s_p.window_path_counts.get("pallas") == 1

    assert len(ref) == len(got)
    for r, g in zip(ref, got):
        assert r.admitted == g.admitted
        assert r.earlier_blocked == g.earlier_blocked
        assert r.packing.driver_node == g.packing.driver_node
        assert r.packing.executor_nodes == g.packing.executor_nodes
