"""Chaos soak for the write-back ladder (VERDICT r2 #8).

The full scheduler runs against the fake apiserver with fault injection:
409 conflict storms, dropped connections (writes AND watch streams), a
tiny watch-history window forcing 410-Gone relists, and namespace
termination. Assertions: no scheduling decision is lost, reservations
CONVERGE in the apiserver once the storm passes, watch-synced state
recovers, and terminating-namespace creates are dropped without a retry
storm (async.go:88-96).
"""

from __future__ import annotations

import pytest

from spark_scheduler_tpu.kube.apiserver import FakeKubeAPIServer
from spark_scheduler_tpu.kube.backend import KubeBackend
from spark_scheduler_tpu.models.reservations import (
    Reservation,
    ResourceReservation,
    ReservationSpec,
    ReservationStatus,
)
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)
from tests.test_kube_watch import wait_until


@pytest.fixture
def chaos_apiserver():
    # Tiny history window: the soak's write volume forces 410-Gone relists
    # on the watch streams (etcd compaction analog).
    server = FakeKubeAPIServer(history_limit=24)
    server.start()
    yield server
    server.stop()


def test_chaos_soak_reservations_converge(chaos_apiserver):
    server = chaos_apiserver
    backend = KubeBackend(server.base_url, qps=10_000, burst=10_000)
    backend.start()
    assert backend.wait_synced(timeout=5.0)
    h = Harness(
        backend=backend,
        binpack_algo="tightly-pack",
        fifo=True,
        sync_writes=False,  # REAL async write-back workers + retries
        async_client_retry_count=25,  # ride out 30% conflict storms
    )
    h.app.start_background()
    names = [f"cn{i}" for i in range(16)]
    h.add_nodes(*(new_node(n) for n in names))

    # Storm on: nearly a third of writes 409, 15% of connections dropped.
    server.chaos_conflict_rate = 0.30
    server.chaos_drop_rate = 0.15

    apps = []
    try:
        for i in range(12):
            pods = static_allocation_spark_pods(f"chaos-{i}", 2)
            apps.append(pods)
            # Decisions must not be lost: local admission always succeeds —
            # the storm only affects durability, never the decision path.
            result = h.schedule(pods[0], names)
            assert result.node_names, (i, result)
            for p in pods[1:]:
                assert h.schedule(p, names).node_names, (i, p.name)
    finally:
        # Let the storm actually bite before switching it off: on a loaded
        # machine all 12 admissions can finish before the async workers
        # attempt a single write, so give the workers time to run into the
        # injected faults first. The fault RNG is SEEDED (apiserver
        # Random(0)), so whether a drop lands inside the window depends on
        # the exact request interleaving — keep the storm FED with no-op
        # rewrites of already-converged reservations (final state
        # unchanged) until both fault kinds have fired, instead of hoping
        # the deterministic sequence cooperates with this box's timing.
        try:
            import time as _time

            deadline = _time.monotonic() + 10.0
            fed = 0
            while _time.monotonic() < deadline:
                if (
                    server.chaos_injected["conflicts"] >= 3
                    and server.chaos_injected["drops"] >= 1
                ):
                    break
                rr = h.app.rr_cache.get("namespace", f"chaos-{fed % 12}")
                if rr is not None:
                    h.app.rr_cache.update(rr.copy())
                fed += 1
                _time.sleep(0.05)
        finally:
            # Storm off: the ladder must now converge.
            server.chaos_conflict_rate = 0.0
            server.chaos_drop_rate = 0.0

    # The storm actually happened.
    assert server.chaos_injected["conflicts"] >= 3, server.chaos_injected
    assert server.chaos_injected["drops"] >= 1, server.chaos_injected

    h.app.rr_cache.flush()  # drain remaining queued writes inline

    def converged():
        stored = server.collections["resourcereservations"].objects
        if len(stored) != 12:
            return False
        for i in range(12):
            wire = stored.get(("namespace", f"chaos-{i}"))
            if wire is None or len(wire["spec"]["reservations"]) != 3:
                return False
            if wire["status"]["pods"].get("driver") != f"chaos-{i}-driver":
                return False
        return True

    assert wait_until(converged, timeout=10.0), {
        "stored": sorted(server.collections["resourcereservations"].objects),
        "metrics": vars(h.app.rr_cache.client.metrics),
    }
    # Retries happened but nothing was dropped: every decision is durable.
    m = h.app.rr_cache.client.metrics
    assert m.retries > 0, vars(m)
    assert m.dropped == 0, vars(m)

    # Watch-synced node state survived the dropped streams + 410 relists.
    assert wait_until(lambda: len(backend.list_nodes()) == 16, timeout=10.0)

    h.app.stop()
    backend.stop()


def test_chaos_storm_under_concurrent_windowed_serving(chaos_apiserver):
    """The full stack under simultaneous stress: concurrent HTTP clients
    coalescing into windowed solves WHILE the apiserver storms (409s,
    dropped connections, 410 relists). Every client gets a placement, the
    window batcher actually coalesced, and reservations converge."""
    import http.client
    import json
    import threading

    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s

    server = chaos_apiserver
    backend = KubeBackend(server.base_url, qps=10_000, burst=10_000)
    backend.start()
    assert backend.wait_synced(timeout=5.0)
    h = Harness(
        backend=backend,
        binpack_algo="tightly-pack",
        fifo=True,
        sync_writes=False,
        async_client_retry_count=25,
    )
    names = [f"wn{i}" for i in range(24)]
    h.add_nodes(*(new_node(n) for n in names))
    http_server = SchedulerHTTPServer(h.app, host="127.0.0.1", port=0)
    http_server.start()

    server.chaos_conflict_rate = 0.25
    server.chaos_drop_rate = 0.10

    n_clients = 10
    errors: list = []

    def client(i):
        try:
            pods = static_allocation_spark_pods(f"storm-{i}", 2)
            backend.add_pod(pods[0])
            conn = http.client.HTTPConnection(
                "127.0.0.1", http_server.port, timeout=120
            )
            body = json.dumps(
                {"Pod": pod_to_k8s(pods[0]), "NodeNames": names}
            ).encode()
            conn.request("POST", "/predicates", body=body)
            resp = json.loads(conn.getresponse().read())
            conn.close()
            assert resp.get("NodeNames"), (i, resp)
            backend.bind_pod(pods[0], resp["NodeNames"][0])
        except Exception as exc:  # surfaced after join
            errors.append(exc)

    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
    finally:
        server.chaos_conflict_rate = 0.0
        server.chaos_drop_rate = 0.0

    h.app.rr_cache.flush()
    assert wait_until(
        lambda: all(
            ("namespace", f"storm-{i}")
            in server.collections["resourcereservations"].objects
            for i in range(n_clients)
        ),
        timeout=10.0,
    )
    assert http_server.batcher.stats()["requests_served"] == n_clients
    m = h.app.rr_cache.client.metrics
    assert m.dropped == 0, vars(m)

    http_server.stop()
    backend.stop()


def test_namespace_terminating_create_dropped_without_retry_storm(chaos_apiserver):
    server = chaos_apiserver
    backend = KubeBackend(server.base_url, qps=10_000, burst=10_000)
    backend.start()
    assert backend.wait_synced(timeout=5.0)
    h = Harness(backend=backend, sync_writes=False)
    h.app.start_background()

    server.terminating_namespaces.add("doomed")
    rr = ResourceReservation(
        name="doomed-app",
        namespace="doomed",
        spec=ReservationSpec(
            reservations={
                "driver": Reservation(
                    node="n0", resources=Resources.from_quantities("1", "1Gi")
                )
            }
        ),
        status=ReservationStatus(pods={"driver": "doomed-app-driver"}),
    )
    h.app.rr_cache.create(rr)
    h.app.rr_cache.flush()

    m = h.app.rr_cache.client.metrics
    # Dropped exactly once, with NO retries: NamespaceTerminating is not
    # retryable (async.go:88-96).
    assert wait_until(lambda: m.dropped == 1, timeout=5.0), vars(m)
    assert m.retries == 0, vars(m)
    assert server.chaos_injected["ns_terminating"] == 1
    assert ("doomed", "doomed-app") not in server.collections[
        "resourcereservations"
    ].objects

    h.app.stop()
    backend.stop()
