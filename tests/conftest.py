"""Test env: force CPU with an 8-device virtual mesh
(`xla_force_host_platform_device_count=8`) so device-sharding tests can run
without TPU hardware. Note: this environment's TPU site hook overrides
JAX_PLATFORMS via `jax.config`, so we must update the config AFTER importing
jax — env vars alone are not enough."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
