"""Test env: force CPU with an 8-device virtual mesh
(`xla_force_host_platform_device_count=8`) so device-sharding tests can run
without TPU hardware. Note: this environment's TPU site hook overrides
JAX_PLATFORMS via `jax.config`, so we must update the config AFTER importing
jax — env vars alone are not enough."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# SPARK_SCHEDULER_TEST_INGEST=native runs every server-constructing suite on
# the native ingest lane (the CI `ingest-native` job leg): tests that do not
# pass an explicit `ingest=` inherit the override, so the whole parametrized
# server matrix re-runs against the C++ framer/decoder without duplicating
# the suites. Tests pinning a specific lane still win (explicit kwarg).
_TEST_INGEST = os.environ.get("SPARK_SCHEDULER_TEST_INGEST")
if _TEST_INGEST:
    import spark_scheduler_tpu.server.http as _http_mod

    _orig_server_init = _http_mod.SchedulerHTTPServer.__init__

    def _ingest_forcing_init(self, *args, **kwargs):
        kwargs.setdefault("ingest", _TEST_INGEST)
        _orig_server_init(self, *args, **kwargs)

    _http_mod.SchedulerHTTPServer.__init__ = _ingest_forcing_init

# SPARK_SCHEDULER_TEST_PRUNE=<k> runs every solver-constructing suite with
# sound top-K candidate pruning enabled (the CI `prune` job leg): solvers
# that do not pin an explicit `prune_top_k` inherit the override, so the
# solver/extender equivalence suites and the chaos-matrix soak re-run with
# the two-tier solve live — pruning cannot silently regress decision
# equality or the fault paths. Tests pinning prune_top_k (including the
# unpruned baselines inside tests/test_prune_equivalence.py, which pass 0)
# still win.
_TEST_PRUNE = os.environ.get("SPARK_SCHEDULER_TEST_PRUNE")
if _TEST_PRUNE and int(_TEST_PRUNE) > 0:  # "0" must mean OFF, not k=8
    from spark_scheduler_tpu.core import solver as _solver_mod

    _orig_solver_init = _solver_mod.PlacementSolver.__init__
    _prune_k = int(_TEST_PRUNE) if int(_TEST_PRUNE) > 1 else 8

    def _prune_forcing_init(self, *args, **kwargs):
        kwargs.setdefault("prune_top_k", _prune_k)
        _orig_solver_init(self, *args, **kwargs)

    _solver_mod.PlacementSolver.__init__ = _prune_forcing_init


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Bound the process's virtual-memory map count across the suite.

    Every XLA:CPU compile JIT-loads code pages as a handful of mmap
    regions, and compiled executables are cached for the life of the
    process — a full run accumulates tens of thousands of mappings and
    crosses the kernel's default vm.max_map_count (65530), at which point
    the next compile's mmap fails and XLA segfaults (observed at ~62k maps,
    deterministically in whichever test compiles next — historically the
    8-device sharded window test). Dropping the executable caches at module
    boundaries keeps the count bounded; cross-module recompiles are cheap
    next to the suite's own per-module compiles."""
    yield
    jax.clear_caches()
