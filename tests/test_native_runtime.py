"""Native C++ runtime parity: the ClusterArena-backed tensor builder against
the pure-Python builder, and the native sharded queue against the Python
queue's dedup/shard/ordering semantics."""

import threading

import numpy as np
import pytest

from spark_scheduler_tpu import native
from spark_scheduler_tpu.core.solver import PlacementSolver
from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.store.queue import (
    Request,
    RequestType,
    ShardedUniqueQueue,
    make_sharded_queue,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime not built"
)


def _node(name, cpu="8", mem="8Gi", gpu="0", zone="z1", ready=True,
          unschedulable=False, labels=None):
    return Node(
        name=name,
        allocatable=Resources.from_quantities(cpu, mem, gpu),
        labels={"topology.kubernetes.io/zone": zone, **(labels or {})},
        ready=ready,
        unschedulable=unschedulable,
    )


def _rand_cluster(rng, n):
    return [
        _node(
            f"n{i:04d}",
            cpu=str(int(rng.integers(1, 64))),
            mem=f"{int(rng.integers(1, 64))}Gi",
            gpu=str(int(rng.integers(0, 2))),
            zone=f"z{int(rng.integers(0, 4))}",
            ready=bool(rng.random() > 0.1),
            unschedulable=bool(rng.random() < 0.1),
        )
        for i in range(n)
    ]


def _tensors_equal_on_valid(a, b):
    """Equality of every field on valid slots; name_rank compared by ORDER
    (the native path uses global ranks — values differ, order must not)."""
    assert np.array_equal(a.valid, b.valid)
    v = np.asarray(a.valid)
    for field in ("available", "schedulable", "zone_id", "label_rank_driver",
                  "label_rank_executor", "unschedulable", "ready"):
        fa, fb = np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        assert np.array_equal(fa[v], fb[v]), field
    ra, rb = np.asarray(a.name_rank)[v], np.asarray(b.name_rank)[v]
    assert np.array_equal(np.argsort(ra, stable=True), np.argsort(rb, stable=True))


def test_arena_solver_matches_python_builder():
    rng = np.random.default_rng(0)
    nodes = _rand_cluster(rng, 50)
    usage = {"n0003": Resources.from_quantities("2", "2Gi"),
             "n0017": Resources.from_quantities("1", "512Mi")}
    overhead = {"n0005": Resources.from_quantities("1", "1Gi")}

    s_native = PlacementSolver(use_native=True)
    s_python = PlacementSolver(use_native=False)
    assert s_native.uses_native_arena and not s_python.uses_native_arena

    t_n = s_native.build_tensors(nodes, usage, overhead)
    t_p = s_python.build_tensors(nodes, usage, overhead)
    _tensors_equal_on_valid(t_n, t_p)

    # Node churn: update one node, drop some from the candidate set, add new.
    nodes[7] = _node("n0007", cpu="2", mem="1Gi", unschedulable=True)
    subset = nodes[:30] + [_node("extra-1", cpu="4", mem="4Gi", zone="z9")]
    t_n2 = s_native.build_tensors(subset, {}, overhead)
    t_p2 = s_python.build_tensors(subset, {}, overhead)
    # Python solver's registry has interned dropped nodes too; valid masks
    # agree because both mark only the passed subset valid.
    _tensors_equal_on_valid(t_n2, t_p2)


def test_arena_solver_same_placements_with_label_priorities():
    rng = np.random.default_rng(1)
    nodes = [
        _node(f"m{i}", cpu="8", mem="8Gi",
              labels={"tier": ["gold", "silver", "bronze"][i % 3]})
        for i in range(12)
    ]
    prio = ("tier", ["gold", "silver"])
    for strategy in ("tightly-pack", "distribute-evenly", "minimal-fragmentation"):
        s_n = PlacementSolver(driver_label_priority=prio, use_native=True)
        s_p = PlacementSolver(driver_label_priority=prio, use_native=False)
        names = [n.name for n in nodes]
        d = Resources.from_quantities("1", "1Gi")
        e = Resources.from_quantities("2", "2Gi")
        t_n = s_n.build_tensors(nodes, {}, {})
        t_p = s_p.build_tensors(nodes, {}, {})
        p_n = s_n.pack(strategy, t_n, d, e, 5, names)
        p_p = s_p.pack(strategy, t_p, d, e, 5, names)
        assert p_n.has_capacity == p_p.has_capacity
        assert p_n.driver_node == p_p.driver_node, strategy
        assert p_n.executor_nodes == p_p.executor_nodes, strategy


def test_native_queue_is_selected_and_python_fallback_works():
    q = make_sharded_queue(5)
    assert isinstance(q, native.NativeShardedQueue)
    q2 = make_sharded_queue(5, prefer_native=False)
    assert isinstance(q2, ShardedUniqueQueue)


def _req(ns, name, typ=RequestType.CREATE):
    return Request(key=(ns, name), type=typ)


def test_native_queue_dedup_and_delete_semantics():
    for q in (make_sharded_queue(4), ShardedUniqueQueue(4)):
        q.add_if_absent(_req("ns", "a"))
        q.add_if_absent(_req("ns", "a", RequestType.UPDATE))  # deduped
        q.add_if_absent(_req("ns", "a", RequestType.DELETE))  # never deduped
        assert sum(q.queue_lengths()) == 2, type(q).__name__

        # Pop everything from every bucket; keys release on pop.
        popped = []
        for b in range(q.num_buckets):
            while (r := q.pop(b, timeout_s=0)) is not None:
                popped.append(r)
        assert [r.type for r in popped] == [RequestType.CREATE, RequestType.DELETE]
        # After release, the same key enqueues again.
        q.add_if_absent(_req("ns", "a", RequestType.UPDATE))
        assert sum(q.queue_lengths()) == 1


def test_native_queue_same_key_same_bucket_and_blocking_pop():
    q = make_sharded_queue(4)
    assert isinstance(q, native.NativeShardedQueue)
    buckets = set()
    for i in range(32):
        q.add_if_absent(_req("ns", "same-key") if False else _req("ns", f"k{i}"))
    lengths = q.queue_lengths()
    assert sum(lengths) == 32 and len(lengths) == 4

    # Same key always lands on the same bucket: drain, re-add twice.
    q2 = make_sharded_queue(4)
    q2.add_if_absent(_req("ns", "stable"))
    b1 = [i for i, n in enumerate(q2.queue_lengths()) if n][0]
    assert q2.pop(b1, timeout_s=0).key == ("ns", "stable")
    q2.add_if_absent(_req("ns", "stable"))
    b2 = [i for i, n in enumerate(q2.queue_lengths()) if n][0]
    assert b1 == b2
    buckets.add(b1)

    # Blocking pop wakes when a producer adds from another thread.
    got = []
    t = threading.Thread(target=lambda: got.append(q2.pop(b1, timeout_s=5.0)))
    q2.pop(b1, timeout_s=0)  # drain first
    t.start()
    q2.add_if_absent(_req("ns", "stable"))
    t.join(timeout=10)
    assert got and got[0] is not None and got[0].key == ("ns", "stable")


def test_native_queue_try_add_full_buffer():
    q = native.NativeShardedQueue(1, buffer_size=2)
    assert q.try_add_if_absent(_req("ns", "x1"))
    assert q.try_add_if_absent(_req("ns", "x2"))
    assert not q.try_add_if_absent(_req("ns", "x3"))  # full -> False
    assert q.try_add_if_absent(_req("ns", "x1", RequestType.UPDATE))  # dedup -> True
    # The full-rollback removed x3 from inflight, so after draining it can
    # be re-added (queue.go:73-88 rollback semantics).
    q.pop(0, timeout_s=0)
    assert q.try_add_if_absent(_req("ns", "x3"))


def test_native_queue_concurrent_producers_consumers():
    q = make_sharded_queue(3, buffer_size=1000)
    n_per, n_prod = 200, 4
    consumed = []
    consumed_lock = threading.Lock()
    stop = threading.Event()

    def consumer(bucket):
        while not stop.is_set():
            r = q.pop(bucket, timeout_s=0.02)
            if r is not None:
                with consumed_lock:
                    consumed.append(r.key)

    consumers = [threading.Thread(target=consumer, args=(b,)) for b in range(3)]
    [c.start() for c in consumers]

    def producer(p):
        for i in range(n_per):
            q.add_if_absent(_req(f"ns{p}", f"key-{p}-{i}"))

    producers = [threading.Thread(target=producer, args=(p,)) for p in range(n_prod)]
    [t.start() for t in producers]
    [t.join() for t in producers]
    deadline = threading.Event()
    for _ in range(200):
        with consumed_lock:
            if len(consumed) == n_per * n_prod:
                break
        deadline.wait(0.05)
    stop.set()
    [c.join(timeout=5) for c in consumers]
    assert len(consumed) == n_per * n_prod  # distinct keys: nothing deduped
    assert len(set(consumed)) == n_per * n_prod
