"""Sound top-K candidate pruning: the two-tier solve's equivalence matrix.

The pruned path (core/prune.py + solver._dispatch_pruned/_fetch_pruned)
must be BYTE-IDENTICAL to the full-tensor solve by construction: the
prefilter only shrinks the gather, zone ranks stay exact via the excluded
zone-sum offsets, and the post-solve certificate escalates any window a
pruned row could have changed to the exact host re-solve. Pinned here:

  - pruned == unpruned decisions across randomized churn and FIFO
    prefixes for every plain fill strategy;
  - composition: prune x fused dispatch (k in {1, 4}), prune x device
    pool {1, 2} with domain partitioning — the equivalence matrix of the
    acceptance criteria;
  - a deliberately-tight-K case where the certificate MUST fire: the
    escalations counter moves and the escalated windows still match the
    full solve decision for decision;
  - the host zone-rank replica == the kernel's zone_ranks, and the
    offset form (gathered subset + excluded sums) == the full solve's
    ranks — the identity the in-kernel offsets rest on;
  - RankIndex incremental maintenance == a from-scratch rebuild under
    random row churn;
  - default-off: an unconfigured solver never routes a window through
    the pruned path.
"""

import numpy as np
import pytest

from spark_scheduler_tpu.core.feature_store import RankIndex
from spark_scheduler_tpu.core.prune import zone_ranks_host, split_zone_sums
from spark_scheduler_tpu.core.solver import (
    FusedWindowView,
    PlacementSolver,
    WindowRequest,
)
from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
from spark_scheduler_tpu.models.resources import Resources

ONE = Resources.from_quantities("1", "1Gi")
TWO = Resources.from_quantities("2", "2Gi")


def _nodes(n, zones=2):
    out = []
    for i in range(n):
        out.append(
            Node(
                name=f"n{i:03d}",
                allocatable=Resources.from_quantities(
                    "8", "8Gi", "1", round_up=False
                ),
                labels={ZONE_LABEL: f"z{i % zones}"},
            )
        )
    return out


def _random_windows(rng, nodes, k, per, *, domains=None, fifo_rows=True):
    names = [n.name for n in nodes]
    windows = []
    r = 0
    for _ in range(k):
        reqs = []
        for _ in range(per):
            rows = []
            if fifo_rows:
                for _ in range(int(rng.integers(0, 3))):
                    rows.append(
                        (ONE, ONE, int(rng.integers(1, 3)),
                         bool(rng.random() < 0.5))
                    )
            res = TWO if rng.random() < 0.3 else ONE
            rows.append((res, ONE, int(rng.integers(1, 4)), False))
            if domains is not None:
                dom = domains[r % len(domains)]
                cand = dom
            else:
                dom, cand = None, names
            reqs.append(
                WindowRequest(
                    rows=rows,
                    driver_candidate_names=cand,
                    domain_node_names=dom,
                )
            )
            r += 1
        windows.append(reqs)
    return windows


def _random_usage(rng, nodes):
    usage = {}
    for n in nodes:
        if rng.random() < 0.3:
            usage[n.name] = Resources.from_quantities(
                str(int(rng.integers(1, 4))), "1Gi"
            )
    return usage


def _run(solver, nodes, batches, usages, strategy):
    """Pipelined serving order: dispatch every window of a batch
    back-to-back, then fetch all; churn lands between batches."""
    out = []
    for usage, wins in zip(usages, batches):
        handles = []
        for w in wins:
            t = solver.build_tensors_pipelined(nodes, usage, {})
            handles.append(solver.pack_window_dispatch(strategy, t, w))
        for h in handles:
            out.extend(solver.pack_window_fetch(h))
    return out


def _run_fused(solver, nodes, batches, usages, strategy):
    out = []
    for usage, wins in zip(usages, batches):
        t = solver.build_tensors_pipelined(nodes, usage, {})
        views = solver.pack_windows_dispatch(strategy, t, wins)
        for v in views:
            out.extend(solver.pack_window_fetch(v))
    return out


@pytest.mark.parametrize(
    "strategy", ["tightly-pack", "distribute-evenly", "minimal-fragmentation"]
)
def test_pruned_matches_full_with_churn(strategy):
    rng = np.random.default_rng(hash(strategy) % 1000)
    nodes = _nodes(96)
    n_batches = 3
    batches = [
        _random_windows(rng, nodes, 2, 3) for _ in range(n_batches)
    ]
    usages = [{}] + [_random_usage(rng, nodes) for _ in range(n_batches - 1)]

    full = _run(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, usages, strategy,
    )
    pruned_solver = PlacementSolver(
        use_native=False, prune_top_k=4, prune_slack=0.75
    )
    pruned = _run(pruned_solver, nodes, batches, usages, strategy)
    assert len(full) == len(pruned)
    for i, (a, b) in enumerate(zip(full, pruned)):
        assert a == b, f"decision {i} diverged: {a} vs {b}"
    # The suite must actually exercise the pruned path, not silently
    # bypass it through the benefit gate.
    assert pruned_solver.prune_stats["windows"] > 0, (
        strategy, pruned_solver.window_path_counts,
    )


@pytest.mark.parametrize("k", [1, 4])
def test_pruned_matches_full_fused(k):
    """prune x fused dispatch: the umbrella window prunes as one batch;
    views slice identically. The fused batch's aggregate demand scales
    with K, so the node count must leave the prefilter headroom."""
    rng = np.random.default_rng(40 + k)
    nodes = _nodes(192)
    batches = [_random_windows(rng, nodes, k, 2) for _ in range(2)]
    usages = [{}, _random_usage(rng, nodes)]
    full = _run_fused(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, usages, "tightly-pack",
    )
    pruned_solver = PlacementSolver(
        use_native=False, prune_top_k=4, prune_slack=0.3
    )
    pruned = _run_fused(pruned_solver, nodes, batches, usages, "tightly-pack")
    assert full == pruned
    assert pruned_solver.prune_stats["windows"] > 0


@pytest.mark.parametrize("pool", [1, 2])
def test_pruned_matches_full_pooled_partitioned(pool):
    """prune x device pool x domain partitioning. On a pool, windows whose
    requests pin DISJOINT domains partition across slots and each
    partition prunes its own gather (the delta-combine threads the carry
    identically). On the single-device path a window must share ONE
    domain to prune — mixed-domain windows fall back to the full solve —
    so the pool=1 case pins the per-window shared-domain form instead."""
    rng = np.random.default_rng(60 + pool)
    nodes = _nodes(96)
    half = (
        [n.name for n in nodes[:48]],
        [n.name for n in nodes[48:]],
    )
    batches = []
    for b in range(2):
        if pool == 1:
            # One shared domain per window, alternating across windows.
            wins = []
            for w in range(2):
                dom = half[w % 2]
                wins.extend(
                    _random_windows(rng, nodes, 1, 2, domains=[dom])
                )
            batches.append(wins)
        else:
            # Per-request alternation: the pooled partition topology.
            batches.append(_random_windows(rng, nodes, 2, 2, domains=half))
    usages = [{}, _random_usage(rng, nodes)]
    full = _run(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, usages, "tightly-pack",
    )
    pruned_solver = PlacementSolver(
        use_native=False, device_pool=pool, prune_top_k=4, prune_slack=0.3
    )
    pruned = _run(pruned_solver, nodes, batches, usages, "tightly-pack")
    assert full == pruned
    assert pruned_solver.prune_stats["windows"] > 0


def test_tight_k_certificate_escalates_and_still_matches():
    """K deliberately too small for the workload: the soundness
    certificate MUST fire (escalations > 0) and every escalated window's
    decisions must still equal the full solve's — the escalation path is
    the byte-identity guarantee, so it is pinned under stress."""
    rng = np.random.default_rng(9)
    nodes = _nodes(128, zones=3)
    n_batches = 3
    batches = [
        _random_windows(rng, nodes, 2, 4) for _ in range(n_batches)
    ]
    usages = [{}] + [_random_usage(rng, nodes) for _ in range(n_batches - 1)]
    full = _run(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, usages, "tightly-pack",
    )
    tight = PlacementSolver(
        use_native=False, prune_top_k=1, prune_slack=0.01
    )
    pruned = _run(tight, nodes, batches, usages, "tightly-pack")
    assert full == pruned
    assert tight.prune_stats["windows"] > 0
    assert tight.prune_stats["escalations"] > 0, tight.prune_stats
    assert tight.prune_stats["reasons"], tight.prune_stats


def test_minimal_fragmentation_escalates_on_excluded_capacity():
    """minimal-fragmentation consumes by capacity DESC, so any excluded
    capacity is an order hazard: with spare excluded rows the certificate
    must escalate rather than trust the pruned order — and decisions
    still match."""
    rng = np.random.default_rng(11)
    nodes = _nodes(96)
    batches = [_random_windows(rng, nodes, 2, 2)]
    full = _run(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, [{}], "minimal-fragmentation",
    )
    pruned_solver = PlacementSolver(
        use_native=False, prune_top_k=2, prune_slack=0.25
    )
    pruned = _run(pruned_solver, nodes, batches, [{}], "minimal-fragmentation")
    assert full == pruned
    st = pruned_solver.prune_stats
    if st["windows"]:
        # With spare capacity everywhere the capacity-order hazard must
        # fire. (Not necessarily once per pruned window: an escalation
        # invalidates its in-flight sibling windows, which re-solve via
        # the exact host path without running their own certificate.)
        assert st["escalations"] >= 1, st
        assert "minfrag-excluded-capacity" in st["reasons"] or st["reasons"], st


def test_default_off_never_prunes():
    rng = np.random.default_rng(3)
    nodes = _nodes(96)
    batches = [_random_windows(rng, nodes, 2, 2)]
    solver = PlacementSolver(use_native=False, prune_top_k=0)
    _run(solver, nodes, batches, [{}], "tightly-pack")
    assert solver.prune_stats["windows"] == 0
    assert "xla-pruned" not in solver.window_path_counts


def test_zone_ranks_host_matches_kernel_and_offsets():
    """The in-kernel offset identity: zone_ranks over a GATHERED subset
    plus the excluded rows' sums-as-offsets equals zone_ranks over the
    full cluster — and both equal the host replica the certificate uses."""
    import jax.numpy as jnp

    from spark_scheduler_tpu.models.cluster import ClusterTensors
    from spark_scheduler_tpu.ops.sorting import zone_ranks

    rng = np.random.default_rng(21)
    n, zb = 64, 4
    avail = rng.integers(-5, 1 << 20, size=(n, 3)).astype(np.int32)
    zone_id = rng.integers(0, 3, size=n).astype(np.int32)
    valid = rng.random(n) < 0.9

    def mk(avail, zone_id, valid):
        n = avail.shape[0]
        return ClusterTensors(
            available=jnp.asarray(avail),
            schedulable=jnp.asarray(avail),
            zone_id=jnp.asarray(zone_id),
            name_rank=jnp.arange(n, dtype=jnp.int32),
            label_rank_driver=jnp.zeros(n, jnp.int32),
            label_rank_executor=jnp.zeros(n, jnp.int32),
            unschedulable=jnp.zeros(n, bool),
            ready=jnp.ones(n, bool),
            valid=jnp.asarray(valid),
        )

    full = np.asarray(
        zone_ranks(mk(avail, zone_id, valid), jnp.ones(n, bool), zb)
    )

    # Host replica over the same sums.
    mask = valid
    mem = np.zeros(zb, np.int64)
    cpu = np.zeros(zb, np.int64)
    np.add.at(mem, zone_id[mask], avail[mask, 1].astype(np.int64))
    np.add.at(cpu, zone_id[mask], avail[mask, 0].astype(np.int64))
    present = np.zeros(zb, bool)
    present[np.unique(zone_id[mask])] = True
    assert np.array_equal(zone_ranks_host(mem, cpu, present), full)

    # Gathered subset + excluded offsets == full.
    keep = np.sort(rng.choice(n, size=20, replace=False))
    excl = np.setdiff1d(np.arange(n), keep)
    excl = excl[valid[excl]]
    e_mem = np.zeros(zb, np.int64)
    e_cpu = np.zeros(zb, np.int64)
    np.add.at(e_mem, zone_id[excl], avail[excl, 1].astype(np.int64))
    np.add.at(e_cpu, zone_id[excl], avail[excl, 0].astype(np.int64))
    e_present = np.zeros(zb, bool)
    e_present[np.unique(zone_id[valid])] = True
    mh, ml = split_zone_sums(e_mem)
    ch, cl = split_zone_sums(e_cpu)
    sub = np.asarray(
        zone_ranks(
            mk(avail[keep], zone_id[keep], valid[keep]),
            jnp.ones(len(keep), bool),
            zb,
            zone_base=tuple(
                jnp.asarray(a) for a in (mh, ml, ch, cl, e_present)
            ),
        )
    )
    assert np.array_equal(sub, full)


def test_rank_index_incremental_matches_rebuild():
    rng = np.random.default_rng(33)
    n = 300
    zb = 4
    avail = rng.integers(0, 1000, size=(n, 3)).astype(np.int32)
    name_rank = rng.permutation(n).astype(np.int32)
    zone_id = rng.integers(0, 3, size=n).astype(np.int32)

    inc = RankIndex()
    inc.rebuild(avail, name_rank, zone_id, zb)
    for _ in range(25):
        dirty = rng.choice(n, size=int(rng.integers(1, 12)), replace=False)
        avail[dirty] = rng.integers(0, 1000, size=(len(dirty), 3))
        inc.update_rows(avail, name_rank, dirty)
        ref = RankIndex()
        ref.rebuild(avail, name_rank, zone_id, zb)
        for z in range(zb):
            assert np.array_equal(inc.zone_order(z), ref.zone_order(z)), z
        assert np.array_equal(inc.order(), ref.order())
    assert inc.incremental_updates > 0 and inc.rebuilds == 1


def test_repeat_window_reuses_plan_and_gather():
    """ISSUE 12: consecutive no-churn windows over the same (full) domain
    must re-serve the cached kept row set AND the gathered statics
    sub-blob — the planner's plan_reuse / gather_reuse counters move,
    zero rows are re-scanned after the cold build, and decisions still
    equal the full solve's."""
    rng = np.random.default_rng(5)
    nodes = _nodes(96)
    # Full-domain windows: no domain_node_names → the solver's resident-
    # aggregate path (dom is host.valid by identity).
    batches = [
        _random_windows(rng, nodes, 1, 2, fifo_rows=False)
        for _ in range(4)
    ]
    usages = [{}] * 4
    full = _run(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, usages, "tightly-pack",
    )
    pruned_solver = PlacementSolver(
        use_native=False, prune_top_k=4, prune_slack=0.3
    )
    pruned = _run(pruned_solver, nodes, batches, usages, "tightly-pack")
    assert full == pruned
    st = pruned_solver.prune_stats
    assert st["windows"] >= 3, st
    # The repeat windows reused the plan + the statics gather (the
    # degenerate re-gather of the bugfix satellite is counted and
    # skipped), and the planner never re-scanned a row after the cold
    # build (placement churn lands on kept rows — benign by design).
    assert st["plan_reuse"] >= 1, st
    assert st["gather_reuse"] >= 1, st
    assert st["planner_rows_scanned"] == 0, st
    assert st["planner_sweep_rows"] == 0, st


def test_planner_full_domain_plan_is_exact():
    """Oracle test for the O(K + changed) planner: every certificate
    input of a plan served from the resident aggregates must equal the
    brute-force recomputation over the full host view — zone sums,
    excluded-row offsets, lexmin keys, per-dim maxima, presence flags."""
    import jax.numpy as jnp

    from spark_scheduler_tpu.core.prune import PrunePlanner
    from spark_scheduler_tpu.models.cluster import ClusterTensors

    rng = np.random.default_rng(13)
    n, zb = 160, 4
    avail = rng.integers(0, 64, size=(n, 3)).astype(np.int32)
    zone_id = rng.integers(0, 3, size=n).astype(np.int32)
    valid = rng.random(n) < 0.92
    unsched = rng.random(n) < 0.1
    ready = rng.random(n) < 0.95
    name_rank = rng.permutation(n).astype(np.int32)
    host = ClusterTensors(
        available=avail,
        schedulable=avail.copy(),
        zone_id=zone_id,
        name_rank=name_rank,
        label_rank_driver=np.zeros(n, np.int32),
        label_rank_executor=np.zeros(n, np.int32),
        unschedulable=unsched,
        ready=ready,
        valid=valid,
    )
    drv = np.asarray([[4, 8, 0], [2, 4, 0]], np.int32)
    exc = np.asarray([[2, 4, 0], [2, 4, 0]], np.int32)
    counts = np.asarray([2, 1], np.int32)
    cand = [np.ones(n, bool), np.ones(n, bool)]

    planner = PrunePlanner()
    planner.sync(host, zb)
    plan = planner.plan_full_domain(
        host, cand_per_req=cand, drv_arr=drv, exc_arr=exc,
        counts=counts, num_zones=zb, top_k=4, slack=0.3,
    )
    assert plan is not None

    # Brute force over the host view.
    mem = np.zeros(zb, np.int64)
    cpu = np.zeros(zb, np.int64)
    np.add.at(mem, zone_id[valid], avail[valid, 1].astype(np.int64))
    np.add.at(cpu, zone_id[valid], avail[valid, 0].astype(np.int64))
    assert np.array_equal(plan.zone_mem, mem)
    assert np.array_equal(plan.zone_cpu, cpu)
    cnt = np.bincount(zone_id[valid], minlength=zb)
    assert np.array_equal(plan.present, cnt > 0)

    keep = plan.keep[: plan.k_real]
    assert np.array_equal(keep, np.sort(keep))  # sorted contract
    excl = valid.copy()
    excl[keep] = False
    e_mem = np.zeros(zb, np.int64)
    e_cpu = np.zeros(zb, np.int64)
    np.add.at(e_mem, zone_id[excl], avail[excl, 1].astype(np.int64))
    np.add.at(e_cpu, zone_id[excl], avail[excl, 0].astype(np.int64))
    mh, ml = split_zone_sums(e_mem)
    ch, cl = split_zone_sums(e_cpu)
    for got, want in zip(plan.zone_base[:4], (mh, ml, ch, cl)):
        assert np.array_equal(got, want)

    min_dr = drv.min(axis=0)
    min_er = exc.min(axis=0)
    fit_e = (avail >= min_er).all(axis=1) & valid & ~unsched & ready
    fit_d = (avail >= min_dr).all(axis=1) & valid
    for which, fit, e_cnt, e_key, e_max in (
        ("exec", fit_e, plan.e_cnt_exec, plan.e_key_exec, plan.e_max_exec),
        ("drv", fit_d, plan.e_cnt_drv, plan.e_key_drv, plan.e_max_drv),
    ):
        for z in range(zb):
            rel = np.flatnonzero(fit & excl & (zone_id == z))
            assert bool(e_cnt[z] > 0) == bool(rel.size), (which, z)
            if rel.size:
                keys = sorted(
                    (
                        int(avail[r, 1]),
                        int(avail[r, 0]),
                        int(name_rank[r]),
                    )
                    for r in rel
                )
                assert tuple(e_key[z]) == keys[0], (which, z)
                assert np.array_equal(
                    e_max[z], avail[rel].max(axis=0).astype(np.int64)
                ), (which, z)

    # The in-kernel offset identity holds for the planner's offsets too.
    def mk():
        return ClusterTensors(
            available=jnp.asarray(avail),
            schedulable=jnp.asarray(avail),
            zone_id=jnp.asarray(zone_id),
            name_rank=jnp.asarray(name_rank),
            label_rank_driver=jnp.zeros(n, jnp.int32),
            label_rank_executor=jnp.zeros(n, jnp.int32),
            unschedulable=jnp.asarray(unsched),
            ready=jnp.asarray(ready),
            valid=jnp.asarray(valid),
        )

    from spark_scheduler_tpu.ops.sorting import zone_ranks

    full_ranks = np.asarray(
        zone_ranks(mk(), jnp.ones(n, bool), zb)
    )
    host_ranks = zone_ranks_host(plan.zone_mem, plan.zone_cpu, plan.present)
    assert np.array_equal(host_ranks, full_ranks)
