"""Observability smoke (tier-1): flight recorder, /debug/decisions,
/debug/state, Prometheus /metrics exposition, and the metric-name lint
(every registry series carries the foundry.spark.scheduler. prefix so
dashboards keyed on the reference's namespace see one flat family).
"""

import http.client
import json

import pytest

from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.http import SchedulerHTTPServer
from spark_scheduler_tpu.server.kube_io import pod_to_k8s
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend
from spark_scheduler_tpu.testing.harness import (
    Harness,
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)

METRIC_PREFIX = "foundry.spark.scheduler."


@pytest.fixture()
def server():
    backend = InMemoryBackend()
    backend.register_crd(DEMAND_CRD)
    for i in range(4):
        backend.add_node(new_node(f"n{i}"))
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            debug_routes=True,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    srv = SchedulerHTTPServer(
        app, registry, port=0, debug_routes=True, request_timeout_s=120.0
    )
    srv.start()
    yield srv
    srv.stop()


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("GET", path, headers=headers or {})
    resp = conn.getresponse()
    body = resp.read()
    ctype = resp.getheader("Content-Type", "")
    conn.close()
    return resp.status, ctype, body


def _post_predicate(port, pod, node_names):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(
        "POST",
        "/predicates",
        body=json.dumps(
            {"Pod": pod_to_k8s(pod), "NodeNames": node_names}
        ).encode(),
    )
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out


def test_debug_decisions_metrics_and_state_smoke(server):
    """The CI smoke: admit one gang, deny one oversized app, then scrape
    /metrics (JSON + Prometheus) and /debug/decisions and lint every
    registry series name."""
    port = server.port
    names = [f"n{i}" for i in range(4)]
    backend = server.app.backend

    ok_pods = static_allocation_spark_pods("obs-app", 2)
    backend.add_pod(ok_pods[0])
    admitted = _post_predicate(port, ok_pods[0], names)
    assert admitted["NodeNames"], admitted

    big = static_allocation_spark_pods("obs-big", 99)[0]
    backend.add_pod(big)
    denied = _post_predicate(port, big, names)
    assert not denied["NodeNames"]

    # ---- /debug/decisions: the denied driver's record is explainable.
    status, _, body = _get(
        port, "/debug/decisions?app=obs-big&verdict=failure-*"
    )
    assert status == 200
    decisions = json.loads(body)["decisions"]
    assert len(decisions) == 1
    rec = decisions[0]
    assert rec["verdict"] == "failure-fit"
    assert set(rec["failed_nodes"]) == set(names)
    assert rec["queue_position"] is not None
    for phase in ("featurize_ms", "solve_ms"):
        assert rec["phases"].get(phase, -1) >= 0, rec["phases"]
    assert rec["solve"] and rec["solve"]["path"] in ("xla", "pallas")
    assert rec["solve"]["compile_cache_hit"] in (True, False)

    # Verdict filter + app filter behave.
    status, _, body = _get(port, "/debug/decisions?app=obs-app&role=driver")
    assert status == 200
    ok_recs = json.loads(body)["decisions"]
    assert ok_recs and ok_recs[0]["verdict"] == "success"
    assert ok_recs[0]["node"] == admitted["NodeNames"][0]

    # ---- /metrics JSON: solver telemetry series exist; lint the names.
    status, ctype, body = _get(port, "/metrics")
    assert status == 200 and "application/json" in ctype
    snap = json.loads(body)
    snap.pop("predicate_batcher", None)
    snap.pop("server_transport", None)  # stats surface, not a registry series
    snap.pop("server_ingest", None)  # ditto (ingest-lane stats surface)
    snap.pop("flight_recorder", None)  # ditto (ring stats surface)
    snap.pop("trace", None)  # ditto (trace-sink stats surface)
    assert any(
        name.startswith("foundry.spark.scheduler.solver.") for name in snap
    ), sorted(snap)
    compiles = snap.get("foundry.spark.scheduler.solver.jit.compiles")
    assert compiles and compiles[0]["value"] >= 1
    occupancy = snap.get("foundry.spark.scheduler.solver.bucket.occupancy")
    assert occupancy and occupancy[0]["count"] >= 1
    assert all(name.startswith(METRIC_PREFIX) for name in snap), [
        n for n in snap if not n.startswith(METRIC_PREFIX)
    ]

    # ---- /metrics Prometheus text: scraped with a text Accept header.
    status, ctype, body = _get(
        port, "/metrics", headers={"Accept": "text/plain"}
    )
    assert status == 200 and ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE foundry_spark_scheduler_requests counter" in text
    assert "foundry_spark_scheduler_solver_jit_compiles" in text
    assert 'quantile="0.99"' in text  # histogram p99 rides exposition
    # explicit format override wins over Accept
    status, ctype, _ = _get(
        port, "/metrics?format=json", headers={"Accept": "text/plain"}
    )
    assert status == 200 and "application/json" in ctype
    # q-values honored: a JSON-preferring client that merely TOLERATES
    # text keeps JSON; a real scraper's openmetrics preference gets text
    status, ctype, _ = _get(
        port, "/metrics",
        headers={"Accept": "application/json, text/plain;q=0.1"},
    )
    assert status == 200 and "application/json" in ctype
    status, ctype, _ = _get(
        port, "/metrics",
        headers={
            "Accept": (
                "application/openmetrics-text;version=1.0.0,"
                "text/plain;version=0.0.4;q=0.9"
            )
        },
    )
    assert status == 200 and ctype.startswith("text/plain")

    # ---- /debug/state: reservations + FIFO queue + fleet in one snapshot.
    status, _, body = _get(port, "/debug/state")
    assert status == 200
    state = json.loads(body)
    assert state["nodes"]["count"] == 4
    rr_names = {r["name"] for r in state["hard_reservations"]}
    assert "obs-app" in rr_names
    queue = {q["name"] for q in state["fifo_queue"]}
    assert big.name in queue  # denied driver still pending in FIFO order
    assert state["demands"], state  # denial created a demand
    assert state["flight_recorder"]["total_recorded"] >= 2


def test_debug_routes_stay_gated_without_flag():
    backend = InMemoryBackend()
    backend.add_node(new_node("n0"))
    app = build_scheduler_app(backend, InstallConfig(sync_writes=True))
    srv = SchedulerHTTPServer(app, MetricRegistry(), port=0)
    srv.start()
    try:
        for path in ("/debug/decisions", "/debug/state"):
            status, _, _ = _get(srv.port, path)
            assert status == 404, path
    finally:
        srv.stop()


def test_recorder_off_strips_the_surface():
    """flight_recorder: false builds no recorder and no solver telemetry —
    the bench's control configuration."""
    h = Harness(binpack_algo="tightly-pack", flight_recorder=False)
    assert h.app.recorder is None
    assert h.app.solver.telemetry is None
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("off-app", 1)
    assert h.schedule_app(pods, ["n0"])  # scheduling unaffected


def test_autoscaler_annotates_fulfilled_demand_on_the_denial():
    """demand->fulfilled transitions annotate the originating decision:
    the denied driver's record gains the scale-up latency once the
    in-process autoscaler provisions for its demand."""
    h = Harness(
        binpack_algo="tightly-pack",
        autoscaler_enabled=True,
        autoscaler_max_cluster_size=64,
    )
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("scale-app", 12)  # cannot fit 1 node
    r = h.schedule(pods[0], ["n0"])
    assert not r.ok
    rec = h.app.recorder.latest_for_app("namespace", "scale-app")
    assert rec is not None and rec.verdict == "failure-fit"
    assert rec.demand is None
    h.autoscaler.run_once()
    assert rec.demand is not None and rec.demand["latency_s"] >= 0.0
    # and the gang now fits on the provisioned nodes
    names = [n.name for n in h.backend.list_nodes()]
    assert h.schedule(pods[0], names).ok


def test_recorder_ring_is_bounded():
    from spark_scheduler_tpu.observability import FlightRecorder

    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.record(
            namespace="ns", pod_name=f"p{i}", app_id=f"a{i}",
            instance_group="ig", role="driver", verdict="success",
            node="n0",
        )
    stats = rec.stats()
    assert stats["size"] == 8 and stats["dropped"] == 12
    newest = rec.query(limit=100)
    assert len(newest) == 8
    assert newest[0]["pod_name"] == "p19" and newest[-1]["pod_name"] == "p12"


def test_decision_filters_instance_group_and_since_seq(server):
    """ISSUE 17 satellite: /debug/decisions grows app_id / instance_group /
    since_seq filters (incident triage: tail by last-seen seq)."""
    port = server.port
    backend = server.app.backend
    names = [f"n{i}" for i in range(4)]
    for i in range(3):
        pods = static_allocation_spark_pods(f"filt-{i}", 1)
        backend.add_pod(pods[0])
        assert _post_predicate(port, pods[0], names)["NodeNames"]

    # app_id aliases app
    status, _, body = _get(port, "/debug/decisions?app_id=filt-1")
    assert status == 200
    recs = json.loads(body)["decisions"]
    assert recs and all(r["app_id"] == "filt-1" for r in recs)

    # instance_group filter: everything here is in the default group
    status, _, body = _get(
        port, "/debug/decisions?instance_group=batch-medium-priority"
    )
    assert status == 200 and json.loads(body)["decisions"]
    status, _, body = _get(port, "/debug/decisions?instance_group=nope")
    assert status == 200 and json.loads(body)["decisions"] == []

    # since_seq keeps only NEWER records; polling with the max seq
    # returns nothing new
    status, _, body = _get(port, "/debug/decisions?limit=100")
    all_recs = json.loads(body)["decisions"]
    top = max(r["seq"] for r in all_recs)
    status, _, body = _get(port, f"/debug/decisions?since_seq={top - 1}")
    newer = json.loads(body)["decisions"]
    assert [r["seq"] for r in newer] == [top]
    status, _, body = _get(port, f"/debug/decisions?since_seq={top}")
    assert json.loads(body)["decisions"] == []
    status, _, _ = _get(port, "/debug/decisions?since_seq=bogus")
    assert status == 400


def test_recorder_dropped_exported_on_metrics(server):
    """ISSUE 17 satellite: ring-overflow drops ride /metrics as
    foundry.spark.scheduler.recorder.dropped (both formats)."""
    port = server.port
    status, ctype, body = _get(
        port, "/metrics", headers={"Accept": "text/plain"}
    )
    assert status == 200 and ctype.startswith("text/plain")
    assert "foundry_spark_scheduler_recorder_dropped" in body.decode()
    status, _, body = _get(port, "/metrics")
    snap = json.loads(body)
    assert snap["flight_recorder"]["dropped"] >= 0
    # and /debug/state carries the same ring stats
    status, _, body = _get(port, "/debug/state")
    assert json.loads(body)["flight_recorder"]["dropped"] >= 0


def test_debug_trace_route(tmp_path):
    """/debug/trace surfaces the trace sink's counters when a trace is
    being written, 404s when not, and stays gated without debug_routes."""
    backend = InMemoryBackend()
    backend.register_crd(DEMAND_CRD)
    backend.add_node(new_node("n0"))
    trace_path = str(tmp_path / "t.jsonl")
    app = build_scheduler_app(
        backend,
        InstallConfig(
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            debug_routes=True,
            trace_path=trace_path,
        ),
    )
    srv = SchedulerHTTPServer(
        app, MetricRegistry(), port=0, debug_routes=True,
        request_timeout_s=120.0,
    )
    srv.start()
    try:
        status, _, body = _get(srv.port, "/debug/trace")
        assert status == 200
        stats = json.loads(body)
        assert stats["path"] == trace_path
        assert stats["events"] >= 2  # header + bootstrap node
        assert stats["write_errors"] == 0
        # Prometheus side carries the sink counters too
        status, _, body = _get(
            srv.port, "/metrics", headers={"Accept": "text/plain"}
        )
        assert "foundry_spark_scheduler_trace_write_errors" in body.decode()
    finally:
        srv.stop()

    # no sink -> 404 even with debug routes on
    backend2 = InMemoryBackend()
    app2 = build_scheduler_app(backend2, InstallConfig(sync_writes=True))
    srv2 = SchedulerHTTPServer(
        app2, MetricRegistry(), port=0, debug_routes=True,
        request_timeout_s=120.0,
    )
    srv2.start()
    try:
        status, _, _ = _get(srv2.port, "/debug/trace")
        assert status == 404
    finally:
        srv2.stop()
