"""Device-slot failure recovery + degraded-mode serving (ISSUE 9).

Acceptance criteria pinned here:

  - 2-slot pool, one slot killed mid-burst: serving continues on the
    survivor with BYTE-IDENTICAL decisions (the re-dispatched partition
    re-solves from the host reconstruction the dead slot's base
    embodied), the dead slot is quarantined, and a later probe
    reinstates it;
  - ALL slots killed: the degraded policy engages — "greedy" keeps
    serving byte-identical decisions via the host fallback and recovers
    once a probe succeeds; "shed" raises DegradedUnavailableError
    carrying Retry-After;
  - the server reflects it: readiness stays 200-but-degraded under
    greedy, flips 503 under shed; /predicates sheds 503 with a
    Retry-After header; /debug/state carries quarantine + degraded
    state.

The conftest's 8-device virtual CPU mesh provides the pool slots.
"""

from __future__ import annotations

import http.client
import json

import numpy as np
import pytest

from spark_scheduler_tpu.core.solver import PlacementSolver, WindowRequest
from spark_scheduler_tpu.faults import (
    DegradedModeController,
    DegradedUnavailableError,
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
from spark_scheduler_tpu.models.resources import Resources

ONE = Resources.from_quantities("1", "1Gi")
TWO = Resources.from_quantities("2", "2Gi")


def _nodes(n):
    return [
        Node(
            name=f"n{i:03d}",
            allocatable=Resources.from_quantities(
                "8", "8Gi", "1", round_up=False
            ),
            labels={ZONE_LABEL: f"z{i % 2}"},
        )
        for i in range(n)
    ]


def _windows(rng, nodes, k, per, domains):
    """K windows of `per` requests, domains cycled per request so every
    window partitions across the pool (test_fused_dispatch idiom)."""
    windows = []
    r = 0
    for _ in range(k):
        reqs = []
        for _ in range(per):
            res = TWO if rng.random() < 0.3 else ONE
            dom = domains[r % len(domains)]
            reqs.append(
                WindowRequest(
                    rows=[(res, ONE, int(rng.integers(1, 4)), False)],
                    driver_candidate_names=dom,
                    domain_node_names=dom,
                )
            )
            r += 1
        windows.append(reqs)
    return windows


def _run(solver, nodes, batches, strategy="tightly-pack"):
    out = []
    for wins in batches:
        handles = []
        for w in wins:
            t = solver.build_tensors_pipelined(nodes, {}, {})
            handles.append(solver.pack_window_dispatch(strategy, t, w))
        for h in handles:
            out.extend(solver.pack_window_fetch(h))
    return out


def _fixture(seed=11, n_batches=3):
    rng = np.random.default_rng(seed)
    nodes = _nodes(16)
    half = [n.name for n in nodes[:8]], [n.name for n in nodes[8:]]
    batches = [_windows(rng, nodes, 1, 4, half) for _ in range(n_batches)]
    return nodes, batches


# -------------------------------------------------- one slot dies mid-burst


def test_slot_kill_mid_burst_byte_identical_on_survivor():
    nodes, batches = _fixture()
    baseline = _run(PlacementSolver(use_native=False), nodes, batches)

    pooled = PlacementSolver(use_native=False, device_pool=2)
    assert pooled.pool_size == 2
    # The 3rd partition solve dies (window 2's first part): tunnel drop
    # mid-burst, classified slot-fatal via DeviceFaultError.
    plan = FaultPlan(
        seed=0, name="slot-kill",
        specs=[FaultSpec(surface="device.dispatch", mode="error",
                         at=[2], limit=1)],
    )
    with FaultInjector(plan) as inj:
        inj.install_device()
        faulted = _run(pooled, nodes, batches)

    assert faulted == baseline, "recovered decisions diverged"
    health = pooled.device_health()
    assert health["healthy"] == 1 and len(health["quarantined"]) == 1
    assert pooled.redispatch_count >= 1

    # Probe-based reinstatement: the injector is gone, so a forced probe
    # brings the slot back; the next burst runs pooled again and still
    # matches the single-device truth.
    assert pooled.probe_quarantined(force=True) == 1
    assert pooled.device_health()["healthy"] == 2
    rng = np.random.default_rng(99)
    half = [n.name for n in nodes[:8]], [n.name for n in nodes[8:]]
    more = [_windows(rng, nodes, 1, 4, half)]
    again = _run(PlacementSolver(use_native=False), nodes, more)
    assert _run(pooled, nodes, more) == again


# ------------------------------------------------------- every slot dies


def _open_ended_dispatch_kill(start):
    """From device-event `start` on, EVERY worker-side dispatch fails —
    both slots die, and probes keep failing until the injector leaves."""
    return FaultPlan(
        seed=0, name="pool-down",
        specs=[FaultSpec(surface="device.dispatch", mode="partition",
                         start=start)],
    )


def test_all_slots_killed_greedy_fallback_byte_identical_then_recovers():
    nodes, batches = _fixture(seed=23, n_batches=4)
    baseline = _run(PlacementSolver(use_native=False), nodes, batches)

    pooled = PlacementSolver(use_native=False, device_pool=2)
    pooled.degraded = DegradedModeController(policy="greedy")
    # Window 1 (2 partition dispatch events) succeeds; everything after
    # fails: window 2 quarantines both slots and serves via the host
    # greedy fallback, windows 3-4 fall back at the dispatch gate.
    with FaultInjector(_open_ended_dispatch_kill(2)) as inj:
        inj.install_device()
        faulted = _run(pooled, nodes, batches)

    assert faulted == baseline, "degraded decisions diverged"
    health = pooled.device_health()
    assert health["healthy"] == 0 and len(health["quarantined"]) == 2
    snap = pooled.degraded.snapshot()
    assert snap["active"] and snap["fallback_decisions"] > 0

    # Probes succeed once the fault plan is gone: slots reinstate,
    # degraded clears, and the pool serves again byte-identically.
    assert pooled.probe_quarantined(force=True) == 2
    assert not pooled.degraded.active
    rng = np.random.default_rng(7)
    half = [n.name for n in nodes[:8]], [n.name for n in nodes[8:]]
    more = [_windows(rng, nodes, 1, 4, half)]
    assert _run(pooled, nodes, more) == _run(
        PlacementSolver(use_native=False), nodes, more
    )


def test_all_slots_killed_shed_policy_raises_retry_after():
    nodes, batches = _fixture(seed=31, n_batches=1)
    pooled = PlacementSolver(use_native=False, device_pool=2)
    pooled.degraded = DegradedModeController(
        policy="shed", retry_after_s=7.0
    )
    with FaultInjector(_open_ended_dispatch_kill(0)) as inj:
        inj.install_device()
        with pytest.raises(DegradedUnavailableError) as ei:
            _run(pooled, nodes, batches)
    assert ei.value.retry_after_s == 7.0
    snap = pooled.degraded.snapshot()
    assert snap["active"] and snap["shed_requests"] >= 1


# ------------------------------------------------------------ server level


def _boot_server(degraded_mode):
    from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )

    backend = InMemoryBackend()
    for i in range(6):
        backend.add_node(new_node(f"srv-n{i}", zone=f"zone{i % 2}"))
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            degraded_mode=degraded_mode,
            degraded_retry_after_s=9.0,
            debug_routes=True,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    server = SchedulerHTTPServer(
        app, registry, host="127.0.0.1", port=0, debug_routes=True,
        request_timeout_s=60.0,
    )
    server.start()
    return backend, app, server


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, dict(r.getheaders()), body


def _predicate(port, backend, app_id):
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )

    pod = static_allocation_spark_pods(app_id, 1)[0]
    backend.add_pod(pod)
    payload = json.dumps(
        {
            "Pod": pod_to_k8s(pod),
            "NodeNames": [n.name for n in backend.list_nodes()],
        }
    )
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(
        "POST", "/predicates", body=payload,
        headers={"Content-Type": "application/json"},
    )
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, dict(r.getheaders()), body


def test_server_greedy_degraded_keeps_serving_and_reports():
    backend, app, server = _boot_server("greedy")
    try:
        plan = FaultPlan(
            seed=0, name="server-down",
            specs=[FaultSpec(surface="device.h2d", mode="partition",
                             start=0)],
        )
        with FaultInjector(plan) as inj:
            inj.install_device()
            status, _, body = _predicate(server.port, backend, "deg-app")
            assert status == 200
            out = json.loads(body)
            assert out.get("NodeNames"), out  # fallback still decides
            status, _, body = _get(server.port, "/status/readiness")
            assert status == 200
            ready = json.loads(body)
            assert ready["degraded"] and ready["policy"] == "greedy"
            status, _, body = _get(server.port, "/debug/state")
            assert status == 200
            faults = json.loads(body)["faults"]
            assert faults["degraded"]["active"]
        # Fault plan gone: the next served window clears degraded.
        status, _, body = _predicate(server.port, backend, "deg-app-2")
        assert status == 200
        status, _, body = _get(server.port, "/status/readiness")
        assert status == 200
        assert "degraded" not in json.loads(body)
    finally:
        server.stop()


def test_server_shed_degraded_readiness_flips_503_under_ha():
    """Degraded mode composes with HA readiness: a SERVING leader that
    sheds every predicate must answer readiness 503 too — the HA branch
    answering 200 {ready, role} first would keep the load balancer
    routing to a replica that 503s every request."""
    from spark_scheduler_tpu.metrics import MetricRegistry, SchedulerMetrics
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
    )

    class _ServingHA:
        role = "leader"

        def is_serving(self):
            return True

        def state(self):
            return {"role": self.role}

        def start(self):
            pass

        def stop(self):
            pass

    backend = InMemoryBackend()
    for i in range(4):
        backend.add_node(new_node(f"ha-n{i}", zone=f"zone{i % 2}"))
    registry = MetricRegistry()
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
            degraded_mode="shed", degraded_retry_after_s=9.0,
            debug_routes=True,
        ),
        metrics=SchedulerMetrics(registry, INSTANCE_GROUP_LABEL),
    )
    server = SchedulerHTTPServer(
        app, registry, host="127.0.0.1", port=0, debug_routes=True,
        request_timeout_s=60.0, ha=_ServingHA(),
    )
    server.start()
    try:
        # Healthy serving leader: 200 with the role.
        status, _, body = _get(server.port, "/status/readiness")
        assert status == 200
        out = json.loads(body)
        assert out["ready"] and out["role"] == "leader"
        plan = FaultPlan(
            seed=0, name="ha-shed",
            specs=[FaultSpec(surface="device.h2d", mode="partition",
                             start=0)],
        )
        with FaultInjector(plan) as inj:
            inj.install_device()
            status, headers, _ = _predicate(server.port, backend, "ha-shed-app")
            assert status == 503
            status, _, body = _get(server.port, "/status/readiness")
            assert status == 503
            out = json.loads(body)
            assert out["degraded"] and out["policy"] == "shed"
            assert out["role"] == "leader"  # HA fields still present
    finally:
        server.stop()


def test_server_shed_degraded_503_retry_after_and_readiness():
    backend, app, server = _boot_server("shed")
    try:
        plan = FaultPlan(
            seed=0, name="server-shed",
            specs=[FaultSpec(surface="device.h2d", mode="partition",
                             start=0)],
        )
        with FaultInjector(plan) as inj:
            inj.install_device()
            status, headers, body = _predicate(
                server.port, backend, "shed-app"
            )
            assert status == 503
            assert headers.get("Retry-After") == "9"
            assert json.loads(body)["degraded"] is True
            status, _, body = _get(server.port, "/status/readiness")
            assert status == 503
            assert json.loads(body)["degraded"] is True
    finally:
        server.stop()
