"""Elastic autoscaler subsystem: provisioner packing math, zone affinity,
the max-cluster-size cap, the reservation-aware two-phase drainer, runtime
reloads of the autoscaler knobs, and the full end-to-end elastic scenario
(demand -> provision -> place -> drain)."""

from __future__ import annotations

import pytest

from spark_scheduler_tpu.autoscaler import (
    PROVISIONED_BY_LABEL,
    PROVISIONER_NAME,
    NodeProvisioner,
    ScaleDownDrainer,
)
from spark_scheduler_tpu.autoscaler.provisioner import nodes_needed
from spark_scheduler_tpu.models.demands import (
    PHASE_CANNOT_FULFILL,
    PHASE_FULFILLED,
    DemandUnit,
)
from spark_scheduler_tpu.models.kube import ZONE_LABEL
from spark_scheduler_tpu.models.reservations import Reservation
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.testing.harness import (
    INSTANCE_GROUP_LABEL,
    Harness,
    new_node,
    static_allocation_spark_pods,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _res(cpu: str, mem: str, gpu: str = "0") -> Resources:
    return Resources.from_quantities(cpu, mem, gpu, round_up=False)


def elastic_harness(clock=None, **kw):
    kw.setdefault("autoscaler_idle_ttl_s", 60.0)
    kw.setdefault("autoscaler_max_cluster_size", 100)
    return Harness(
        autoscaler_enabled=True, clock=clock or FakeClock(), **kw
    )


# -- provisioner packing math ------------------------------------------------


def test_nodes_needed_first_fit():
    template = _res("8", "8Gi", "1")
    # 1 driver (1cpu/1Gi) + 15 executors (1cpu/1Gi) = 16 cpu -> 2 nodes.
    units = [
        DemandUnit(resources=_res("1", "1Gi"), count=1),
        DemandUnit(resources=_res("1", "1Gi"), count=15),
    ]
    assert nodes_needed(units, template) == 2
    # memory-bound: 3 units of 4Gi -> 2 per node -> 2 nodes
    assert nodes_needed([DemandUnit(_res("1", "4Gi"), 3)], template) == 2


def test_nodes_needed_impossible_unit():
    template = _res("8", "8Gi", "1")
    # A 16-cpu unit can never fit an 8-cpu template node.
    assert nodes_needed([DemandUnit(_res("16", "1Gi"), 1)], template) is None


def test_provisioner_zone_pin_and_labels():
    from spark_scheduler_tpu.store.backend import InMemoryBackend

    backend = InMemoryBackend()
    prov = NodeProvisioner(
        backend, INSTANCE_GROUP_LABEL, _res("8", "8Gi", "1"),
        zones=["za", "zb"],
    )
    pinned = prov.provision(3, "group-x", "zb")
    assert all(n.zone == "zb" for n in pinned)
    assert all(
        n.labels[PROVISIONED_BY_LABEL] == PROVISIONER_NAME
        and n.labels[INSTANCE_GROUP_LABEL] == "group-x"
        for n in pinned
    )
    spread = prov.provision(4, "group-x", None)
    assert {n.zone for n in spread} == {"za", "zb"}  # round-robin spread
    assert len(backend.list_nodes()) == 7


# -- controller phase decisions ----------------------------------------------


def test_demand_fulfilled_honors_demand_zone():
    """v1alpha2 zone affinity: a demand pinned to a zone gets every node
    in that zone and the phase reports it as fulfilled_zone."""
    h = elastic_harness(autoscaler_zones=["zone1", "zone2", "zone3"])
    driver = static_allocation_spark_pods("app-z", 2)[0]
    h.add_pods(driver)
    demand = h.app.demand_manager.create_demand_for_executor(
        driver, _res("1", "1Gi"), zone="zone2"
    )
    assert demand is not None
    h.autoscaler.run_once()
    d = h.backend.get("demands", demand.namespace, demand.name)
    assert d.status.phase == PHASE_FULFILLED
    assert d.status.fulfilled_zone == "zone2"
    added = [
        n for n in h.backend.list_nodes()
        if n.labels.get(PROVISIONED_BY_LABEL) == PROVISIONER_NAME
    ]
    assert added and all(n.labels[ZONE_LABEL] == "zone2" for n in added)


def test_cap_marks_cannot_fulfill():
    h = elastic_harness(autoscaler_max_cluster_size=2)
    h.add_nodes(new_node("n0"), new_node("n1"))  # already at the cap
    pods = static_allocation_spark_pods("app-cap", 30)
    r = h.schedule(pods[0], ["n0", "n1"])
    assert not r.ok
    summary = h.autoscaler.run_once()
    assert summary["unfulfillable"] == 1 and summary["nodes_added"] == 0
    # Phase lives on the BACKEND object (the autoscaler writes like the
    # external one would); the owner cache only fast-forwards rv on watch.
    d = h.backend.list("demands")[0]
    assert d.status.phase == PHASE_CANNOT_FULFILL
    assert h.autoscaler.metrics.counts()["demands_unfulfillable"] == 1


def test_oldest_first_partial_fulfillment_under_cap():
    """Two pending demands, cap headroom for one: the older fulfills, the
    newer goes cannot-fulfill."""
    clock = FakeClock()
    h = elastic_harness(clock=clock, autoscaler_max_cluster_size=3)
    h.add_nodes(new_node("n0"))
    old_driver = static_allocation_spark_pods("app-old", 10)[0]
    h.add_pods(old_driver)
    assert not h.schedule(old_driver, ["n0"]).ok
    clock.advance(5.0)
    new_driver = static_allocation_spark_pods("app-new", 10)[0]
    h.add_pods(new_driver)
    assert not h.schedule(new_driver, ["n0"]).ok
    h.autoscaler.run_once()
    phases = {
        d.name: d.status.phase for d in h.backend.list("demands")
    }
    assert phases["demand-app-old-driver"] == PHASE_FULFILLED
    assert phases["demand-app-new-driver"] == PHASE_CANNOT_FULFILL


# -- drainer -----------------------------------------------------------------


def _drainer_rig(clock, ttl=60.0):
    h = elastic_harness(clock=clock, autoscaler_idle_ttl_s=ttl)
    prov = h.autoscaler.provisioner
    nodes = prov.provision(2, "batch-medium-priority", None)
    return h, nodes


def test_drainer_two_phase_and_ttl():
    clock = FakeClock()
    h, nodes = _drainer_rig(clock)
    drainer = h.autoscaler.drainer
    assert drainer.run_once() == []  # idle clock starts now
    clock.advance(59.0)
    assert drainer.run_once() == []  # under TTL: nothing, not even cordon
    assert not any(n.unschedulable for n in h.backend.list_nodes())
    clock.advance(2.0)
    assert drainer.run_once() == []  # phase 1: cordon only
    assert all(n.unschedulable for n in h.backend.list_nodes())
    assert sorted(drainer.run_once()) == sorted(n.name for n in nodes)
    assert h.backend.list_nodes() == []


def test_drainer_never_touches_reserved_nodes():
    """Hard reservation on one provisioned node, soft on the other: neither
    may be cordoned or drained, whatever the idle age."""
    clock = FakeClock()
    h, nodes = _drainer_rig(clock)
    hard, soft = nodes
    # Hard slot via the reservation cache (reservation_manager truth).
    from spark_scheduler_tpu.models.reservations import (
        ResourceReservation,
        ReservationSpec,
    )

    h.app.rr_cache.create(
        ResourceReservation(
            name="app-hard",
            namespace="namespace",
            spec=ReservationSpec(
                reservations={"driver": Reservation(hard.name, _res("1", "1Gi"))}
            ),
        )
    )
    h.app.soft_store.create_soft_reservation_if_not_exists("app-soft")
    h.app.soft_store.add_reservation_for_pod(
        "app-soft", "exec-1", Reservation(soft.name, _res("1", "1Gi"))
    )
    clock.advance(1e6)
    for _ in range(3):
        assert h.autoscaler.drainer.run_once() == []
    live = {n.name: n for n in h.backend.list_nodes()}
    assert set(live) == {hard.name, soft.name}
    assert not any(n.unschedulable for n in live.values())


def test_drainer_uncordons_when_node_becomes_busy():
    clock = FakeClock()
    h, nodes = _drainer_rig(clock)
    drainer = h.autoscaler.drainer
    drainer.run_once()  # idle tracking starts here
    clock.advance(61.0)
    drainer.run_once()  # cordons both
    target = nodes[0].name
    h.app.soft_store.create_soft_reservation_if_not_exists("app-race")
    h.app.soft_store.add_reservation_for_pod(
        "app-race", "exec-1", Reservation(target, _res("1", "1Gi"))
    )
    drained = drainer.run_once()  # busy one uncordoned, idle one drained
    assert drained == [n.name for n in nodes if n.name != target]
    survivor = h.backend.get_node(target)
    assert survivor is not None and not survivor.unschedulable


def test_drainer_readopts_cordoned_nodes_after_restart():
    """A provisioned node cordoned by a PRE-RESTART drain pass (durable
    backends persist nodes; the drainer's phase memory dies with the
    process) must not leak forever: a fresh drainer re-adopts it and
    removes it only after a FULL fresh TTL — never instantly."""
    clock = FakeClock()
    h, nodes = _drainer_rig(clock)
    drainer = h.autoscaler.drainer
    drainer.run_once()
    clock.advance(61.0)
    drainer.run_once()  # phase 1: both cordoned... then the process dies
    assert all(n.unschedulable for n in h.backend.list_nodes())
    fresh = ScaleDownDrainer(
        h.backend, h.app.rr_cache, h.app.soft_store,
        idle_ttl_s=60.0, clock=clock,
    )
    assert fresh.run_once() == []  # re-adopted, fresh TTL starts — no delete
    clock.advance(59.0)
    assert fresh.run_once() == []  # still under the fresh TTL
    clock.advance(2.0)
    assert fresh.run_once() == []  # TTL crossed: marked for drain
    assert sorted(fresh.run_once()) == sorted(n.name for n in nodes)
    assert h.backend.list_nodes() == []


def test_drainer_ignores_static_fleet():
    clock = FakeClock()
    h = elastic_harness(clock=clock, autoscaler_idle_ttl_s=10.0)
    h.add_nodes(new_node("static-0"))
    clock.advance(1e6)
    for _ in range(3):
        assert h.autoscaler.drainer.run_once() == []
    assert h.backend.get_node("static-0") is not None


# -- runtime config reload ---------------------------------------------------


def test_runtime_reload_of_autoscaler_knobs(tmp_path):
    from spark_scheduler_tpu.server.runtime import RuntimeConfigManager

    h = elastic_harness()
    path = tmp_path / "runtime.yml"
    path.write_text(
        "autoscaler:\n  idle-ttl: 5m\n  max-cluster-size: 7\n"
    )
    mgr = RuntimeConfigManager(h.app, str(path))
    assert mgr.check_now()
    assert h.autoscaler.drainer.idle_ttl_s == 300.0
    assert h.autoscaler.max_cluster_size == 7


# -- end to end --------------------------------------------------------------


@pytest.mark.parametrize("binpack", ["tightly-pack", "single-az-tightly-pack"])
def test_end_to_end_elastic_scenario(binpack):
    """The acceptance scenario: a gang that cannot fit creates demands, the
    autoscaler provisions nodes, the solver places the gang on them, idle
    nodes later drain — and no reserved node is ever drained."""
    clock = FakeClock()
    h = Harness(
        binpack_algo=binpack,
        autoscaler_enabled=True,
        autoscaler_idle_ttl_s=60.0,
        autoscaler_max_cluster_size=50,
        autoscaler_zones=["zone1", "zone2"],
        clock=clock,
    )
    h.add_nodes(new_node("n0"))
    pods = static_allocation_spark_pods("app-e2e", 20)
    assert not h.schedule(pods[0], ["n0"]).ok
    summary = h.autoscaler.run_once()
    assert summary["fulfilled"] == 1 and summary["nodes_added"] >= 2
    names = [n.name for n in h.backend.list_nodes()]
    for p in pods:
        assert h.schedule(p, names).ok, p.name
    assert h.demands() == []  # deleted on successful schedule
    # Reserved nodes never drain, however old.
    clock.advance(1e5)
    for _ in range(3):
        h.autoscaler.run_once()
    reserved = h.autoscaler.drainer.reserved_node_names()
    assert reserved and reserved <= {n.name for n in h.backend.list_nodes()}
    # Teardown -> nodes idle past TTL -> cordon, then drain.
    for p in pods:
        cur = h.backend.get("pods", p.namespace, p.name)
        if cur is not None:
            h.backend.delete_pod(cur)
    rr = h.get_reservation("namespace", "app-e2e")
    h.app.rr_cache.delete(rr.namespace, rr.name)
    h.autoscaler.run_once()  # nodes observed idle: TTL clock starts
    clock.advance(61.0)
    h.autoscaler.run_once()  # cordon pass
    drained = h.autoscaler.run_once()["drained"]
    assert drained  # provisioned capacity handed back
    assert h.backend.get_node("n0") is not None  # static fleet intact
    counts = h.autoscaler.metrics.counts()
    assert counts["demands_fulfilled"] == 1
    assert counts["nodes_drained"] == len(drained)
    assert h.autoscaler.metrics.scaleup_latency_samples()
