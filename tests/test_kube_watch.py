"""List+watch ingestion tests (the informer slot, SURVEY.md L3).

Covers:
  - basic list+watch: apiserver mutations propagate into the backend;
  - resourceVersion resume: watch-window re-arms do NOT relist;
  - 410 Gone: expired history forces a relist that converges;
  - e2e: a scheduler served over HTTP learns nodes/pods exclusively from a
    fake apiserver watch stream and gang-schedules against them
    (cmd/server.go:111-147 + cmd/endpoints.go:28-42 end to end).
"""

from __future__ import annotations

import http.client
import json
import time

import pytest

from spark_scheduler_tpu.kube.apiserver import FakeKubeAPIServer
from spark_scheduler_tpu.kube.reflector import (
    INFORMER_DELAY_METRIC,
    BackendSyncTarget,
    GoneError,
    KubeIngestion,
    Reflector,
)
from spark_scheduler_tpu.metrics.registry import MetricRegistry
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.server.kube_io import node_from_k8s
from spark_scheduler_tpu.store.backend import InMemoryBackend


def k8s_node(name: str, cpu: str = "8", memory: str = "8Gi", gpu: str = "1") -> dict:
    return {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": {
            "name": name,
            "labels": {
                "failure-domain.beta.kubernetes.io/zone": "zone1",
                "resource_channel": "batch-medium-priority",
            },
        },
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory, "nvidia.com/gpu": gpu},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def k8s_spark_pod(
    name: str,
    app_id: str,
    role: str,
    executors: int = 2,
    namespace: str = "ns",
    created: float | None = None,
) -> dict:
    annotations = {}
    if role == "driver":
        annotations = {
            "spark-driver-cpu": "1",
            "spark-driver-mem": "1Gi",
            "spark-executor-cpu": "1",
            "spark-executor-mem": "1Gi",
            "spark-executor-count": str(executors),
        }
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"spark-role": role, "spark-app-id": app_id},
            "annotations": annotations,
            "creationTimestamp": created if created is not None else time.time(),
        },
        "spec": {
            "schedulerName": "spark-scheduler",
            "nodeSelector": {"resource_channel": "batch-medium-priority"},
            "containers": [
                {
                    "name": "main",
                    "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
                }
            ],
        },
        "status": {"phase": "Pending"},
    }


def wait_until(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def apiserver():
    server = FakeKubeAPIServer()
    server.start()
    yield server
    server.stop()


class TestListWatchBasic:
    def test_mutations_propagate(self, apiserver):
        apiserver.create("nodes", k8s_node("n1"))
        backend = InMemoryBackend()
        registry = MetricRegistry()
        ingestion = KubeIngestion(
            backend, apiserver.base_url, metrics=registry, watch_timeout_s=5.0
        )
        ingestion.start()
        try:
            assert ingestion.wait_synced(timeout=5.0)
            assert backend.get_node("n1") is not None  # listed

            apiserver.create("nodes", k8s_node("n2"))
            assert wait_until(lambda: backend.get_node("n2") is not None)

            apiserver.create("pods", k8s_spark_pod("app-driver", "app", "driver"))
            assert wait_until(
                lambda: backend.get("pods", "ns", "app-driver") is not None
            )
            # informer-delay histogram recorded for the watch-added pod
            snap = registry.snapshot()
            assert snap[INFORMER_DELAY_METRIC][0]["count"] >= 1

            # MODIFIED: kube-scheduler binds the pod
            raw = apiserver.collections["pods"].objects[("ns", "app-driver")]
            bound = json.loads(json.dumps(raw))
            bound["spec"]["nodeName"] = "n1"
            bound["status"]["phase"] = "Running"
            apiserver.update("pods", bound)
            assert wait_until(
                lambda: backend.get("pods", "ns", "app-driver").node_name == "n1"
            )

            apiserver.delete("pods", "ns", "app-driver")
            assert wait_until(lambda: backend.get("pods", "ns", "app-driver") is None)
        finally:
            ingestion.stop()

    def test_rest_write_paths(self, apiserver):
        """The apiserver's own REST CRUD (what kubelet/kube-scheduler would
        use) produces watch events identical to in-process mutations."""
        conn = http.client.HTTPConnection("127.0.0.1", apiserver.port, timeout=5)

        def call(method, path, payload=None):
            conn.request(
                method, path, body=json.dumps(payload).encode() if payload else None
            )
            resp = conn.getresponse()
            resp.read()  # drain so the persistent connection can be reused
            return resp.status

        assert call("POST", "/api/v1/nodes", k8s_node("n1")) == 201
        # conflict on duplicate create
        assert call("POST", "/api/v1/nodes", k8s_node("n1")) == 409
        # update with stale rv conflicts
        stale = k8s_node("n1")
        stale["metadata"]["resourceVersion"] = "999"
        assert call("PUT", "/api/v1/nodes/n1", stale) == 409
        # namespaced pod create + delete
        pod = k8s_spark_pod("p1", "app", "executor")
        assert call("POST", "/api/v1/namespaces/ns/pods", pod) == 201
        assert call("DELETE", "/api/v1/namespaces/ns/pods/p1") == 200
        conn.close()
        history = [(etype, obj["metadata"]["name"]) for _, res, etype, obj in apiserver._history if res == "pods"]
        assert history == [("ADDED", "p1"), ("DELETED", "p1")]


class TestResume:
    def test_watch_window_rearm_does_not_relist(self, apiserver):
        apiserver.create("nodes", k8s_node("n1"))
        backend = InMemoryBackend()
        reflector = Reflector(
            apiserver.base_url,
            "/api/v1/nodes",
            node_from_k8s,
            BackendSyncTarget(backend, "nodes"),
            watch_timeout_s=0.3,  # force several window re-arms
        )
        reflector.start()
        try:
            assert reflector.wait_synced(timeout=5.0)
            time.sleep(1.0)  # at least 2 watch windows elapse
            apiserver.create("nodes", k8s_node("n2"))
            assert wait_until(lambda: backend.get_node("n2") is not None)
            # resumed from resourceVersion across window re-arms: one LIST only
            assert reflector.relist_count == 1
            assert reflector.last_resource_version == apiserver.current_rv()
        finally:
            reflector.stop()

    def test_expired_history_emits_410(self, apiserver):
        """Protocol level: watching from an rv older than the replay window
        yields an ERROR 410 event (the etcd-compaction contract)."""
        small = FakeKubeAPIServer(history_limit=3)
        small.start()
        try:
            for i in range(10):
                small.create("nodes", k8s_node(f"n{i}"))
            conn = http.client.HTTPConnection("127.0.0.1", small.port, timeout=5)
            conn.request(
                "GET", "/api/v1/nodes?watch=true&resourceVersion=1&timeoutSeconds=2"
            )
            resp = conn.getresponse()
            event = json.loads(resp.readline())
            assert event["type"] == "ERROR"
            assert event["object"]["code"] == 410
            conn.close()
        finally:
            small.stop()

    def test_mid_stream_pruning_forces_relist(self, apiserver):
        """Events pruned while a watcher is connected must NOT be silently
        skipped: the server errors the watch (410) and the reflector relists
        and converges (real-apiserver watch-expiry behavior)."""
        small = FakeKubeAPIServer(history_limit=3)
        small.start()
        try:
            small.create("nodes", k8s_node("seed"))
            backend = InMemoryBackend()
            reflector = Reflector(
                small.base_url,
                "/api/v1/nodes",
                node_from_k8s,
                BackendSyncTarget(backend, "nodes"),
                watch_timeout_s=5.0,
            )
            reflector.start()
            try:
                assert reflector.wait_synced(timeout=5.0)
                # One atomic burst larger than the history window: the
                # connected watcher cannot interleave, so its next scan sees
                # pruned history and must take the 410 path.
                small.create_many(
                    "nodes", [k8s_node(f"burst{i}") for i in range(6)]
                )
                assert wait_until(
                    lambda: len(backend.list_nodes()) == 7, timeout=5.0
                )
                assert reflector.relist_count >= 2
            finally:
                reflector.stop()
        finally:
            small.stop()

    def test_gone_triggers_relist_and_converges(self, apiserver):
        small = FakeKubeAPIServer(history_limit=3)
        small.start()
        try:
            for i in range(3):
                small.create("nodes", k8s_node(f"seed{i}"))
            backend = InMemoryBackend()
            reflector = Reflector(
                small.base_url,
                "/api/v1/nodes",
                node_from_k8s,
                BackendSyncTarget(backend, "nodes"),
                watch_timeout_s=5.0,
            )
            # Simulate a reflector that fell behind: list, then miss a burst
            # of events larger than the server's replay window.
            rv = reflector._list()
            reflector.last_resource_version = rv
            for i in range(6):
                small.create("nodes", k8s_node(f"burst{i}"))
            with pytest.raises(GoneError):
                reflector._watch_once()
            # The run loop recovers by relisting; start it and converge.
            reflector.start()
            assert wait_until(
                lambda: len(backend.list_nodes()) == 9, timeout=5.0
            )
            assert reflector.relist_count >= 2
            reflector.stop()
        finally:
            small.stop()


class TestSecureAPIServer:
    def test_reflector_over_tls_with_bearer_token(self, tmp_path):
        """The in-cluster client shape: HTTPS apiserver + serviceaccount CA
        + bearer token read from a (rotatable) file."""
        import subprocess

        cert, key = str(tmp_path / "api.crt"), str(tmp_path / "api.key")
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
                "-keyout", key, "-out", cert, "-days", "1",
                "-subj", "/CN=127.0.0.1",
                "-addext", "subjectAltName=IP:127.0.0.1",
            ],
            check=True,
            capture_output=True,
        )
        token_file = tmp_path / "token"
        token_file.write_text("sa-token-1\n")
        api = FakeKubeAPIServer(
            cert_file=cert, key_file=key, required_token="sa-token-1"
        )
        api.start()
        try:
            api.create("nodes", k8s_node("n1"))
            backend = InMemoryBackend()
            ingestion = KubeIngestion(
                backend,
                api.base_url,
                watch_timeout_s=5.0,
                ca_file=cert,
                token_file=str(token_file),
            )
            ingestion.start()
            try:
                assert ingestion.wait_synced(timeout=5.0)
                api.create("nodes", k8s_node("n2"))
                assert wait_until(lambda: backend.get_node("n2") is not None)
            finally:
                ingestion.stop()

            # wrong token is rejected outright
            bad = Reflector(
                api.base_url,
                "/api/v1/nodes",
                node_from_k8s,
                BackendSyncTarget(InMemoryBackend(), "nodes"),
                ca_file=cert,
            )
            import http.client as hc

            with pytest.raises(hc.HTTPException):
                bad._list()
        finally:
            api.stop()


class TestEndToEnd:
    def test_scheduler_served_from_watch_stream(self, apiserver):
        """Full loop: cluster state arrives ONLY via the watch stream; gang
        scheduling works over HTTP; executor lands on its reserved node."""
        from spark_scheduler_tpu.server.http import SchedulerHTTPServer

        for i in range(3):
            apiserver.create("nodes", k8s_node(f"n{i}"))
        backend = InMemoryBackend()
        app = build_scheduler_app(
            backend,
            InstallConfig(sync_writes=True, kube_api_url=apiserver.base_url),
        )
        server = SchedulerHTTPServer(app, host="127.0.0.1", port=0)
        server.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
            # readiness flips once ingestion syncs (WaitForCacheSync analog)
            assert wait_until(lambda: server.ready.is_set(), timeout=5.0)

            driver = k8s_spark_pod("app1-driver", "app1", "driver", executors=2)
            apiserver.create("pods", driver)
            assert wait_until(
                lambda: backend.get("pods", "ns", "app1-driver") is not None
            )

            args = {"Pod": driver, "NodeNames": ["n0", "n1", "n2"]}
            conn.request("POST", "/predicates", body=json.dumps(args).encode())
            resp = json.loads(conn.getresponse().read())
            assert resp["NodeNames"], resp
            driver_node = resp["NodeNames"][0]

            # kube-scheduler binds the driver through the apiserver; the
            # watch stream carries the update back into the backend.
            bound = json.loads(json.dumps(driver))
            bound["spec"]["nodeName"] = driver_node
            bound["status"]["phase"] = "Running"
            apiserver.update("pods", bound)
            assert wait_until(
                lambda: backend.get("pods", "ns", "app1-driver").node_name == driver_node
            )

            # executor arrives via watch, gets the reserved node
            executor = k8s_spark_pod("app1-exec-1", "app1", "executor")
            apiserver.create("pods", executor)
            assert wait_until(
                lambda: backend.get("pods", "ns", "app1-exec-1") is not None
            )
            args = {"Pod": executor, "NodeNames": ["n0", "n1", "n2"]}
            conn.request("POST", "/predicates", body=json.dumps(args).encode())
            resp = json.loads(conn.getresponse().read())
            assert resp["NodeNames"], resp

            # reservations recorded for the gang
            rrs = backend.list("resourcereservations")
            assert len(rrs) == 1
            conn.close()
        finally:
            server.stop()
