"""Greedy test oracle — promoted into the package (ISSUE 9).

The implementation now lives in spark_scheduler_tpu/core/greedy.py so
degraded-mode serving (core/fallback.py) can reuse the reference-literal
packing semantics; this module keeps the historical import path for the
golden parity suites.
"""

from spark_scheduler_tpu.core.greedy import (  # noqa: F401
    GREEDY_FILLS,
    INF,
    _ReservedMap,
    greedy_avg_efficiency,
    greedy_capacity,
    greedy_distribute,
    greedy_fits,
    greedy_minimal_fragmentation,
    greedy_priority_order,
    greedy_single_az_bin_pack,
    greedy_spark_bin_pack,
    greedy_strategy_pack,
    greedy_tightly,
)
