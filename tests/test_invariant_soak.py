"""Randomized invariant soak on the CPU/XLA window path — the engine
lives in spark_scheduler_tpu/testing/soak.py (shared with the bench's
on-silicon soak); see its docstring for the op mix and the four
invariants. Step count: SOAK_STEPS env (default 2_000 so the full suite
stays fast; CI's dedicated soak job runs SOAK_STEPS=10000 — every op
counts).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from spark_scheduler_tpu.testing.soak import Soak

STEPS = int(os.environ.get("SOAK_STEPS", "2000"))
# Roster size of the soak family (ISSUE 11): the default stays tiny for
# tier-1; the scale-tier CI leg and out-of-band million-node runs raise it
# (SOAK_NODES=1000000 SOAK_STEPS=60 is the 1M family).
NODES = int(os.environ.get("SOAK_NODES", "12"))


@pytest.mark.parametrize(
    "strategy",
    ["tightly-pack", "az-aware-tightly-pack", "single-az-tightly-pack"],
)
def test_invariant_soak(strategy):
    """Seeded soak across the three strategy families (plain fill,
    az-aware wrapper, single-AZ wrapper — the zone-restricted executor
    reschedule path only runs under single-az). The XLA scan serves all
    of them here on CPU; the same programs run in-kernel on TPU (the
    bench's on-silicon soak). STEPS ops total, invariants swept every
    soak.CHECK_EVERY."""
    rng = np.random.default_rng(20260731)
    soak = Soak(rng, strategy, n_nodes=NODES)
    # Split the budget across the matrix so the default CI run totals
    # ~SOAK_STEPS ops.
    soak.run(STEPS // 3)
    assert soak.app_seq > 0 and soak.op_counts, soak.op_counts
