"""Fleet-fused device dispatch (ISSUE 20): the FleetDispatchCoordinator
stacks CONCURRENT per-cluster serving windows into one device launch.

The bar is byte-identity: stacking is a transport optimization, never a
semantic one. Every test here drives real fleet traffic with the
coordinator on and holds `verify_cluster_equivalence` — each cluster's
decision stream and durable reservation state must replay byte-identical
on a standalone (unstacked) stack — plus the structural facts: windows
actually stack when clusters are concurrent, stragglers fall back per
cluster without blocking, mixed shapes split into different pad buckets,
and a cluster killed mid-gather resolves via the forced fallback while
the survivors' stack flushes clean.
"""

import threading
import time

import numpy as np
import pytest

from spark_scheduler_tpu.fleet import (
    FleetDispatchCoordinator,
    FleetFacade,
    verify_cluster_equivalence,
)
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.testing.harness import (
    INSTANCE_GROUP_LABEL,
    new_node,
    static_allocation_spark_pods,
)


def _config(**kw):
    return InstallConfig(
        fifo=True,
        sync_writes=True,
        instance_group_label=INSTANCE_GROUP_LABEL,
        **kw,
    )


def _fleet(n, stack_ms, nodes_per_cluster=2, **cfg_kw):
    f = FleetFacade(
        n, _config(**cfg_kw), record_ops=True, stack_window_ms=stack_ms
    )
    for c in range(n):
        for i in range(nodes_per_cluster):
            f.add_node(c, new_node(f"c{c}-n{i}", instance_group=f"ig-{c}"))
    return f


def _concurrent_churn(f, n, rounds=3, seed=7):
    """Per-cluster worker threads so windows from different clusters are
    in flight together and meet inside the gather window. Each thread
    owns its RNG (seeded per cluster) — the traffic is deterministic per
    cluster even though the interleaving is not, and the equivalence
    oracle replays each cluster's own oplog, which is order-exact."""

    def worker(c):
        rng = np.random.default_rng(seed + c)
        live = []
        for k in range(rounds):
            app = f"stk-c{c}-{k}"
            pods = static_allocation_spark_pods(
                app, int(rng.integers(1, 3)), instance_group=f"ig-{c}"
            )
            d = f.schedule(pods[0])
            for p in pods[1:]:
                f.schedule(p)
            if d.ok:
                live.append((d.cluster, pods))
            if live and rng.random() < 0.4:
                cluster, old = live.pop(0)
                for p in old:
                    f.stacks[cluster].delete_pod(p)

    ts = [threading.Thread(target=worker, args=(c,)) for c in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def _sequential_decisions(f, apps, group):
    """Drive `apps` driver-only gangs at one group, return decision bytes."""
    out = []
    for k in range(apps):
        pods = static_allocation_spark_pods(
            f"seq-{group}-{k}", 1, instance_group=group
        )
        for p in pods:
            d = f.schedule(p)
            out.append((d.ok, tuple(d.result.node_names), d.result.outcome))
    return out


DISPATCH_CONFIGS = [
    pytest.param({}, id="default"),
    pytest.param({"solver_prune_top_k": 4}, id="pruned"),
    pytest.param({"solver_device_pool": 2}, id="pooled"),
]


class TestStackedIdentity:
    @pytest.mark.parametrize("cfg_kw", DISPATCH_CONFIGS)
    @pytest.mark.parametrize("n", [2, 4])
    def test_concurrent_churn_replays_byte_identical(self, n, cfg_kw):
        f = _fleet(n, 150.0, **cfg_kw)
        try:
            _concurrent_churn(f, n)
            st = f.state()["stacking"]
            if not cfg_kw:
                # Standard pipelined XLA serving: concurrent windows must
                # actually stack, and nothing may need a forced resolve.
                assert st["stacked_dispatches"] > 0, st
            elif "solver_device_pool" in cfg_kw:
                # Pooled windows dispatch through the slot pool before
                # the lane hook — they never defer.
                assert st["stacked_dispatches"] == 0, st
                assert st["deferred"] == 0, st
            # Pruned is a conditional fast path: windows it accepts skip
            # the lane, fall-through windows defer like standard ones —
            # either way the byte-identity bar below is the contract.
            assert st["forced_resolves"] == 0, st
            report = verify_cluster_equivalence(f)
            assert set(report) == set(range(n))
            assert all(r["identical"] for r in report.values())
            for s in f.stacks:
                assert s.aggregates.oracle_equals(), f"cluster {s.index}"
        finally:
            f.stop()


class TestStragglerFallback:
    def test_lone_cluster_times_out_and_matches_unstacked(self):
        """Traffic at ONE cluster of three: its windows defer, nobody
        joins the gather, and each flush falls back to the per-cluster
        solve — decisions byte-equal to a stack-off facade."""
        on = _fleet(3, 60.0)
        off = _fleet(3, 0.0)
        try:
            got = _sequential_decisions(on, 3, "ig-0")
            want = _sequential_decisions(off, 3, "ig-0")
            assert got == want
            st = on.state()["stacking"]
            assert st["stacked_dispatches"] == 0, st
            assert st["fallbacks"] >= 3, st
            assert st["forced_resolves"] == 0, st
            assert all(
                r["identical"]
                for r in verify_cluster_equivalence(on).values()
            )
        finally:
            on.stop()
            off.stop()


class TestMixedShapeGrouping:
    def test_different_node_buckets_never_share_a_stack(self):
        """Clusters at 2 vs 12 nodes pad to different node buckets (8 vs
        16): their concurrent windows gather together but group apart,
        each solved as a singleton fallback — and stay byte-identical."""
        f = FleetFacade(2, _config(), record_ops=True, stack_window_ms=300.0)
        try:
            for i in range(2):
                f.add_node(0, new_node(f"c0-n{i}", instance_group="ig-0"))
            for i in range(12):
                f.add_node(1, new_node(f"c1-n{i}", instance_group="ig-1"))

            def pump(c):
                pods = static_allocation_spark_pods(
                    f"mix-{c}", 1, instance_group=f"ig-{c}"
                )
                for p in pods:
                    f.schedule(p)

            ts = [
                threading.Thread(target=pump, args=(c,)) for c in range(2)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            st = f.state()["stacking"]
            assert st["deferred"] >= 2, st
            assert st["stacked_dispatches"] == 0, st
            assert st["fallbacks"] >= 2, st
            assert all(
                r["identical"]
                for r in verify_cluster_equivalence(f).values()
            )
        finally:
            f.stop()


class TestKillMidGather:
    def test_victim_forced_and_survivors_stack(self):
        f = _fleet(3, 3000.0)
        try:
            done = threading.Event()

            def victim_pump():
                pod = static_allocation_spark_pods(
                    "kill-victim", 1, instance_group="ig-0"
                )[0]
                f.schedule(pod)
                done.set()

            t = threading.Thread(target=victim_pump)
            t.start()
            # Wait until the victim's window is parked in the gather.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if f.dispatch.describe()["pending"] >= 1:
                    break
                time.sleep(0.005)
            else:
                pytest.fail("victim window never deferred")
            f.kill_cluster(0)
            assert done.wait(5.0), "victim window never resolved after kill"
            t.join()
            st = f.state()["stacking"]
            assert st["forced_resolves"] == 1, st
            assert st["expected"] == 2, st

            # Survivors still stack with each other.
            def pump(c):
                pods = static_allocation_spark_pods(
                    f"surv-{c}", 1, instance_group=f"ig-{c}"
                )
                for p in pods:
                    f.schedule(p)

            ts = [
                threading.Thread(target=pump, args=(c,)) for c in (1, 2)
            ]
            for s in ts:
                s.start()
            for s in ts:
                s.join()
            st = f.state()["stacking"]
            assert st["stacked_dispatches"] >= 1, st
            assert all(
                r["identical"]
                for r in verify_cluster_equivalence(f).values()
            )
        finally:
            f.stop()


class TestRowBucketPolicy:
    def test_deferred_windows_use_fleet_quantum_serving_stays_32(self):
        """The row-bucket split (ISSUE 20 satellite): deferred fleet
        windows pad app rows at the lane quantum (8); every non-deferred
        serving window keeps the solver's 32 — with the window OPEN but
        stacking not triggering, blobs are byte-unchanged from stack-off."""
        assert FleetDispatchCoordinator.row_bucket_quantum == 8
        on = _fleet(2, 200.0)
        off = _fleet(2, 0.0)
        try:
            for g in (on, off):
                for s in g.stacks:
                    assert s.app.solver._row_bucket_quantum == 32

            def pump(g, c):
                pods = static_allocation_spark_pods(
                    f"rbq-{c}", 1, instance_group=f"ig-{c}"
                )
                for p in pods:
                    g.schedule(p)

            ts = [
                threading.Thread(target=pump, args=(on, c))
                for c in range(2)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert on.state()["stacking"]["stacked_dispatches"] > 0
            for c in range(2):
                pump(off, c)
            # Deferred windows padded at the lane quantum; the unstacked
            # facade's identical windows padded at the serving quantum.
            for g, want in ((on, 8), (off, 32)):
                for s in g.stacks:
                    info = s.app.solver.last_solve_info
                    assert info["row_bucket"] == want, (s.index, info)

            # Window open but stacking no longer triggering (one live
            # cluster => accepts() is False): serving windows run the
            # normal path at quantum 32 and decisions byte-match
            # stack-off.
            on.kill_cluster(1)
            off.kill_cluster(1)
            got = _sequential_decisions(on, 2, "ig-0")
            want = _sequential_decisions(off, 2, "ig-0")
            assert got == want
            assert (
                on.stacks[0].app.solver.last_solve_info["row_bucket"] == 32
            )
            deferred_before = on.state()["stacking"]["deferred"]
            assert deferred_before == 2, on.state()["stacking"]
            assert all(
                r["identical"]
                for r in verify_cluster_equivalence(on).values()
            )
        finally:
            on.stop()
            off.stop()


class TestDefaultOff:
    def test_stack_window_defaults_off_and_pins_pr19_serving(self):
        assert InstallConfig().fleet_stack_window_ms == 0.0
        f = _fleet(3, 0.0)
        try:
            assert f.dispatch is None
            for s in f.stacks:
                assert s.app.solver._dispatch_lane is None
            assert f.state()["stacking"] == {"enabled": False}
            _sequential_decisions(f, 2, "ig-0")
            assert all(
                r["identical"]
                for r in verify_cluster_equivalence(f).values()
            )
        finally:
            f.stop()

    def test_facade_honors_config_default(self):
        cfg = _config(fleet_stack_window_ms=120.0)
        f = FleetFacade(2, cfg, record_ops=True)
        try:
            assert f.dispatch is not None
            assert f.dispatch.describe()["window_ms"] == 120.0
            for s in f.stacks:
                assert s.app.solver._dispatch_lane is f.dispatch
        finally:
            f.stop()


class TestBucketStackedKernel:
    """Direct vmap-identity pin for the stacked kernel, below the fleet
    plumbing: M windows from DIFFERENT clusters (different statics, apps,
    row counts, mixed fills) solved in one `bucket_stacked_fifo_pack`
    dispatch must be bitwise equal to each member's own
    `batched_fifo_pack` solve at its original (unpadded) row count."""

    @pytest.mark.parametrize("seed", [0, 3])
    def test_matches_per_member_solves_bitwise(self, seed):
        import jax.numpy as jnp

        from spark_scheduler_tpu.models.cluster import cluster_statics
        from spark_scheduler_tpu.ops.batched import (
            batched_fifo_pack,
            bucket_stacked_fifo_pack,
            pad_app_batch,
            stack_app_batches,
        )
        from tests.test_batched import random_apps
        from tests.test_packing_golden import random_cluster

        emax, zones, n = 16, 4, 24
        rng = np.random.default_rng(seed)
        members = []
        for b, fill in (
            (5, "tightly-pack"),
            (3, "distribute-evenly"),
            (7, "tightly-pack"),
        ):
            members.append(
                (random_cluster(rng, n), random_apps(rng, b), fill)
            )
        # The coordinator's stacking protocol: equal fills adjacent, app
        # rows re-padded to the group max.
        members.sort(key=lambda m: m[2])
        rows = max(m[1].driver_req.shape[0] for m in members)
        fills = tuple(m[2] for m in members)
        n_statics = len(cluster_statics(members[0][0]))
        avail_stack = jnp.stack(
            [jnp.asarray(m[0].available) for m in members]
        )
        statics_stack = tuple(
            jnp.stack(
                [jnp.asarray(cluster_statics(m[0])[i]) for m in members]
            )
            for i in range(n_statics)
        )
        apps_stack = stack_app_batches(
            [pad_app_batch(m[1], rows) for m in members]
        )
        blob, avail_after = bucket_stacked_fifo_pack(
            avail_stack,
            statics_stack,
            apps_stack,
            fills=fills,
            emax=emax,
            num_zones=zones,
        )
        blob, avail_after = np.asarray(blob), np.asarray(avail_after)
        for i, (c, apps, fill) in enumerate(members):
            out = batched_fifo_pack(
                c, apps, fill=fill, emax=emax, num_zones=zones
            )
            want = np.concatenate(
                [
                    np.asarray(out.driver_node)[:, None],
                    np.asarray(out.admitted)[:, None].astype(np.int32),
                    np.asarray(out.packed)[:, None].astype(np.int32),
                    np.asarray(out.executor_nodes),
                ],
                axis=1,
            )
            b = apps.driver_req.shape[0]
            np.testing.assert_array_equal(
                blob[i, :b], want, err_msg=f"member {i} blob"
            )
            np.testing.assert_array_equal(
                avail_after[i],
                np.asarray(out.available_after),
                err_msg=f"member {i} avail",
            )

    def test_mismatched_fills_raise(self):
        import jax.numpy as jnp

        from spark_scheduler_tpu.ops.batched import (
            bucket_stacked_fifo_pack,
        )

        with pytest.raises(ValueError, match="fills"):
            bucket_stacked_fifo_pack(
                jnp.zeros((2, 8, 3), jnp.int32),
                (),
                None,
                fills=("tightly-pack",),
                emax=8,
                num_zones=2,
            )

    def test_stack_app_batches_rejects_mixed_noneness(self):
        from spark_scheduler_tpu.ops.batched import stack_app_batches
        from tests.test_batched import random_apps

        rng = np.random.default_rng(5)
        a, b = random_apps(rng, 4), random_apps(rng, 4)
        b = b._replace(driver_cand=np.zeros((4, 8), np.bool_))
        with pytest.raises(ValueError, match="mixed None-ness"):
            stack_app_batches([a, b])
