"""The million-node tier (ISSUE 11): O(changed) host paths, delta static
uploads, and the scale-tier escalation re-solve.

Pinned here:

  - SoftReservationStore.used_soft_reservation_resources() is a memoized
    IMMUTABLE view maintained incrementally — equal to the reference's
    per-call walk under churn, same object while nothing changed, and
    mutation raises (the PR 5 FrozenResources contract);
  - node-ADD budget: N adds pay ZERO full roster rebuilds (the add-patch
    path), and the patched roster/tensors equal a from-scratch rebuild —
    name ranks compared by ORDER (the gapped-rank scheme's only contract);
  - delta-vs-full static upload equivalence: randomized node churn
    (add / update / delete) x device pool {1, 2} x {pruned, unpruned},
    asserting byte-identical decisions AND resident-tensor == host-truth
    equality after every event;
  - torn update: a pool replica whose missed epochs left the journal must
    full re-upload, never scatter against a stale epoch;
  - ClusterCensus == from-scratch walks under churn, and the census-backed
    drainer keeps the reservation-refusal rule;
  - scale-tier escalation re-solve == the host greedy escalation, byte
    for byte, with the sharded path actually exercised.
"""

import dataclasses

import numpy as np
import pytest

from spark_scheduler_tpu.core.census import ClusterCensus
from spark_scheduler_tpu.core.soft_reservations import SoftReservationStore
from spark_scheduler_tpu.core.solver import PlacementSolver, WindowRequest
from spark_scheduler_tpu.models.kube import Node, ZONE_LABEL
from spark_scheduler_tpu.models.reservations import Reservation
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.store.backend import InMemoryBackend
from spark_scheduler_tpu.store.cache import ResourceReservationCache
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    static_allocation_spark_pods,
)

ONE = Resources.from_quantities("1", "1Gi")
TWO = Resources.from_quantities("2", "2Gi")


# ------------------------------------------------- soft-usage memoized view


def _soft_walk_oracle(store: SoftReservationStore) -> dict:
    out: dict[str, Resources] = {}
    for sr in store.get_all_copy().values():
        for r in sr.reservations.values():
            out.setdefault(r.node, Resources.zero()).add(r.resources)
    return out


def test_soft_usage_view_memoized_immutable():
    store = SoftReservationStore()
    store.create_soft_reservation_if_not_exists("app-a")
    store.add_reservation_for_pod("app-a", "e1", Reservation("n1", ONE.copy()))
    store.add_reservation_for_pod("app-a", "e2", Reservation("n1", TWO.copy()))
    view = store.used_soft_reservation_resources()
    assert dict(view) == _soft_walk_oracle(store)
    # Memoized: no mutation => the SAME object (zero work per call).
    assert store.used_soft_reservation_resources() is view
    # Immutable: the mapping and its values both refuse mutation.
    with pytest.raises(TypeError):
        view["n9"] = ONE
    with pytest.raises(TypeError):
        view["n1"].add(ONE)
    with pytest.raises(TypeError):
        view["n1"].cpu_milli = 0
    # A mutation invalidates the memo and the new view reflects it.
    store.remove_executor_reservation("app-a", "e1")
    view2 = store.used_soft_reservation_resources()
    assert view2 is not view
    assert dict(view2) == _soft_walk_oracle(store)


def test_soft_usage_view_matches_walk_under_churn():
    rng = np.random.default_rng(7)
    store = SoftReservationStore()
    apps = [f"app-{i}" for i in range(4)]
    for a in apps:
        store.create_soft_reservation_if_not_exists(a)
    live: list[tuple[str, str]] = []
    for step in range(200):
        op = rng.random()
        if op < 0.55 or not live:
            a = apps[int(rng.integers(0, len(apps)))]
            pod = f"p{step}"
            res = Resources(
                int(rng.integers(0, 4)) * 500, int(rng.integers(1, 4)), 0
            )
            store.add_reservation_for_pod(a, pod, Reservation(
                f"n{int(rng.integers(0, 6))}", res
            ))
            live.append((a, pod))
        elif op < 0.9:
            a, pod = live.pop(int(rng.integers(0, len(live))))
            store.remove_executor_reservation(a, pod)
        else:
            a = apps[int(rng.integers(0, len(apps)))]
            store.remove_driver_reservation(a)
            live = [(x, p) for x, p in live if x != a]
            store.create_soft_reservation_if_not_exists(a)
        assert dict(store.used_soft_reservation_resources()) == (
            _soft_walk_oracle(store)
        ), f"diverged at step {step}"
    # A node whose reservations all vanished must drop out of the view —
    # including the zero-resource ones the refcount (not the sum) tracks.
    for a, pod in list(live):
        store.remove_executor_reservation(a, pod)
    assert dict(store.used_soft_reservation_resources()) == {}


# ------------------------------------------------------- node-ADD budget


def test_node_add_budget_zero_roster_rebuilds():
    """N node ADDs after the cold build pay ZERO full roster rebuilds
    (counter-pinned, the tier-1 budget contract), and the patched state
    equals a from-scratch rebuild — tensors compared field-exact with
    name ranks by ORDER."""
    h = Harness(binpack_algo="tightly-pack", fifo=False)
    base_nodes = [new_node(f"a{i:03d}", zone=f"zone{i % 2}") for i in range(32)]
    h.add_nodes(*base_nodes)
    store = h.app.extender.features
    store.snapshot()
    rebuilds_cold = store.roster_rebuilds
    added = [new_node(f"late{j:02d}", zone=f"zone{j % 2}") for j in range(24)]
    for j, node in enumerate(added):
        h.add_nodes(node)
        snap = store.snapshot()
        assert len(snap.nodes) == 32 + j + 1
    assert store.roster_rebuilds == rebuilds_cold, (
        "a node ADD paid the full roster rebuild"
    )
    assert store.roster_add_patches >= 1

    # From-scratch twin on the same backend state: the patched roster and
    # the rebuilt roster must agree, and both solvers' tensors must match.
    twin = Harness(
        binpack_algo="tightly-pack", fifo=False, backend=h.backend
    )
    snap_fresh = twin.app.extender.features.snapshot()
    snap_patched = store.snapshot()
    assert [n.name for n in snap_patched.nodes] == sorted(
        (n.name for n in snap_fresh.nodes),
        key=[n.name for n in snap_patched.nodes].index,
    )
    assert set(n.name for n in snap_patched.nodes) == set(
        n.name for n in snap_fresh.nodes
    )

    def tensors_of(app, snap):
        return app.solver.build_tensors(
            snap.nodes, {}, {}, full_node_list=True,
            topo_version=snap.nodes_version, roster_rows=snap.roster_rows,
        )

    ta = tensors_of(h.app, snap_patched)
    tb = tensors_of(twin.app, snap_fresh)
    va, vb = np.asarray(ta.valid), np.asarray(tb.valid)
    # Same live set by NAME (registry row assignment may differ).
    names_a = {h.app.solver.registry.name_of(i) for i in np.flatnonzero(va)}
    names_b = {twin.app.solver.registry.name_of(i) for i in np.flatnonzero(vb)}
    assert names_a == names_b
    # Per-name field equality + name-rank ORDER equality.
    rows_a = {h.app.solver.registry.name_of(i): i for i in np.flatnonzero(va)}
    rows_b = {twin.app.solver.registry.name_of(i): i for i in np.flatnonzero(vb)}
    for field in ("available", "schedulable", "zone_id", "unschedulable",
                  "ready"):
        fa, fb = np.asarray(getattr(ta, field)), np.asarray(getattr(tb, field))
        for name in names_a:
            assert np.array_equal(fa[rows_a[name]], fb[rows_b[name]]), (
                field, name,
            )
    ranks_a = np.asarray(ta.name_rank)
    ranks_b = np.asarray(tb.name_rank)
    order_a = sorted(names_a, key=lambda n: int(ranks_a[rows_a[n]]))
    order_b = sorted(names_b, key=lambda n: int(ranks_b[rows_b[n]]))
    assert order_a == order_b == sorted(names_a)

    # The added capacity is real: a gang lands on a late node.
    driver = static_allocation_spark_pods("late-gang", 2)[0]
    h.add_pods(driver)
    res = h.schedule(driver, ["late23"])
    assert res.node_names == ["late23"], res
    h.app.stop()
    twin.app.stop()


# ----------------------------------- delta-vs-full static upload equivalence


def _mk_churn_harness(pool, prune, delta):
    kw = dict(
        binpack_algo="tightly-pack",
        fifo=False,
        solver_delta_statics=delta,
    )
    if pool > 1:
        kw["solver_device_pool"] = pool
    if prune:
        kw["solver_prune_top_k"] = prune
        kw["solver_prune_slack"] = 0.75
    return Harness(**kw)


def _apply_event(h, rng, spare_names, live):
    """One randomized node event applied to harness `h`; mirrors exactly
    by seeding both harnesses identically."""
    op = rng.random()
    if op < 0.4 and spare_names:
        name = spare_names.pop()
        h.add_nodes(new_node(name, zone=f"zone{len(live) % 2}"))
        live.append(name)
        return ("add", name)
    if op < 0.8 and live:
        name = live[int(rng.integers(0, len(live)))]
        cur = h.backend.get_node(name)
        h.backend.update(
            "nodes",
            dataclasses.replace(cur, unschedulable=not cur.unschedulable),
        )
        return ("update", name)
    if live:
        name = live.pop(int(rng.integers(0, len(live))))
        h.backend.delete("nodes", "", name)
        return ("delete", name)
    return ("noop", None)


@pytest.mark.parametrize("pool,prune", [(1, 0), (1, 4), (2, 0), (2, 4)])
def test_delta_vs_full_static_uploads_equivalent(pool, prune):
    """Randomized node churn x {pool 1,2} x {pruned, unpruned}: the
    delta-statics solver's decisions are byte-identical to the
    full-upload solver's after every event, and its resident device
    tensors equal its own host truth (the mirror invariant delta uploads
    must preserve)."""
    n0 = 16
    h_delta = _mk_churn_harness(pool, prune, True)
    h_full = _mk_churn_harness(pool, prune, False)
    for h in (h_delta, h_full):
        h.add_nodes(*[new_node(f"n{i:02d}", zone=f"zone{i % 2}")
                      for i in range(n0)])
    live_d = [f"n{i:02d}" for i in range(n0)]
    live_f = list(live_d)
    spare_d = [f"x{j:02d}" for j in range(40, 20, -1)]
    spare_f = list(spare_d)
    rng_d = np.random.default_rng(123)
    rng_f = np.random.default_rng(123)
    app_seq = iter(range(10_000))

    def serve(h, live):
        from spark_scheduler_tpu.core.extender import ExtenderArgs

        names = list(live)
        drivers = []
        for _ in range(2):
            d = static_allocation_spark_pods(
                f"churn-{next(app_seq)}", 2
            )[0]
            h.add_pods(d)
            drivers.append(d)
        t = h.extender.predicate_window_dispatch(
            [ExtenderArgs(pod=d, node_names=names) for d in drivers]
        )
        return [tuple(r.node_names) for r in
                h.extender.predicate_window_complete(t)]

    for step in range(14):
        ev_d = _apply_event(h_delta, rng_d, spare_d, live_d)
        ev_f = _apply_event(h_full, rng_f, spare_f, live_f)
        assert ev_d == ev_f  # identical seeded streams
        # Window IDs must match across harnesses: reset the shared counter
        # per-step by construction (same sequence consumed on both).
        app_seq_start = next(app_seq)
        a = serve(h_delta, live_d)
        b = serve(h_full, live_f)
        assert a == b, f"step {step} ({ev_d}): {a} vs {b}"
        # Resident-tensor == host-truth equality on the delta solver.
        p = h_delta.app.solver._pipe
        if p is not None:
            host = p["host"]
            from spark_scheduler_tpu.models.cluster import cluster_statics

            for host_f, dev_f in zip(
                cluster_statics(host), cluster_statics(p["tensors"])
            ):
                assert np.array_equal(
                    np.asarray(host_f), np.asarray(dev_f)
                ), f"resident statics diverged from host truth at {step}"
        _ = app_seq_start
    # The delta path must actually have been exercised.
    stats = h_delta.app.solver.device_state_stats
    assert stats["static_delta_uploads"] > 0, stats
    if pool > 1 and not prune:
        # With pruning on, eligible windows gather fresh per-window
        # statics and never touch the resident replica — only the
        # unpruned pool arm exercises the slot-level delta catch-up.
        slot_stats = h_delta.app.solver.device_pool_stats()
        assert any(v.get("delta", 0) > 0 for v in slot_stats.values()), (
            slot_stats
        )
    h_delta.app.stop()
    h_full.app.stop()


def test_torn_static_delta_forces_full_reupload():
    """A pool replica whose missed epochs are NOT all in the journal must
    take the full re-upload — a delta applied against a stale epoch would
    silently skew the resident statics."""
    import jax

    from spark_scheduler_tpu.core.solver import _PoolSlot
    from spark_scheduler_tpu.models.cluster import (
        build_cluster_tensors,
        cluster_statics,
        NodeRegistry,
    )

    reg = NodeRegistry()
    nodes = [
        Node(
            name=f"n{i}",
            allocatable=Resources.from_quantities("8", "8Gi", "1",
                                                  round_up=False),
            labels={ZONE_LABEL: "z0"},
        )
        for i in range(8)
    ]
    host1 = build_cluster_tensors(nodes, {}, {}, reg, pad_to=8)
    slot = _PoolSlot(jax.devices()[0])
    clock = lambda: 0.0  # noqa: E731
    slot.resident_statics(host1, 1, clock, None)
    assert slot.uploads == {"full": 1, "delta": 0, "reuse": 0}

    # Epoch 2's rows present in the journal: delta catch-up, and the
    # resident replica equals the new host statics exactly.
    nodes2 = [dataclasses.replace(n) for n in nodes]
    nodes2[3] = dataclasses.replace(nodes2[3], unschedulable=True)
    host2 = build_cluster_tensors(nodes2, {}, {}, reg, pad_to=8)
    journal = {2: np.asarray([3])}
    statics = slot.resident_statics(host2, 2, clock, None, journal=journal)
    assert slot.uploads["delta"] == 1
    for host_f, dev_f in zip(cluster_statics(host2), statics):
        assert np.array_equal(np.asarray(host_f), np.asarray(dev_f))

    # Epoch 3 evicted from the journal (only 4 present): the slot is TORN
    # — it must full re-upload, not scatter epoch 4 alone.
    nodes3 = list(nodes2)
    nodes3[5] = dataclasses.replace(nodes3[5], unschedulable=True)
    host3 = build_cluster_tensors(nodes3, {}, {}, reg, pad_to=8)
    statics = slot.resident_statics(
        host3, 4, clock, None, journal={4: np.asarray([5])}
    )
    assert slot.uploads["full"] == 2, slot.uploads
    for host_f, dev_f in zip(cluster_statics(host3), statics):
        assert np.array_equal(np.asarray(host_f), np.asarray(dev_f))


# --------------------------------------------------------------- census


def test_census_matches_walk_oracle_under_churn():
    rng = np.random.default_rng(31)
    backend = InMemoryBackend()
    rr_cache = ResourceReservationCache(backend, sync_writes=True)
    soft = SoftReservationStore(backend)
    census = ClusterCensus(backend, rr_cache, soft)
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )

    node_names = []
    rrs = []
    for step in range(120):
        op = rng.random()
        if op < 0.35:
            name = f"c{step}"
            backend.add_node(new_node(name))
            node_names.append(name)
        elif op < 0.5 and node_names:
            backend.delete(
                "nodes", "",
                node_names.pop(int(rng.integers(0, len(node_names)))),
            )
        elif op < 0.7 and node_names:
            driver = static_allocation_spark_pods(f"capp-{step}", 1)[0]
            target = node_names[int(rng.integers(0, len(node_names)))]
            rr = new_resource_reservation(
                target, [target], driver, ONE, ONE
            )
            if rr_cache.create(rr):
                rrs.append(rr)
        elif op < 0.85 and rrs:
            rr = rrs.pop(int(rng.integers(0, len(rrs))))
            rr_cache.delete(rr.namespace, rr.name)
        elif node_names:
            soft.create_soft_reservation_if_not_exists(f"sapp-{step}")
            soft.add_reservation_for_pod(
                f"sapp-{step}", f"sp-{step}",
                Reservation(
                    node_names[int(rng.integers(0, len(node_names)))],
                    ONE.copy(),
                ),
            )
        oracle = ClusterCensus(backend, rr_cache, soft)
        assert census.node_count() == oracle.node_count(), step
        assert census.reserved_node_names() == (
            oracle.reserved_node_names()
        ), step
        for name in node_names:
            assert census.is_busy(name) == oracle.is_busy(name), (
                step, name,
            )


def test_census_backed_drainer_refuses_reserved_nodes():
    """The absolute refusal rule survives the census: a node a
    reservation names is never cordoned, an idle provisioned node drains
    after a full TTL."""
    from spark_scheduler_tpu.autoscaler.drainer import ScaleDownDrainer
    from spark_scheduler_tpu.autoscaler.provisioner import (
        PROVISIONED_BY_LABEL,
        PROVISIONER_NAME,
    )
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )

    backend = InMemoryBackend()
    rr_cache = ResourceReservationCache(backend, sync_writes=True)
    soft = SoftReservationStore(backend)
    census = ClusterCensus(
        backend, rr_cache, soft,
        eligible_label=(PROVISIONED_BY_LABEL, PROVISIONER_NAME),
    )
    for name in ("idle-1", "busy-1"):
        n = new_node(name)
        n.labels[PROVISIONED_BY_LABEL] = PROVISIONER_NAME
        backend.add_node(n)
    backend.add_node(new_node("static-1"))  # not provisioned: untouchable
    driver = static_allocation_spark_pods("keeper", 1)[0]
    rr_cache.create(
        new_resource_reservation("busy-1", ["busy-1"], driver, ONE, ONE)
    )
    t = [0.0]
    drainer = ScaleDownDrainer(
        backend, rr_cache, soft, idle_ttl_s=10.0,
        clock=lambda: t[0], census=census,
    )
    drainer.run_once()  # starts the idle clock
    t[0] = 11.0
    drainer.run_once()  # cordons idle-1 only
    assert backend.get_node("idle-1").unschedulable
    assert not backend.get_node("busy-1").unschedulable
    assert not backend.get_node("static-1").unschedulable
    t[0] = 12.0
    drained = drainer.run_once()
    assert drained == ["idle-1"]
    assert backend.get_node("busy-1") is not None
    assert backend.get_node("static-1") is not None


# ------------------------------------------------ scale-tier escalation


def _esc_nodes(n, zones=3):
    return [
        Node(
            name=f"n{i:03d}",
            allocatable=Resources.from_quantities("8", "8Gi", "1",
                                                  round_up=False),
            labels={ZONE_LABEL: f"z{i % zones}"},
        )
        for i in range(n)
    ]


def _esc_windows(rng, nodes, k, per):
    names = [n.name for n in nodes]
    windows = []
    for _ in range(k):
        reqs = []
        for _ in range(per):
            rows = []
            for _ in range(int(rng.integers(0, 3))):
                rows.append(
                    (ONE, ONE, int(rng.integers(1, 3)),
                     bool(rng.random() < 0.5))
                )
            res = TWO if rng.random() < 0.3 else ONE
            rows.append((res, ONE, int(rng.integers(1, 4)), False))
            reqs.append(
                WindowRequest(rows=rows, driver_candidate_names=names)
            )
        windows.append(reqs)
    return windows


def _esc_run(solver, nodes, batches, usages, strategy):
    out = []
    for usage, wins in zip(usages, batches):
        handles = []
        for w in wins:
            t = solver.build_tensors_pipelined(nodes, usage, {})
            handles.append(solver.pack_window_dispatch(strategy, t, w))
        for hd in handles:
            out.extend(solver.pack_window_fetch(hd))
    return out


def test_scale_tier_escalation_matches_host_resolve():
    """Tight-K pruning forces certificate escalations; with
    solver.scale-tier the escalated windows re-solve on the node-sharded
    device path and must equal the host greedy re-solve byte for byte."""
    rng = np.random.default_rng(9)
    nodes = _esc_nodes(128)
    n_batches = 3
    batches = [_esc_windows(rng, nodes, 2, 4) for _ in range(n_batches)]
    usages = [{}] * n_batches
    host_esc = PlacementSolver(
        use_native=False, prune_top_k=1, prune_slack=0.01
    )
    a = _esc_run(host_esc, nodes, batches, usages, "tightly-pack")
    sharded_esc = PlacementSolver(
        use_native=False, prune_top_k=1, prune_slack=0.01, scale_tier=True
    )
    b = _esc_run(sharded_esc, nodes, batches, usages, "tightly-pack")
    assert host_esc.prune_stats["escalations"] > 0
    assert sharded_esc.prune_stats["escalations"] > 0
    assert a == b
    assert sharded_esc.scale_tier_stats["resolves"] > 0, (
        sharded_esc.scale_tier_stats
    )
    assert sharded_esc.scale_tier_stats["fallbacks"] == 0, (
        sharded_esc.scale_tier_stats
    )
    # On the 8-device CPU mesh the re-solve really shards the node axis.
    assert sharded_esc.scale_tier_stats["sharded"] > 0

    # And the full unpruned solve agrees with both (the usual bar).
    full = _esc_run(
        PlacementSolver(use_native=False, prune_top_k=0),
        nodes, batches, usages, "tightly-pack",
    )
    assert full == a
